"""Roll-up CI gate: O(hosts) fleet observability (ISSUE 20).

Runs the `sim swarm` orchestrator multi-process with the hierarchical
roll-up plane on (handel_tpu/obs/rollup.py), and asserts the acceptance
surface in three acts:

1. **boundedness** — the master's merged series count must stay under a
   bound that depends on the key union, never the identity count, and the
   measured delta wire bytes per host per second must ride the summary.
2. **host-loss drill** — the dumped per-process host digests are replayed
   into a fresh `FleetRollup` feeding an `AlertPlane` on a manual clock;
   one forced host loss must open EXACTLY ONE incident whose attribution
   names the lost host, and recovery must close it.
3. **regression gate** — the run writes a bench-record-shaped
   rollup_report.json carrying the three SIDE_METRICS flat
   (fleet_series_count, rollup_bytes_per_host_s, fleet_eval_ms) and hands
   it to scripts/bench_check.py --dry-run against any committed history
   (results/rollup_report*.json).

Usage: python scripts/rollup_smoke.py [--artifact-dir DIR]
       [--identities N] [--processes M] [--series-bound K]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.obs import AlertPlane  # noqa: E402
from handel_tpu.obs.rollup import FleetRollup  # noqa: E402
from handel_tpu.sim.config import AlertParams, SimConfig, SwarmParams  # noqa: E402
from handel_tpu.swarm.driver import run_swarm  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def host_loss_drill(digests: list[dict]) -> None:
    """Replay the dumped host digests into a rollup-fed AlertPlane on a
    manual clock and force one host loss: exactly one incident, its
    attribution naming the lost host, closed again on recovery."""
    t = {"now": 0.0}
    plane = AlertPlane.from_params(
        AlertParams(window_scale=0.01, min_hold_s=0.5, cooldown_s=2.0),
        clock=lambda: t["now"],
    )
    fleet = FleetRollup(stale_after_s=1.0, clock=lambda: t["now"])
    fleet.attach_alerts(plane)
    lost = digests[-1]["host"]

    def step(hosts):
        for d in hosts:
            fleet.ingest_digest(d, now=t["now"])
        plane.tick()
        t["now"] += 0.1

    while t["now"] < 2.0:  # healthy baseline: every host reports
        step(digests)
    assert plane.incidents.opened == 0, "baseline opened an incident"
    assert fleet.hosts_up() == len(digests)

    while t["now"] < 4.0:  # the loss: the last host goes dark
        step(digests[:-1])
    inc = plane.incidents.current
    assert inc is not None, "host loss never opened an incident"
    assert inc.attribution["lost_hosts"] == [lost], (
        f"attribution missed the lost host: {inc.attribution['lost_hosts']}"
    )
    assert fleet.hosts_up() == len(digests) - 1

    while t["now"] < 7.0:  # recovery: the host reports again
        step(digests)
    assert plane.incidents.current is None, "incident never closed"
    assert plane.incidents.opened == 1, (
        f"expected exactly one incident, got {plane.incidents.opened}"
    )
    assert inc.state == "closed"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep rollup_report.json + fleet_rollup.json here (CI upload)",
    )
    ap.add_argument("--identities", type=int, default=512)
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument(
        "--series-bound", type=int, default=512,
        help="max allowed master-side merged series count",
    )
    args = ap.parse_args(argv)
    assert args.processes >= 2, "the roll-up gate needs a real fleet"

    cfg = SimConfig(
        swarm=SwarmParams(
            identities=args.identities,
            processes=args.processes,
            period_ms=10000.0,
            timeout_ms=50.0,
            fast_path=3,
            timeout_s=600.0,
        ),
    )

    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        summary = asyncio.run(run_swarm(cfg, d))

        assert summary["ok"], (
            f"only {summary['completed']}/{summary['swarm_identities']} "
            "vnodes reached threshold"
        )
        # -- act 1: boundedness --------------------------------------------
        assert summary["fleet_hosts"] == args.processes
        series = summary["fleet_series_count"]
        assert 0 < series <= args.series_bound, (
            f"master holds {series} series for {args.identities} "
            f"identities — the roll-up leaked per-identity state "
            f"(bound {args.series_bound})"
        )
        assert summary["rollup_bytes_per_host_s"] > 0
        assert summary["fleet_eval_ms"] >= 0
        with open(os.path.join(d, "fleet_rollup.json")) as f:
            fleet_doc = json.load(f)
        assert fleet_doc["fleet"]["hosts_up"] == args.processes
        assert len(fleet_doc["fleet"]["hosts"]) == args.processes

        # -- act 2: the host-loss drill ------------------------------------
        digests = []
        for i in range(args.processes):
            with open(os.path.join(d, f"host_digest_{i}.json")) as f:
                digests.append(json.load(f))
        host_loss_drill(digests)

        # -- act 3: the bench-record artifact + regression gate ------------
        record = {
            "metric": "fleet_series_count",
            "value": series,
            "unit": "series",
            "backend": "cpu",
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "fleet_series_count": series,
            "rollup_bytes_per_host_s": summary["rollup_bytes_per_host_s"],
            "fleet_eval_ms": summary["fleet_eval_ms"],
            "rollup": {
                "identities": args.identities,
                "processes": args.processes,
                "series_bound": args.series_bound,
                "hosts": fleet_doc["fleet"]["hosts_up"],
                "surfaces": fleet_doc["fleet"]["surfaces"],
                "ingest_bytes": fleet_doc["fleet"]["ingest_bytes"],
            },
        }
        report_path = os.path.join(d, "rollup_report.json")
        with open(report_path, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        rc = subprocess.call([
            sys.executable,
            os.path.join(REPO, "scripts", "bench_check.py"),
            "--history",
            os.path.join(REPO, "results", "rollup_report*.json"),
            "--fresh", report_path,
            "--dry-run",
        ])
        assert rc == 0, "bench_check --dry-run failed on the rollup report"

        print(
            f"rollup smoke OK: {args.identities} identities / "
            f"{args.processes} hosts -> {series} master series "
            f"(bound {args.series_bound}), "
            f"{summary['rollup_bytes_per_host_s']:.0f} B/host/s, "
            f"merge {summary['fleet_eval_ms']:.2f}ms, "
            "host-loss drill: exactly one incident, attributed, closed"
        )
        if args.artifact_dir:
            print(f"artifacts: {report_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

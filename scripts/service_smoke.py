"""Multi-tenant service CI gate: concurrent sessions through one verifier.

Three stages, all seconds-fast on any machine (fake crypto, no jax):

1. Single-session baseline: 1 session of 16 nodes over a 64-lane shared
   verifier — records the launch fill ratio a lone tenant achieves.
2. 8 concurrent 16-node sessions through ONE BatchVerifierService: every
   session must reach threshold, and the coalesced launch fill ratio must
   BEAT the single-session baseline — the reason the service exists.
   The /metrics endpoint is scraped mid-run shape-wise: the session-labeled
   service plane (`handel_service_*{session=...}`) must be present.
3. A 2-process `sim serve` fleet (4 sessions x 8 nodes over 2 workers):
   the driver's worker sharding, summary merge and service_summary.json
   artifact all gate here.

A service regression fails this script on its own named CI step
(.github/workflows/ci.yml) before the full tier runs.

Usage: python scripts/service_smoke.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.core.metrics import parse_exposition  # noqa: E402
from handel_tpu.service.driver import (  # noqa: E402
    MultiSessionCluster,
    run_service,
)
from handel_tpu.sim.config import ServiceParams, SimConfig  # noqa: E402

SESSIONS, NODES, LANES = 8, 16, 64


async def run_shape(sessions: int, metrics: bool = False) -> dict:
    cluster = MultiSessionCluster(
        sessions,
        NODES,
        batch_size=LANES,
        metrics_port=0 if metrics else None,
    )
    addr = cluster.metrics_server.address if metrics else None
    scrape_task = None
    if addr:
        async def scrape_once():
            # poll until the session-labeled plane shows up mid-run
            for _ in range(200):
                text = await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://{addr}/metrics", timeout=2
                    ).read().decode()
                )
                fams = parse_exposition(text)
                labeled = [
                    n
                    for n, fam in fams.items()
                    if n.startswith("handel_service_")
                    and any("session" in lb for lb, _ in fam["samples"])
                ]
                if labeled:
                    return text, labeled
                await asyncio.sleep(0.01)
            return text, []

        scrape_task = asyncio.create_task(scrape_once())
    try:
        summary = await cluster.run(60.0)
        if scrape_task is not None:
            text, labeled = await scrape_task
            assert labeled, "no session-labeled handel_service_* families"
            assert "handel_device_verifier_launch_fill_ratio" in text
            summary["labeled_families"] = len(labeled)
        return summary
    finally:
        cluster.stop()


async def stage_serve_2proc(workdir: str) -> dict:
    cfg = SimConfig(
        scheme="fake",
        service=ServiceParams(sessions=4, nodes=8, processes=2,
                              session_ttl_s=30.0, batch_size=32),
        max_timeout_s=60.0,
    )
    summary = await run_service(cfg, workdir)
    assert summary["ok"], f"serve fleet failed: {summary}"
    assert summary["workers"] == 2
    assert summary["completed"] == 4
    path = os.path.join(workdir, "service_summary.json")
    assert os.path.exists(path), "service_summary.json not written"
    with open(path) as f:
        assert json.load(f)["sessions"] == 4
    return summary


def main() -> int:
    base = asyncio.run(run_shape(1))
    assert base["completed"] == 1, base
    multi = asyncio.run(run_shape(SESSIONS, metrics=True))
    assert multi["completed"] == SESSIONS, (
        f"only {multi['completed']}/{SESSIONS} sessions reached threshold"
    )
    assert multi["expired"] == 0, multi
    assert multi["launch_fill_ratio"] > base["launch_fill_ratio"], (
        f"coalescing win missing: multi fill {multi['launch_fill_ratio']} "
        f"<= single-session baseline {base['launch_fill_ratio']}"
    )
    assert multi["coalesced_launches"] > 0, "no cross-session launches"
    with tempfile.TemporaryDirectory() as d:
        fleet = asyncio.run(stage_serve_2proc(d))
    print(
        json.dumps(
            {
                "baseline_fill": base["launch_fill_ratio"],
                "multi_fill": multi["launch_fill_ratio"],
                "coalesced_launches": multi["coalesced_launches"],
                "aggregates_per_s": multi["aggregates_per_s"],
                "session_p99_s": multi["session_p99_s"],
                "labeled_families": multi["labeled_families"],
                "fleet_completed": fleet["completed"],
            }
        )
    )
    print(
        f"service smoke OK: {SESSIONS} sessions fill "
        f"{multi['launch_fill_ratio']:.2f} vs single-session "
        f"{base['launch_fill_ratio']:.2f}, 2-process fleet completed "
        f"{fleet['completed']}/4"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Observability CI gate: a 16-node traced LocalCluster smoke run.

Runs a fully traced in-process cluster (fake crypto, seconds on any
machine), asserts the trace export is non-empty with every pipeline stage
present, the contribution chains attributable, the flow links resolvable,
and — the ISSUE 10 acceptance — that the critical-path walk from the first
`threshold_reached` instant yields a single causal chain covering >= 90%
of the wall time-to-threshold with bounded clock offsets. Then prints the
trace CLI's analysis and writes `trace_report.json`, so a tracing
regression fails CI on its own named step (.github/workflows/ci.yml)
before the full tier runs, and the artifact upload step has evidence to
keep.

Usage: python scripts/trace_smoke.py [--artifact-dir DIR] [--nodes N]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.core.test_harness import run_cluster  # noqa: E402
from handel_tpu.core.trace import FlightRecorder, merge_traces  # noqa: E402
from handel_tpu.sim import trace_cli  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep the trace dump + trace_report.json here (CI upload)",
    )
    ap.add_argument("--nodes", type=int, default=16)
    args = ap.parse_args(argv)

    rec = FlightRecorder(capacity=1 << 17)
    finals = asyncio.run(run_cluster(args.nodes, recorder=rec))
    assert len(finals) == args.nodes, (
        f"only {len(finals)}/{args.nodes} nodes reached threshold"
    )

    events = rec.export()["traceEvents"]
    assert events, "trace export is empty"
    names = {e["name"] for e in events}
    missing = {"recv", "queue", "verify", "merge", "send",
               "level_complete", "threshold_reached"} - names
    assert not missing, f"missing pipeline spans: {missing}"

    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        rec.dump(os.path.join(d, "trace_0.json"))
        exports = trace_cli.load_exports([d])
        loaded = merge_traces(exports)["traceEvents"]

        chains = trace_cli.contribution_chains(loaded)
        assert chains, "no contribution chains reconstructed"
        best = max(c["coverage"] for c in chains.values())
        assert best >= 0.95, f"best chain coverage {best:.1%} < 95%"

        # ISSUE 10 acceptance: one causal chain, >= 90% of time-to-threshold
        cp = trace_cli.critical_path(loaded)
        assert cp is not None, "no threshold_reached anchor in trace"
        assert cp["chain"], "critical path is empty"
        assert cp["wall_ms"] > 0, "zero wall time-to-threshold"
        assert cp["coverage"] >= 0.90, (
            f"critical path covers {cp['coverage']:.1%} of "
            f"time-to-threshold < 90%"
        )
        assert cp["hops"] >= 1, "critical path crossed no network hop"

        frac, linked, total = trace_cli.flow_linkage(loaded)
        assert total > 0, "no trace-context-bearing recvs"
        assert frac >= 0.95, f"flow linkage {frac:.1%} ({linked}/{total})"

        # clock offsets ride each export; in-process they must be ~zero,
        # and any estimator blow-up (bad sync math) trips this bound
        offsets = [
            float(ex.get("clockOffset", 0.0) or 0.0) for ex in exports
        ]
        assert all(abs(o) < 1.0 for o in offsets), (
            f"unbounded clock offsets: {offsets}"
        )

        report_path = os.path.join(d, "trace_report.json")
        trace_cli.main([d, "--top", "5", "--critical-path",
                        "--report", report_path])
        with open(report_path) as f:
            report = json.load(f)
        assert report["backend"] == "trace"
        assert report["critical_path_coverage"] >= 0.90

    print(
        f"\ntrace smoke OK: {len(events)} events, {len(chains)} chains, "
        f"best coverage {best:.1%}; critical path {cp['wall_ms']:.1f} ms "
        f"over {cp['hops']} hops at {cp['coverage']:.1%} coverage, "
        f"flow linkage {frac:.1%}"
        + (f"; artifacts -> {args.artifact_dir}" if args.artifact_dir else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

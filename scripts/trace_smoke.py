"""Observability CI gate: an 8-node traced LocalCluster smoke run.

Runs a fully traced in-process cluster (fake crypto, seconds on any
machine), asserts the trace export is non-empty with every pipeline stage
present and the contribution chains attributable, then prints the trace
CLI's analysis — so a tracing regression fails CI on its own named step
(.github/workflows/ci.yml) before the full tier runs.

Usage: python scripts/trace_smoke.py
"""

from __future__ import annotations

import asyncio
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.core.test_harness import run_cluster  # noqa: E402
from handel_tpu.core.trace import FlightRecorder  # noqa: E402
from handel_tpu.sim import trace_cli  # noqa: E402


def main() -> int:
    rec = FlightRecorder(capacity=1 << 16)
    finals = asyncio.run(run_cluster(8, recorder=rec))
    assert len(finals) == 8, f"only {len(finals)}/8 nodes reached threshold"

    events = rec.export()["traceEvents"]
    assert events, "trace export is empty"
    names = {e["name"] for e in events}
    missing = {"recv", "queue", "verify", "merge", "level_complete"} - names
    assert not missing, f"missing pipeline spans: {missing}"

    with tempfile.TemporaryDirectory() as d:
        rec.dump(os.path.join(d, "trace_0.json"))
        loaded = trace_cli.load_traces([d])
        chains = trace_cli.contribution_chains(loaded)
        assert chains, "no contribution chains reconstructed"
        best = max(c["coverage"] for c in chains.values())
        assert best >= 0.95, f"best chain coverage {best:.1%} < 95%"
        trace_cli.main([d, "--top", "5"])

    print(f"\ntrace smoke OK: {len(events)} events, {len(chains)} chains, "
          f"best coverage {best:.1%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Stage-level profile of the flagship verify launch on the live chip.

Decomposes the bench headline (results/bench_tpu.json: 4096-key registry,
128 lanes, p50 101.3 ms) into:

  * dispatch round-trip — a null jitted op with device-resident input and a
    16-word fetch, measuring the axon-tunnel floor every launch pays;
  * range aggregation — the prefix-table G2 stage alone;
  * Miller loop — batched ate loop at the launch's 2C lane count;
  * final exponentiation — the shared final-exp at the same lane count;
  * full launch — the production `_verify_batch_range` p50, for reconciling
    the stage sum against the headline.

The point: the tunnel RT is environment overhead a co-located host would not
pay, and the compute split tells us which kernel to optimize for the
headline. Writes results/verify_profile.json.

    python scripts/verify_profile.py [trials]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import PIPELINE_DEPTH, measure_pipelined, write_json_atomic
from handel_tpu.utils.jaxenv import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp
import numpy as np


def p50(fn, force, trials: int) -> float:
    force(fn())  # warm/compile
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        force(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def main() -> int:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 9
    from bench import build_problem
    from handel_tpu.models.bn254 import BN254PublicKey
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.curve import BN254Curves

    n_registry, lanes, n_cands = 4096, 128, 64
    curves = BN254Curves()
    pks, miss_k, args = build_problem(curves, n_registry, lanes, n_cands)
    dev = BN254Device(
        [BN254PublicKey(p) for p in pks], batch_size=lanes, curves=curves
    )
    lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid = args

    out: dict[str, float] = {}
    force = lambda r: jax.device_get(jax.tree_util.tree_leaves(r)[0])

    # 1. dispatch round-trip floor
    x = jnp.ones((8, 128), jnp.uint32)
    null = jax.jit(lambda v: v + 1)
    out["dispatch_rt_ms"] = p50(lambda: null(x)[:1, :1], force, trials)

    # 2. range aggregation alone (prefix-table G2 stage)
    agg_fn = dev._range_agg_kernel(miss_k)
    mk_agg = lambda: agg_fn(lo, hi, miss_idx, miss_ok)
    out["range_agg_ms"] = p50(mk_agg, force, trials)
    agg = mk_agg()

    # 3/4. pairing stages at the launch's lane count (2C: H-lane + sig-lane)
    g2 = curves.g2
    qx, qy, _ = jax.jit(g2.to_affine)(agg)
    b2x = curves.T.f2_pack([bn.G2_GEN[0]] * 1)
    b2y = curves.T.f2_pack([bn.G2_GEN[1]] * 1)
    C = lanes
    px = jnp.concatenate([jnp.broadcast_to(h_x, sig_x.shape), sig_x], axis=1)
    py = jnp.concatenate(
        [jnp.broadcast_to(h_y, sig_y.shape), jax.jit(curves.F.neg)(sig_y)], axis=1
    )
    qx2 = tuple(
        jnp.concatenate([qx[i], jnp.broadcast_to(b2x[i], qx[i].shape)], axis=1)
        for i in range(2)
    )
    qy2 = tuple(
        jnp.concatenate([qy[i], jnp.broadcast_to(b2y[i], qy[i].shape)], axis=1)
        for i in range(2)
    )
    mask = jnp.concatenate([valid, valid])

    pair = dev.pairing
    miller = jax.jit(lambda p, q, m: pair.miller_loop(p, q, m))
    out["miller_loop_2c_ms"] = p50(
        lambda: miller((px, py), (qx2, qy2), mask), force, trials
    )
    f = miller((px, py), (qx2, qy2), mask)
    fexp = jax.jit(pair.final_exp)
    out["final_exp_2c_ms"] = p50(lambda: fexp(f), force, trials)

    # 5. the full production launch (the headline path)
    kern = dev._range_kernel(miss_k)
    out["full_launch_ms"] = p50(
        lambda: kern(lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid),
        force,
        trials,
    )

    # 6. pipelined sustained rate — the shared methodology (bench.py
    #    measure_pipelined): the effective per-batch latency the pipelined
    #    BatchVerifierService (parallel/batch_verifier.py) sustains, vs
    #    the single-shot full_launch_ms above.
    ts = measure_pipelined(
        lambda: kern(lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid),
        force,
        trials,
    )
    out["pipelined_depth"] = PIPELINE_DEPTH
    out["pipelined_per_launch_ms"] = float(np.median(ts))

    out["backend"] = jax.default_backend()
    out["device"] = str(jax.devices()[0])
    out["trials"] = trials
    out["registry"], out["lanes"], out["candidates"] = n_registry, lanes, n_cands
    print(json.dumps(out, indent=1))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "results", "verify_profile.json")
    write_json_atomic(os.path.normpath(path), out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

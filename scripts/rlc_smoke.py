"""RLC batch-verification smoke: verdict parity, counters, bench gate.

The fast-tier guard for the random-linear-combination batch check
(models/rlc.py + the HostDevice/BN254Device wiring): RLC verdicts must
equal per-candidate verdicts on valid AND forged batches, for both launch
shapes the service dispatches — single-message `dispatch` launches and
mixed-message `dispatch_multi` launches — with the per-launch pairing cost
asserted at M+1 Miller loops / 1 final exponentiation via the RlcStats
kernel counters (against the 2C / C per-candidate baseline). A forged
batch must come back with exactly the per-candidate culprit set, found by
bisection. Then the host bench captures `rlc_verify_p50_ms` /
`rlc_speedup_x` at batch 64 (acceptance: >= 3x) and self-tests
`scripts/bench_check.py --dry-run` against a fresh artifact carrying both,
keyed per fp_backend.

Scope note: one CPU core takes minutes of XLA per MSM/pairing-tail graph,
so this smoke drives the host-math RLC engine (native bn254 group ops) —
the combined-check equation, grouping, bisection and counters are the same
code the device path shares via models/rlc.py. The device MSM kernel and
the fused pairing tail compile in the slow tier (tests/test_msm.py,
BN254Device.warmup in rlc mode); here the device side is covered to the
dispatch seam: rlc-mode `BN254Device.dispatch`/`dispatch_multi` route both
packing classes (range + dense) into the rlc handle without a kernel.
Set HANDEL_TPU_RLC_SMOKE_DEVICE=1 to also compile the tiny-shape device
MSM stage and check S/X against the host oracle (minutes of XLA, off by
default in CI).
"""

import json
import os
import random
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("HANDEL_TPU_PLATFORM", "cpu")

from handel_tpu.core.bitset import BitSet  # noqa: E402
from handel_tpu.models import rlc  # noqa: E402
from handel_tpu.models.bn254 import BN254Scheme  # noqa: E402
from handel_tpu.service.driver import HostDevice  # noqa: E402

N = 16  # registry size
C = 64  # candidates per launch (the acceptance batch)
M = 4  # distinct messages in the mixed-message launch


def build_batch(scheme, keys, pubs, rng, messages, forged=()):
    """C candidates over `messages` distinct messages; indices in `forged`
    carry a wrong-message aggregate signature."""
    from handel_tpu.sim.adversary import forged_signature

    items = []
    for j in range(C):
        msg = messages[j % len(messages)]
        bs = BitSet(N)
        sig = None
        for i in rng.sample(range(N), rng.randrange(2, 6)):
            bs.set(i)
            s = (
                forged_signature(keys[i][0], msg)
                if j in forged
                else keys[i][0].sign(msg)
            )
            sig = s if sig is None else sig.combine(s)
        items.append((msg, pubs, bs, sig))
    return items


def check_parity(scheme, items, label):
    """RLC verdicts == per-candidate verdicts; returns both stat blocks."""
    pc = HostDevice(scheme.constructor)
    v_pc = pc.fetch(pc.dispatch_multi(items))
    dev = HostDevice(
        scheme.constructor, batch_check="rlc", rlc_rng=random.Random(1717)
    )
    v_rlc = dev.fetch(dev.dispatch_multi(items))
    assert v_rlc == v_pc, f"{label}: verdict mismatch {v_rlc} != {v_pc}"
    return v_rlc, dev.rlc_stats, pc.rlc_stats


def main() -> int:
    t0 = time.perf_counter()
    rng = random.Random(0x51C)
    scheme = BN254Scheme()
    keys = [scheme.keygen(i) for i in range(N)]
    pubs = [pk for _, pk in keys]
    single = [b"rlc-smoke-single"]
    multi = [f"rlc-smoke-{m}".encode() for m in range(M)]

    # -- valid batches: one combined check, M+1 Miller loops, 1 final exp --
    for msgs, label in ((single, "single-message"), (multi, "mixed-message")):
        items = build_batch(scheme, keys, pubs, rng, msgs)
        v, st, pst = check_parity(scheme, items, label)
        assert all(v), f"{label}: valid batch rejected"
        m = len(msgs)
        assert st.rlc_launches == 1 and st.bisection_ct == 0, st
        assert st.miller_lanes == m + 1, (
            f"{label}: {st.miller_lanes} Miller lanes, want M+1 = {m + 1}"
        )
        assert st.final_exp_lanes == 1, st
        # the per-candidate baseline the RLC launch replaces: 2C / C
        assert pst.miller_lanes == 2 * C and pst.final_exp_lanes == C, pst
        print(
            f"rlc_smoke: {label} valid batch of {C}: verdict parity, "
            f"{st.miller_lanes} Miller loops + {st.final_exp_lanes} final "
            f"exp (per-candidate: {pst.miller_lanes} + {pst.final_exp_lanes})"
        )

    # -- forged batches: bisection isolates the exact culprit set ----------
    for msgs, label in ((single, "single-message"), (multi, "mixed-message")):
        culprits = set(rng.sample(range(C), 3))
        items = build_batch(scheme, keys, pubs, rng, msgs, forged=culprits)
        v, st, _ = check_parity(scheme, items, label)
        found = {j for j, ok in enumerate(v) if not ok}
        assert found == culprits, f"{label}: isolated {found} != {culprits}"
        assert st.rlc_launches == 1 and st.bisection_ct > 0, st
        assert st.bisection_depth_max >= 1, st
        print(
            f"rlc_smoke: {label} forged batch: bisection isolated "
            f"{sorted(culprits)} in {st.bisection_ct} rechecks "
            f"(depth {st.bisection_depth_max})"
        )

    # -- BLS12-381 inherits via the generic ops seam (tiny: pure-ref math) -
    from handel_tpu.models.bls12_381 import BLS12381Scheme

    bscheme = BLS12381Scheme()
    bkeys = [bscheme.keygen(i) for i in range(4)]
    bops = rlc.host_ops_for(bscheme.constructor)
    bcands = []
    for j, msg in enumerate((b"bls-a", b"bls-b")):
        sk, pk = bkeys[j]
        bcands.append((msg, pk.point, sk.sign(msg).point))
    bst = rlc.RlcStats()
    assert rlc.host_rlc_check(bops, bcands, stats=bst)
    assert bst.miller_lanes == 3 and bst.final_exp_lanes == 1
    bad = [bcands[0], (b"bls-b", bkeys[1][1].point, bkeys[1][0].sign(b"x").point)]
    assert not rlc.host_rlc_check(bops, bad)
    print("rlc_smoke: bls12-381 host ops seam: valid accepted, forged rejected")

    # -- device dispatch seam: both packing classes route into rlc ---------
    import numpy as np  # noqa: F401

    from handel_tpu import native as nat
    from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops import bn254_ref as bn

    n_dev = 130  # > MISS_CAP so the dense class is reachable
    sks = [rng.randrange(1, 1 << 20) for _ in range(n_dev)]
    dpks = [
        BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * n_dev, sks)
    ]
    device = BN254Device(dpks, batch_size=4, batch_check="rlc")
    range_bs = BitSet(n_dev)
    for i in range(8):
        range_bs.set(i)
    dense_bs = BitSet(n_dev)
    dense_bs.set(0)
    dense_bs.set(n_dev - 1)  # full hull, > 64 holes -> dense class
    for i in rng.sample(range(n_dev), 40):
        dense_bs.set(i)
    for bs, kind in ((range_bs, "range"), (dense_bs, "dense")):
        plan = device._pack_requests([(bs, BN254Signature(bn.G1_GEN))])
        assert plan.kind == kind, (kind, plan.kind)
        handle = device.dispatch(b"m", [(bs, BN254Signature(bn.G1_GEN))])
        assert handle[0] == "rlc", handle[0]
    print("rlc_smoke: rlc-mode device routes range + dense packing classes")

    if os.environ.get("HANDEL_TPU_RLC_SMOKE_DEVICE") == "1":
        _device_msm_phase(device, dpks, rng)

    # -- bench: rlc_verify_p50_ms / rlc_speedup_x at batch 64 --------------
    from bench import rlc_bench

    trials = int(os.environ.get("HANDEL_TPU_RLC_SMOKE_TRIALS", "3"))
    m = rlc_bench(batch=C, messages=M, trials=trials)
    assert m["rlc_speedup_x"] >= 3.0, (
        f"rlc speedup {m['rlc_speedup_x']}x below the 3x acceptance at "
        f"batch {C}"
    )
    print(
        f"rlc_smoke: batch-{C} host bench: rlc {m['rlc_verify_p50_ms']} ms "
        f"vs per-candidate {m['rlc_per_candidate_p50_ms']} ms "
        f"({m['rlc_speedup_x']}x)"
    )

    # -- bench_check --dry-run over a fresh artifact with both rows --------
    fresh = {
        "metric": "rlc_smoke",
        "backend": "cpu",
        "records": [
            {
                "metric": "rlc_verify_p50_ms",
                "value": m["rlc_verify_p50_ms"],
                "unit": "ms",
                "backend": "cpu",
                "fp_backend": fp,
                **{k: v for k, v in m.items() if k != "rlc_verify_p50_ms"},
            }
            for fp in ("cios", "rns")
        ],
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(fresh, f)
        path = f.name
    try:
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--dry-run",
                "--fresh",
                path,
            ],
            capture_output=True,
            text=True,
        )
        sys.stdout.write(r.stdout)
        sys.stderr.write(r.stderr)
        assert r.returncode == 0, "bench_check --dry-run failed"
        assert "rlc_verify_p50_ms" in r.stdout, (
            "bench_check did not consider rlc_verify_p50_ms"
        )
        assert "rlc_speedup_x" in r.stdout, (
            "bench_check did not consider rlc_speedup_x"
        )
    finally:
        os.unlink(path)
    print(
        f"rlc_smoke: bench_check --dry-run gated both rlc metrics "
        f"(total {time.perf_counter() - t0:.1f}s)"
    )
    return 0


def _device_msm_phase(device, dpks, rng):
    """Optional (HANDEL_TPU_RLC_SMOKE_DEVICE=1): compile the tiny-shape
    device MSM stage for the range class and check S / X against the host
    scalar oracle. Minutes of XLA on one CPU core."""
    import numpy as np

    from handel_tpu import native as nat
    from handel_tpu.models.bn254 import BN254Signature
    from handel_tpu.ops import bn254_ref as bn

    items = []
    for j in range(device.batch_size):
        bs = BitSet(len(dpks))
        lo = rng.randrange(0, 8)
        for i in range(lo, lo + 4):
            bs.set(i)
        items.append((f"dev-{j % 2}".encode(), bs,
                      BN254Signature(bn.g1_mul(bn.G1_GEN, j + 2))))
    handle = device._dispatch_rlc(items)
    verdicts = device._fetch_rlc(handle)
    # forged inputs (generator-multiple sigs): every candidate must fail,
    # via a combined check that *ran on device* and bisected to the oracle
    assert verdicts == [False] * len(items), verdicts
    assert device.rlc_stats.rlc_launches >= 1
    print("rlc_smoke: device MSM + pairing tail compiled and bisected")


if __name__ == "__main__":
    sys.exit(main())

"""Launch-path micro-smoke: 8 packed launches + batched combine, CPU tier.

The fast-tier guard for the zero-copy dispatch path (models/bn254_jax.py):
runs 8 packed launches through pack → rotated-staging handoff → on-device
registry aggregation (prefix gather + hole patch), checks every aggregate
key against the host oracle, runs the batched `combine_batch` entry against
host pairing-library folds, then produces a fresh bench artifact carrying
the `host_pack_ms`/`host_dispatch_ms` split (bench.py host_pipeline_bench,
small shape) and self-tests `scripts/bench_check.py --dry-run` against it —
so the perf gate covers the dispatch split from day one.

Scope note: on one CPU core the pairing-tail kernels take minutes of XLA
each, so this smoke drives the AGGREGATION stage of the verify path — the
stage that consumes the registry/prefix residents and the staged launch
inputs; the identical staged arrays feed the pairing tail, which the slow
tier compiles and checks end to end (tests/test_bn254_device.py). Expected
wall: ~2 min of XLA compile on a cold cache, then milliseconds per launch.
"""

import json
import os
import random
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# 8 virtual host devices — must land before jax initializes, so the
# devices-in-{1,8} parametrization below runs on a real multi-device
# topology (the same one conftest/multichip_smoke force)
from handel_tpu.utils.jaxenv import apply_platform_env  # noqa: E402

os.environ.setdefault("HANDEL_TPU_PLATFORM", "cpu")
apply_platform_env(force_host_device_count=8)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from handel_tpu import native as nat  # noqa: E402
from handel_tpu.core.bitset import BitSet  # noqa: E402
from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature  # noqa: E402
from handel_tpu.models.bn254_jax import BN254Device  # noqa: E402
from handel_tpu.ops import bn254_ref as bn  # noqa: E402

N, C, LAUNCHES = 12, 4, 8
# plane sizes the fleet phase covers; override for a quick local run with
# HANDEL_TPU_SMOKE_DEVICES=1 (each pinned engine pays one XLA compile —
# persistent-cache-warm in CI after the first push)
DEVICE_COUNTS = tuple(
    int(x)
    for x in os.environ.get("HANDEL_TPU_SMOKE_DEVICES", "1,8").split(",")
)


def host_agg(pks, bs):
    acc = None
    for i in bs.indices():
        acc = pks[i].point if acc is None else bn.g2_add(acc, pks[i].point)
    return acc


def main() -> int:
    # share the persistent compile cache CI restores across runs (same dir
    # as bench.py / the slow tier): warm pushes skip the XLA compiles
    jax.config.update(
        "jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache"
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    rng = random.Random(99)
    sks = [rng.randrange(1, 1 << 20) for _ in range(N)]
    pks = [BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * N, sks)]
    device = BN254Device(pks, batch_size=C)
    sig = BN254Signature(bn.G1_GEN)

    # warm the miss_k=8 aggregation class once so the 8 timed launches
    # measure steady state, not the cold XLA compile
    warm_bs = BitSet(N)
    for i in range(4):
        warm_bs.set(i, True)
    plan = device._pack_requests([(warm_bs, sig)])
    jax.block_until_ready(
        device._range_agg_kernel(plan.miss_k)(*device._stage_plan(plan)[:4])
    )
    device.reset_host_counters()

    # -- 8 packed launches through the staged aggregation path -------------
    t0 = time.perf_counter()
    checked = 0
    for launch in range(LAUNCHES):
        reqs = []
        for _ in range(C):
            size = rng.randrange(2, N)
            lo = rng.randrange(0, N - size + 1)
            holes = set(
                rng.sample(range(lo + 1, lo + size - 1), min(2, size - 2))
            )
            bs = BitSet(N)
            for i in range(lo, lo + size):
                if i not in holes:
                    bs.set(i, True)
            reqs.append((bs, sig))
        tp = time.perf_counter()
        plan = device._pack_requests(reqs)
        td = time.perf_counter()
        device.host_pack_ms += (td - tp) * 1000.0
        device.host_pack_launches += 1
        args = device._stage_plan(plan)
        agg = device._range_agg_kernel(plan.miss_k)(*args[:4])
        device.host_dispatch_ms += (time.perf_counter() - td) * 1000.0
        device.host_dispatch_launches += 1
        x, y, inf = device.curves.g2.to_affine(agg)
        xs = device.curves.T.f2_unpack(x)
        ys = device.curves.T.f2_unpack(y)
        infs = np.asarray(inf)
        for j, (bs, _) in enumerate(reqs):
            want = host_agg(pks, bs)
            got = None if infs[j] else (xs[j], ys[j])
            assert got == want, f"launch {launch} lane {j}: aggregate mismatch"
            checked += 1
    assert device.host_pack_launches == LAUNCHES
    assert device.host_dispatch_ms > 0.0
    print(
        f"launch_smoke: {LAUNCHES} launches, {checked} aggregates verified "
        f"against the host oracle in {time.perf_counter() - t0:.1f}s "
        f"(pack {device.host_pack_ms / LAUNCHES:.3f} ms/launch, dispatch "
        f"{device.host_dispatch_ms / LAUNCHES:.3f} ms/launch)"
    )

    # -- fleet parametrization: the same staged aggregation on a plane of
    # k pinned engines, one launch per device, every aggregate against the
    # host oracle (devices in {1, 8}; 1 is the measured loop above) -------
    from handel_tpu.parallel.plane import bn254_plane

    for k in DEVICE_COUNTS:
        if k <= 1:
            continue  # the single-device loop above IS the k=1 phase
        plane = bn254_plane(pks, k, batch_size=C, curves=device.curves)
        t1 = time.perf_counter()
        fleet_checked = 0
        for lane in plane.lanes:
            eng = lane.engine
            reqs = []
            for _ in range(C):
                size = rng.randrange(2, N)
                lo = rng.randrange(0, N - size + 1)
                bs = BitSet(N)
                for i in range(lo, lo + size):
                    bs.set(i, True)
                reqs.append((bs, sig))
            plan = eng._pack_requests(reqs)
            agg = eng._range_agg_kernel(plan.miss_k)(
                *eng._stage_plan(plan)[:4]
            )
            placed = {b.device for b in jax.tree_util.tree_leaves(agg)}
            assert placed == {eng.jax_device}, (
                f"lane {lane.index}: launch ran on {placed}, "
                f"pinned to {eng.jax_device}"
            )
            lane.launches += 1
            x, y, inf = eng.curves.g2.to_affine(agg)
            xs = eng.curves.T.f2_unpack(x)
            ys = eng.curves.T.f2_unpack(y)
            infs = np.asarray(inf)
            for j, (bs, _) in enumerate(reqs):
                want = host_agg(pks, bs)
                got = None if infs[j] else (xs[j], ys[j])
                assert got == want, (
                    f"lane {lane.index} candidate {j}: aggregate mismatch"
                )
                fleet_checked += 1
        assert all(lane.launches >= 1 for lane in plane.lanes)
        print(
            f"launch_smoke: {k}-device plane, one pinned launch per "
            f"engine, {fleet_checked} aggregates verified in "
            f"{time.perf_counter() - t1:.1f}s"
        )

    # -- batched combine vs host pairing-library folds ---------------------
    pts = [bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R)) for _ in range(8)]
    groups = [
        [rng.choice(pts) for _ in range(rng.randrange(2, 7))]
        for _ in range(2 * C)
    ]
    got = device.combine_batch(groups)
    for g, out in zip(groups, got):
        acc = g[0]
        for p in g[1:]:
            acc = bn.g1_add(acc, p)
        assert out == acc, "combine_batch mismatch vs host fold"
    print(f"launch_smoke: combine_batch verified on {len(groups)} groups")

    # -- bench_check --dry-run over a fresh artifact with the new split ----
    from bench import host_pipeline_bench

    fresh = {
        "metric": f"{N}sig_launch_smoke_p50_ms",
        "value": round(device.host_pack_ms / LAUNCHES, 3),
        "unit": "ms",
        "backend": jax.default_backend(),
        **host_pipeline_bench(n_registry=64, lanes=8, trials=5),
    }
    assert "host_dispatch_ms" in fresh and fresh["host_dispatch_ms"] >= 0.0
    assert fresh["no_transfer_steady_state"] == 1.0, (
        "steady-state staging performed an implicit host->device transfer"
    )
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(fresh, f)
        path = f.name
    try:
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--dry-run",
                "--fresh",
                path,
            ],
            capture_output=True,
            text=True,
        )
        sys.stdout.write(r.stdout)
        sys.stderr.write(r.stderr)
        assert r.returncode == 0, "bench_check --dry-run failed"
        assert "host_dispatch_ms" in r.stdout, (
            "bench_check did not consider host_dispatch_ms"
        )
    finally:
        os.unlink(path)
    print("launch_smoke: bench_check --dry-run gated the dispatch split")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""One-off: profile where the multichip dryrun's compile time goes.

Runs dryrun_multichip(8) with a scratch compilation cache (so the real
cache stays warm for the driver gate) and jax compile logging, printing
per-program compile durations. Evidence for shrinking the gate's compile
surface (VERDICT r04 next-round item 1).
"""

import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as ge

ge._force_cpu_devices(8)
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/scratch_jax_cache")
jax.config.update("jax_log_compiles", True)
logging.basicConfig(level=logging.DEBUG)
for name in ("jax._src.dispatch", "jax._src.interpreters.pxla", "jax._src.compiler"):
    logging.getLogger(name).setLevel(logging.DEBUG)

ge.dryrun_multichip(8)

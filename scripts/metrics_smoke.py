"""Live-telemetry CI gate: boot a node fleet, scrape /metrics + /readyz.

Two stages, both seconds-fast on any machine (fake crypto, no jax):

1. A localhost-platform run (8 nodes, one real `sim.node` process) with
   `metrics = true`: the smoke scrapes the process's endpoint DURING the
   run, asserts /readyz answers 200, and that /metrics carries >= 20
   distinct metric families (the acceptance bar) across the sigs / net /
   penalty planes.

2. An in-process LocalCluster wired to a stub-device BatchVerifierService:
   the same bar, plus the device_verifier plane that a single fake-scheme
   node process doesn't have — so all four planes (protocol, device
   verifier, network, penalties) are pinned by CI.

A telemetry regression fails this script on its own named CI step
(.github/workflows/ci.yml) before the full tier runs.

Usage: python scripts/metrics_smoke.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.core.metrics import parse_exposition  # noqa: E402
from handel_tpu.core.test_harness import LocalCluster  # noqa: E402
from handel_tpu.parallel.batch_verifier import BatchVerifierService  # noqa: E402
from handel_tpu.sim import watch_cli  # noqa: E402
from handel_tpu.sim.config import RunConfig, SimConfig  # noqa: E402
from handel_tpu.sim.platform import run_simulation  # noqa: E402

MIN_FAMILIES = 20


def _families(text: str) -> set[str]:
    return {n for n in parse_exposition(text) if n.startswith("handel_")}


async def stage_node_process(workdir: str) -> set[str]:
    cfg = SimConfig(
        network="udp",
        scheme="fake",
        metrics=True,
        metrics_linger_s=2.0,
        max_timeout_s=30.0,
        runs=[RunConfig(nodes=8, threshold=8, processes=1)],
    )
    task = asyncio.create_task(run_simulation(cfg, workdir))
    deadline = time.monotonic() + 25
    fams: set[str] = set()
    ready = None
    while time.monotonic() < deadline and not task.done():
        for addr in watch_cli.discover_endpoints(workdir):
            got = await asyncio.to_thread(watch_cli.scrape, addr)
            if got is None:
                continue
            fams = _families(got[1])
            try:
                r = await asyncio.to_thread(
                    urllib.request.urlopen,
                    f"http://{addr}/readyz",
                    None,
                    2.0,
                )
                ready = r.status
            except Exception:
                pass
        if fams and ready == 200:
            break
        await asyncio.sleep(0.2)
    results = await task
    assert results and results[0].ok, "sim run failed"
    assert ready == 200, f"/readyz never answered 200 (last: {ready})"
    assert len(fams) >= MIN_FAMILIES, (
        f"only {len(fams)} families scraped: {sorted(fams)}"
    )
    for plane in ("handel_sigs_", "handel_net_", "handel_penalty_"):
        assert any(n.startswith(plane) for n in fams), f"missing {plane}*"
    return fams


class _StubDevice:
    batch_size = 8

    def dispatch(self, msg, reqs):
        return len(reqs)

    def fetch(self, handle):
        return [True] * handle


async def stage_in_process() -> set[str]:
    svc = BatchVerifierService(_StubDevice(), max_delay_ms=0.1)
    cluster = LocalCluster(8, metrics_port=0, verifier_service=svc)
    addr = cluster.metrics_server.address
    cluster.start()
    finals = await cluster.wait_complete_success(10)
    assert len(finals) == 8
    text = urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=3
    ).read().decode()
    svc.stop()
    cluster.stop()
    fams = _families(text)
    assert len(fams) >= MIN_FAMILIES, sorted(fams)
    for plane in (
        "handel_sigs_",
        "handel_net_",
        "handel_penalty_",
        "handel_device_verifier_",
    ):
        assert any(n.startswith(plane) for n in fams), f"missing {plane}*"
    return fams


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        fams1 = asyncio.run(stage_node_process(d))
    fams2 = asyncio.run(stage_in_process())
    print(
        json.dumps(
            {
                "node_process_families": len(fams1),
                "in_process_families": len(fams2),
                "planes": sorted(
                    {n.split("_")[1] for n in fams1 | fams2}
                ),
            }
        )
    )
    print(f"metrics smoke OK: {len(fams1)}/{len(fams2)} families "
          f"(node-process/in-process), all planes present")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Alerting CI gate: the SLO burn-rate + incident-plane chaos drill
(ISSUE 19).

Runs `sim load`'s open-loop traffic in-process TWICE with the alert plane
on (handel_tpu/obs/):

1. **drill** — a forced mid-run region kill. The region-health detector
   must open EXACTLY ONE incident, its causal attribution must name the
   killed region, detection latency must stay under the bound, and the
   incident must close after recovery (hold_while + min-hold, not
   detector adaptation).
2. **clean control** — the identical load with no kill. ZERO incidents
   may open: `false_positive_rate` must be exactly 0.0.

`detection_latency_ms` and `false_positive_rate` ride the report flat
(bench-record shape), so the final step hands the drill artifact to
scripts/bench_check.py for SIDE_METRICS regression gating against any
committed incident history (results/incident_report*.json — via the
federation report that carries the same keys).

Usage: python scripts/alert_smoke.py [--artifact-dir DIR] [--duration S]
       [--rate SPS] [--latency-bound-ms MS]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.sim.config import (  # noqa: E402
    AlertParams,
    FederationParams,
    LoadParams,
)
from handel_tpu.sim.load import run_load  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep incident_report.json here (CI upload)",
    )
    ap.add_argument(
        "--duration", type=float, default=30.0,
        help="load window per run in seconds (x2 runs: drill + control)",
    )
    ap.add_argument(
        "--rate", type=float, default=5.0,
        help="open-loop arrival rate (sessions/s)",
    )
    ap.add_argument(
        "--latency-bound-ms", type=float, default=3000.0,
        help="max allowed kill -> incident-open latency",
    )
    args = ap.parse_args(argv)

    lo = LoadParams(
        rate_sps=args.rate, duration_s=args.duration, nodes=6, seed=19
    )
    # window_scale compresses the 1m/15m burn windows to drill scale;
    # min_hold/cooldown tightened so the close lands inside the run
    al = AlertParams(window_scale=0.02, min_hold_s=1.0, cooldown_s=3.0,
                     tick_interval_s=0.25)

    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)

        # -- the drill: forced region kill ------------------------------
        fe = FederationParams(kill_region="us-east")
        report = asyncio.run(run_load(lo, fe, d, alert_p=al))
        al_block = report["alerts"]
        assert al_block is not None, "alert plane did not run"
        incidents = al_block["report"]["incidents"]
        kill = report["federation"]["kill"]
        print(
            f"drill: {len(incidents)} incident(s), "
            f"detection {report['detection_latency_ms']:.1f}ms, "
            f"false-positive rate {report['false_positive_rate']}, "
            f"kill at {kill['killed_at_s']}s"
        )
        assert len(incidents) == 1, (
            f"expected exactly one incident, got {len(incidents)}: "
            f"{json.dumps(incidents, indent=1)}"
        )
        inc = incidents[0]
        # correct attribution: the snapshot captured at open time must
        # name the killed region
        attributed = inc["attribution"].get("unhealthy_regions", [])
        assert "us-east" in attributed, (
            f"incident attribution missed the killed region: {attributed}"
        )
        # bounded detection latency
        assert 0.0 < report["detection_latency_ms"] <= args.latency_bound_ms, (
            f"detection latency {report['detection_latency_ms']}ms "
            f"outside (0, {args.latency_bound_ms}]"
        )
        # the drill's open was expected, so nothing counts as a false pos
        assert report["false_positive_rate"] == 0.0
        # closed after recovery, not left dangling
        assert inc["state"] == "closed", (
            f"incident never closed: {json.dumps(inc, indent=1)}"
        )
        artifact = os.path.join(d, "incident_report.json")
        assert os.path.exists(artifact), "incident_report.json not written"

        # -- the clean control: same load, no kill ----------------------
        with tempfile.TemporaryDirectory() as tmp2:
            clean = asyncio.run(
                run_load(lo, FederationParams(), tmp2, alert_p=al)
            )
        opened = clean["alerts"]["report"]["opened"]
        print(
            f"control: {opened} incident(s), "
            f"false-positive rate {clean['false_positive_rate']}"
        )
        assert opened == 0, (
            f"clean control opened {opened} incident(s): "
            f"{json.dumps(clean['alerts']['report']['incidents'], indent=1)}"
        )
        assert clean["false_positive_rate"] == 0.0

        # regression gate: the drill report carries the SIDE_METRICS flat
        # (detection_latency_ms, false_positive_rate) — dry-run keeps the
        # gate self-testing even with no committed history yet
        rc = subprocess.call([
            sys.executable,
            os.path.join(REPO, "scripts", "bench_check.py"),
            "--history",
            os.path.join(REPO, "results", "federation_report*.json"),
            "--fresh", os.path.join(d, "federation_report.json"),
            "--dry-run",
        ])
        assert rc == 0, "bench_check --dry-run failed on the drill report"

    print("alert smoke: exactly-one-incident drill + clean control held")
    return 0


if __name__ == "__main__":
    sys.exit(main())

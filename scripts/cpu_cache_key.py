"""Print a digest of the host CPU feature set, for XLA cache keys in CI.

The XLA persistent compile cache stores *machine-code* executables. XLA
refuses (or worse, SIGILLs on older XLA) when an executable compiled on a
runner with AVX-512 is restored onto a runner without it: GitHub's
`ubuntu-latest` pool mixes CPU generations, and `runner.os` alone keys all
of them to the same cache line. Keying on `platform.machine()` plus a
digest of the CPU flag set partitions the cache per micro-architecture
feature set, so a restore can only hand an executable to a host able to
run it.

Usage (CI): `echo "cpukey=$(python scripts/cpu_cache_key.py)" >> "$GITHUB_OUTPUT"`
Prints a single token like `x86_64-1f2e3d4c` — stable across reboots of
the same machine type, different across feature-set changes.
"""

import hashlib
import platform
import sys


def cpu_flags() -> list[str]:
    """The CPU feature flags, sorted; empty where /proc/cpuinfo has no
    flags line (macOS, exotic kernels) — the digest then keys on the
    machine arch alone, which is strictly no worse than today's key."""
    try:
        with open("/proc/cpuinfo", encoding="ascii", errors="replace") as f:
            for line in f:
                # x86 calls it "flags", arm64 calls it "Features"
                if line.lower().startswith(("flags", "features")):
                    return sorted(set(line.split(":", 1)[1].split()))
    except OSError:
        pass
    return []


def cache_key() -> str:
    digest = hashlib.sha256(
        " ".join(cpu_flags()).encode("ascii", "replace")
    ).hexdigest()[:8]
    return f"{platform.machine()}-{digest}"


if __name__ == "__main__":
    print(cache_key())
    sys.exit(0)

"""Lifecycle CI gate: the production soak drill (ISSUE 12 acceptance).

Runs `sim soak`'s continuously-loaded service in-process — sustained
tiered sessions on a multi-lane host plane with a mid-run epoch registry
rotation and a forced lane-0 breaker loss — then asserts the lifecycle
invariants the report carries:

- zero dropped work: every spawned session reached a terminal verdict and
  none by expiry, across both the swap and the lane loss
- the epoch advanced exactly once (stage -> quiesce -> flip completed)
- the swap hid between launches: neither the gate-closed stall nor the
  launch gap straddling the flip exceeded the steady-state cadence bound
- the autoscaler replaced the broken lane (attach-first, so the plane
  never dipped) and per-tenant p99 stayed inside every SLO tier target

The report is bench-record shaped, so the final step hands it to
scripts/bench_check.py for SIDE_METRICS regression gating against any
soak history the checkout carries (results/soak_report*.json).

Usage: python scripts/soak_smoke.py [--artifact-dir DIR] [--duration S]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.sim.config import SoakParams  # noqa: E402
from handel_tpu.sim.report_checks import SOAK_CHECKS, assert_checks  # noqa: E402
from handel_tpu.sim.soak import run_soak  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep soak_report.json here (CI upload)",
    )
    ap.add_argument(
        "--duration", type=float, default=90.0,
        help="load window in seconds (the ~90 s CI soak)",
    )
    args = ap.parse_args(argv)

    p = SoakParams(duration_s=args.duration)
    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        report = asyncio.run(run_soak(p, d))

        soak = report["soak"]
        print(
            f"soak: {soak['completed']} sessions over {soak['wall_s']}s, "
            f"epoch swap stall {report['epoch_swap_stall_ms']}ms "
            f"(bound {soak['swap_gap_bound_ms']}ms), "
            f"p99 {report['soak_p99_s']}s, shed {report['shed_rate']}"
        )
        for name, ok in report["checks"].items():
            print(f"  check {name}: {'ok' if ok else 'FAILED'}")
        # the SAME predicate specs the report builder stamped `ok` with
        # (sim/report_checks.py): re-evaluated from the report, so the
        # smoke and the artifact can never assert different invariants
        assert_checks(report, SOAK_CHECKS)
        assert report["ok"], f"soak checks failed: {report['checks']}"

        # regression gate: like-for-like SIDE_METRICS comparison against
        # any committed soak history (first runs pass on min-history)
        rc = subprocess.call([
            sys.executable,
            os.path.join(REPO, "scripts", "bench_check.py"),
            "--history", os.path.join(REPO, "results", "soak_report*.json"),
            "--fresh", os.path.join(d, "soak_report.json"),
        ])
        assert rc == 0, "bench_check regression gate failed on the soak report"

    print("soak smoke: all lifecycle invariants held")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Montgomery-mul kernel lab: candidate Pallas/XLA formulations, cross-checked
and raced against the production `Field.mul`.

Motivation (results/fp_microbench.json): the production CIOS kernel measures
~357M 254-bit muls/s MARGINAL on the one available chip (the 15.5M/s figure
once cited here was a tunnel-dispatch artifact — see `Field._throughput_bench`),
and the verify p50 is dominated by the ~66 ms dispatch floor, not field muls.
The lab's goal is therefore chip-side compute for co-located deployments,
where the dispatch floor vanishes and mul throughput is the bound again. The
production kernel body (`Field._mul_cols`) accumulates columns with per-limb
(B,)-shaped 1-D ops; on TPU a 1-D vector occupies one sublane of the (8, 128)
VPU tile, so up to 7/8 of the unit idles. The variants here restructure the
arithmetic into full-width (nlimbs, B) ops:

  * `mul_cios_fullwidth` — same interleaved CIOS algebra, but the schoolbook
    products and the m*p rows accumulate via static slice-adds on (2n+1, B)
    arrays (only the per-i m scalar row stays 1-D).
  * `mul_separated` — separated Montgomery: T = a*b, m = (T mod R)*p' mod R,
    t = (T + m*p)>>256, with the two constant-operand products (p', p)
    unrolled as full-width multiply-accumulates against scalar limb constants
    split 8-bit to keep every column < 2^24 in uint32.

The lab also races the RNS backend (`Field(backend="rns")`, ops/rns.py) —
the MXU-shaped dot_general formulation — as a first-class candidate.

Every candidate is validated against its own Montgomery-constant oracle
(the production path is itself oracle-validated in tests/test_fp_jax.py),
then timed with the SHARED chained-dispatch marginal helper
(`handel_tpu.ops.fp.chained_marginal` — the same methodology behind
`_throughput_bench` and scripts/mxu_limb_lab.py, so every figure in
results/fp_microbench.json is like-for-like). Run on the target backend:

    python scripts/fp_kernel_lab.py [batch] [--variants v1,v2,...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.utils.jaxenv import apply_platform_env

apply_platform_env()  # honor $HANDEL_TPU_PLATFORM (sitecustomize-proof)

import jax
import jax.numpy as jnp
import numpy as np

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import (
    LIMB_BITS,
    LIMB_MASK,
    Field,
    _int_to_limbs,
    chained_marginal,
)

_LANE = 128


def _split8(x: int) -> tuple[int, int]:
    return x & 0xFF, (x >> 8) & 0xFF


def _slice_add(acc, v, i: int, pad: bool):
    """acc[i:i+len(v)] += v with a static offset. `.at[].add` traces to
    scatter-add, which Pallas TPU cannot lower — the pad form traces to
    pad+add, which it can."""
    if not pad:
        return acc.at[i : i + v.shape[0]].add(v)
    return acc + jnp.pad(v, ((i, acc.shape[0] - i - v.shape[0]), (0, 0)))


class LabField:
    """Variant mul formulations sharing the production Field's constants."""

    def __init__(self, F: Field):
        self.F = F
        self.n = F.nlimbs
        self.p = F.p
        self.n0 = F.n0
        # full n-limb Montgomery multiplier p' = -p^{-1} mod R
        R = 1 << (LIMB_BITS * self.n)
        self.pprime = (-pow(F.p, -1, R)) % R
        self.pprime_limbs = [int(v) for v in _int_to_limbs(self.pprime, self.n)]
        self.p_limbs = [int(v) for v in F.p_limbs_np]

    # -- V1: CIOS with full-width column accumulation -----------------------

    def cios_fullwidth_body(self, a, b, pad=False):
        """Interleaved CIOS identical in algebra to Field._mul_cols, but the
        n^2 product terms land via n static slice-adds on a (2n+1, B) array
        (full-width VPU ops) instead of n^2 per-limb 1-D adds."""
        F, n = self.F, self.n
        bsz = a.shape[1]
        cols = jnp.zeros((2 * n + 1, bsz), jnp.uint32)
        for i in range(n):
            prod = a[i][None, :] * b  # (n, B) exact
            lo = prod & LIMB_MASK
            hi = prod >> LIMB_BITS
            cols = _slice_add(cols, lo, i, pad)
            cols = _slice_add(cols, hi, i + 1, pad)
        n0 = jnp.uint32(self.n0)
        # built from python-int scalars: Pallas kernels may not capture
        # device-array constants from the closure
        p_col = jnp.concatenate(
            [jnp.full((1, 1), int(v), jnp.uint32) for v in F.p_limbs_np], axis=0
        )
        carry = jnp.zeros((bsz,), jnp.uint32)
        for i in range(n):
            t0 = cols[i] + carry
            m = (t0 * n0) & LIMB_MASK
            mp = m[None, :] * p_col  # (n, B)
            mlo = mp & LIMB_MASK
            mhi = mp >> LIMB_BITS
            carry = (t0 + mlo[0]) >> LIMB_BITS
            cols = _slice_add(cols, mlo[1:], i + 1, pad)
            cols = _slice_add(cols, mhi, i + 1, pad)
        cols = _slice_add(cols, carry[None, :], n, pad)
        hi = cols[n : 2 * n]
        spill = jnp.pad(hi >> LIMB_BITS, ((1, 0), (0, 0)))[:n]
        rows = [(hi[k] & LIMB_MASK) + spill[k] for k in range(n)]
        carry2 = jnp.zeros_like(rows[0])
        out = []
        for k in range(n):
            t = rows[k] + carry2
            out.append(t & LIMB_MASK)
            carry2 = t >> LIMB_BITS
        return F._cond_sub_p_rows(out)

    # -- V2: separated Montgomery, constant-operand products ----------------

    def _mac_const(self, acc, x, limb_consts, lo_col0: int, keep: int, pad=False):
        """acc[lo_col0+j : ...] += x * limb_consts[j] for each 16-bit constant
        limb, with the constant split 8-bit so products of x < 2^17 stay in
        uint32, truncated to columns < keep. x: (n, B) rows of value < 2^17.
        Full-width ops only."""
        n = x.shape[0]
        for j, c in enumerate(limb_consts):
            base = lo_col0 + j
            if base >= keep:
                break
            w = min(n, keep - base)
            clo, chi = _split8(c)
            if clo:
                v = x[:w] * jnp.uint32(clo)  # < 2^25
                acc = _slice_add(acc, v & LIMB_MASK, base, pad)
                acc = _slice_add(acc, v >> LIMB_BITS, base + 1, pad)
            if chi:
                v = x[:w] * jnp.uint32(chi)  # < 2^25
                # times 2^8 straddles the 16-bit column boundary; mask before
                # shifting so the uint32 lane cannot overflow
                acc = _slice_add(acc, (v & 0xFF) << 8, base, pad)
                acc = _slice_add(acc, v >> 8, base + 1, pad)
        return acc

    def _norm_pass1(self, cols, pad=False):
        """One lazy-carry pass: (k, B) columns < 2^c -> rows < 2^16 + 2^(c-16),
        returning (rows, carry_rows_shifted_in) as a single array."""
        r = cols & LIMB_MASK
        c = cols >> LIMB_BITS
        return _slice_add(r, c[:-1], 1, pad), c[-1]

    def _ks_rows(self, s, nl):
        """0/1 carry closure over nl<=16 limb rows with values < 2^17 via the
        packed-word adder identity (Field._carry_word)."""
        r = s & LIMB_MASK
        g = s >> LIMB_BITS
        pr = (r == LIMB_MASK).astype(jnp.uint32)
        # scalar-unrolled bit packing (no closure-captured arrays: Pallas)
        gb = jnp.zeros_like(r[0])
        pb = jnp.zeros_like(r[0])
        for i in range(nl):
            gb = gb | (g[i] << i)
            pb = pb | (pr[i] << i)
        bor = gb | pb
        cw = (gb + bor) ^ gb ^ bor
        rows = [(r[i] + ((cw >> i) & 1)) & LIMB_MASK for i in range(nl)]
        return jnp.stack(rows), ((cw >> nl) & 1).astype(jnp.uint32)

    def separated_body(self, a, b, pad=False):
        F, n = self.F, self.n
        bsz = a.shape[1]
        # T = a*b in column basis: (2n, B), columns < 2^21
        T = jnp.zeros((2 * n, bsz), jnp.uint32)
        for i in range(n):
            prod = a[i][None, :] * b
            T = _slice_add(T, prod & LIMB_MASK, i, pad)
            T = _slice_add(T, prod >> LIMB_BITS, i + 1, pad)
        # semi-normalize low half for the constant product (values < 2^17)
        tlo, _tlo_carry = self._norm_pass1(T[:n], pad)
        # note: dropping _tlo_carry is sound MOD R (it carries 2^256 weight),
        # and m is only needed mod R
        # m = tlo * p' mod R, columns < 2^25 accumulated 8-bit-split
        m_acc = jnp.zeros((n + 1, bsz), jnp.uint32)
        m_acc = self._mac_const(m_acc, tlo, self.pprime_limbs, 0, n, pad)
        m1, _ = self._norm_pass1(m_acc[:n], pad)
        m, _ = self._ks_rows(m1, n)  # canonical m < R (mod-R truncation sound)
        # Acc = T + m*p exactly (m canonical 16-bit rows < 2^16)
        acc = _slice_add(jnp.zeros((2 * n + 1, bsz), jnp.uint32), T, 0, pad)
        acc = self._mac_const(acc, m, self.p_limbs, 0, 2 * n + 1, pad)
        # low half is ≡ 0 mod R; propagate its real carry into column n
        low1, lowc = self._norm_pass1(acc[:n], pad)
        _, ks_out = self._ks_rows(low1, n)
        hi1, _hic = self._norm_pass1(acc[n : 2 * n], pad)
        hi1 = _slice_add(hi1, (lowc + ks_out)[None, :], 0, pad)
        hi2, _c2 = self._ks_rows(hi1, n)
        # _hic/_c2/acc[2n] are identically 0: every column sum is nonnegative
        # and the result t = (T + m*p)/R < 2p < 2^255, so any weight >= 2^256
        # contribution would contradict T + m*p < p^2 + R*p. validate() checks.
        return F._cond_sub_p_rows([hi2[k] for k in range(n)])

    # -- wrappers -----------------------------------------------------------

    def jit_xla(self, body):
        return jax.jit(body)

    def jit_pallas(self, body, bsz: int, tile: int = 512):
        import functools

        body = functools.partial(body, pad=True)
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        n = self.n
        while bsz % tile != 0:
            tile //= 2

        def kernel(a_ref, b_ref, o_ref):
            o_ref[:] = body(a_ref[:], b_ref[:])

        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, bsz), jnp.uint32),
            grid=(bsz // tile,),
            in_specs=[
                pl.BlockSpec((n, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((n, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(
                (n, tile), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        )


def validate(F: Field, fn, bsz: int = 256, seed: int = 7) -> None:
    """Exactness vs the bigint oracle, under the candidate field's OWN
    Montgomery constant (mont_r is R mod p for CIOS-family candidates, the
    base-A product M mod p for the RNS backend — pow(mont_r, -1, p) is the
    right quotient either way)."""
    rng = np.random.default_rng(seed)
    xs = [int(rng.integers(0, 1 << 62)) * int(rng.integers(0, 1 << 62)) % F.p
          for _ in range(bsz)]
    ys = [int(rng.integers(0, 1 << 62)) * int(rng.integers(0, 1 << 62)) % F.p
          for _ in range(bsz)]
    a = F.pack(xs, mont=False)
    b = F.pack(ys, mont=False)
    got = F.unpack(np.asarray(jax.device_get(fn(a, b))), mont=False)
    m_inv = pow(F.mont_r, -1, F.p)
    want = [x * y * m_inv % F.p for x, y in zip(xs, ys)]
    bad = [k for k in range(bsz) if got[k] != want[k]]
    assert not bad, f"mismatch at lanes {bad[:5]} (of {len(bad)})"


def bench(name: str, fn, a, b, trials: int = 5) -> float:
    """Chained-dispatch marginal rate (shared methodology — see
    chained_marginal): a naive time-one-call loop here once measured the
    ~60 ms tunnel instead of the kernel."""
    rate, _floor = chained_marginal(fn, a, b, k1=4, k2=20, trials=trials)
    if rate is None:
        print(f"  {name:28s} marginal slope unmeasurable (timing noise)")
        return 0.0
    print(f"  {name:28s} {rate/1e6:8.2f}M muls/s marginal")
    return rate


def main() -> int:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 1 << 18
    F = Field(bn.P)
    lab = LabField(F)
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.integers(0, 1 << LIMB_BITS, (F.nlimbs, batch), np.uint32))
    b = jnp.asarray(rng.integers(0, 1 << LIMB_BITS, (F.nlimbs, batch), np.uint32))
    on_tpu = jax.default_backend() != "cpu"
    print(f"backend={jax.default_backend()} batch={batch}")

    # (name, bench_fn, validate_fn, field): pallas builds are
    # shape-specialized to the bench batch with a fixed grid, so they are
    # validated through a SEPARATE small-batch build of the same body —
    # validating the bench build with 256-wide inputs would shape-mismatch
    # every pallas variant out of the race (advisor finding, r04). One
    # shared small-batch build per body: the tile variants share algebra,
    # so revalidating per tile would only re-pay compiles. Non-pallas
    # entries validate the bench fn itself (shape-polymorphic). `field`
    # carries each candidate's Montgomery constant into validate().
    prod = jax.jit(F.mul)
    F_rns = Field(bn.P, backend="rns")
    rns = jax.jit(F_rns.mul)
    candidates: list[tuple[str, object, object, Field]] = [
        ("prod(Field.mul)", prod, prod, F),
        ("rns(Field backend)", rns, rns, F_rns),
    ]
    for nm, body in (
        ("cios_fullwidth", lab.cios_fullwidth_body),
        ("separated", lab.separated_body),
    ):
        xla_fn = lab.jit_xla(body)
        candidates.append((f"xla:{nm}", xla_fn, xla_fn, F))
        if on_tpu:
            vfn = lab.jit_pallas(body, 256, 256)
            for tile in (256, 512, 1024, 2048):
                candidates.append(
                    (f"pallas:{nm}:t{tile}", lab.jit_pallas(body, batch, tile),
                     vfn, F)
                )

    for nm, _fn, vfn, cf in candidates:
        try:
            validate(cf, vfn)
            print(f"  {nm:28s} validate: OK")
        except Exception as e:  # noqa: BLE001
            print(f"  {nm:28s} validate: FAIL ({type(e).__name__}: {e})")
            candidates = [c for c in candidates if c[0] != nm]
    print("-- timing --")
    for nm, fn, _vfn, _cf in candidates:
        try:
            bench(nm, fn, a, b)
        except Exception as e:  # noqa: BLE001
            print(f"  {nm:28s} bench FAIL ({type(e).__name__}: {e})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

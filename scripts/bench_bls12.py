"""BLS12-381 device-verify benchmark — the second curve family on chip.

Mirror of bench.py's headline measurement for the `bls12-381-jax` scheme
(same launch engine, 381-bit field / M-type twist / |z|-bit Miller loop):
the SAME `bench.build_problem` candidate generator, parameterized with the
BLS12-381 oracle and pure-Python host keygen (the native C++ path is
BN254-only), a device-resident registry, one fused multi-pairing launch,
p50 over trials. Persists results/bench_bls12.json. Registry is smaller
than the BN254 headline's (pure-Python keygen cost; launch cost is
registry-size independent on the range path).

    python scripts/bench_bls12.py [trials]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.utils.jaxenv import apply_platform_env

apply_platform_env()

import jax
import numpy as np


def main() -> int:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 9
    from bench import build_problem
    from handel_tpu.models.bls12_381 import BLS12381PublicKey
    from handel_tpu.models.bls12_381_jax import BLS12381Device
    from handel_tpu.ops import bls12_381_ref as bls
    from handel_tpu.ops.curve import BLS12Curves

    n_registry, lanes, n_cands = 1024, 64, 32
    curves = BLS12Curves()
    pks, miss_k, args = build_problem(
        curves,
        n_registry,
        lanes,
        n_cands,
        ref=bls,
        g1_mul_batch=lambda pts, ks: [
            bls.g1_mul(p, k) for p, k in zip(pts, ks)
        ],
        g2_mul_batch=lambda pts, ks: [
            bls.g2_mul(p, k) for p, k in zip(pts, ks)
        ],
        miss_k=4,
        seed=7,
    )
    dev = BLS12381Device(
        [BLS12381PublicKey(p) for p in pks], batch_size=lanes, curves=curves
    )
    kern = dev._range_kernel(miss_k)
    verdicts = np.asarray(jax.device_get(kern(*args)))
    assert verdicts[:n_cands].all(), f"verification failed: {verdicts[:n_cands]}"
    assert not verdicts[n_cands:].any(), "padding lanes must not verify"

    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.device_get(kern(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.median(times))
    out = {
        "metric": f"bls12_381_{n_registry}reg_{lanes}lane_verify_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "registry": n_registry,
        "lanes": lanes,
        "candidates": n_cands,
        "trials_ms": [round(t, 3) for t in times],
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(out))
    path = os.path.normpath(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "results",
            "bench_bls12.json",
        )
    )
    if out["backend"] != "cpu":
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

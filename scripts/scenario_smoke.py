"""Scenario-engine CI gate: the composed WAN drill (ISSUE 13 acceptance).

Runs one in-process `sim scenario` round with every axis active at once —
a 32-node committee spread over the fast 3-region planet, ~10% of it
departing mid-round on the seeded membership schedule, a join admitted
through the epoch path, and completion gated on pareto-distributed stake
instead of a contribution count — then asserts the invariants the report
carries:

- the weighted threshold was reached (achieved stake >= the stake gate)
- every survivor marked every churner departed (re-leveling happened)
- the join advanced the epoch at least once (stage -> quiesce -> flip)
- the trace's critical path attributes >= 1 WAN hop to a region pair

The report is bench-record shaped (`geo_weighted_ttt_s` headline), so the
final step hands it to scripts/bench_check.py for regression gating
against the committed capture history (results/geo_weighted_report*.json).

Usage: python scripts/scenario_smoke.py [--artifact-dir DIR] [--nodes N]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.scenario import run_scenario  # noqa: E402
from handel_tpu.sim.confgen import scenario_geo_weighted  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep scenario_report.json + trace here (CI upload)",
    )
    ap.add_argument(
        "--nodes", type=int, default=32,
        help="committee size (3-region fast planet, ~10%% churn)",
    )
    args = ap.parse_args(argv)

    cfg = scenario_geo_weighted(args.nodes)
    # CI shape: the fast planet keeps WAN delays ~ms so the drill is quick
    cfg.scenario.planet = "planet-3region-fast"
    cfg.scenario.jitter_ms = 1.0
    cfg.scenario.joins = 1

    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        report = asyncio.run(run_scenario(cfg, d))

        s = report["scenario"]
        print(
            f"scenario: {s['nodes']} nodes / {len(s['regions'])} regions, "
            f"{s['churners']} departed, {s['joins']} joined "
            f"({s['epochs_advanced']} epoch advance), stake "
            f"{s['achieved_weight']:.2f}/{s['weight_threshold']:.2f}, "
            f"ttt {report['geo_weighted_ttt_s']}s"
        )
        for name, ok in report["checks"].items():
            print(f"  check {name}: {'ok' if ok else 'FAILED'}")
        assert report["checks"]["threshold_reached"], (
            f"weighted threshold missed: {s['achieved_weight']} < "
            f"{s['weight_threshold']}"
        )
        assert report["checks"]["departures_marked"], (
            f"churners {s['departed_ids']} not marked departed everywhere"
        )
        assert report["checks"]["epoch_advanced"], (
            "join did not advance the epoch"
        )
        assert report["checks"]["region_attributed"], (
            "critical path attributed no WAN hop to a region pair"
        )
        assert s["region_hops"], "trace carried no region-tagged hops"
        assert report["ok"], f"scenario checks failed: {report['checks']}"

        # regression gate: like-for-like SIDE_METRICS comparison against
        # the committed capture history (first runs pass on min-history)
        rc = subprocess.call([
            sys.executable,
            os.path.join(REPO, "scripts", "bench_check.py"),
            "--history",
            os.path.join(REPO, "results", "geo_weighted_report*.json"),
            "--fresh", os.path.join(d, "scenario_report.json"),
        ])
        assert rc == 0, (
            "bench_check regression gate failed on the scenario report"
        )

    print("scenario smoke: all WAN scenario invariants held")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Robustness CI gate: the geo-federation region-kill drill (ISSUE 18).

Runs `sim load`'s open-loop traffic in-process — a seeded Poisson arrival
clock against a 3-region federation (service/federation.py) with a forced
mid-run region kill and epoch-path recovery — then asserts the federation
invariants the report carries:

- zero dropped work: every arrival reached an attributed outcome
  (completed / shed / failed / expired) across the kill, the spillover
  storm and the recovery — nothing vanished silently
- the gold tier's open-loop arrival->verdict p99 stayed inside its SLO
  target with a whole region gone for a third of the run
- shed stayed bounded under the configured ceiling (spill-over and
  retry absorbed the lost capacity; the front door did not give up)
- the kill drill ran end to end: the front door detected the death,
  arrivals spilled to surviving regions, and the revived region rejoined
  via a federation-wide epoch rotation and COMPLETED work again

The report is bench-record shaped, so the final step hands it to
scripts/bench_check.py for SIDE_METRICS regression gating against any
federation history the checkout carries (results/federation_report*.json).

Usage: python scripts/load_smoke.py [--artifact-dir DIR] [--duration S]
       [--rate SPS]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.sim.config import FederationParams, LoadParams  # noqa: E402
from handel_tpu.sim.load import run_load  # noqa: E402
from handel_tpu.sim.report_checks import (  # noqa: E402
    FEDERATION_CHECKS,
    assert_checks,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep federation_report.json here (CI upload)",
    )
    ap.add_argument(
        "--duration", type=float, default=45.0,
        help="load window in seconds (the ~45 s CI drill)",
    )
    ap.add_argument(
        "--rate", type=float, default=5.0,
        help="open-loop arrival rate (sessions/s)",
    )
    args = ap.parse_args(argv)

    lo = LoadParams(
        rate_sps=args.rate, duration_s=args.duration, nodes=6, seed=18
    )
    fe = FederationParams(kill_region="us-east")
    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        report = asyncio.run(run_load(lo, fe, d))

        fed = report["federation"]
        kill = fed["kill"]
        print(
            f"load: {fed['completed']}/{fed['arrivals']} arrivals over "
            f"{fed['wall_s']}s, p99 {report['open_loop_p99_s']:.3f}s, "
            f"spillovers {fed['spillovers']}, "
            f"shed {report['shed_rate']}, "
            f"kill->detect "
            f"{kill['unhealthy_detected_s'] - kill['killed_at_s']:.2f}s, "
            f"recovery {report['region_recovery_s']}s "
            f"({kill['post_recovery_completed']} post-recovery completions)"
        )
        for name, ok in report["checks"].items():
            print(f"  check {name}: {'ok' if ok else 'FAILED'}")
        # the SAME predicate specs the report builder stamped `ok` with
        # (sim/report_checks.py): re-evaluated from the report, so the
        # smoke and the artifact can never assert different invariants
        assert_checks(report, FEDERATION_CHECKS)
        assert report["ok"], f"federation checks failed: {report['checks']}"
        # the kill drill must have actually interrupted a live plane,
        # not killed an idle region between arrivals
        assert kill is not None and kill["killed_at_s"] is not None

        # regression gate: like-for-like SIDE_METRICS comparison against
        # any committed federation history (first runs pass on min-history)
        rc = subprocess.call([
            sys.executable,
            os.path.join(REPO, "scripts", "bench_check.py"),
            "--history",
            os.path.join(REPO, "results", "federation_report*.json"),
            "--fresh", os.path.join(d, "federation_report.json"),
        ])
        assert rc == 0, (
            "bench_check regression gate failed on the federation report"
        )

    print("load smoke: all federation invariants held")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Ed25519 vs BN254-BLS comparison at one committee size.

The scenario engine's weighted/geo runs are scheme-agnostic, which begs
the question the results/README.md row answers: what does the aggregating
curve actually buy? This script times the full signer-side + verifier-side
pipeline for both host backends at the same committee size (default 64,
Ed25519's MAX_SIGNERS envelope):

  keygen     n deterministic keypairs
  sign       n individual signatures over one message
  aggregate  fold of Signature.combine (BLS: point adds; Ed25519: set union)
  verify     aggregate-public-key verify of the combined signature
  wire       marshal size of the combined signature

Persists results/eddsa_compare.json (always — both backends are
deterministic host code, no device provenance caveat applies).

    python scripts/eddsa_compare.py [nodes]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.models.registry import new_scheme

MSG = b"eddsa-compare:handel scenario message"


def _bench_scheme(name: str, n: int) -> dict:
    scheme = new_scheme(name)
    t0 = time.perf_counter()
    pairs = [scheme.keygen(i) for i in range(n)]
    t_keygen = time.perf_counter() - t0

    t0 = time.perf_counter()
    sigs = [sk.sign(MSG) for sk, _ in pairs]
    t_sign = time.perf_counter() - t0

    t0 = time.perf_counter()
    agg_sig = sigs[0]
    for s in sigs[1:]:
        agg_sig = agg_sig.combine(s)
    t_aggregate = time.perf_counter() - t0

    t0 = time.perf_counter()
    agg_pub = pairs[0][1]
    for _, pk in pairs[1:]:
        agg_pub = agg_pub.combine(pk)
    ok = agg_pub.verify(MSG, agg_sig)
    t_verify = time.perf_counter() - t0
    assert ok, f"{name}: aggregate verify failed"
    assert not agg_pub.verify(b"tampered", agg_sig), f"{name}: forgery accepted"

    wire = agg_sig.marshal()
    assert len(wire) == scheme.constructor.signature_size()
    return {
        "keygen_ms": round(t_keygen * 1e3, 2),
        "sign_ms": round(t_sign * 1e3, 2),
        "aggregate_ms": round(t_aggregate * 1e3, 2),
        "verify_ms": round(t_verify * 1e3, 2),
        "agg_sig_bytes": len(wire),
    }


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    out = {
        "metric": f"eddsa_vs_bn254_{n}n",
        "nodes": n,
        "message_bytes": len(MSG),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "schemes": {
            "eddsa": _bench_scheme("eddsa", n),
            "bn254": _bench_scheme("bn254", n),
        },
    }
    print(json.dumps(out, indent=1))
    path = os.path.normpath(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "results",
            "eddsa_compare.json",
        )
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

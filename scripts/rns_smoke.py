"""RNS-backend CI gate (fast tier, CPU XLA path — ISSUE 14 + 16 acceptance).

Seven checks, each a hard exit-nonzero failure:

1. Bit-exactness: a seeded batch of products (random + edge operands,
   including both operands at p-1) through `Field(backend="rns")` must
   match the CIOS kernel BIT-FOR-BIT at the canonical boundary — the
   representation the two backends contract to agree on (their Montgomery
   constants differ: R = 2^16n vs the base-A product M).
2. CRT round-trip: to_rns -> from_rns_base_b is exact over the full
   16n-bit positional range (top value 2^256-1 exercises the Shenoy
   alpha-recovery channel at its limit).
3. Backend plumbing: fp_backend survives TOML load/dump round-trip,
   rejects junk values, and reaches the constructed Field through
   new_scheme (TOML -> SimConfig -> scheme kwargs -> Curves -> Field).
4. bench_check dry-run: constructed per-fp-backend `mont_muls_per_s`
   records flow through scripts/bench_check.py keyed as
   "<backend>/<fp_backend>" — an RNS row gates only against RNS history,
   and a CIOS-only history yields a cross-backend refusal, never a
   judgment.
5. Residue-resident conversion count (ISSUE 16): tracing the resident
   pairing crosses the CRT boundary O(line boundaries) times (points in,
   f12 out — <= 8), while the legacy form round-trips once per tower mul
   (thousands). Counted at trace time via `jax.eval_shape`, no compile.
6. Resident tower bit-exactness (compile-cheap): a seeded batch through
   the RESIDENT `f12_mul` — residue planes in, lazy CRT reconstruction
   out — matches the scalar oracle and the CIOS tower bit-for-bit at the
   canonical boundary.
7. bench_check dry-run over `pairing_p50_ms` / `rns_conversions_per_
   pairing` (bench.py _pairing_bench): per-fp keying and the
   cross-backend-judgment-refused rule, same contract as check 4.

`--full` additionally runs the full resident BN254 pairing NUMERICALLY
against the CIOS oracle — valid + forged candidates through both launch
classes (`pairing` and the batched `pairing_check` product) — minutes of
XLA compile on CPU, so it is opt-in (nightly), not every-push.

On real hardware the MXU lab (scripts/mxu_limb_lab.py --persist) captures
the actual marginal figures; this gate is the CPU-only stand-in that keeps
the kernel and the gating plumbing honest on every commit.

Usage: python scripts/rns_smoke.py [--full]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check_bit_exact() -> None:
    import numpy as np

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.fp import Field

    Fr = Field(bn.P, backend="rns")
    Fc = Field(bn.P, use_pallas=False)
    rng = np.random.default_rng(2024)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(12)]
    xs += [0, 1, bn.P - 1, bn.P - 1]
    ys = list(reversed(xs))

    # correctness vs the bigint oracle
    got = Fr.unpack(Fr.mul(Fr.pack(xs), Fr.pack(ys)))
    want = [x * y % bn.P for x, y in zip(xs, ys)]
    assert got == want, "rns mul disagrees with the bigint oracle"

    # canonical-boundary limbs bitwise equal to CIOS
    plain = Fr.pack(xs, mont=False)
    assert np.array_equal(
        np.asarray(plain), np.asarray(Fc.pack(xs, mont=False))
    ), "canonical pack differs between backends"
    out_r = Fr.from_mont(Fr.mul(Fr.to_mont(plain), Fr.to_mont(plain)))
    out_c = Fc.from_mont(Fc.mul(Fc.to_mont(plain), Fc.to_mont(plain)))
    assert np.array_equal(np.asarray(out_r), np.asarray(out_c)), (
        "boundary limbs not bit-identical between rns and cios"
    )
    print(f"rns_smoke: bit-exact vs cios over {len(xs)} seeded products")


def check_crt_roundtrip() -> None:
    import jax.numpy as jnp
    import numpy as np

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.fp import Field

    F = Field(bn.P, backend="rns")
    n = F.nlimbs
    tops = [(1 << (16 * n)) - 1, bn.P, bn.P + 1, 12345, 0]
    arr = np.zeros((n, len(tops)), np.uint32)
    for j, v in enumerate(tops):
        for i in range(n):
            arr[i, j] = (v >> (16 * i)) & 0xFFFF
    r = F.to_rns(jnp.asarray(arr))
    v16 = np.asarray(
        F.from_rns_base_b(r[F.kA : F.kA + F.kB], r[F.kA + F.kB])
    )
    for j, v in enumerate(tops):
        rec = sum(int(v16[i, j]) << (16 * i) for i in range(F.n16out))
        assert rec == v, f"CRT round-trip broke at {v:#x}"
    print(f"rns_smoke: CRT round-trip exact over {len(tops)} values "
          f"(top {tops[0].bit_length()} bits)")


def check_toml_plumbing() -> None:
    from handel_tpu.models.registry import new_scheme
    from handel_tpu.ops.rns import RnsField
    from handel_tpu.sim.config import dump_config, load_config

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "cfg.toml")
        with open(path, "w") as f:
            f.write('scheme = "bn254-jax"\nfp_backend = "rns"\n'
                    '[service]\nfp_backend = "cios"\n')
        cfg = load_config(path)
        assert cfg.fp_backend == "rns"
        assert cfg.service.fp_backend == "cios"
        dumped = dump_config(cfg)
        assert 'fp_backend = "rns"' in dumped
        bad = os.path.join(d, "bad.toml")
        with open(bad, "w") as f:
            f.write('fp_backend = "vpu"\n')
        try:
            load_config(bad)
        except ValueError:
            pass
        else:
            raise AssertionError("junk fp_backend accepted")
    sch = new_scheme(
        "bn254-jax", batch_size=4, mesh_devices=1, fp_backend="rns",
        warmup=False,
    )
    F = sch.constructor.curves.F
    assert type(F) is RnsField and F.backend == "rns"
    print("rns_smoke: fp_backend plumbed TOML -> SimConfig -> Field")


def check_bench_check_dry_run() -> None:
    def rec(fp_backend: str, value: float) -> dict:
        return {
            "metric": "mont_muls_per_s",
            "value": value,
            "unit": "M muls/s",
            "backend": "cpu",
            "fp_backend": fp_backend,
            "batch": 1024,
            "captured_at": f"2026-01-01T00:00:0{int(value) % 10}Z",
        }

    with tempfile.TemporaryDirectory() as d:
        for i, (cios, rns) in enumerate([(350.0, 420.0), (360.0, 410.0)]):
            with open(os.path.join(d, f"BENCH_h{i}.json"), "w") as f:
                json.dump({"records": [rec("cios", cios), rec("rns", rns)]},
                          f)
        fresh = os.path.join(d, "fresh.json")
        with open(fresh, "w") as f:
            # rns holds steady; cios "regresses" — dry-run must key them
            # separately and never let the cios row judge the rns row
            json.dump({"records": [rec("cios", 100.0), rec("rns", 415.0)]},
                      f)
        report_path = os.path.join(d, "report.json")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "BENCH_*.json"),
                "--fresh", fresh,
                "--dry-run", "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        report = json.load(open(report_path))
        keys = {
            (e["metric"], e["backend"])
            for sec in ("regressions", "improved", "ok")
            for e in report[sec]
        }
        assert ("mont_muls_per_s", "cpu/cios") in keys, report
        assert ("mont_muls_per_s", "cpu/rns") in keys, report
        regressed = {e["backend"] for e in report["regressions"]}
        assert regressed == {"cpu/cios"}, (
            f"per-fp-backend keying broken: {report}"
        )

        # cios-only history must REFUSE to judge an rns row
        fresh2 = os.path.join(d, "fresh2.json")
        with open(fresh2, "w") as f:
            json.dump(rec("rns", 1.0), f)
        for i in range(2):
            with open(os.path.join(d, f"CONLY_h{i}.json"), "w") as f:
                json.dump(rec("cios", 350.0 + i), f)
        r2 = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "CONLY_*.json"),
                "--fresh", fresh2, "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r2.returncode == 0, (r2.stdout, r2.stderr[-2000:])
        report2 = json.load(open(report_path))
        assert report2["skipped"] and "cross-backend" in (
            report2["skipped"][0]["reason"]
        ), report2
    print("rns_smoke: bench_check keys mont_muls_per_s per fp_backend "
          "(cross-backend judgment refused)")


def _pairing_stack():
    """One RNS curve/pairing stack shared by the resident checks (the
    Field carries the conversion counters; the gamma re-packs at Tower
    construction must happen before any counter reset)."""
    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.ops.pairing import BN254Pairing

    curves = BN254Curves(backend="rns")
    return curves, BN254Pairing(curves), BN254Pairing(curves, resident=False)


def check_resident_conversions(stack) -> None:
    import jax

    from handel_tpu.ops import bn254_ref as bn

    curves, pr, legacy = stack
    F = curves.F
    B = 4
    xp = F.pack([bn.G1_GEN[0]] * B)
    yp = F.pack([bn.G1_GEN[1]] * B)
    xq = curves.T.f2_pack([bn.G2_GEN[0]] * B)
    yq = curves.T.f2_pack([bn.G2_GEN[1]] * B)
    p, q = (xp, yp), (xq, yq)

    F.reset_conversion_counts()
    jax.eval_shape(lambda p, q: pr.pairing(p, q), p, q)
    res = F.conversion_counts()["total"]
    F.reset_conversion_counts()
    jax.eval_shape(lambda p, q: legacy.pairing(p, q), p, q)
    leg = F.conversion_counts()["total"]
    F.reset_conversion_counts()

    assert res <= 8, (
        f"resident pairing crossed the CRT boundary {res} times — "
        "expected O(line boundaries) (points in + gamma embeds + f12 out)"
    )
    # the Miller scan body traces ONCE, so the legacy count here is
    # per-TRACED-mul (each executed iteration multiplies it again at
    # runtime); an order of magnitude at trace time is already the
    # O(tower muls) -> O(line boundaries) collapse
    assert leg >= 10 * res, (
        f"legacy trace converted only {leg} times vs resident {res} — "
        "the per-mul round trip should dominate by an order of magnitude"
    )
    print(f"rns_smoke: resident pairing converts {res}x per trace "
          f"(legacy per-mul form: {leg}x)")


def check_resident_tower_bit_exact(stack) -> None:
    import random as _random

    import jax

    from handel_tpu.ops import bn254_ref as bn

    curves, _, _ = stack
    rng = _random.Random(1606)

    def rand_f12():
        return tuple(
            tuple(
                (rng.randrange(bn.P), rng.randrange(bn.P)) for _ in range(3)
            )
            for _ in range(2)
        )

    a_vals = [rand_f12() for _ in range(4)]
    b_vals = [rand_f12() for _ in range(4)]
    # near-p operands stress the bound walk right at the modulus
    a_vals[0] = tuple(
        tuple((bn.P - 1, bn.P - 1) for _ in range(3)) for _ in range(2)
    )
    b_vals[0] = a_vals[0]

    Tr = curves.T.as_resident()
    ar, br = Tr.f12_pack(a_vals), Tr.f12_pack(b_vals)
    got = Tr.f12_unpack(jax.jit(Tr.f12_mul)(ar, br))
    exp = [bn.f12_mul(x, y) for x, y in zip(a_vals, b_vals)]
    assert got == exp, "resident f12_mul disagrees with the scalar oracle"

    Tc = curves.T
    got_c = Tc.f12_unpack(
        jax.jit(Tc.f12_mul)(Tc.f12_pack(a_vals), Tc.f12_pack(b_vals))
    )
    assert got == got_c, (
        "resident and per-mul towers disagree at the canonical boundary"
    )
    print("rns_smoke: resident f12_mul bit-exact vs oracle + legacy tower "
          f"over {len(a_vals)} lanes (incl. all-(p-1) operands)")


def check_pairing_bench_gate() -> None:
    """bench_check --dry-run over the new pairing metrics: per-fp keying
    plus the cross-backend-judgment-refused rule (check 4's contract,
    extended to bench.py _pairing_bench records)."""

    def rec(metric: str, fp_backend: str, value: float) -> dict:
        return {
            "metric": metric,
            "value": value,
            "unit": "ms",
            "backend": "cpu",
            "fp_backend": fp_backend,
            "batch": 4,
            "captured_at": f"2026-02-01T00:00:0{int(value) % 10}Z",
        }

    def recs(cios_ms: float, rns_ms: float, conv: float) -> dict:
        return {
            "records": [
                rec("pairing_p50_ms", "cios", cios_ms),
                rec("pairing_p50_ms", "rns", rns_ms),
                rec("rns_conversions_per_pairing", "rns", conv),
            ]
        }

    with tempfile.TemporaryDirectory() as d:
        for i, (c, r) in enumerate([(120.0, 80.0), (118.0, 82.0)]):
            with open(os.path.join(d, f"PBENCH_h{i}.json"), "w") as f:
                json.dump(recs(c, r, 6.0), f)
        fresh = os.path.join(d, "fresh.json")
        with open(fresh, "w") as f:
            # cios p50 "regresses"; the rns rows hold — keying must judge
            # them apart, and the conversion count gates as its own metric
            json.dump(recs(500.0, 81.0, 6.0), f)
        report_path = os.path.join(d, "report.json")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "PBENCH_*.json"),
                "--fresh", fresh,
                "--dry-run", "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        report = json.load(open(report_path))
        keys = {
            (e["metric"], e["backend"])
            for sec in ("regressions", "improved", "ok")
            for e in report[sec]
        }
        assert ("pairing_p50_ms", "cpu/cios") in keys, report
        assert ("pairing_p50_ms", "cpu/rns") in keys, report
        assert ("rns_conversions_per_pairing", "cpu/rns") in keys, report
        regressed = {(e["metric"], e["backend"])
                     for e in report["regressions"]}
        assert regressed == {("pairing_p50_ms", "cpu/cios")}, (
            f"per-fp pairing keying broken: {report}"
        )

        # cios-only pairing history must REFUSE to judge an rns row
        fresh2 = os.path.join(d, "fresh2.json")
        with open(fresh2, "w") as f:
            json.dump(rec("pairing_p50_ms", "rns", 1000.0), f)
        for i in range(2):
            with open(os.path.join(d, f"PONLY_h{i}.json"), "w") as f:
                json.dump(rec("pairing_p50_ms", "cios", 120.0 + i), f)
        r2 = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "PONLY_*.json"),
                "--fresh", fresh2, "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r2.returncode == 0, (r2.stdout, r2.stderr[-2000:])
        report2 = json.load(open(report_path))
        assert report2["skipped"] and "cross-backend" in (
            report2["skipped"][0]["reason"]
        ), report2
    print("rns_smoke: bench_check keys pairing_p50_ms per fp_backend "
          "(cross-backend judgment refused)")


def check_resident_pairing_full(stack) -> None:
    """--full only: the resident pairing NUMERICALLY vs the CIOS oracle —
    valid + forged candidates through both launch classes. Minutes of XLA
    compile on CPU."""
    import random as _random

    import jax
    import jax.numpy as jnp

    from handel_tpu.ops import bn254_ref as bn

    curves, pr, _ = stack
    rng = _random.Random(16)
    B = 4

    # launch class 1: plain per-lane pairing vs the scalar oracle
    g1s = [bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R)) for _ in range(B)]
    g2s = [bn.g2_mul(bn.G2_GEN, rng.randrange(1, bn.R)) for _ in range(B)]
    p = (curves.F.pack([pt[0] for pt in g1s]),
         curves.F.pack([pt[1] for pt in g1s]))
    q = (curves.T.f2_pack([pt[0] for pt in g2s]),
         curves.T.f2_pack([pt[1] for pt in g2s]))
    got = curves.T.f12_unpack(jax.jit(lambda p, q: pr.pairing(p, q))(p, q))
    exp = [bn.pairing(q_, p_) for p_, q_ in zip(g1s, g2s)]
    assert got == exp, "resident pairing disagrees with the oracle"
    print("rns_smoke[full]: resident pairing == oracle over "
          f"{B} seeded lanes")

    # launch class 2: the batched product check — one valid BLS candidate,
    # one forged (corrupted signature scalar)
    h = bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R))
    sks = [rng.randrange(1, bn.R) for _ in range(2)]
    pks = [bn.g2_mul(bn.G2_GEN, sk) for sk in sks]
    sigs = [bn.g1_mul(h, sks[0]), bn.g1_mul(h, sks[1] + 1)]  # lane 1 forged
    g1s = [h, h, bn.g1_neg(sigs[0]), bn.g1_neg(sigs[1])]
    g2s = [pks[0], pks[1], bn.G2_GEN, bn.G2_GEN]
    p = (curves.F.pack([pt[0] for pt in g1s]),
         curves.F.pack([pt[1] for pt in g1s]))
    q = (curves.T.f2_pack([pt[0] for pt in g2s]),
         curves.T.f2_pack([pt[1] for pt in g2s]))
    mask = jnp.ones((4,), bool)
    ok = jax.jit(lambda p, q, m: pr.pairing_check(p, q, m, 2))(p, q, mask)
    assert list(map(bool, ok)) == [True, False], (
        "resident pairing_check verdicts wrong on valid+forged candidates"
    )
    print("rns_smoke[full]: resident pairing_check accepts valid / "
          "rejects forged")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    full = "--full" in sys.argv[1:]
    check_bit_exact()
    check_crt_roundtrip()
    check_toml_plumbing()
    check_bench_check_dry_run()
    stack = _pairing_stack()
    check_resident_conversions(stack)
    check_resident_tower_bit_exact(stack)
    check_pairing_bench_gate()
    if full:
        check_resident_pairing_full(stack)
    print("rns_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""RNS-backend CI gate (fast tier, CPU XLA path — ISSUE 14 acceptance).

Four checks, each a hard exit-nonzero failure:

1. Bit-exactness: a seeded batch of products (random + edge operands,
   including both operands at p-1) through `Field(backend="rns")` must
   match the CIOS kernel BIT-FOR-BIT at the canonical boundary — the
   representation the two backends contract to agree on (their Montgomery
   constants differ: R = 2^16n vs the base-A product M).
2. CRT round-trip: to_rns -> from_rns_base_b is exact over the full
   16n-bit positional range (top value 2^256-1 exercises the Shenoy
   alpha-recovery channel at its limit).
3. Backend plumbing: fp_backend survives TOML load/dump round-trip,
   rejects junk values, and reaches the constructed Field through
   new_scheme (TOML -> SimConfig -> scheme kwargs -> Curves -> Field).
4. bench_check dry-run: constructed per-fp-backend `mont_muls_per_s`
   records flow through scripts/bench_check.py keyed as
   "<backend>/<fp_backend>" — an RNS row gates only against RNS history,
   and a CIOS-only history yields a cross-backend refusal, never a
   judgment.

On real hardware the MXU lab (scripts/mxu_limb_lab.py --persist) captures
the actual marginal figures; this gate is the CPU-only stand-in that keeps
the kernel and the gating plumbing honest on every commit.

Usage: python scripts/rns_smoke.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check_bit_exact() -> None:
    import numpy as np

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.fp import Field

    Fr = Field(bn.P, backend="rns")
    Fc = Field(bn.P, use_pallas=False)
    rng = np.random.default_rng(2024)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(12)]
    xs += [0, 1, bn.P - 1, bn.P - 1]
    ys = list(reversed(xs))

    # correctness vs the bigint oracle
    got = Fr.unpack(Fr.mul(Fr.pack(xs), Fr.pack(ys)))
    want = [x * y % bn.P for x, y in zip(xs, ys)]
    assert got == want, "rns mul disagrees with the bigint oracle"

    # canonical-boundary limbs bitwise equal to CIOS
    plain = Fr.pack(xs, mont=False)
    assert np.array_equal(
        np.asarray(plain), np.asarray(Fc.pack(xs, mont=False))
    ), "canonical pack differs between backends"
    out_r = Fr.from_mont(Fr.mul(Fr.to_mont(plain), Fr.to_mont(plain)))
    out_c = Fc.from_mont(Fc.mul(Fc.to_mont(plain), Fc.to_mont(plain)))
    assert np.array_equal(np.asarray(out_r), np.asarray(out_c)), (
        "boundary limbs not bit-identical between rns and cios"
    )
    print(f"rns_smoke: bit-exact vs cios over {len(xs)} seeded products")


def check_crt_roundtrip() -> None:
    import jax.numpy as jnp
    import numpy as np

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.fp import Field

    F = Field(bn.P, backend="rns")
    n = F.nlimbs
    tops = [(1 << (16 * n)) - 1, bn.P, bn.P + 1, 12345, 0]
    arr = np.zeros((n, len(tops)), np.uint32)
    for j, v in enumerate(tops):
        for i in range(n):
            arr[i, j] = (v >> (16 * i)) & 0xFFFF
    r = F.to_rns(jnp.asarray(arr))
    v16 = np.asarray(
        F.from_rns_base_b(r[F.kA : F.kA + F.kB], r[F.kA + F.kB])
    )
    for j, v in enumerate(tops):
        rec = sum(int(v16[i, j]) << (16 * i) for i in range(F.n16out))
        assert rec == v, f"CRT round-trip broke at {v:#x}"
    print(f"rns_smoke: CRT round-trip exact over {len(tops)} values "
          f"(top {tops[0].bit_length()} bits)")


def check_toml_plumbing() -> None:
    from handel_tpu.models.registry import new_scheme
    from handel_tpu.ops.rns import RnsField
    from handel_tpu.sim.config import dump_config, load_config

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "cfg.toml")
        with open(path, "w") as f:
            f.write('scheme = "bn254-jax"\nfp_backend = "rns"\n'
                    '[service]\nfp_backend = "cios"\n')
        cfg = load_config(path)
        assert cfg.fp_backend == "rns"
        assert cfg.service.fp_backend == "cios"
        dumped = dump_config(cfg)
        assert 'fp_backend = "rns"' in dumped
        bad = os.path.join(d, "bad.toml")
        with open(bad, "w") as f:
            f.write('fp_backend = "vpu"\n')
        try:
            load_config(bad)
        except ValueError:
            pass
        else:
            raise AssertionError("junk fp_backend accepted")
    sch = new_scheme(
        "bn254-jax", batch_size=4, mesh_devices=1, fp_backend="rns",
        warmup=False,
    )
    F = sch.constructor.curves.F
    assert type(F) is RnsField and F.backend == "rns"
    print("rns_smoke: fp_backend plumbed TOML -> SimConfig -> Field")


def check_bench_check_dry_run() -> None:
    def rec(fp_backend: str, value: float) -> dict:
        return {
            "metric": "mont_muls_per_s",
            "value": value,
            "unit": "M muls/s",
            "backend": "cpu",
            "fp_backend": fp_backend,
            "batch": 1024,
            "captured_at": f"2026-01-01T00:00:0{int(value) % 10}Z",
        }

    with tempfile.TemporaryDirectory() as d:
        for i, (cios, rns) in enumerate([(350.0, 420.0), (360.0, 410.0)]):
            with open(os.path.join(d, f"BENCH_h{i}.json"), "w") as f:
                json.dump({"records": [rec("cios", cios), rec("rns", rns)]},
                          f)
        fresh = os.path.join(d, "fresh.json")
        with open(fresh, "w") as f:
            # rns holds steady; cios "regresses" — dry-run must key them
            # separately and never let the cios row judge the rns row
            json.dump({"records": [rec("cios", 100.0), rec("rns", 415.0)]},
                      f)
        report_path = os.path.join(d, "report.json")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "BENCH_*.json"),
                "--fresh", fresh,
                "--dry-run", "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        report = json.load(open(report_path))
        keys = {
            (e["metric"], e["backend"])
            for sec in ("regressions", "improved", "ok")
            for e in report[sec]
        }
        assert ("mont_muls_per_s", "cpu/cios") in keys, report
        assert ("mont_muls_per_s", "cpu/rns") in keys, report
        regressed = {e["backend"] for e in report["regressions"]}
        assert regressed == {"cpu/cios"}, (
            f"per-fp-backend keying broken: {report}"
        )

        # cios-only history must REFUSE to judge an rns row
        fresh2 = os.path.join(d, "fresh2.json")
        with open(fresh2, "w") as f:
            json.dump(rec("rns", 1.0), f)
        for i in range(2):
            with open(os.path.join(d, f"CONLY_h{i}.json"), "w") as f:
                json.dump(rec("cios", 350.0 + i), f)
        r2 = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--history", os.path.join(d, "CONLY_*.json"),
                "--fresh", fresh2, "--json", report_path,
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r2.returncode == 0, (r2.stdout, r2.stderr[-2000:])
        report2 = json.load(open(report_path))
        assert report2["skipped"] and "cross-backend" in (
            report2["skipped"][0]["reason"]
        ), report2
    print("rns_smoke: bench_check keys mont_muls_per_s per fp_backend "
          "(cross-backend judgment refused)")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    check_bit_exact()
    check_crt_roundtrip()
    check_toml_plumbing()
    check_bench_check_dry_run()
    print("rns_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

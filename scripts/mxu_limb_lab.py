"""MXU experiment: can the systolic array beat the VPU CIOS kernel at
254-bit Montgomery multiplication? (SURVEY.md §2.2 "Montgomery/CRT form
suited to MXU"; VERDICT r04 next-round item 6.)

The structural question: the MXU wants deep contractions (K≥128 on a
128×128 array); a batched limb product is an OUTER product per element
(contraction depth 1), so the only MXU-shaped pieces are (a) the
schoolbook product against a CONSTANT matrix, which doesn't exist (both
operands vary), and (b) the reduction-by-constant REDMAT. This lab
measures the candidates and the raw ceiling so the question is closed
with numbers either way:

  * prod            — the production Pallas CIOS kernel (ops/fp.py), the
                      bar to beat (357M muls/s marginal, fp_microbench).
  * outer8_f32      — 8-bit limb split (32 limbs), full (B,32,32) outer
                      product via einsum→dot_general, anti-diagonal fold,
                      then uint32 Montgomery reduction. All f32 products
                      ≤ 255²·63 < 2^24, so the fold is EXACT; the einsum
                      is the piece XLA may or may not map to the MXU.
  * mxu_int8_ceiling — a dense 4096³ s8×s8→s32 matmul: the chip's raw
                      int8 MXU rate, for computing what ANY
                      MXU-formulated mul could at best achieve.

  * rns             — the shipped answer to this lab's question:
                      `Field(backend="rns")` (ops/rns.py), residues +
                      base-extension as constant-matrix dot_general
                      contractions — deep-K MXU shape, no outer product.

Marginal methodology IS Field._throughput_bench's, via the shared
`handel_tpu.ops.fp.chained_marginal` helper (one copy, imported here and
by scripts/fp_kernel_lab.py): k-deep dependent chains inside one
executable so the ~60 ms tunnel dispatch floor cancels. Results land in
results/fp_microbench.json under "mxu_lab" when run with --persist.

    python scripts/mxu_limb_lab.py [batch] [--persist]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.utils.jaxenv import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp
import numpy as np

from bench import write_json_atomic
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import LIMB_BITS, Field, chained_marginal

N8 = 32  # 8-bit limbs for 256 bits


def split8(a16):
    """(16, B) uint32 16-bit limbs -> (32, B) uint32 8-bit limbs."""
    lo = a16 & 0xFF
    hi = (a16 >> 8) & 0xFF
    return jnp.concatenate(
        [jnp.stack([lo[i], hi[i]]) for i in range(a16.shape[0])], axis=0
    )


def outer8_product(a8, b8):
    """Exact schoolbook product of 8-bit-limb vectors via one einsum.

    P[b,i,j] = a8[i,b]·b8[j,b] in f32 (products ≤ 65025, exact), then the
    anti-diagonal fold c[k,b] = Σ_{i+j=k} P[b,i,j] with column sums ≤
    63·65025 < 2^24 — still exactly representable. Returns (63, B) f32.
    The einsum lowers to dot_general with batch dim b and NO contraction
    (outer product): the MXU-mapping question in one op.
    """
    af = a8.astype(jnp.float32)
    bf = b8.astype(jnp.float32)
    P = jnp.einsum("ib,jb->bij", af, bf)  # (B, 32, 32)
    # anti-diagonal fold: row i contributes to columns k = i..i+31
    B = P.shape[0]
    rows = [
        jnp.pad(P[:, i, :], ((0, 0), (i, N8 - 1 - i)))  # (B, 63)
        for i in range(N8)
    ]
    c = jnp.sum(jnp.stack(rows), axis=0)  # (B, 63)
    return c.T  # (63, B)


def make_outer8_mont(F: Field):
    """Full Montgomery mul in the outer-product formulation, oracle-exact.

    Reduction: carry-normalize the f32 columns to uint32 8-bit limbs, then
    Montgomery-reduce 8 bits at a time (32 iterations, m = c0·(-p^-1) mod
    2^8, c = (c + m·p) >> 8) with lazy uint32 carries — the standard CIOS
    tail at radix 2^8 on the VPU. The MXU (or not) part is the product.
    """
    p8 = np.zeros(N8, np.uint32)
    pv = F.p
    for i in range(N8):
        p8[i] = (pv >> (8 * i)) & 0xFF
    # the reduction accumulator keeps 64 8-bit columns; p only ever adds
    # into the low 32 at the current offset, so pad it with high zeros
    p8j = jnp.asarray(np.concatenate([p8, np.zeros(N8, np.uint32)]), jnp.uint32)
    ninv8 = (-pow(F.p, -1, 1 << 8)) % (1 << 8)

    def mont(a16, b16):
        a8 = split8(a16)
        b8 = split8(b16)
        c = outer8_product(a8, b8).astype(jnp.uint32)  # (63, B), ≤2^24
        c = jnp.concatenate([c, jnp.zeros((1, c.shape[1]), jnp.uint32)])

        def red_step(c, _):
            m = ((c[0] & 0xFF) * ninv8) & 0xFF  # (B,)
            c = c + m[None, :] * p8j[:, None]  # lazy, ≤ 2^24 + 2^16·2^8
            # shift one 8-bit limb: propagate c[0]'s carry into c[1] first
            c = c.at[1].add(c[0] >> 8)
            return jnp.concatenate([c[1:], jnp.zeros((1, c.shape[1]), jnp.uint32)]), None

        c, _ = jax.lax.scan(red_step, c, None, length=N8)
        # final carry propagation to canonical 8-bit limbs
        def carry_step(carry, limb):
            v = limb + carry
            return v >> 8, v & 0xFF

        _, c = jax.lax.scan(carry_step, jnp.zeros((c.shape[1],), jnp.uint32), c)
        # repack 8-bit (64,B) -> 16-bit (16,B); rows ≥32 are zero
        c16 = c[0::2] + (c[1::2] << 8)
        c16 = c16[: F.nlimbs]
        # canonicalize: Montgomery leaves results < 2p; match the
        # production kernel's < p convention with one borrow-propagated
        # conditional subtract
        p16 = jnp.asarray(
            [(F.p >> (LIMB_BITS * i)) & 0xFFFF for i in range(F.nlimbs)],
            jnp.uint32,
        )[:, None]

        def sub_step(borrow, xy):
            x, y = xy
            d = x - y - borrow
            return (d >> 31) & 1, d & 0xFFFF

        borrow_out, diff = jax.lax.scan(
            sub_step,
            jnp.zeros((c16.shape[1],), jnp.uint32),
            (c16, jnp.broadcast_to(p16, c16.shape)),
        )
        ge_p = borrow_out == 0
        return jnp.where(ge_p[None, :], diff, c16)

    return mont


def marginal(fn, a, b, k1=4, k2=20, trials=5):
    """Lab-depth wrapper over the shared `chained_marginal` (one copy of
    the chained-dispatch methodology for every fp_microbench figure).
    Returns muls/s, or None (JSON null, never NaN) when the slope is lost
    to timing noise — best-of-trials per chain depth happens inside the
    shared helper, so one contended trial only inflates that trial's time
    instead of poisoning the slope."""
    rate, _floor = chained_marginal(fn, a, b, k1=k1, k2=k2, trials=trials)
    return rate


def main() -> int:
    batch = 1 << 15
    persist = "--persist" in sys.argv
    for arg in sys.argv[1:]:
        if arg.isdigit():
            batch = int(arg)
    F = Field(bn.P)
    print(f"backend={jax.default_backend()} batch={batch}")

    rng = np.random.default_rng(11)
    # full-range residues (256 random bits mod p): every 8-bit limb row,
    # every anti-diagonal pad, and the high-limb carry paths must carry
    # nonzero data through the agreement check below — small operands
    # (earlier draft: < 2^75) would leave rows i >= 10 multiplied by zero
    # and the "oracle-exact" claim unverified there
    raw = rng.integers(0, 256, (batch, 32), np.uint8)
    vals_a = [int.from_bytes(bytes(r), "little") % F.p for r in raw]
    raw_b = rng.integers(0, 256, (batch, 32), np.uint8)
    vals_b = [int.from_bytes(bytes(r), "little") % F.p for r in raw_b]
    a = F.pack(vals_a, mont=False)
    b = F.pack(vals_b, mont=False)

    # correctness first: outer8 Montgomery vs the production kernel
    mont8 = make_outer8_mont(F)
    got = np.asarray(jax.device_get(jax.jit(mont8)(a[:, :256], b[:, :256])))
    want = np.asarray(jax.device_get(jax.jit(F.mul)(a[:, :256], b[:, :256])))
    ok = np.array_equal(got, want)
    print(f"outer8_f32 vs prod agreement: {ok}")
    if not ok:
        bad = np.nonzero((got != want).any(axis=0))[0][:4]
        print(f"  first mismatching lanes: {bad}")
        return 1
    # rns gate: its Montgomery constant is M (not R), so compare against
    # the bigint oracle under its own constant rather than F.mul's output
    F_rns = Field(bn.P, backend="rns")
    got_r = F_rns.unpack(
        jax.device_get(jax.jit(F_rns.mul)(a[:, :256], b[:, :256])), mont=False
    )
    m_inv = pow(F_rns.mont_r, -1, F.p)
    want_r = [x * y * m_inv % F.p
              for x, y in zip(vals_a[:256], vals_b[:256])]
    ok_r = got_r == want_r
    print(f"rns vs oracle agreement: {ok_r}")
    if not ok_r:
        bad = [k for k in range(256) if got_r[k] != want_r[k]][:4]
        print(f"  first mismatching lanes: {bad}")
        return 1

    out = {"batch": batch, "backend": jax.default_backend()}
    for key, label, fn in (
        ("prod_muls_per_s", "prod (Pallas CIOS)", F.mul),
        ("outer8_muls_per_s", "outer8_f32 (einsum)", mont8),
        ("rns_muls_per_s", "rns (dot_general)", F_rns.mul),
    ):
        r = marginal(fn, a, b)
        out[key] = r
        if r is None:
            # provenance for the null, carried into the artifact so a
            # re-run keeps the committed entry reproducible
            out[key.split("_")[0] + "_note"] = (
                "slope lost to host timing noise; the top-level artifact "
                "carries the production figure"
            )
        shown = f"{r/1e6:9.1f}M muls/s marginal" if r else "unmeasurable (noise)"
        print(f"{label:22s} {shown}")

    # raw int8 MXU ceiling: one dense matmul, amortized over repeats
    n = 4096
    x8 = jnp.asarray(rng.integers(-127, 127, (n, n), np.int32), jnp.int8)

    @jax.jit
    def mm(x):
        y = x
        for _ in range(8):
            y = jax.lax.dot_general(
                y, x, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            ).astype(jnp.int8)
        return y

    jax.block_until_ready(mm(x8))
    t0 = time.perf_counter()
    jax.block_until_ready(mm(x8))
    dt = time.perf_counter() - t0
    out["mxu_int8_ops_per_s"] = 8 * 2 * n**3 / dt
    print(f"mxu int8 ceiling:     {out['mxu_int8_ops_per_s']/1e12:9.2f} T int8-ops/s")
    # context: one 254-bit mont mul at radix 2^8 needs ~2·32² limb
    # mul-adds ≈ 4096 int8-ops, so the ceiling implies
    ceiling = out["mxu_int8_ops_per_s"] / 4096
    print(
        f"  => if the mul were perfectly MXU-shaped: ~{ceiling/1e9:.1f}B muls/s; "
        f"the blocker is that outer products contract over K=1, wasting "
        f"127/128 of the array"
    )

    # clobber protections mirroring bench.py's artifact contract: honor the
    # same env override tests use to redirect writes, never overwrite the
    # committed TPU capture from a CPU fallback, and never replace it with
    # a tiny-batch run's noise-depressed figures
    path = os.environ.get("HANDEL_TPU_BENCH_FP_ARTIFACT") or os.path.normpath(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "..",
            "results",
            "fp_microbench.json",
        )
    )
    if (
        persist
        and jax.default_backend() == "cpu"
        and not os.environ.get("HANDEL_TPU_BENCH_FP_ARTIFACT")
    ):
        # a redirected artifact (the env override) can't clobber the
        # committed TPU capture, so CPU-only tests may drive the persist
        # path through it
        print("refusing --persist on the cpu backend (would overwrite the "
              "TPU-captured mxu_lab entry)")
        persist = False
    if (
        persist
        and batch < (1 << 15)
        and not os.environ.get("HANDEL_TPU_BENCH_FP_ARTIFACT")
    ):
        print(
            f"refusing --persist at batch {batch} < 32768 to the default "
            "artifact (set HANDEL_TPU_BENCH_FP_ARTIFACT to redirect a "
            "small-batch run)"
        )
        persist = False
    if persist:
        art = {}
        if os.path.exists(path):
            # same corrupt-artifact guard as bench.py's merge: a truncated
            # file (non-atomic writer killed mid-write) must not crash the
            # persist after minutes of TPU measurement
            try:
                with open(path) as fh:
                    art = json.load(fh)
            except (json.JSONDecodeError, OSError):
                pass
        entry = {
            **{k: (round(v, 1) if isinstance(v, float) else v) for k, v in out.items()},
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        prev = art.get("mxu_lab", {})
        if isinstance(prev, dict):
            # a lost slope (None) must not erase a previously captured valid
            # figure for the same key (bench.py keeps its artifact on
            # rate<=0 for the same reason)
            for k in ("prod_muls_per_s", "outer8_muls_per_s",
                      "rns_muls_per_s"):
                if entry.get(k) is None and prev.get(k) is not None:
                    entry[k] = prev[k]
                    # provenance: the carried figure was measured under the
                    # PRIOR entry's conditions, not this run's batch/time
                    entry[k.split("_")[0] + "_note"] = (
                        "carried from the prior capture (batch "
                        f"{prev.get('batch')}, {prev.get('captured_at')}); "
                        "this run's slope was lost to host timing noise"
                    )
        art["mxu_lab"] = entry
        write_json_atomic(path, art)
        print(f"persisted mxu_lab -> {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

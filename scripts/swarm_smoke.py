"""Swarm CI gate: 4096 virtual nodes reach threshold on one host.

Runs the `sim swarm` orchestrator (handel_tpu/swarm/driver.py run_swarm)
on a 4096-identity committee in <= 2 processes with tracing on, and
asserts the ISSUE 11 acceptance surface: every vnode reaches threshold,
the windowed store actually retired levels (the memory contract), the
merged summary carries the three bench-gated metrics, and the streamed
trace report shows the per-level completion wave plus a non-trivial
critical path. A swarm regression then fails CI on its own named step
(.github/workflows/ci.yml) before the full tier runs.

Gossip is set sparse (period 10s): the in-memory router is lossless and
the id-staggered fast-path cascade covers every level deterministically,
so the run is fast-path-paced — about a minute on one core.

Usage: python scripts/swarm_smoke.py [--artifact-dir DIR]
       [--identities N] [--processes M]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.sim.config import SimConfig, SwarmParams  # noqa: E402
from handel_tpu.swarm.driver import run_swarm  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--artifact-dir", default="",
        help="keep swarm_summary.json + swarm_trace_report.json here",
    )
    ap.add_argument("--identities", type=int, default=4096)
    ap.add_argument("--processes", type=int, default=1)
    args = ap.parse_args(argv)
    assert args.processes <= 2, "the smoke gate is a <=2 process shape"

    cfg = SimConfig(
        trace=True,
        trace_capacity=1 << 20,
        swarm=SwarmParams(
            identities=args.identities,
            processes=args.processes,
            period_ms=10000.0,
            timeout_ms=50.0,
            fast_path=3,
            timeout_s=600.0,
        ),
    )

    with tempfile.TemporaryDirectory() as tmp:
        d = args.artifact_dir or tmp
        if args.artifact_dir:
            os.makedirs(d, exist_ok=True)
        summary = asyncio.run(run_swarm(cfg, d))

        assert summary["ok"], (
            f"only {summary['completed']}/{summary['swarm_identities']} "
            "vnodes reached threshold"
        )
        assert summary["swarm_identities"] == args.identities
        # the three bench-gated metrics (scripts/bench_check.py SIDE_METRICS)
        assert summary["mem_bytes_per_identity"] > 0
        assert summary["swarm_time_to_threshold_s"] > 0
        # windowed store must actually retire completed levels — a silent
        # fallback to the unwindowed store would pass completion but leak
        assert summary["retired_level_ct"] > 0, "no levels retired"
        if args.processes == 1:
            assert summary["udp_sent"] == 0.0, "single process sent UDP"
        else:
            assert summary["udp_sent"] > 0, "blocks never crossed the socket"

        rep = summary.get("trace_report") or {}
        wave = rep.get("level_wave") or {}
        assert wave, "trace report has no level-completion wave"
        for lvl, w in wave.items():
            assert w["first"] <= w["median"] <= w["last"], (
                f"level {lvl} wave out of order: {w}"
            )
        assert rep.get("critical_path_len", 0) >= 1

        print(
            f"swarm smoke OK: {summary['swarm_identities']} vnodes / "
            f"{summary['processes']} proc, "
            f"ttt {summary['swarm_time_to_threshold_s']:.1f}s, "
            f"{summary['mem_bytes_per_identity']:.0f} B/identity, "
            f"{summary['retired_level_ct']} levels retired, "
            f"wave levels {sorted(wave, key=int)}"
        )
        if args.artifact_dir:
            print(f"artifacts: {os.path.join(d, 'swarm_summary.json')}")
        else:
            # still show the merged record for the CI log
            print(json.dumps({k: v for k, v in summary.items()
                              if k != "per_process"}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

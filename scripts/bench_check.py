"""Bench-regression gate: compare the fresh bench artifact to its history.

The driver persists one BENCH_r<NN>.json per round (repo root) and bench.py
keeps the latest accelerator capture in results/bench_tpu.json — but until
now nobody READ them, so a regression like PR 1's 22.5 -> 6.3 ms pack win
could silently un-happen. This script loads the whole history, compares the
fresh artifact like-for-like — same metric AND same backend, so a
TPU-persisted p50 is never judged against a CPU-fallback smoke — and exits
nonzero with a named report when any metric degrades more than
`--threshold` (default 20%) against the trailing median.

Usage:
    python scripts/bench_check.py                 # gate (exit 1 on regression)
    python scripts/bench_check.py --dry-run       # CI self-test: report only
    python scripts/bench_check.py --history 'BENCH_*.json' \
        --fresh results/bench_tpu.json --threshold 0.2 --min-history 2

History records come in two shapes, both accepted: the driver wrapper
({"n": .., "parsed": {<line>}}) and a raw bench line / persisted artifact.
Persisted re-emits (source == "persisted") are deduped by captured_at so an
outage round doesn't multiply one capture into fake history weight.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from statistics import median

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# metric key -> direction ("lower" is better, or "higher"). The headline
# metric's name comes from the record itself (e.g. 4096sig_batch_verify_
# p50_ms); the side metrics ride every accelerator line.
SIDE_METRICS = {
    "pipelined_p50_ms": "lower",
    "host_pack_ms": "lower",
    "host_pack_dense_ms": "lower",
    "host_dispatch_ms": "lower",
    "no_transfer_steady_state": "higher",
    "dedup_hit_rate": "higher",
    # multi-tenant service plane (bench.py service_bench / sim serve):
    # sustained session completions per second, tail completion latency
    # under concurrent load, and coalesced launch lane fill
    "aggregates_per_s": "higher",
    "session_p99_s": "lower",
    "launch_fill_ratio": "higher",
    # fleet-of-chips verify plane (bench.py fleet_bench): K-lane DevicePlane
    # scheduler throughput, its speedup over an identical 1-lane run, and
    # the fleet's per-launch lane fill
    "launches_per_s": "higher",
    "fleet_speedup_x": "higher",
    "fleet_fill_ratio": "higher",
    # mesh latency plane (bench.py small_batch_bench / parallel/
    # mesh_plane.py): p50 wall of a small gold-tier launch riding the
    # whole-mesh lane, and its speedup over the identical-code 1-device
    # run (the dual-mode scheduling contract: > 1x, ~K/2 at batch <= 64)
    "small_batch_verify_p50_ms": "lower",
    "small_batch_speedup_x": "higher",
    # causal-tracing plane (sim trace --report / scripts/trace_smoke.py):
    # wall time from the critical chain's first send to threshold, the
    # fraction of that wall the chain's spans attribute, cross-process
    # flow-link resolution rate, and mean device-lane busy fraction
    "time_to_threshold_s": "lower",
    "critical_path_coverage": "higher",
    "flow_linkage": "higher",
    "lane_occupancy": "higher",
    # virtual-node swarm (bench.py swarm_bench / sim swarm): identities one
    # host carries, summed-RSS bytes per identity (the 1M extrapolation
    # basis), and wall until the LAST member held a threshold signature
    "swarm_identities": "higher",
    "mem_bytes_per_identity": "lower",
    "swarm_time_to_threshold_s": "lower",
    # lifecycle soak (sim soak / scripts/soak_smoke.py): the epoch-swap
    # gate-closed wall, tail session completion under the full drill
    # (swap + forced lane loss), and the SLO admission shed fraction
    "epoch_swap_stall_ms": "lower",
    "soak_p99_s": "lower",
    # WAN scenario engine (sim scenario / scripts/scenario_smoke.py):
    # wall to the weighted threshold under the composed geo + churn +
    # stake-weight drill
    "geo_weighted_ttt_s": "lower",
    "shed_rate": "lower",
    # Fp-backend marginal modmul throughput (bench.py _fp_microbench /
    # ops/fp.py chained_marginal): captured once per Field backend
    # (CIOS, RNS) under the same chained-dispatch methodology
    "mont_muls_per_s": "higher",
    # residue-resident pairing (bench.py _pairing_bench / ops/pairing.py):
    # p50 wall of a batch-4 full pairing per Field backend, and the CRT
    # boundary crossings per pairing trace (resident form: O(line
    # boundaries); legacy: once per tower mul)
    "pairing_p50_ms": "lower",
    "rns_conversions_per_pairing": "lower",
    # RLC batch verification (models/rlc.py / scripts/rlc_smoke.py): p50
    # wall of one combined check over a full batch, and its speedup over
    # the per-candidate check of the same batch (acceptance: >= 3x at
    # batch 64 on the host path)
    "rlc_verify_p50_ms": "lower",
    "rlc_speedup_x": "higher",
    # geo-federation robustness (bench.py federation_bench / sim load /
    # scripts/load_smoke.py): gold-tier open-loop arrival->verdict p99
    # under a mid-run region kill, wall from recovery start to the
    # revived region's first completion, and the fraction of arrivals
    # that spilled to a non-nearest region
    "open_loop_p99_s": "lower",
    "region_recovery_s": "lower",
    "spillover_rate": "lower",
    # SLO alerting + incident plane (handel_tpu/obs/ / sim load /
    # scripts/alert_smoke.py): wall from the forced region kill to the
    # incident opening, and the unexpected-open fraction across the
    # drill (clean control runs must hold this at exactly 0.0)
    "detection_latency_ms": "lower",
    "false_positive_rate": "lower",
    # hierarchical roll-up plane (obs/rollup.py / bench.py rollup_bench /
    # scripts/rollup_smoke.py): master-side merged series count (must
    # stay O(hosts) — flat across identity sweeps), delta wire bytes per
    # host per emission interval, and the master's merge wall
    "fleet_series_count": "lower",
    "rollup_bytes_per_host_s": "lower",
    "fleet_eval_ms": "lower",
}

# Metrics that exist once per Field backend. Their comparison key grows a
# "/<fp_backend>" suffix so a CIOS row is never judged against an RNS row
# (the per-backend like-for-like rule, same spirit as tpu-vs-cpu refusal).
PER_FP_BACKEND = {
    "mont_muls_per_s",
    "pairing_p50_ms",
    "rns_conversions_per_pairing",
    "rlc_verify_p50_ms",
    "rlc_speedup_x",
}


def normalize(obj: dict) -> dict | None:
    """One bench record from either wrapper shape, or None when the round
    produced no parsable line (rc != 0, empty tail)."""
    if not isinstance(obj, dict):
        return None
    if "parsed" in obj or "rc" in obj:  # driver wrapper
        rec = obj.get("parsed")
        return rec if isinstance(rec, dict) else None
    # "records" alone is enough: a container of nested per-fp-backend
    # captures with no headline of its own is still a bench record
    return obj if "metric" in obj or "records" in obj else None


def extract_metrics(rec: dict) -> dict[tuple[str, str], float]:
    """{(metric name, backend): value} for every comparable number in one
    record. Records without a backend tag (old CPU smokes) are keyed under
    "cpu" only when their metric name says so, else skipped entirely —
    an unlabeled number cannot be compared like-for-like. PER_FP_BACKEND
    metrics key as "<backend>/<fp_backend>"; a "records" list of nested
    captures is walked with the same rules."""
    out: dict[tuple[str, str], float] = {}
    # nested per-fp-backend captures (bench.py _fp_microbench "records")
    for sub in rec.get("records") or []:
        if isinstance(sub, dict):
            out.update(extract_metrics(sub))
    backend = rec.get("backend")
    if not backend:
        backend = "cpu" if "cpu_smoke" in str(rec.get("metric", "")) else None
    if not backend:
        return out

    def keyed(metric: str) -> str:
        fp = rec.get("fp_backend")
        if metric in PER_FP_BACKEND and fp:
            return f"{backend}/{fp}"
        return backend

    name, value = rec.get("metric"), rec.get("value")
    if name and isinstance(value, (int, float)):
        if not rec.get("forced_shape") and not rec.get("invalid_measurement"):
            out[(str(name), keyed(str(name)))] = float(value)
    for key in SIDE_METRICS:
        v = rec.get(key)
        if isinstance(v, (int, float)):
            out[(key, keyed(key))] = float(v)
    return out


def direction(metric: str) -> str:
    return SIDE_METRICS.get(metric, "lower")


def load_history(pattern: str) -> list[dict]:
    """Chronologically ordered, deduped history records."""
    recs: list[dict] = []
    seen_capture: set[str] = set()
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path) as f:
                rec = normalize(json.load(f))
        except (OSError, ValueError):
            continue
        if rec is None:
            continue
        cap = rec.get("captured_at")
        if rec.get("source") == "persisted" and cap:
            if cap in seen_capture:
                continue  # same capture re-emitted across outage rounds
            seen_capture.add(cap)
        elif cap:
            seen_capture.add(cap)
        recs.append(rec)
    return recs


def detect_regressions(
    history: list[dict],
    fresh: dict,
    threshold: float = 0.20,
    min_history: int = 2,
) -> dict:
    """Compare `fresh` against the trailing median of `history`,
    like-for-like. Returns the full report:
    {"regressions": [...], "improved": [...], "ok": [...], "skipped": [...]}.
    Each entry names metric, backend, fresh value, trailing median, delta.
    """
    hist_vals: dict[tuple[str, str], list[float]] = {}
    hist_backends: dict[str, set[str]] = {}
    for rec in history:
        for key, v in extract_metrics(rec).items():
            hist_vals.setdefault(key, []).append(v)
            hist_backends.setdefault(key[0], set()).add(key[1])

    report = {"regressions": [], "improved": [], "ok": [], "skipped": []}
    for (metric, backend), value in extract_metrics(fresh).items():
        past = hist_vals.get((metric, backend), [])
        if len(past) < min_history:
            other = hist_backends.get(metric, set()) - {backend}
            reason = (
                f"history exists only for backend(s) {sorted(other)} — "
                f"cross-backend comparison refused"
                if other
                else f"only {len(past)} comparable record(s) "
                f"(< {min_history})"
            )
            report["skipped"].append(
                {"metric": metric, "backend": backend, "value": value,
                 "reason": reason}
            )
            continue
        med = median(past)
        if med == 0:
            report["skipped"].append(
                {"metric": metric, "backend": backend, "value": value,
                 "reason": "trailing median is 0"}
            )
            continue
        if direction(metric) == "lower":
            delta = (value - med) / med
        else:
            delta = (med - value) / med
        entry = {
            "metric": metric,
            "backend": backend,
            "value": value,
            "trailing_median": med,
            "n_history": len(past),
            "degradation": round(delta, 4),
        }
        if delta > threshold:
            report["regressions"].append(entry)
        elif delta < 0:
            report["improved"].append(entry)
        else:
            report["ok"].append(entry)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--history", default=os.path.join(REPO, "BENCH_*.json"),
        help="glob of historical bench records (driver wrapper or raw line)",
    )
    ap.add_argument(
        "--fresh", default=os.path.join(REPO, "results", "bench_tpu.json"),
        help="the artifact under judgment",
    )
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="fractional degradation that fails the gate")
    ap.add_argument("--min-history", type=int, default=2,
                    help="comparable records required before judging")
    ap.add_argument("--dry-run", action="store_true",
                    help="validate + report, always exit 0 (CI self-test)")
    ap.add_argument("--json", default="", help="also write the report here")
    args = ap.parse_args(argv)

    history = load_history(args.history)
    try:
        with open(args.fresh) as f:
            fresh = normalize(json.load(f))
    except (OSError, ValueError) as e:
        print(f"bench_check: cannot read fresh artifact {args.fresh}: {e}",
              file=sys.stderr)
        return 0 if args.dry_run else 2
    if fresh is None:
        print(f"bench_check: {args.fresh} holds no bench record",
              file=sys.stderr)
        return 0 if args.dry_run else 2

    report = detect_regressions(
        history, fresh, threshold=args.threshold,
        min_history=args.min_history,
    )
    print(
        f"bench_check: {len(history)} history records "
        f"({os.path.basename(args.history)}), fresh = {args.fresh}"
    )
    for entry in report["regressions"]:
        print(
            f"  REGRESSION {entry['metric']} [{entry['backend']}]: "
            f"{entry['value']:g} vs trailing median "
            f"{entry['trailing_median']:g} over {entry['n_history']} runs "
            f"({entry['degradation']:+.1%}, threshold "
            f"{args.threshold:.0%})"
        )
    for entry in report["improved"]:
        print(
            f"  improved   {entry['metric']} [{entry['backend']}]: "
            f"{entry['value']:g} vs median {entry['trailing_median']:g} "
            f"({entry['degradation']:+.1%})"
        )
    for entry in report["ok"]:
        print(
            f"  ok         {entry['metric']} [{entry['backend']}]: "
            f"{entry['value']:g} vs median {entry['trailing_median']:g} "
            f"({entry['degradation']:+.1%})"
        )
    for entry in report["skipped"]:
        print(
            f"  skipped    {entry['metric']} [{entry['backend']}]: "
            f"{entry['reason']}"
        )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)

    if report["regressions"] and not args.dry_run:
        print(
            f"bench_check: FAILED — {len(report['regressions'])} metric(s) "
            f"regressed past {args.threshold:.0%}",
            file=sys.stderr,
        )
        return 1
    if args.dry_run and report["regressions"]:
        print("bench_check: dry-run — regressions reported, exit 0",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Fleet-of-chips verify-plane smoke: 8 forced host devices, end to end.

Promotes the MULTICHIP dryrun to a CI gate over the wired fleet path
(parallel/plane.py DevicePlane + the per-lane dispatch queues in
parallel/batch_verifier.py). Four phases:

1. Kernel fleet: 8 `BN254Device` engines pinned to distinct jax devices
   (`bn254_plane`), each committing the registry to its own chip, driving
   the AGGREGATION stage only (the same scope note as launch_smoke.py —
   pairing tails are the slow tier's job) with the launch-smoke shape
   (N=12, C=4) so the XLA persistent cache is shared with that gate.
   Every aggregate key is checked against the host oracle and every
   device must execute >= 1 launch.
2. Service fleet: a DevicePlane of 8 host-math engines behind ONE
   BatchVerifierService — every lane must dispatch >= 1 launch and every
   verdict must match the scheme's own serial batch_verify.
3. Degraded fleet: lane 0's breaker forced open before start — the run
   must complete on the 7 healthy lanes and lane 0 must launch nothing.
4. Fleet bench gate: bench.py fleet_bench (8 lanes vs identical 1-lane
   baseline, simulated launch wall) must report >= 4x launches/s, a clean
   no-idle-while-queued scheduler audit, and survive
   `scripts/bench_check.py --dry-run` over a fresh artifact carrying
   launches_per_s / fleet_speedup_x / fleet_fill_ratio.
5. Latency plane (parallel/mesh_plane.py), three sub-gates:
   a. Mesh kernel: ONE `BN254Device(mesh_devices=8)` spanning all 8
      forced host devices drives a batch-8 launch through BOTH whole-mesh
      aggregation entries — the range class (`_range_agg_kernel`) and the
      dense masked-sum class (`_sharded_sum`, via the rule-placed padded
      mask exactly as `_run_plan` stages it; the registry size is chosen
      indivisible by 8 so the edge-padded shard boundary is live) — and
      every aggregate must match the host oracle bit-exactly.
   b. Mode pick: a dual-mode service (throughput HostDevice lanes + a
      HostMeshDevice mesh lane) must route a small gold-tier group to the
      mesh lane and a bulk standard-tier flood to the per-lane path, with
      verdicts matching the scheme and zero mesh fallbacks.
   c. Bench gate: bench.py small_batch_bench (8-device mesh lane vs the
      identical-code 1-device run) must report > 1x speedup (the
      small_batch_verify_p50_ms contract) and survive
      `scripts/bench_check.py --dry-run` over a fresh artifact.
"""

import json
import os
import random
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# 8 virtual host devices — must land before jax initializes its backends
from handel_tpu.utils.jaxenv import apply_platform_env  # noqa: E402

os.environ.setdefault("HANDEL_TPU_PLATFORM", "cpu")
apply_platform_env(force_host_device_count=8)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from handel_tpu import native as nat  # noqa: E402
from handel_tpu.core.bitset import BitSet  # noqa: E402
from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature  # noqa: E402
from handel_tpu.ops import bn254_ref as bn  # noqa: E402

N, C, DEVICES = 12, 4, 8


def host_agg(pks, bs):
    acc = None
    for i in bs.indices():
        acc = pks[i].point if acc is None else bn.g2_add(acc, pks[i].point)
    return acc


def kernel_fleet_smoke() -> None:
    """Phase 1: one aggregation launch per pinned BN254 engine, aggregate
    keys vs the host oracle, every device dispatched."""
    from handel_tpu.parallel.plane import bn254_plane

    jax.config.update(
        "jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache"
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    rng = random.Random(99)
    sks = [rng.randrange(1, 1 << 20) for _ in range(N)]
    pks = [BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * N, sks)]
    sig = BN254Signature(bn.G1_GEN)
    assert len(jax.devices()) >= DEVICES, (
        f"forced host device count not applied: {len(jax.devices())}"
    )
    plane = bn254_plane(pks, DEVICES, batch_size=C)

    t0 = time.perf_counter()
    checked = 0
    for lane in plane.lanes:
        device = lane.engine
        reqs = []
        for _ in range(C):
            size = rng.randrange(2, N)
            lo = rng.randrange(0, N - size + 1)
            bs = BitSet(N)
            for i in range(lo, lo + size):
                bs.set(i, True)
            reqs.append((bs, sig))
        plan = device._pack_requests(reqs)
        args = device._stage_plan(plan)
        agg = device._range_agg_kernel(plan.miss_k)(*args[:4])
        # the launch must have executed on THIS lane's pinned chip
        devs = {b.device for b in jax.tree_util.tree_leaves(agg)}
        assert devs == {device.jax_device}, (
            f"lane {lane.index}: launch ran on {devs}, "
            f"pinned to {device.jax_device}"
        )
        lane.launches += 1
        x, y, inf = device.curves.g2.to_affine(agg)
        xs = device.curves.T.f2_unpack(x)
        ys = device.curves.T.f2_unpack(y)
        infs = np.asarray(inf)
        for j, (bs, _) in enumerate(reqs):
            want = host_agg(pks, bs)
            got = None if infs[j] else (xs[j], ys[j])
            assert got == want, (
                f"lane {lane.index} candidate {j}: aggregate mismatch"
            )
            checked += 1
    assert all(lane.launches >= 1 for lane in plane.lanes)
    print(
        f"multichip_smoke: {DEVICES} pinned engines, {checked} aggregates "
        f"verified against the host oracle in "
        f"{time.perf_counter() - t0:.1f}s"
    )


def _service_run(trip_lane: int | None = None) -> dict:
    """One fleet service run over 8 host-math lanes; returns per-lane
    launch counts + verdict check. trip_lane forces that lane's breaker
    open before the service starts."""
    import asyncio
    import concurrent.futures

    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.models.fake import FakePublic, FakeSignature
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.parallel.plane import DevicePlane
    from handel_tpu.service.driver import HostDevice
    from handel_tpu.utils.breaker import CircuitBreaker

    scheme = FakeScheme()
    pks = [FakePublic(True) for _ in range(16)]
    engines = [
        HostDevice(scheme.constructor, batch_size=4, launch_ms=2.0)
        for _ in range(DEVICES)
    ]
    breakers = [
        CircuitBreaker(cooldown_s=600.0) for _ in range(DEVICES)
    ]
    plane = DevicePlane(engines, breakers=breakers)
    if trip_lane is not None:
        br = plane.lanes[trip_lane].breaker
        for _ in range(br.threshold):
            br.record_failure()
        assert not br.allow()

    reqs = []
    for i in range(96):
        b = BitSet(16)
        b.set(i % 16, True)
        # an invalid signature every 8th request: the verdict check below
        # must see the scheme's own False, not a blanket True
        reqs.append(
            (i.to_bytes(4, "big"), (b, FakeSignature(i % 8 != 7)))
        )
    want = [
        scheme.constructor.batch_verify(msg, pks, [r])[0]
        for msg, r in reqs
    ]

    async def go():
        loop = asyncio.get_running_loop()
        loop.set_default_executor(
            concurrent.futures.ThreadPoolExecutor(
                max_workers=2 * DEVICES + 4
            )
        )
        svc = BatchVerifierService(plane, max_delay_ms=0.2)
        try:
            got = await asyncio.gather(
                *(
                    svc.verify(msg, pks, [r], session=f"s{i % 4}")
                    for i, (msg, r) in enumerate(reqs)
                )
            )
            return [v[0] for v in got], svc.values()
        finally:
            svc.stop()

    got, vals = asyncio.run(go())
    assert got == want, "fleet verdicts diverge from the host scheme"
    return {
        "per_lane": [lane.engine.dispatched for lane in plane.lanes],
        "values": vals,
    }


def service_fleet_smoke() -> None:
    """Phase 2: all 8 lanes dispatch, verdicts match the host scheme."""
    out = _service_run()
    per_lane = out["per_lane"]
    assert all(n >= 1 for n in per_lane), (
        f"idle lane in a flooded fleet: {per_lane}"
    )
    print(
        f"multichip_smoke: service fleet per-lane launches {per_lane}, "
        f"fill {out['values']['launchFillRatio']:.2f}"
    )


def degraded_fleet_smoke() -> None:
    """Phase 3: breaker-open on lane 0 degrades to the 7 healthy lanes."""
    out = _service_run(trip_lane=0)
    per_lane = out["per_lane"]
    assert per_lane[0] == 0, (
        f"breaker-open lane 0 still dispatched: {per_lane}"
    )
    assert all(n >= 1 for n in per_lane[1:]), (
        f"healthy lane idle in degraded fleet: {per_lane}"
    )
    assert out["values"]["devicesAvailable"] == DEVICES - 1
    assert out["values"]["failoverBatches"] == 0.0
    print(
        f"multichip_smoke: degraded fleet completed on {DEVICES - 1} "
        f"lanes, per-lane launches {per_lane}"
    )


def bench_gate() -> None:
    """Phase 4: fleet bench >= 4x + clean audit, under bench_check."""
    from bench import fleet_bench

    fleet = fleet_bench(devices=8, requests_n=160, batch_size=4,
                        launch_ms=8.0)
    assert fleet["fleet_speedup_x"] >= 4.0, (
        f"fleet speedup below the gate: {fleet}"
    )
    assert fleet["fleet_idle_violations"] == 0, (
        f"scheduler idled a device while launches queued: {fleet}"
    )
    fresh = {
        "metric": "fleet_verify_plane_smoke",
        "value": fleet["launches_per_s"],
        "unit": "launches/s",
        "backend": jax.default_backend(),
        **fleet,
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(fresh, f)
        path = f.name
    try:
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--dry-run",
                "--fresh",
                path,
            ],
            capture_output=True,
            text=True,
        )
        sys.stdout.write(r.stdout)
        sys.stderr.write(r.stderr)
        assert r.returncode == 0, "bench_check --dry-run failed"
        assert "fleet_speedup_x" in r.stdout, (
            "bench_check did not consider fleet_speedup_x"
        )
    finally:
        os.unlink(path)
    print(
        f"multichip_smoke: fleet bench gated — "
        f"{fleet['launches_per_s']} launches/s, "
        f"{fleet['fleet_speedup_x']}x over 1 lane, "
        f"fill {fleet['fleet_fill_ratio']}"
    )


def mesh_kernel_smoke() -> None:
    """Phase 5a: one whole-mesh engine, batch-8 launch, both aggregation
    classes bit-exact vs the host oracle across the edge-padded registry
    shard boundary."""
    from handel_tpu.parallel.mesh_plane import bn254_mesh_engine

    # registry indivisible by the mesh width: 70 % 8 = 6, so the last
    # registry shard carries 2 padded identity rows — the boundary the
    # sharding tests call out
    n_mesh, c = 70, 8
    rng = random.Random(7)
    sks = [rng.randrange(1, 1 << 20) for _ in range(n_mesh)]
    pks = [
        BN254PublicKey(p)
        for p in nat.g2_mul_batch([bn.G2_GEN] * n_mesh, sks)
    ]
    sig = BN254Signature(bn.G1_GEN)
    eng = bn254_mesh_engine(pks, DEVICES, batch_size=c)
    assert eng.mesh is not None and eng._mesh_pad == 2, (
        f"mesh pad not live: pad={eng._mesh_pad}"
    )
    t0 = time.perf_counter()

    def check(plan, agg, reqs, label):
        x, y, inf = eng.curves.g2.to_affine(agg)
        xs = eng.curves.T.f2_unpack(x)
        ys = eng.curves.T.f2_unpack(y)
        infs = np.asarray(inf)
        for j, (bs, _) in enumerate(reqs):
            want = host_agg(pks, bs)
            got = None if infs[j] else (xs[j], ys[j])
            assert got == want, (
                f"mesh {label} candidate {j}: aggregate mismatch"
            )

    # range class: contiguous signer windows -> _range_agg_kernel over the
    # mesh-resident prefix table
    reqs = []
    for _ in range(c):
        size = rng.randrange(2, 16)
        lo = rng.randrange(0, n_mesh - size + 1)
        bs = BitSet(n_mesh)
        for i in range(lo, lo + size):
            bs.set(i, True)
        reqs.append((bs, sig))
    plan = eng._pack_requests(reqs)
    assert plan.kind == "range", plan.kind
    staged = eng._stage_plan(plan)
    agg = eng._range_agg_kernel(plan.miss_k)(*staged[:4])
    check(plan, agg, reqs, "range")

    # dense class: sparse signers across the full hull (> MISS_CAP holes)
    # -> the rule-placed padded mask into _sharded_sum, exactly the
    # staging _run_plan performs
    reqs = []
    for _ in range(c):
        bs = BitSet(n_mesh)
        bs.set(0, True)
        bs.set(n_mesh - 1, True)
        for i in rng.sample(range(1, n_mesh - 1), 3):
            bs.set(i, True)
        reqs.append((bs, sig))
    plan = eng._pack_requests(reqs)
    assert plan.kind == "dense", plan.kind
    mask = (
        np.unpackbits(
            plan.words.view(np.uint8), axis=1, count=n_mesh,
            bitorder="little",
        )
        .view(np.bool_)
        .T.copy()
    )
    mask = np.pad(mask, ((0, eng._mesh_pad), (0, 0)))
    mask = eng._mesh_put["mask"](mask)
    (rx0, rx1), (ry0, ry1) = eng._reg_sharded
    agg = eng._sharded_sum(rx0, rx1, ry0, ry1, mask)
    check(plan, agg, reqs, "dense")
    print(
        f"multichip_smoke: whole-mesh engine over {DEVICES} devices, "
        f"2x{c} aggregates (range + edge-padded dense) bit-exact vs the "
        f"host oracle in {time.perf_counter() - t0:.1f}s"
    )


def mode_pick_smoke() -> None:
    """Phase 5b: gold/small -> mesh lane, bulk -> per-lane, verdicts exact,
    zero fallbacks."""
    import asyncio
    import concurrent.futures

    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.models.fake import FakePublic, FakeSignature
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.parallel.mesh_plane import (
        ModePolicy,
        enable_latency_plane,
        host_mesh_engine,
    )
    from handel_tpu.parallel.plane import host_plane

    scheme = FakeScheme()
    pks = [FakePublic(True) for _ in range(16)]
    # lane batch == mesh batch: the collector plans launch groups at the
    # throughput batch size, so a smaller lane batch would split the
    # 8-candidate gold group and the second half would find the mesh busy
    plane = host_plane(scheme.constructor, 2, batch_size=8, launch_ms=1.0)
    mesh_eng = host_mesh_engine(
        scheme.constructor, devices=DEVICES, batch_size=8,
        per_candidate_ms=0.2,
    )

    # bulk flood: distinct messages, default (standard) tier, every 8th
    # signature invalid so the verdict check is live
    bulk = []
    for i in range(48):
        b = BitSet(16)
        b.set(i % 16, True)
        bulk.append(
            (i.to_bytes(4, "big"), (b, FakeSignature(i % 8 != 7)))
        )
    want_bulk = [
        scheme.constructor.batch_verify(msg, pks, [r])[0]
        for msg, r in bulk
    ]
    # small gold group: one message, 8 distinct candidates
    gold = []
    for i in range(8):
        b = BitSet(16)
        b.set(i, True)
        gold.append((b, FakeSignature(True)))

    async def go():
        loop = asyncio.get_running_loop()
        loop.set_default_executor(
            concurrent.futures.ThreadPoolExecutor(max_workers=24)
        )
        svc = BatchVerifierService(plane, max_delay_ms=0.2)
        enable_latency_plane(
            svc, mesh_eng, policy=ModePolicy(small_batch_max=8)
        )
        svc.queue.set_tier("gold0", "gold")
        try:
            got_gold = await asyncio.gather(
                *(
                    svc.verify(b"gold-round", pks, [q], session="gold0")
                    for q in gold
                )
            )
            got_bulk = await asyncio.gather(
                *(
                    svc.verify(msg, pks, [r], session=f"s{i % 4}")
                    for i, (msg, r) in enumerate(bulk)
                )
            )
            return [v[0] for v in got_gold], [v[0] for v in got_bulk], (
                svc.values()
            )
        finally:
            svc.stop()

    got_gold, got_bulk, vals = asyncio.run(go())
    assert all(got_gold), "gold-tier mesh verdicts diverge"
    assert got_bulk == want_bulk, "bulk verdicts diverge from the scheme"
    assert vals["meshLanes"] == 1.0 and vals["meshLanesAvailable"] == 1.0
    assert mesh_eng.mesh_launches >= 1, (
        "small gold-tier group never rode the mesh lane"
    )
    assert vals["modeLatencyLaunches"] >= 1.0, vals
    assert vals["modeThroughputLaunches"] >= 1.0, (
        f"bulk flood never took the per-lane path: {vals}"
    )
    assert vals["meshFallbacks"] == 0.0, vals
    per_lane = [l.engine.dispatched for l in plane.lanes if not l.mesh]
    assert all(n >= 1 for n in per_lane), (
        f"idle throughput lane under the bulk flood: {per_lane}"
    )
    print(
        f"multichip_smoke: mode pick — "
        f"{vals['modeLatencyLaunches']:.0f} latency launches "
        f"({mesh_eng.mesh_candidates} candidates on the mesh), "
        f"{vals['modeThroughputLaunches']:.0f} throughput launches "
        f"across lanes {per_lane}, 0 fallbacks"
    )


def latency_bench_gate() -> None:
    """Phase 5c: small-batch mesh bench > 1x + bench_check dry-run."""
    from bench import small_batch_bench

    m = small_batch_bench(devices=8, rounds=12)
    assert m["small_batch_speedup_x"] is not None and (
        m["small_batch_speedup_x"] > 1.0
    ), f"latency plane speedup below the gate: {m}"
    assert m["small_batch_mesh_fallbacks"] == 0, m
    fresh = {
        "metric": "small_batch_verify_plane_smoke",
        "value": m["small_batch_verify_p50_ms"],
        "unit": "ms",
        "backend": jax.default_backend(),
        **m,
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(fresh, f)
        path = f.name
    try:
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "bench_check.py"),
                "--dry-run",
                "--fresh",
                path,
            ],
            capture_output=True,
            text=True,
        )
        sys.stdout.write(r.stdout)
        sys.stderr.write(r.stderr)
        assert r.returncode == 0, "bench_check --dry-run failed"
        assert "small_batch_verify_p50_ms" in r.stdout, (
            "bench_check did not consider small_batch_verify_p50_ms"
        )
    finally:
        os.unlink(path)
    print(
        f"multichip_smoke: latency bench gated — "
        f"{m['small_batch_verify_p50_ms']} ms p50 at "
        f"batch {m['small_batch_n']}, {m['small_batch_speedup_x']}x over "
        f"the 1-device run"
    )


def main() -> int:
    kernel_fleet_smoke()
    service_fleet_smoke()
    degraded_fleet_smoke()
    bench_gate()
    mesh_kernel_smoke()
    mode_pick_smoke()
    latency_bench_gate()
    return 0


if __name__ == "__main__":
    sys.exit(main())

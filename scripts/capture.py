"""Capture simulation results into a combined CSV under results/.

Runs every `[[runs]]` entry of a simulation TOML on the localhost platform
and merges the per-run stats rows (one per run) into one CSV — the shape
of the reference's shipped result files (simul/plots/csv/*.csv, one row
per run with run/nodes/threshold/failing + measure columns).

Usage:
    python scripts/capture.py out.csv config.toml [--platform localhost]

The per-run work dirs land next to out.csv in a .work/ directory and are
kept for debugging.
"""

from __future__ import annotations

import argparse
import asyncio
import csv
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handel_tpu.sim.config import load_config  # noqa: E402
from handel_tpu.sim.platform import run_simulation  # noqa: E402


def merge_csvs(paths: list[str], out: str) -> int:
    """Union-of-columns row merge, sorted column order (stats.go style)."""
    rows: list[dict[str, str]] = []
    cols: set[str] = set()
    for p in paths:
        with open(p, newline="") as f:
            for row in csv.DictReader(f):
                rows.append(row)
                cols.update(row)
    header = sorted(cols)
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=header, restval="0")
        w.writeheader()
        for row in rows:
            w.writerow(row)
    return len(rows)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("out")
    ap.add_argument("config")
    ap.add_argument("--platform", default="localhost")
    args = ap.parse_args()

    cfg = load_config(args.config)
    workdir = os.path.join(os.path.dirname(os.path.abspath(args.out)) or ".", ".work")
    results = asyncio.run(run_simulation(cfg, workdir, platform=args.platform))
    csvs = []
    for i, r in enumerate(results):
        status = "ok" if r.ok else "FAILED"
        print(f"run {i}: {status} -> {r.csv_path}", flush=True)
        if not r.ok:
            for _, err in r.outputs:
                sys.stderr.write(err.decode(errors="replace")[-2000:])
            return 1
        csvs.append(r.csv_path)
    n = merge_csvs(csvs, args.out)
    print(f"{args.out}: {n} rows")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Driver benchmark: batched BLS verification on one chip.

Measures the headline target from BASELINE.md: verify a batch of aggregate
BN254 signatures over a 4096-key registry (the reference's 4000-node AWS
scenario, README.md:32-33: ~900 ms avg completion) with the device path —
masked G2 aggregation + batched product-of-pairings check in one launch per
128 candidates.

Prints ONE JSON line:
  {"metric": "4096sig_batch_verify_p50_ms", "value": ..., "unit": "ms",
   "vs_baseline": <reference 900 ms / our p50>}

Resilience contract (round-2 verdict, "What's weak" #1): the TPU is reached
through a tunnel with intermittent outages, so
  * the backend probe retries with backoff for up to ~10 minutes
    (HANDEL_TPU_PROBE_BUDGET_S overrides) before giving up — but is
    skipped outright when the env already pins a CPU backend
    (JAX_PLATFORMS=cpu: no tunnel involved, nothing to probe) or via the
    BENCH_SKIP_PROBE=1 escape hatch, so CPU-tier CI starts instantly;
  * every successful accelerator measurement is ALSO persisted to
    results/bench_tpu.json with backend/device provenance, so a tunnel
    outage at driver time cannot erase the round's evidence — on fallback
    the persisted artifact is re-emitted (marked "source": "persisted");
  * with no artifact either, the CPU smoke is reported under an honest
    metric name with vs_baseline null (a 16-sig CPU number must not be
    ratio'd against the reference's 4000-sig 900 ms headline).
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
# artifact paths overridable so tests never clobber a captured TPU result
ARTIFACT = os.environ.get(
    "HANDEL_TPU_BENCH_ARTIFACT", os.path.join(REPO, "results", "bench_tpu.json")
)
FP_ARTIFACT = os.environ.get(
    "HANDEL_TPU_BENCH_FP_ARTIFACT",
    os.path.join(REPO, "results", "fp_microbench.json"),
)
PAIRING_ARTIFACT = os.environ.get(
    "HANDEL_TPU_BENCH_PAIRING_ARTIFACT",
    os.path.join(REPO, "results", "pairing_bench.json"),
)
REFERENCE_HEADLINE_MS = 900.0  # README.md:32-33, 4000-sig AWS scenario


def _probe_default_backend(timeout_s: float = 90.0) -> bool:
    """True if jax can initialize its default platform within the timeout.

    The environment's TPU is reached through a tunnel whose outage makes
    `import jax` + device init hang FOREVER (not error). Probing in a
    subprocess keeps this process safe; on failure the bench falls back to
    CPU so the driver always records a line.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _probe_cache_path() -> str:
    """Host-local probe-verdict file (NOT a committed artifact), shared by
    every checkout/run on one host.

    Lives under a STABLE per-user cache root (XDG_CACHE_HOME, else
    ~/.cache) — NOT tempfile.gettempdir(): the tempdir honors TMPDIR,
    which bench drivers commonly point at a fresh per-round directory, so
    a verdict written there evaporates between rounds and the full
    unreachable-retry ladder replays every time (BENCH_r05's ~8.5 min
    tail, despite the verdict having been recorded). The tempdir remains
    only the last-resort fallback when no home directory resolves."""
    import getpass
    import tempfile

    override = os.environ.get("HANDEL_TPU_PROBE_CACHE")
    if override:
        return override
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = str(os.getuid()) if hasattr(os, "getuid") else "any"
    root = os.environ.get("XDG_CACHE_HOME", "").strip()
    if not root:
        home = os.path.expanduser("~")
        if home and home != "~":
            root = os.path.join(home, ".cache")
    if not root:
        return os.path.join(
            tempfile.gettempdir(), f"handel_tpu_probe_{user}.json"
        )
    return os.path.join(root, "handel_tpu", f"probe_{user}.json")


def _cached_probe_failure() -> float | None:
    """Age in seconds of a still-fresh cached 'unreachable' verdict, else
    None (no cache / stale / last verdict was reachable)."""
    ttl = float(os.environ.get("HANDEL_TPU_PROBE_CACHE_TTL_S", "3600"))
    try:
        with open(_probe_cache_path()) as f:
            v = json.load(f)
        if v.get("reachable"):
            return None
        age = time.time() - float(v["checked_at"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    return age if 0 <= age < ttl else None


def _record_probe_verdict(reachable: bool) -> None:
    try:
        path = _probe_cache_path()
        parent = os.path.dirname(path)
        if parent:  # the ~/.cache/handel_tpu dir may not exist yet
            os.makedirs(parent, exist_ok=True)
        write_json_atomic(
            path, {"reachable": reachable, "checked_at": time.time()}
        )
    except OSError:
        pass  # a read-only cache root must not fail the bench


def _probe_with_retries() -> bool:
    """Probe the default backend repeatedly with backoff until it answers or
    the budget (default 10 min) is spent. A transient tunnel blip must not
    cost a round's TPU evidence.

    The verdict persists to a host-local cache: an unreachable backend costs
    the full retry ladder once per host per TTL (default 1 h), not once per
    run — BENCH_r05's tail showed the ~8.5 min ladder replaying on every
    round of an outage. A reachable verdict is never trusted from cache (a
    live probe succeeds in seconds and the tunnel can drop between runs)."""
    if os.environ.get("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL"):
        # test hook: a deterministic outage. Masking JAX_PLATFORMS is not
        # enough — the environment's sitecustomize re-selects the real
        # platform through the config API inside the probe child, so with a
        # live tunnel the outage path would be untestable. Never writes the
        # host cache: a forced verdict must not poison real runs.
        print("bench: probe failure forced by env", file=sys.stderr)
        return False
    age = _cached_probe_failure()
    if age is not None:
        print(
            f"bench: backend probe skipped — host cache says unreachable "
            f"{age/60:.1f} min ago ({_probe_cache_path()}; delete or wait "
            f"out HANDEL_TPU_PROBE_CACHE_TTL_S to re-probe)",
            file=sys.stderr,
        )
        return False
    budget = float(os.environ.get("HANDEL_TPU_PROBE_BUDGET_S", "600"))
    deadline = time.monotonic() + budget
    delay = 15.0
    attempt = 0
    while True:
        attempt += 1
        left = deadline - time.monotonic()
        if left <= 0:
            print(f"bench: backend probe gave up after {attempt - 1} attempts",
                  file=sys.stderr)
            _record_probe_verdict(False)
            return False
        if _probe_default_backend(timeout_s=min(90.0, max(left, 10.0))):
            _record_probe_verdict(True)
            return True
        left = deadline - time.monotonic()
        if left <= 0:
            print(f"bench: backend probe gave up after {attempt} attempts",
                  file=sys.stderr)
            _record_probe_verdict(False)
            return False
        print(
            f"bench: backend unreachable (attempt {attempt}), retrying in "
            f"{delay:.0f}s ({left:.0f}s budget left)",
            file=sys.stderr,
        )
        time.sleep(min(delay, left))
        delay = min(delay * 2, 120.0)


def _probe_short_circuit() -> str | None:
    """Reason to skip the backend probe entirely, or None to probe.

    The probe exists to keep a downed TPU *tunnel* from hanging the bench —
    but it burns up to ~8.5 min of retry backoff even when the caller
    already pinned a CPU backend (JAX_PLATFORMS=cpu in CI, local smoke
    runs), where no tunnel is involved and the probe can't learn anything.
    BENCH_SKIP_PROBE=1 is the unconditional escape hatch (assume the
    backend is reachable and go straight to measurement). The forced-outage
    test hook keeps priority: it owns the probe path deterministically."""
    if os.environ.get("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL"):
        return None
    if os.environ.get("BENCH_SKIP_PROBE"):
        return "BENCH_SKIP_PROBE=1"
    plats = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if plats and plats.split(",")[0].strip() == "cpu":
        return "JAX_PLATFORMS selects cpu"
    return None


def _emit(line: dict) -> None:
    print(json.dumps(line))


def write_json_atomic(path: str, obj: dict) -> None:
    """All evidence-artifact writers go through here: unique temp +
    os.replace so a watchdog kill mid-write can never truncate an
    already-captured artifact, and two concurrent writers (bench.py and the
    lab scripts share results/fp_microbench.json) can't interleave on one
    scratch file (the corrupt-read guards downstream are a second line of
    defense, not a license to write non-atomically). Newline-terminated so
    the committed file's final byte doesn't flap between writers."""
    import tempfile

    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


PIPELINE_DEPTH = 8


def host_pipeline_bench(
    n_registry: int = 1024,
    lanes: int = 256,
    trials: int = 20,
    seed: int = 77,
) -> dict:
    """Host half of the verify pipeline, measured on ANY backend (no verify
    kernel launches): per-launch cost of the zero-copy packer vs the old
    per-candidate loop at `lanes` candidates — for BOTH the range path
    (Handel's contiguous partitioner hulls) and the dense fallback
    (scattered signer sets) — the staging-handoff half of dispatch
    (`host_dispatch_ms`), a steady-state probe that pins the handoff to
    explicit transfers only (`jax.transfer_guard`), and the dedup hit rate
    of the service cache on a Handel-shaped duplicate-delivery trace.
    Returns the metric dict merged into the bench line: host_pack_ms,
    host_pack_loop_ms, host_pack_speedup, host_pack_dense_ms,
    host_dispatch_ms, no_transfer_steady_state, dedup_hit_rate.
    """
    import asyncio
    import threading  # noqa: F401  (parity with the service's test stubs)

    import jax
    import numpy as np

    from handel_tpu import native as nat
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops import bn254_ref as bn

    rng = random.Random(seed)
    sks = [rng.randrange(1, 1 << 20) for _ in range(n_registry)]
    pks = [
        BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * n_registry, sks)
    ]
    device = BN254Device(pks, batch_size=lanes)

    # Handel-realistic requests: contiguous partitioner ranges, <=8 holes
    sig = BN254Signature(bn.G1_GEN)
    requests = []
    for _ in range(lanes):
        size = rng.choice([n_registry // 8, n_registry // 4, n_registry // 2])
        lo = rng.randrange(0, n_registry - size)
        max_holes = min(9, max(1, size - 2))
        holes = set(
            rng.sample(range(lo + 1, lo + size - 1), rng.randrange(0, max_holes))
        )
        bs = BitSet(n_registry)
        for i in range(lo, lo + size):
            if i not in holes:
                bs.set(i, True)
        requests.append((bs, sig))
    # dense-fallback phase: scattered signer sets (> MISS_CAP hull holes)
    dense_requests = []
    for _ in range(lanes):
        bs = BitSet(n_registry)
        for i in rng.sample(range(n_registry), n_registry // 4):
            bs.set(i, True)
        dense_requests.append((bs, sig))

    def p50(pack, reqs):
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            pack(reqs)
            ts.append((time.perf_counter() - t0) * 1000.0)
        return float(np.percentile(ts, 50))

    # phase boundaries reset the device's cumulative host counters so no
    # phase inherits the previous phase's accumulation
    device.reset_host_counters()
    pack_vec_ms = p50(device._pack_requests, requests)
    pack_loop_ms = p50(device._pack_requests_loop, requests)
    device.reset_host_counters()
    pack_dense_ms = p50(device._pack_requests, dense_requests)
    device.reset_host_counters()

    def stage_p50(reqs):
        ts = []
        for _ in range(trials):
            plan = device._pack_requests(reqs)
            t0 = time.perf_counter()
            device._stage_plan(plan)
            ts.append((time.perf_counter() - t0) * 1000.0)
        return float(np.percentile(ts, 50))

    dispatch_ms = stage_p50(requests)

    # steady-state no-transfer probe: with implicit host->device transfers
    # disallowed, a warm pack+stage cycle must run clean (registry/prefix
    # are device-resident; staging moves via explicit jax.device_put only)
    try:
        with jax.transfer_guard_host_to_device("disallow"):
            device._stage_plan(device._pack_requests(requests))
            device._stage_plan(device._pack_requests(dense_requests))
        no_implicit = 1.0
    except Exception as e:
        print(f"bench: steady-state transfer probe tripped: {e}",
              file=sys.stderr)
        no_implicit = 0.0
    device.reset_host_counters()

    # dedup hit rate over a multi-peer delivery trace: 32 distinct winning
    # aggregates, each re-delivered by 8 peers, shuffled — the shape
    # processing.go re-verifies in full and the cache short-circuits
    class _StubDevice:
        batch_size = lanes

        def dispatch(self, msg, reqs):
            return len(reqs)

        def fetch(self, handle):
            return [True] * handle

    distinct, fanout = min(32, lanes), 8
    deliveries = list(range(distinct)) * fanout
    rng.shuffle(deliveries)

    async def dedup_trace():
        from handel_tpu.parallel.batch_verifier import BatchVerifierService

        svc = BatchVerifierService(_StubDevice(), max_delay_ms=0.1)
        for i in deliveries:
            await svc.verify(b"bench", [], [requests[i]])
        vals = svc.values()
        svc.stop()
        return vals

    vals = asyncio.run(dedup_trace())
    return {
        "host_pack_ms": round(pack_vec_ms, 3),
        "host_pack_loop_ms": round(pack_loop_ms, 3),
        "host_pack_speedup": round(pack_loop_ms / pack_vec_ms, 2)
        if pack_vec_ms > 0
        else None,
        "host_pack_dense_ms": round(pack_dense_ms, 3),
        "host_dispatch_ms": round(dispatch_ms, 3),
        "no_transfer_steady_state": no_implicit,
        "dedup_hit_rate": round(vals["dedupHitRate"], 4),
    }


def service_bench(
    sessions: int = 8,
    nodes: int = 16,
    batch_size: int = 64,
    timeout_s: float = 120.0,
) -> dict:
    """Multi-tenant service sustained rate (ROADMAP item 3): K concurrent
    fake-crypto sessions share one BatchVerifierService and its coalesced
    launches; reports sustained completed aggregations per second, the p99
    session-completion latency under that concurrency, and the per-launch
    lane fill ratio the cross-session coalescing achieves. Protocol-layer
    and backend-independent (no kernels) — the 64x128 capture form runs
    through `sim serve` (results/handel_service_64.json); this in-bench
    shape keeps the metric fresh every round without minutes of wall.
    Returns {aggregates_per_s, session_p99_s, launch_fill_ratio}.
    """
    import asyncio

    from handel_tpu.service.driver import MultiSessionCluster

    async def go():
        cluster = MultiSessionCluster(
            sessions, nodes, batch_size=batch_size
        )
        try:
            return await cluster.run(timeout_s)
        finally:
            cluster.stop()

    summary = asyncio.run(go())
    if summary["completed"] != sessions:
        # a partial run must not publish a flattering rate
        print(
            f"bench: service bench completed {summary['completed']}/"
            f"{sessions} sessions",
            file=sys.stderr,
        )
        return {}
    return {
        "aggregates_per_s": summary["aggregates_per_s"],
        "session_p99_s": summary["session_p99_s"],
        "launch_fill_ratio": summary["launch_fill_ratio"],
    }


def _service_metrics() -> dict:
    """service_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_SERVICE_SHAPE =
    'sessions,nodes,batch')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_SERVICE_SHAPE")
    try:
        if shape:
            sessions, nodes, batch = (int(x) for x in shape.split(","))
            return service_bench(sessions, nodes, batch)
        return service_bench()
    except Exception as e:
        print(f"bench: service bench failed: {e}", file=sys.stderr)
        return {}


def fleet_bench(
    devices: int = 8,
    requests_n: int = 160,
    batch_size: int = 4,
    launch_ms: float = 8.0,
    timeout_s: float = 60.0,
) -> dict:
    """Fleet-of-chips verify plane: K-lane DevicePlane throughput vs an
    identical 1-lane baseline under a flood of distinct aggregates. The
    launch wall is simulated by HostDevice.launch_ms so what's measured is
    the plane scheduler (least-loaded pick, per-lane queues overlapping
    dispatch), not crypto — the per-chip crypto figure is the headline
    above. Reports launches/s for the fleet, the speedup over the 1-lane
    run (the no-idle-while-queued claim: with launch wall dominating, K
    lanes must approach Kx), the fleet's per-launch fill, and the
    scheduler's idle-violation audit counter (a pick that left a queued
    batch while an idle lane existed — must stay 0).
    """
    import asyncio
    import concurrent.futures

    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.models.fake import FakePublic, FakeSignature
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.parallel.plane import host_plane

    pks = [FakePublic(True) for _ in range(16)]

    def reqs():
        out = []
        for i in range(requests_n):
            bs = BitSet(16)
            bs.set(i % 16, True)
            # distinct message per request: no dedup/coalescing — every
            # request is a real candidate the plane must launch
            out.append((i.to_bytes(4, "big"), (bs, FakeSignature(True))))
        return out

    async def run(k: int) -> tuple[float, dict]:
        # a 1-core default executor (5 threads) would cap lane overlap
        # below the plane width — give the loop enough threads that every
        # lane's dispatch and fetch can be in flight at once
        loop = asyncio.get_running_loop()
        loop.set_default_executor(
            concurrent.futures.ThreadPoolExecutor(max_workers=2 * k + 4)
        )
        plane = host_plane(
            FakeScheme().constructor,
            k,
            batch_size=batch_size,
            launch_ms=launch_ms,
        )
        svc = BatchVerifierService(plane, max_delay_ms=0.2)
        try:
            t0 = time.perf_counter()
            verdicts = await asyncio.wait_for(
                asyncio.gather(
                    *(
                        svc.verify(msg, pks, [r], session=f"s{i % 8}")
                        for i, (msg, r) in enumerate(reqs())
                    )
                ),
                timeout_s,
            )
            wall = time.perf_counter() - t0
            if not all(v == [True] for v in verdicts):
                raise RuntimeError("fleet bench verdict mismatch")
            vals = svc.values()
            vals["_wall_s"] = wall
            return wall, vals
        finally:
            svc.stop()

    base_wall, base_vals = asyncio.run(run(1))
    fleet_wall, fleet_vals = asyncio.run(run(devices))
    base_rate = base_vals["verifierLaunches"] / base_wall
    fleet_rate = fleet_vals["verifierLaunches"] / fleet_wall
    return {
        "launches_per_s": round(fleet_rate, 2),
        "fleet_speedup_x": round(fleet_rate / base_rate, 2)
        if base_rate > 0
        else None,
        "fleet_fill_ratio": round(fleet_vals["launchFillRatio"], 4),
        "fleet_idle_violations": int(fleet_vals["schedIdleViolations"]),
        "fleet_devices": int(fleet_vals["devicesTotal"]),
    }


def small_batch_bench(
    devices: int = 8,
    rounds: int = 20,
    batch: int = 64,
    per_candidate_ms: float = 1.0,
    timeout_s: float = 60.0,
) -> dict:
    """Mesh latency plane: p50 verify latency of SMALL gold-tier launches
    riding the whole-mesh lane (parallel/mesh_plane.py) vs an identical-code
    single-device mesh lane. Where fleet_bench floods the throughput path
    with distinct aggregates, this bench issues one small launch group at a
    time — the regime where K per-chip lanes can't help (one launch lands
    on one chip) but one K-device mesh launch cuts the wall ~K/2x. The
    engine is HostMeshDevice: real verdict math + real threads, simulated
    per-candidate wall (per_candidate_ms each, sharded over `devices`
    workers, plus a serial collective share) — the measured quantity is the
    dual-mode routing plus genuine intra-launch parallelism, thread
    contention and Amdahl included. Both runs go through the full service
    latency path (gold tier -> ModePolicy -> pick_mesh), so the speedup is
    the contract the MULTICHIP smoke gates: > 1x, approaching K/2 at
    batch <= 64.
    """
    import asyncio
    import concurrent.futures

    import numpy as np

    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.models.fake import FakePublic, FakeSignature
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.parallel.mesh_plane import (
        ModePolicy,
        enable_latency_plane,
        host_mesh_engine,
    )
    from handel_tpu.parallel.plane import host_plane

    # registry as wide as the batch so every candidate in a round is a
    # DISTINCT bitset — the dedup layer must not shrink the launch group
    # under the bench's feet
    n_keys = max(16, batch)
    pks = [FakePublic(True) for _ in range(n_keys)]

    async def run(k: int) -> tuple[float, dict]:
        loop = asyncio.get_running_loop()
        loop.set_default_executor(
            concurrent.futures.ThreadPoolExecutor(max_workers=2 * k + 4)
        )
        # one throughput lane (never picked here — every group is small +
        # gold) plus the mesh lane under test; k=1 is the baseline with
        # the exact same code path
        plane = host_plane(FakeScheme().constructor, 1, batch_size=64)
        svc = BatchVerifierService(plane, max_delay_ms=0.2)
        enable_latency_plane(
            svc,
            host_mesh_engine(
                FakeScheme().constructor,
                devices=k,
                batch_size=64,
                per_candidate_ms=per_candidate_ms,
            ),
            policy=ModePolicy(small_batch_max=64, latency_tiers=("gold",)),
        )
        svc.queue.set_tier("gold0", "gold")
        walls = []
        try:
            for r in range(rounds):
                msg = r.to_bytes(4, "big")
                reqs = []
                for i in range(batch):
                    bs = BitSet(n_keys)
                    bs.set(i % n_keys, True)
                    reqs.append((bs, FakeSignature(True)))
                t0 = time.perf_counter()
                verdicts = await asyncio.wait_for(
                    asyncio.gather(
                        *(
                            svc.verify(msg, pks, [q], session="gold0")
                            for q in reqs
                        )
                    ),
                    timeout_s,
                )
                walls.append((time.perf_counter() - t0) * 1000.0)
                if not all(v == [True] for v in verdicts):
                    raise RuntimeError("small-batch bench verdict mismatch")
            return float(np.percentile(walls, 50)), svc.values()
        finally:
            svc.stop()

    mesh_p50, mesh_vals = asyncio.run(run(devices))
    base_p50, base_vals = asyncio.run(run(1))
    if mesh_vals["modeLatencyLaunches"] < rounds:
        raise RuntimeError(
            "small-batch bench groups leaked off the latency path: "
            f"{mesh_vals['modeLatencyLaunches']:.0f}/{rounds} rode the mesh"
        )
    return {
        "small_batch_verify_p50_ms": round(mesh_p50, 3),
        "small_batch_baseline_p50_ms": round(base_p50, 3),
        "small_batch_speedup_x": round(base_p50 / mesh_p50, 2)
        if mesh_p50 > 0
        else None,
        "small_batch_mesh_devices": devices,
        "small_batch_n": batch,
        "small_batch_latency_launches": int(
            mesh_vals["modeLatencyLaunches"]
        ),
        "small_batch_mesh_fallbacks": int(mesh_vals["meshFallbacks"]),
    }


def _small_batch_metrics() -> dict:
    """small_batch_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_SMALL_BATCH_SHAPE =
    'devices,rounds,batch')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_SMALL_BATCH_SHAPE")
    try:
        if shape:
            devices, rounds, batch = (int(x) for x in shape.split(","))
            return small_batch_bench(devices, rounds, batch)
        return small_batch_bench()
    except Exception as e:
        print(f"bench: small-batch bench failed: {e}", file=sys.stderr)
        return {}


def swarm_bench(
    identities: int = 512,
    batch_size: int = 64,
    timeout_s: float = 120.0,
) -> dict:
    """Virtual-node swarm runtime (ROADMAP swarm item): one SwarmHost
    multiplexing `identities` Handel instances as vnodes on a single event
    loop — the in-process form of the `sim swarm` capture
    (results/swarm_65536_summary.json). Reports the committee size carried,
    summed-RSS bytes per identity (the 1M-identity extrapolation basis),
    and the wall until the LAST member held a threshold signature. Returns
    {} unless every vnode finished — a partial swarm must not publish a
    flattering memory figure.
    """
    import asyncio

    from handel_tpu.swarm.driver import SwarmHost, merge_summaries

    async def go():
        host = SwarmHost(identities, 0, identities, batch_size=batch_size)
        return await host.run(timeout_s)

    m = merge_summaries([asyncio.run(go())])
    if not m["ok"]:
        print(
            f"bench: swarm bench completed {m['completed']}/{identities} "
            "vnodes",
            file=sys.stderr,
        )
        return {}
    return {
        "swarm_identities": m["swarm_identities"],
        "mem_bytes_per_identity": m["mem_bytes_per_identity"],
        "swarm_time_to_threshold_s": m["swarm_time_to_threshold_s"],
    }


def _swarm_metrics() -> dict:
    """swarm_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_SWARM_SHAPE =
    'identities,batch')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_SWARM_SHAPE")
    try:
        if shape:
            identities, batch = (int(x) for x in shape.split(","))
            return swarm_bench(identities, batch)
        return swarm_bench()
    except Exception as e:
        print(f"bench: swarm bench failed: {e}", file=sys.stderr)
        return {}


def federation_bench(
    rate_sps: float = 5.0,
    duration_s: float = 8.0,
    nodes: int = 6,
) -> dict:
    """Geo-federated open-loop robustness (service/federation.py driven by
    sim/load.py): a seeded Poisson arrival clock against a 3-region
    federation with a mid-run region kill + epoch-path recovery. Reports
    the gold-tier open-loop arrival->verdict p99, the kill-to-first-
    post-recovery-completion wall, and the fraction of arrivals that
    spilled to a non-nearest region. This in-bench shape keeps the three
    SIDE_METRICS fresh every round; the 10-minute capture form runs
    through `sim load` (results/federation_report.json). Returns {} unless
    every report check held — a run that dropped work or never recovered
    must not publish a flattering p99.
    """
    import asyncio

    from handel_tpu.sim.config import FederationParams, LoadParams
    from handel_tpu.sim.load import LoadRun

    lp = LoadParams(
        rate_sps=rate_sps, duration_s=duration_s, nodes=nodes, seed=7
    )
    fp = FederationParams(
        kill_region="us-east", session_ttl_s=15.0,
        trace_capacity=1 << 14,
    )
    report = asyncio.run(LoadRun(lp, fp).run())
    if not report["ok"]:
        failed = [k for k, v in report["checks"].items() if not v]
        print(
            f"bench: federation bench checks failed: {failed}",
            file=sys.stderr,
        )
        return {}
    return {
        "open_loop_p99_s": report["open_loop_p99_s"],
        "region_recovery_s": report["region_recovery_s"],
        "spillover_rate": report["spillover_rate"],
    }


def _federation_metrics() -> dict:
    """federation_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_FEDERATION_SHAPE =
    'rate_sps,duration_s,nodes')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_FEDERATION_SHAPE")
    try:
        if shape:
            rate, duration, nodes = shape.split(",")
            return federation_bench(
                float(rate), float(duration), int(nodes)
            )
        return federation_bench()
    except Exception as e:
        print(f"bench: federation bench failed: {e}", file=sys.stderr)
        return {}


def _fleet_metrics() -> dict:
    """fleet_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_FLEET_SHAPE =
    'devices,requests,batch')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_FLEET_SHAPE")
    try:
        if shape:
            devices, requests_n, batch = (int(x) for x in shape.split(","))
            return fleet_bench(devices, requests_n, batch)
        return fleet_bench()
    except Exception as e:
        print(f"bench: fleet bench failed: {e}", file=sys.stderr)
        return {}


def rollup_bench(
    hosts: int = 4,
    vnodes: int = 1024,
    rounds: int = 20,
) -> dict:
    """Hierarchical roll-up plane (obs/rollup.py): `hosts` HostRollups
    each folding `vnodes` reporter surfaces emit changed-keys deltas into
    one master FleetRollup for `rounds` emission intervals. Reports the
    master's merged series count (the O(hosts) contract: flat across
    vnode sweeps), the wire bytes per host per emission interval (the
    1 Hz default cadence makes that bytes/host/s), and the master-side
    merge wall — all three must stay flat as identities scale.
    """
    import random as _random

    from handel_tpu.core.trace import LogHistogram
    from handel_tpu.obs.rollup import FleetRollup, HostRollup

    rng = _random.Random(13)
    fleet = FleetRollup(top_k=8, clock=lambda: 0.0)
    states = []
    hrs = []
    for h in range(hosts):
        state = [
            {"msgSentCt": 0.0, "verifiedCt": 0.0, "levelRate": 0.0}
            for _ in range(vnodes)
        ]
        states.append(state)

        hist = LogHistogram()

        class _Rep:
            def __init__(self, state, hist):
                self.state = state
                self.hist = hist

            def values(self):
                return {"launchesCt": float(sum(
                    v["verifiedCt"] for v in self.state))}

            def gauge_keys(self):
                return set()

            def histograms(self):
                return {"verifyLatencyS": self.hist}

        hr = HostRollup(f"bench{h}", clock=lambda: 0.0)
        hr.attach_fold(
            "swarm",
            lambda state=state: ((v, {"levelRate"}) for v in state),
        )
        hr.attach_reporter("device", _Rep(state, hist))
        hrs.append((hr, hist))
    for _ in range(rounds):
        for h in range(hosts):
            for v in states[h]:
                v["msgSentCt"] += rng.randrange(1, 8)
                v["verifiedCt"] += rng.randrange(0, 4)
                v["levelRate"] = rng.randrange(0, 64) / 8.0
            hrs[h][1].add(rng.randrange(1, 1 << 16) / 1e6)
            hrs[h][0].emit(fleet.ingest)
    series = fleet.series_count()  # refreshes last_merge_ms too
    return {
        "fleet_series_count": series,
        "rollup_bytes_per_host_s": round(
            fleet.ingest_bytes / hosts / rounds, 1
        ),
        "fleet_eval_ms": round(fleet.last_merge_ms, 3),
    }


def _rollup_metrics() -> dict:
    """rollup_bench behind the degrade-don't-die contract (+ a shape
    override for tests: HANDEL_TPU_BENCH_ROLLUP_SHAPE =
    'hosts,vnodes,rounds')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_ROLLUP_SHAPE")
    try:
        if shape:
            hosts, vnodes, rounds = (int(x) for x in shape.split(","))
            return rollup_bench(hosts, vnodes, rounds)
        return rollup_bench()
    except Exception as e:
        print(f"bench: rollup bench failed: {e}", file=sys.stderr)
        return {}


def rlc_bench(batch: int = 64, messages: int = 4, trials: int = 5) -> dict:
    """Random-linear-combination batch verification (models/rlc.py) vs the
    per-candidate pairing loop, host math path: one launch of `batch`
    candidates over `messages` distinct messages, checked both ways.

    Both modes ride every bench line: `rlc_per_candidate_p50_ms` is the C
    independent 2-pairing checks (the per_candidate device contract),
    `rlc_verify_p50_ms` is the single combined check — two MSMs plus one
    M+1-Miller-loop product pairing — and `rlc_speedup_x` is their ratio
    (acceptance: >= 3x at batch 64). Aggregation cost is excluded from
    both sides: the apk is built once up front, exactly what a device
    launch stages, so the ratio isolates the pairing-tail change."""
    rng = random.Random(4096)

    from statistics import median

    from handel_tpu.core.bitset import BitSet
    from handel_tpu.models import rlc
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()
    n = 16
    keys = [scheme.keygen(i) for i in range(n)]
    pubs = [pk for _, pk in keys]
    msgs = [f"rlc-bench-{m}".encode() for m in range(messages)]
    cands = []
    for j in range(batch):
        msg = msgs[j % messages]
        bs = BitSet(n)
        sig = None
        for i in rng.sample(range(n), rng.randrange(2, 6)):
            bs.set(i)
            s = keys[i][0].sign(msg)
            sig = s if sig is None else sig.combine(s)
        apk = scheme.constructor.aggregate_public_keys(pubs, bs)
        cands.append((msg, apk.point, sig.point))

    ops = rlc.host_ops_for(scheme.constructor)
    pc_times, rlc_times = [], []
    for _ in range(trials):
        t0 = time.perf_counter()
        for msg, x, s in cands:
            assert ops.pairing_check(
                [(ops.hash_to_g1(msg), x), (ops.g1_neg(s), ops.g2_gen)]
            )
        pc_times.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        assert rlc.host_rlc_check(ops, cands)
        rlc_times.append((time.perf_counter() - t0) * 1e3)
    pc_p50, rlc_p50 = median(pc_times), median(rlc_times)
    return {
        "rlc_verify_p50_ms": round(rlc_p50, 3),
        "rlc_per_candidate_p50_ms": round(pc_p50, 3),
        "rlc_speedup_x": round(pc_p50 / rlc_p50, 2),
        "rlc_batch": batch,
        "rlc_messages": messages,
    }


def _rlc_metrics() -> dict:
    """rlc_bench behind the degrade-don't-die contract (+ a shape override
    for tests: HANDEL_TPU_BENCH_RLC_SHAPE = 'batch,messages,trials')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_RLC_SHAPE")
    try:
        if shape:
            batch, messages, trials = (int(x) for x in shape.split(","))
            return rlc_bench(batch, messages, trials)
        return rlc_bench()
    except Exception as e:
        print(f"bench: rlc bench failed: {e}", file=sys.stderr)
        return {}


def _host_metrics() -> dict:
    """host_pipeline_bench behind the bench's degrade-don't-die contract
    (+ a shape override for tests: HANDEL_TPU_BENCH_HOST_SHAPE =
    'registry,lanes,trials')."""
    shape = os.environ.get("HANDEL_TPU_BENCH_HOST_SHAPE")
    try:
        if shape:
            n_registry, lanes, trials = (int(x) for x in shape.split(","))
            return host_pipeline_bench(n_registry, lanes, trials)
        return host_pipeline_bench()
    except Exception as e:
        print(f"bench: host pipeline bench failed: {e}", file=sys.stderr)
        return {}


def measure_pipelined(launch, block, trials: int, depth: int = PIPELINE_DEPTH):
    """Sustained per-launch latency, ms: dispatch `depth` launches
    back-to-back and block only on the last (the chip executes in order, so
    the last completing implies all did) — the per-dispatch tunnel round
    trip then overlaps on-chip compute of the queued launches, which is how
    production traffic flows through the two-stage BatchVerifierService
    (parallel/batch_verifier.py). ONE copy of the methodology: bench.py and
    scripts/verify_profile.py must publish figures measured identically.
    """
    rs = [launch() for _ in range(depth)]
    block(rs[-1])  # warm
    out = []
    for _ in range(trials):
        t0 = time.perf_counter()
        rs = [launch() for _ in range(depth)]
        block(rs[-1])
        out.append((time.perf_counter() - t0) * 1000.0 / depth)
    return out


def _emit_persisted_or_smoke() -> bool:
    """Fallback path when no accelerator is reachable: re-emit the round's
    persisted TPU artifact if one exists. Returns True if emitted."""
    try:
        with open(ARTIFACT) as f:
            art = json.load(f)
        if art.get("backend") not in (None, "cpu"):
            line = {
                "metric": art["metric"],
                "value": art["value"],
                "unit": art["unit"],
                "vs_baseline": art.get("vs_baseline"),
                "source": "persisted",
                "backend": art.get("backend"),
                "captured_at": art.get("captured_at"),
            }
            # the pipelined sustained-rate figures ride the same
            # outage-persistence contract as the headline p50
            for k in ("pipelined_p50_ms", "pipelined_vs_baseline"):
                if k in art:
                    line[k] = art[k]
            _emit(line)
            return True
    except (OSError, ValueError, KeyError):
        pass
    return False


def build_problem(
    curves,
    n_registry: int,
    lanes: int,
    n_candidates: int,
    ref=None,
    g1_mul_batch=None,
    g2_mul_batch=None,
    miss_k: int = 8,
    seed: int = 2024,
):
    """Handel-realistic candidate batch: contiguous partitioner ranges with a
    few offline holes, exactly the traffic `batch_verify` sees. Returns the
    range-kernel argument tuple (lo, hi, miss_idx, miss_ok, sig, h, valid)
    plus the keypair material.

    Curve-parametric (scripts/bench_bls12.py reuses it for BLS12-381):
    `ref` is the scalar-oracle module (G1_GEN/G2_GEN/R) and the *_mul_batch
    hooks do host keygen — defaults are BN254 through the native C++ path.
    """
    import jax.numpy as jnp
    import numpy as np

    if ref is None:
        from handel_tpu import native as nat
        from handel_tpu.ops import bn254_ref as ref

        g1_mul_batch = nat.g1_mul_batch
        g2_mul_batch = nat.g2_mul_batch
    bn = ref

    rng = random.Random(seed)
    # small scalars keep host-side keygen fast; verification cost on device
    # is independent of scalar magnitude
    sks = [rng.randrange(1, 1 << 30) for _ in range(n_registry)]
    pks = g2_mul_batch([bn.G2_GEN] * n_registry, sks)
    h = g1_mul_batch([bn.G1_GEN], [rng.randrange(1, bn.R)])[0]

    lo = np.zeros((lanes,), np.int32)
    hi = np.zeros((lanes,), np.int32)
    miss_idx = np.zeros((miss_k, lanes), np.int64)
    miss_ok = np.zeros((miss_k, lanes), dtype=bool)
    agg_sks = []
    for j in range(n_candidates):
        size = rng.choice([n_registry // 8, n_registry // 4, n_registry // 2])
        lo[j] = rng.randrange(0, n_registry - size)
        hi[j] = lo[j] + size
        max_holes = min(miss_k, size - 1)  # leave at least one signer
        holes = sorted(
            rng.sample(
                range(int(lo[j]), int(hi[j])),
                rng.randrange(0, max_holes) if max_holes > 0 else 0,
            )
        )
        miss_idx[: len(holes), j] = holes
        miss_ok[: len(holes), j] = True
        signers = set(range(int(lo[j]), int(hi[j]))) - set(holes)
        agg_sks.append(sum(sks[i] for i in signers) % bn.R)
    sig_pts = g1_mul_batch([h] * n_candidates, agg_sks)
    sig_pts += [bn.G1_GEN] * (lanes - n_candidates)

    F = curves.F
    valid = np.zeros((lanes,), dtype=bool)
    valid[:n_candidates] = True
    return (
        pks,
        miss_k,
        (
            jnp.asarray(lo),
            jnp.asarray(hi),
            jnp.asarray(miss_idx.reshape(-1)),
            jnp.asarray(miss_ok.reshape(-1)),
            F.pack([p[0] for p in sig_pts]),
            F.pack([p[1] for p in sig_pts]),
            F.pack([h[0]]),
            F.pack([h[1]]),
            jnp.asarray(valid),
        ),
    )


def _fp_microbench() -> None:
    """Capture the ops/fp.py throughput figure as a persisted artifact
    (round-2 verdict, "What's weak" #5: the ~150M mults/s docstring claim
    had no in-repo capture). Measures BOTH Field backends (CIOS and RNS)
    under the same chained-dispatch methodology: the legacy headline keys
    stay CIOS (history continuity), and a per-fp_backend "records" list
    carries one `mont_muls_per_s` row each for scripts/bench_check.py's
    like-for-like gate (a CIOS row never judges an RNS row)."""
    import contextlib

    import jax

    from handel_tpu.ops.fp import _throughput_bench

    batch = int(os.environ.get("HANDEL_TPU_BENCH_FP_BATCH", str(1 << 18)))
    measured = {}
    with contextlib.redirect_stdout(sys.stderr):
        # the microbench prints a human line; stdout is reserved for the
        # single headline JSON line
        for fp_backend in ("cios", "rns"):
            measured[fp_backend] = _throughput_bench(
                batch=batch, trials=3, backend=fp_backend
            )
    rate, floor = measured["cios"]
    if all(r <= 0 for r, _ in measured.values()) and os.path.exists(
        FP_ARTIFACT
    ):
        # a failed slope measurement must not erase previously captured
        # valid evidence (same resilience contract as the main artifact)
        print(
            "bench: fp microbench slope unmeasurable; keeping the existing "
            f"artifact {FP_ARTIFACT}",
            file=sys.stderr,
        )
        return
    os.makedirs(os.path.dirname(FP_ARTIFACT), exist_ok=True)
    # carry forward side-channel captures (scripts/mxu_limb_lab.py merges
    # an "mxu_lab" entry into this artifact) and the batch-scaling
    # reconciliation note: overwriting with only our own keys would
    # destroy captured evidence
    extra = {}
    if os.path.exists(FP_ARTIFACT):
        try:
            with open(FP_ARTIFACT) as f:
                prev = json.load(f)
            extra = {k: prev[k] for k in ("mxu_lab", "note") if k in prev}
        except (json.JSONDecodeError, OSError):
            pass
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    records = [
        {
            "metric": "mont_muls_per_s",
            "value": round(r / 1e6, 1),
            "invalid_measurement": r <= 0,
            "unit": "M muls/s",
            "dispatch_floor_ms": round(f * 1e3, 1),
            "backend": jax.default_backend(),
            "fp_backend": fp_backend,
            "batch": batch,
            "captured_at": now,
            # the reconciliation note travels with every new record so a
            # reader of one row still sees the one-number story
            **({"note": extra["note"]} if "note" in extra else {}),
        }
        for fp_backend, (r, f) in measured.items()
    ]
    write_json_atomic(
        FP_ARTIFACT,
        {
            "metric": "fp254_mont_mul_throughput_marginal",
            "value": round(rate / 1e6, 1),
            # rate 0.0 = the marginal slope was not measurable (timing
            # noise at this batch); an explicit marker, never a made-up
            # number (_throughput_bench retries once, then gives up)
            "invalid_measurement": rate <= 0,
            "unit": "M muls/s",
            "dispatch_floor_ms": round(floor * 1e3, 1),
            "backend": jax.default_backend(),
            "batch": batch,
            "captured_at": now,
            "device": str(jax.devices()[0]),
            "records": records,
            **extra,
        },
    )


def _pairing_bench() -> None:
    """Capture the full-pairing wall per Field backend plus the residue
    conversion count per pairing (residue-resident pairing, ops/rns.py /
    ops/pairing.py). Two record families in results/pairing_bench.json:

    - `pairing_p50_ms`, one row per fp_backend ("cios", "rns"): p50 wall
      of a jitted batch-4 `BN254Pairing.pairing` launch. Registered in
      scripts/bench_check.py SIDE_METRICS and PER_FP_BACKEND, so a CIOS
      row gates only against CIOS history (cross-backend judgment
      refused, same rule as mont_muls_per_s).
    - `rns_conversions_per_pairing` (rns only): CRT boundary crossings
      counted at TRACE time (`RnsField.conversion_counts`). The resident
      form converts O(line boundaries) per pairing — points in, f12 out —
      where the legacy form round-trips once per tower mul; the legacy
      trace count rides the same row as `legacy_per_mul` so the drop is
      one visible number.
    """
    import contextlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.ops.pairing import BN254Pairing

    B = 4
    trials = int(os.environ.get("HANDEL_TPU_BENCH_PAIRING_TRIALS", "5"))
    rng = random.Random(1307)
    g1s = [bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R)) for _ in range(B)]
    g2s = [bn.g2_mul(bn.G2_GEN, rng.randrange(1, bn.R)) for _ in range(B)]
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    records = []
    with contextlib.redirect_stdout(sys.stderr):
        for fp_backend in ("cios", "rns"):
            curves = BN254Curves(backend=fp_backend)
            pr = BN254Pairing(curves)
            xp = curves.F.pack([p[0] for p in g1s])
            yp = curves.F.pack([p[1] for p in g1s])
            xq = curves.T.f2_pack([q[0] for q in g2s])
            yq = curves.T.f2_pack([q[1] for q in g2s])
            args = ((xp, yp), (xq, yq))
            fn = jax.jit(lambda p, q: pr.pairing(p, q))
            jax.block_until_ready(fn(*args))  # compile + warm
            times = []
            for _ in range(trials):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                times.append((time.perf_counter() - t0) * 1e3)
            records.append(
                {
                    "metric": "pairing_p50_ms",
                    "value": round(float(np.percentile(times, 50)), 3),
                    "unit": "ms",
                    "backend": jax.default_backend(),
                    "fp_backend": fp_backend,
                    "batch": B,
                    "trials": trials,
                    "captured_at": now,
                }
            )
            if fp_backend != "rns":
                continue
            # conversion counters increment at trace time — eval_shape is
            # enough, no compile. Construct the legacy (non-resident)
            # pairing BEFORE resetting so its gamma re-packs don't pollute
            # the count.
            legacy = BN254Pairing(curves, resident=False)
            F = curves.F
            F.reset_conversion_counts()
            jax.eval_shape(lambda p, q: pr.pairing(p, q), args[0], args[1])
            resident_n = F.conversion_counts()["total"]
            F.reset_conversion_counts()
            jax.eval_shape(
                lambda p, q: legacy.pairing(p, q), args[0], args[1]
            )
            legacy_n = F.conversion_counts()["total"]
            records.append(
                {
                    "metric": "rns_conversions_per_pairing",
                    "value": resident_n,
                    "unit": "CRT boundary crossings per pairing trace",
                    "backend": jax.default_backend(),
                    "fp_backend": fp_backend,
                    "legacy_per_mul": legacy_n,
                    "batch": B,
                    "captured_at": now,
                }
            )
    os.makedirs(os.path.dirname(PAIRING_ARTIFACT), exist_ok=True)
    write_json_atomic(
        PAIRING_ARTIFACT,
        {
            "metric": "pairing_bench",
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "batch": B,
            "captured_at": now,
            "records": records,
        },
    )


def main() -> None:
    """Parent process: probe, then run the measurement in a watchdogged child.

    The tunnel can drop AFTER a successful probe — `import jax`/compile/launch
    then hang forever rather than erroring — so the measurement itself runs in
    a subprocess with a hard timeout (HANDEL_TPU_MEASURE_BUDGET_S, default
    20 min to absorb cold compiles). On any child failure the persisted
    artifact (or an honest CPU smoke) still produces the line.
    """
    if os.environ.get("HANDEL_TPU_BENCH_CHILD"):
        _measure()
        return

    skip_reason = (
        None if os.environ.get("HANDEL_TPU_PLATFORM")
        else _probe_short_circuit()
    )
    if skip_reason:
        print(f"bench: backend probe skipped ({skip_reason})",
              file=sys.stderr)
        if skip_reason.startswith("JAX_PLATFORMS"):
            # pin through the config API too: the environment's
            # sitecustomize overrides the env var, and a cpu-tier run must
            # never accidentally dial the tunnel
            os.environ["HANDEL_TPU_PLATFORM"] = "cpu"
            _measure()  # CPU smoke inline: no tunnel, no hang risk
            return
    elif not os.environ.get("HANDEL_TPU_PLATFORM") and not _probe_with_retries():
        # TPU tunnel down: force CPU through the config API (the env var
        # alone is overridden by the environment's sitecustomize)
        os.environ["HANDEL_TPU_PLATFORM"] = "cpu"
        print("bench: default backend unreachable, falling back to CPU",
              file=sys.stderr)
        if _emit_persisted_or_smoke():
            return
        _measure()  # CPU smoke inline: no tunnel, no hang risk
        return

    budget = float(os.environ.get("HANDEL_TPU_MEASURE_BUDGET_S", "1200"))
    env = dict(os.environ, HANDEL_TPU_BENCH_CHILD="1")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            timeout=budget,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        r = None
        print(f"bench: measurement child hung past {budget:.0f}s, killed",
              file=sys.stderr)
    if r is not None:
        sys.stderr.write(r.stderr)
        if r.returncode == 0 and r.stdout.strip():
            sys.stdout.write(r.stdout)
            return
        print(f"bench: measurement child failed (rc={r.returncode})",
              file=sys.stderr)
    # child died or hung: surface whatever evidence exists. Drop the
    # force-shape hook first — if IT killed the child (bad value), the
    # inline fallback must still record an honest smoke line
    os.environ.pop("HANDEL_TPU_BENCH_FORCE_ACCEL_SHAPE", None)
    if not _emit_persisted_or_smoke():
        os.environ["HANDEL_TPU_PLATFORM"] = "cpu"
        _measure()


def _measure() -> None:
    from handel_tpu.utils.jaxenv import apply_platform_env

    apply_platform_env()  # no-op when HANDEL_TPU_PLATFORM is unset
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import numpy as np

    from handel_tpu.models.bn254 import BN254PublicKey
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops.curve import BN254Curves

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    # test hook: exercise the FULL accelerator measurement path (persist,
    # provenance, vs_baseline ratio) on the CPU backend with tiny sizes —
    # this plumbing must not wait for a live tunnel to get its first run
    # (tests/test_bench.py; round-3 verdict "What's weak" #1)
    force_shape = os.environ.get("HANDEL_TPU_BENCH_FORCE_ACCEL_SHAPE")
    if force_shape:
        if not os.environ.get("HANDEL_TPU_BENCH_ARTIFACT"):
            # a forced run writing the DEFAULT artifact path would clobber
            # the real captured TPU evidence with a cpu-backend record
            print(
                "bench: HANDEL_TPU_BENCH_FORCE_ACCEL_SHAPE requires "
                "HANDEL_TPU_BENCH_ARTIFACT to protect results/bench_tpu.json",
                file=sys.stderr,
            )
            raise SystemExit(2)
        try:
            n_registry, lanes, n_candidates, trials = (
                int(x) for x in force_shape.split(",")
            )
            if min(n_registry, lanes, n_candidates, trials) < 1:
                raise ValueError("all fields must be >= 1")
        except ValueError as e:
            print(
                f"bench: bad HANDEL_TPU_BENCH_FORCE_ACCEL_SHAPE "
                f"{force_shape!r} (want 'registry,lanes,candidates,trials'):"
                f" {e}",
                file=sys.stderr,
            )
            raise SystemExit(2) from e
        on_accel = True
    else:
        # TPU: the 4000-node scenario; CPU fallback: small smoke so the
        # driver always records a line
        n_registry = 4096 if on_accel else 16
        lanes = 128 if on_accel else 4
        n_candidates = 64 if on_accel else 4
        trials = 10 if on_accel else 2

    curves = BN254Curves()
    pks, miss_k, args = build_problem(curves, n_registry, lanes, n_candidates)
    device = BN254Device(
        [BN254PublicKey(p) for p in pks], batch_size=lanes, curves=curves
    )
    kernel = device._range_kernel(miss_k)

    # warmup (compile)
    verdicts = kernel(*args)
    verdicts.block_until_ready()
    ok = np.asarray(verdicts)[:n_candidates]
    assert ok.all(), f"bench batch failed verification: {ok}"

    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        kernel(*args).block_until_ready()
        times.append((time.perf_counter() - t0) * 1000.0)
    p50 = float(np.percentile(times, 50))

    if on_accel:
        # reference headline: 4000-sig aggregation ~900 ms (README.md:32-33)
        line = {
            "metric": f"{n_registry}sig_batch_verify_p50_ms",
            "value": round(p50, 3),
            "unit": "ms",
            "vs_baseline": round(REFERENCE_HEADLINE_MS / p50, 3),
            "backend": backend,
        }
        if force_shape:
            # a forced tiny-shape run must never read as a real accelerator
            # measurement on the one-line contract
            line["forced_shape"] = True
            line["vs_baseline"] = None
        # host half of the pipeline: packing + dedup metrics (host-side,
        # backend-independent — measured in-process, no extra launches)
        line.update(_host_metrics())
        # multi-tenant service plane: sustained aggregates/s + p99 session
        # completion + coalesced launch fill (protocol-layer, no kernels)
        line.update(_service_metrics())
        # fleet plane: K-lane DevicePlane scheduler throughput vs 1 lane
        line.update(_fleet_metrics())
        # latency plane: small gold-tier launches over the whole-mesh lane
        line.update(_small_batch_metrics())
        # vnode swarm: identities carried + bytes/identity + completion wall
        line.update(_swarm_metrics())
        # geo-federation robustness: open-loop p99 under a region kill,
        # recovery wall, spillover fraction (protocol-layer, no kernels)
        line.update(_federation_metrics())
        # hierarchical roll-up plane: O(hosts) fleet series count, wire
        # bytes/host/s, and master merge wall (obs/rollup.py)
        line.update(_rollup_metrics())
        # RLC batch-check plane: both check modes on every line, keyed per
        # fp_backend in bench_check (PER_FP_BACKEND) via the line's tag
        line["fp_backend"] = curves.F.backend
        line.update(_rlc_metrics())

        def persist(extra_line: dict) -> None:
            # provenance so a later tunnel outage can't erase the capture
            os.makedirs(os.path.dirname(ARTIFACT), exist_ok=True)
            write_json_atomic(
                ARTIFACT,
                {
                    **extra_line,
                    "backend": backend,
                    "device": str(jax.devices()[0]),
                    "device_count": jax.device_count(),
                    "registry": n_registry,
                    "lanes": lanes,
                    "candidates": n_candidates,
                    "trials_ms": [round(t, 3) for t in times],
                    "captured_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                    ),
                },
            )

        # persist the headline BEFORE the pipelined extension: those extra
        # launches ride the same flaky tunnel, and a hang there kills this
        # child via the parent watchdog — the already-measured p50 must
        # already be on disk so the parent's fallback re-emits it
        persist(line)

        # pipelined sustained rate (measure_pipelined above). Accel-only:
        # the CPU smoke line never reports it, so the degraded path skips
        # the extra launches.
        try:
            pipe_times = measure_pipelined(
                lambda: kernel(*args), lambda r: r.block_until_ready(), trials
            )
            pipe_p50 = float(np.percentile(pipe_times, 50))
            line["pipelined_p50_ms"] = round(pipe_p50, 3)
            line["pipelined_vs_baseline"] = (
                None if force_shape else round(REFERENCE_HEADLINE_MS / pipe_p50, 3)
            )
            persist(line)
        except Exception as e:
            # degrade to headline-only, never lose the p50 over the extension
            print(f"bench: pipelined extension failed: {e}", file=sys.stderr)

        # headline line FIRST: a tunnel drop during the fp microbench must
        # not cost an already-captured measurement
        _emit(line)
        sys.stdout.flush()
        try:
            _fp_microbench()
        except Exception as e:
            print(f"bench: fp microbench failed: {e}", file=sys.stderr)
        try:
            _pairing_bench()
        except Exception as e:
            print(f"bench: pairing bench failed: {e}", file=sys.stderr)
    else:
        # honest CPU smoke: different problem size, no baseline ratio
        line = {
            "metric": f"{n_registry}sig_batch_verify_cpu_smoke_p50_ms",
            "value": round(p50, 3),
            "unit": "ms",
            "vs_baseline": None,
            "note": "CPU fallback smoke (16 keys); not comparable to the "
            "reference 4000-sig headline",
        }
        line.update(_host_metrics())
        line.update(_service_metrics())
        line.update(_fleet_metrics())
        line.update(_small_batch_metrics())
        line.update(_swarm_metrics())
        line.update(_federation_metrics())
        line.update(_rollup_metrics())
        line["fp_backend"] = curves.F.backend
        line.update(_rlc_metrics())
        _emit(line)


if __name__ == "__main__":
    main()

"""Driver benchmark: batched BLS verification on one chip.

Measures the headline target from BASELINE.md: verify a batch of aggregate
BN254 signatures over a 4096-key registry (the reference's 4000-node AWS
scenario, README.md:32-33: ~900 ms avg completion) with the device path —
masked G2 aggregation + batched product-of-pairings check in one launch per
128 candidates.

Prints ONE JSON line:
  {"metric": "4096sig_batch_verify_p50_ms", "value": ..., "unit": "ms",
   "vs_baseline": <reference 900 ms / our p50>}

Runs on whatever jax.default_backend() is (TPU on the bench host; falls back
to a reduced CPU-sized problem so the line is always emitted).
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time


def _probe_default_backend(timeout_s: float = 90.0) -> bool:
    """True if jax can initialize its default platform within the timeout.

    The environment's TPU is reached through a tunnel whose outage makes
    `import jax` + device init hang FOREVER (not error). Probing in a
    subprocess keeps this process safe; on failure the bench falls back to
    CPU so the driver always records a line.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def build_problem(curves, n_registry: int, lanes: int, n_candidates: int):
    """Handel-realistic candidate batch: contiguous partitioner ranges with a
    few offline holes, exactly the traffic `batch_verify` sees. Returns the
    range-kernel argument tuple (lo, hi, miss_idx, miss_ok, sig, h, valid)
    plus the keypair material."""
    import jax.numpy as jnp
    import numpy as np

    from handel_tpu import native as nat
    from handel_tpu.ops import bn254_ref as bn

    rng = random.Random(2024)
    # small scalars keep host-side keygen fast; verification cost on device
    # is independent of scalar magnitude
    sks = [rng.randrange(1, 1 << 30) for _ in range(n_registry)]
    pks = nat.g2_mul_batch([bn.G2_GEN] * n_registry, sks)
    h = nat.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R))

    miss_k = 8  # up to 8 offline signers patched per candidate
    lo = np.zeros((lanes,), np.int32)
    hi = np.zeros((lanes,), np.int32)
    miss_idx = np.zeros((miss_k, lanes), np.int64)
    miss_ok = np.zeros((miss_k, lanes), dtype=bool)
    agg_sks = []
    for j in range(n_candidates):
        size = rng.choice([n_registry // 8, n_registry // 4, n_registry // 2])
        lo[j] = rng.randrange(0, n_registry - size)
        hi[j] = lo[j] + size
        max_holes = min(miss_k, size - 1)  # leave at least one signer
        holes = sorted(
            rng.sample(
                range(int(lo[j]), int(hi[j])),
                rng.randrange(0, max_holes) if max_holes > 0 else 0,
            )
        )
        miss_idx[: len(holes), j] = holes
        miss_ok[: len(holes), j] = True
        signers = set(range(int(lo[j]), int(hi[j]))) - set(holes)
        agg_sks.append(sum(sks[i] for i in signers) % bn.R)
    sig_pts = nat.g1_mul_batch([h] * n_candidates, agg_sks)
    sig_pts += [bn.G1_GEN] * (lanes - n_candidates)

    F = curves.F
    valid = np.zeros((lanes,), dtype=bool)
    valid[:n_candidates] = True
    return (
        pks,
        miss_k,
        (
            jnp.asarray(lo),
            jnp.asarray(hi),
            jnp.asarray(miss_idx.reshape(-1)),
            jnp.asarray(miss_ok.reshape(-1)),
            F.pack([p[0] for p in sig_pts]),
            F.pack([p[1] for p in sig_pts]),
            F.pack([h[0]]),
            F.pack([h[1]]),
            jnp.asarray(valid),
        ),
    )


def main() -> None:
    from handel_tpu.utils.jaxenv import apply_platform_env

    if not os.environ.get("HANDEL_TPU_PLATFORM") and not _probe_default_backend():
        # TPU tunnel down: force CPU through the config API (the env var
        # alone is overridden by the environment's sitecustomize)
        os.environ["HANDEL_TPU_PLATFORM"] = "cpu"
        print("bench: default backend unreachable, falling back to CPU",
              file=sys.stderr)
    apply_platform_env()  # no-op when HANDEL_TPU_PLATFORM is unset
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import numpy as np

    from handel_tpu.models.bn254 import BN254PublicKey
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops.curve import BN254Curves

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    # TPU: the 4000-node scenario; CPU fallback: small smoke so the driver
    # always records a line
    n_registry = 4096 if on_accel else 16
    lanes = 128 if on_accel else 4
    n_candidates = 64 if on_accel else 4
    trials = 10 if on_accel else 2

    curves = BN254Curves()
    pks, miss_k, args = build_problem(curves, n_registry, lanes, n_candidates)
    device = BN254Device(
        [BN254PublicKey(p) for p in pks], batch_size=lanes, curves=curves
    )
    kernel = device._range_kernel(miss_k)

    # warmup (compile)
    verdicts = kernel(*args)
    verdicts.block_until_ready()
    ok = np.asarray(verdicts)[:n_candidates]
    assert ok.all(), f"bench batch failed verification: {ok}"

    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        kernel(*args).block_until_ready()
        times.append((time.perf_counter() - t0) * 1000.0)
    p50 = float(np.percentile(times, 50))

    # reference headline: 4000-sig aggregation ~900 ms (README.md:32-33)
    print(
        json.dumps(
            {
                "metric": f"{n_registry}sig_batch_verify_p50_ms",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(900.0 / p50, 3) if p50 > 0 else 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()

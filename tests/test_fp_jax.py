"""JAX limb field arithmetic vs the Python bigint oracle.

SURVEY.md §7 step 1: property tests of the Montgomery limb kernels against
ops/bn254_ref.py. Runs on CPU (pure-XLA path); the Pallas TPU path shares the
same `_mul_cols` body and is exercised by bench.py on hardware.

The `F` fixture is parametrized over the Field backend seam (ops/fp.py):
every property runs against BOTH the CIOS kernel and the RNS Montgomery
pipeline (ops/rns.py). The two backends use different Montgomery constants
(R vs the base-A product M), so properties are stated on unpacked integers
/ canonical boundary limbs — the representation the backends contract to
agree on bit-exactly. RNS-specific edge cases (operands near p, residue
overflow bounds, CRT exactness at the pairing-line boundary) follow at the
bottom; compile-cheap RNS unit checks live in the fast tier
(tests/test_rns.py, scripts/rns_smoke.py).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# slow tier: XLA-compile-bound (every property test jits fresh field
# kernels) — runs in test-slow/test-all (nightly/CI); the fast tier keeps
# the oracle + protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field, LIMB_MASK

rng = random.Random(99)


@pytest.fixture(scope="module", params=["cios", "rns"])
def F(request):
    return Field(bn.P, use_pallas=False, backend=request.param)


def rand_elems(k):
    return [rng.randrange(bn.P) for _ in range(k)]


B = 8


def test_pack_unpack_roundtrip(F):
    xs = rand_elems(B) + [0, 1, bn.P - 1]
    assert F.unpack(F.pack(xs)) == xs
    assert F.unpack(F.pack(xs, mont=False), mont=False) == xs


def test_mul(F):
    xs, ys = rand_elems(B), rand_elems(B)
    out = jax.jit(F.mul)(F.pack(xs), F.pack(ys))
    assert F.unpack(out) == [x * y % bn.P for x, y in zip(xs, ys)]


def test_mul_edge_cases(F):
    xs = [0, 1, bn.P - 1, bn.P - 1, 2, (bn.P - 1) // 2]
    ys = [0, bn.P - 1, bn.P - 1, 1, (bn.P + 1) // 2, 2]
    out = jax.jit(F.mul)(F.pack(xs), F.pack(ys))
    assert F.unpack(out) == [x * y % bn.P for x, y in zip(xs, ys)]


def test_add_sub_neg(F):
    xs, ys = rand_elems(B) + [0, bn.P - 1], rand_elems(B) + [0, 1]
    ax, ay = F.pack(xs), F.pack(ys)
    assert F.unpack(jax.jit(F.add)(ax, ay)) == [
        (x + y) % bn.P for x, y in zip(xs, ys)
    ]
    assert F.unpack(jax.jit(F.sub)(ax, ay)) == [
        (x - y) % bn.P for x, y in zip(xs, ys)
    ]
    assert F.unpack(jax.jit(F.neg)(ax)) == [(-x) % bn.P for x in xs]


def test_mont_conversions(F):
    xs = rand_elems(B)
    plain = F.pack(xs, mont=False)
    m = jax.jit(F.to_mont)(plain)
    assert F.unpack(m) == xs
    back = jax.jit(F.from_mont)(m)
    assert F.unpack(back, mont=False) == xs


def test_pow_const_and_inv(F):
    xs = rand_elems(4)
    ax = F.pack(xs)
    out = jax.jit(lambda a: F.pow_const(a, 65537))(ax)
    assert F.unpack(out) == [pow(x, 65537, bn.P) for x in xs]
    inv = jax.jit(F.inv)(ax)
    assert F.unpack(inv) == [pow(x, -1, bn.P) for x in xs]


@pytest.mark.parametrize("window", [1, 4])
def test_pow_const_windowed_edges(F, window):
    """Both pow lowerings across their edge shapes: exponents at/below the
    window width (direct-chain branch), widths that pad, digits of 0 (skip
    lanes), and agreement with python pow on irregular bit patterns.

    The window is pinned EXPLICITLY (ADVICE r5 #2): default_pow_window
    returns 1 on the CPU CI backend, so leaving it to the default would
    silently drop coverage of the window=4 table+gather lowering — the
    production path on accelerators."""
    xs = rand_elems(3)
    ax = F.pack(xs)
    for e in (2, 3, 15, 16, 17, 0x8001, 0x10010, 0xF0F0F0F, bn.P - 2):
        got = F.unpack(
            jax.jit(lambda a, e=e: F.pow_const(a, e, window=window))(ax)
        )
        assert got == [pow(x, e, bn.P) for x in xs], f"e={e:#x} w={window}"


def test_windowed_pow_digits():
    from handel_tpu.ops.fp import windowed_pow_digits

    assert windowed_pow_digits(9, 4) is None  # <= window bits: direct chain
    assert windowed_pow_digits(0x1F, 4) == [1, 15]  # left-pad keeps MSB != 0
    assert windowed_pow_digits(0x100, 4) == [1, 0, 0]  # zero digits preserved
    digits = windowed_pow_digits(bn.P - 2, 4)
    acc = 0
    for d in digits:
        acc = (acc << 4) | d
    assert acc == bn.P - 2  # decomposition is exact


def test_eq_is_zero_select(F):
    xs = [0, 5, 7, 0]
    ys = [0, 5, 8, 1]
    ax, ay = F.pack(xs), F.pack(ys)
    assert jax.jit(F.eq)(ax, ay).tolist() == [True, True, False, False]
    assert jax.jit(F.is_zero)(F.pack(xs, mont=False)).tolist() == [
        True,
        False,
        False,
        True,
    ]
    mask = jnp.asarray([True, False, True, False])
    sel = F.select(mask, ax, ay)
    assert F.unpack(sel) == [0, 5, 7, 1]


def test_random_fuzz_mul(F):
    # wider fuzz: 64 random products in one batch
    xs, ys = rand_elems(64), rand_elems(64)
    out = jax.jit(F.mul)(F.pack(xs), F.pack(ys))
    assert F.unpack(out) == [x * y % bn.P for x, y in zip(xs, ys)]


@pytest.mark.parametrize("backend", ["cios", "rns"])
def test_bls12_381_field_params(backend):
    # the same engine must serve BLS12-381's 381-bit prime (24 limbs)
    p381 = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
    F381 = Field(p381, use_pallas=False, backend=backend)
    assert F381.nlimbs == 24
    xs, ys = [rng.randrange(p381) for _ in range(4)], [
        rng.randrange(p381) for _ in range(4)
    ]
    out = jax.jit(F381.mul)(F381.pack(xs), F381.pack(ys))
    assert F381.unpack(out) == [x * y % p381 for x, y in zip(xs, ys)]


# -- RNS-specific edges (ops/rns.py) ------------------------------------------


@pytest.fixture(scope="module")
def Frns():
    return Field(bn.P, backend="rns")


@pytest.fixture(scope="module")
def Fcios():
    return Field(bn.P, use_pallas=False)


def test_rns_operands_near_p(Frns, Fcios):
    """The canonicalization ladder's worst inputs: both operands at the top
    of the field, where r = (T + q_hat*p)/M approaches the (kA+1)p bound
    and every binary conditional-subtract step fires. Boundary limbs must
    stay bit-identical to the CIOS backend."""
    near = [bn.P - 1 - k for k in range(6)] + [1, 2]
    a_r, b_r = Frns.pack(near), Frns.pack(list(reversed(near)))
    got = Frns.unpack(jax.jit(Frns.mul)(a_r, b_r))
    want = [x * y % bn.P for x, y in zip(near, reversed(near))]
    assert got == want
    # canonical-boundary bit-exactness vs the CIOS oracle
    plain = Frns.pack(near, mont=False)
    r_out = jax.jit(lambda a: Frns.from_mont(Frns.mul(Frns.to_mont(a),
                                                      Frns.to_mont(a))))(plain)
    c_out = jax.jit(lambda a: Fcios.from_mont(Fcios.mul(Fcios.to_mont(a),
                                                        Fcios.to_mont(a))))(plain)
    assert np.array_equal(np.asarray(r_out), np.asarray(c_out))


def test_rns_residue_overflow_bounds(Frns):
    """Construction-time range invariants the int32 exactness proofs rest
    on, plus a mul where every residue row sits at its maximum (operands
    whose residues are m_i - 1 for many i): no intermediate may exceed the
    float-assisted reduction's 2^30 domain."""
    F = Frns
    assert F.M >= 4 * F.p  # r < (kA+1)p bound
    assert F.MB > 2 * (F.kA + 1) * F.p  # second-extension CRT range
    assert F.mr > F.kB + 1  # exact alpha recovery channel
    assert all(m < (1 << 13) for m in F.mA + F.mB + [F.mr])
    assert (1 << 16 * F.nlimbs) <= F.MB  # any 16n-bit value CRT-round-trips
    # operands ≡ -1 mod every base-A prime: maximal residues through the
    # product, xi, and base-extension paths
    import math

    prodA = F.M
    x = prodA - 1  # < M but > p — reduce into the field first
    vals = [x % F.p, (prodA // 2) % F.p, (F.MB - 1) % F.p, F.p - 1]
    a = F.pack(vals)
    b = F.pack([F.p - 1] * len(vals))
    got = F.unpack(jax.jit(F.mul)(a, b))
    assert got == [v * (F.p - 1) % F.p for v in vals]
    assert math.gcd(F.M, F.MB * F.mr) == 1  # bases coprime (CRT validity)


def test_rns_crt_roundtrip_full_range(Frns):
    """to_rns -> from_rns_base_b is EXACT over the full 16n-bit positional
    range (not just < p): the Shenoy alpha recovery must hold at the very
    top, 2^256 - 1."""
    F = Frns
    n = F.nlimbs
    tops = [(1 << (16 * n)) - 1, F.p, F.p + 1, (1 << (16 * n)) - F.p, 12345]
    arr = np.zeros((n, len(tops)), np.uint32)
    for j, v in enumerate(tops):
        for i in range(n):
            arr[i, j] = (v >> (16 * i)) & 0xFFFF
    a = jnp.asarray(arr)
    r = jax.jit(F.to_rns)(a)
    v16 = jax.jit(
        lambda rB, rr: F.from_rns_base_b(rB, rr)
    )(r[F.kA : F.kA + F.kB], r[F.kA + F.kB])
    got = np.asarray(v16)
    for j, v in enumerate(tops):
        rec = sum(int(got[i, j]) << (16 * i) for i in range(F.n16out))
        assert rec == v, f"CRT round-trip broke at {v:#x}"


def test_resident_chain_bit_exact_near_p(Frns, Fcios):
    """Residue-RESIDENT chains (residue-resident pairing) over seeded and
    near-p operands: mul -> add -> sub(blog) -> mul stays in the residue
    domain throughout and reconstructs ONCE; the boundary limbs must be
    bit-identical to the CIOS backend computing the same chain
    positionally."""
    A = Frns.resident()
    xs = rand_elems(6) + [bn.P - 1, bn.P - 1]
    ys = [bn.P - 1 - k for k in range(6)] + [1, bn.P - 1]

    def chain_resident():
        a, b = A.pack(xs), A.pack(ys)
        c = A.mul(a, b)
        d = A.add(c, a)
        e = A.sub(d, b, 7)
        # the two backends carry different Montgomery constants (M vs R):
        # bit-identity is contracted at the CANONICAL boundary, after
        # from_mont strips the backend's own constant
        return Frns.from_mont(Frns.from_resident(A.mul(e, c)))

    def chain_cios():
        a, b = Fcios.pack(xs), Fcios.pack(ys)
        c = Fcios.mul(a, b)
        d = Fcios.add(c, a)
        e = Fcios.sub(d, b)
        return Fcios.from_mont(Fcios.mul(e, c))

    r_out = jax.jit(chain_resident)()
    c_out = jax.jit(chain_cios)()
    assert np.array_equal(np.asarray(r_out), np.asarray(c_out))
    want = [
        (x * y % bn.P + x - y) * (x * y) % bn.P for x, y in zip(xs, ys)
    ]
    assert Frns.unpack(jnp.asarray(r_out), mont=False) == want


def test_resident_pairing_line_boundary(Frns, Fcios):
    """The pairing's genuine boundary shape, computed RESIDENT: the
    sparse-line expression l = a*b + c*d + e accumulates in residues and
    crosses the CRT exactly once at the end — bit-identical to the CIOS
    backend paying positional form at every hop. Near-p operands push the
    Montgomery-quotient overshoot to its worst case."""
    A = Frns.resident()
    vals = rand_elems(4) + [bn.P - 1, bn.P - 2, 1, bn.P - 1]
    rev = list(reversed(vals))

    def line_resident():
        a, b = A.pack(vals), A.pack(rev)
        t1 = A.mul(a, b)
        t2 = A.mul(A.add(t1, a), A.sub(t1, b, 7))
        out = A.add(A.mul(t2, A.refresh(t1)), a)
        return Frns.from_mont(Frns.from_resident(out))

    def line_cios():
        a, b = Fcios.pack(vals), Fcios.pack(rev)
        t1 = Fcios.mul(a, b)
        t2 = Fcios.mul(Fcios.add(t1, a), Fcios.sub(t1, b))
        return Fcios.from_mont(Fcios.add(Fcios.mul(t2, t1), a))

    assert np.array_equal(
        np.asarray(jax.jit(line_resident)()),
        np.asarray(jax.jit(line_cios)()),
    )


def test_resident_inv_and_pow(Frns):
    """The adapter's Fermat inverse and windowed pow on resident values,
    against python pow — the exponent path the final-exp tower leans on."""
    A = Frns.resident()
    xs = rand_elems(3) + [bn.P - 1]
    a = A.pack(xs)
    got = A.unpack(jax.jit(A.inv)(a))
    assert got == [pow(x, -1, bn.P) for x in xs]
    got = A.unpack(jax.jit(lambda v: A.pow_const(v, 0x113, window=4))(a))
    assert got == [pow(x, 0x113, bn.P) for x in xs]


def test_rns_exact_at_pairing_line_boundary(Frns, Fcios):
    """The pairing consumes positional form at line evaluations: chains of
    mul -> add -> mul (each mul paying a full CRT reconstruction). A
    sparse-line-shaped expression l = a*b + c*d + e must agree bit-exactly
    with the CIOS backend at the canonical boundary after EVERY hop, not
    just at the end."""
    vals = rand_elems(8)
    packs = {}
    for name, Fx in (("rns", Frns), ("cios", Fcios)):
        a, b = Fx.pack(vals), Fx.pack(list(reversed(vals)))
        t1 = Fx.mul(a, b)
        t2 = Fx.mul(Fx.add(t1, a), Fx.sub(t1, b))
        line = Fx.add(Fx.mul(t2, t1), a)
        packs[name] = [Fx.unpack(t) for t in (t1, t2, line)]
    assert packs["rns"] == packs["cios"]

"""Observability plane tests (ISSUE 19): burn-rate math vs the
closed-form oracle, detector determinism under seed replay, the incident
open -> escalate -> close lifecycle with flap suppression, the AlertPlane
wiring (attribution snapshots, /alerts endpoint, metric families), the
[alerts] config round trip, and the chaos-drill integration over a short
in-process load run."""

from __future__ import annotations

import asyncio
import json
import os
import urllib.error
import urllib.request

import pytest

from handel_tpu.obs import (
    AlertPlane,
    BurnRateEvaluator,
    BurnRule,
    DetectorBank,
    EwmaDetector,
    IncidentLog,
    MadDetector,
    counter_rate,
    histogram_quantile_source,
    reporter_key_source,
)

# -- burn-rate math vs the closed-form oracle ---------------------------------


def _run_constant_error(frac: float, budget: float = 0.01,
                        page_x: float = 14.4, warn_x: float = 6.0):
    """Feed a constant error fraction `frac` through both windows: the
    closed form says burn = frac / budget on every window, exactly."""
    ev = BurnRateEvaluator(fast_window_s=60.0, slow_window_s=900.0,
                           clock=lambda: 0.0)
    state = {"t": 0.0}

    def src():
        total = state["t"] * 10.0
        return total * (1.0 - frac), total * frac

    ev.add_rule(
        BurnRule("r", budget=budget, page_x=page_x, warn_x=warn_x), src
    )
    for t in range(0, 1801, 30):
        state["t"] = float(t)
        ev.tick(now=float(t))
    return ev


def test_burn_oracle_1x_is_ok():
    ev = _run_constant_error(0.01)  # exactly the budget: burn 1.0x
    fast, slow = ev.burns("r")
    assert fast == pytest.approx(1.0) and slow == pytest.approx(1.0)
    assert ev.states()["r"] == "ok"
    assert ev.firing() == []


def test_burn_oracle_6x_is_warn():
    ev = _run_constant_error(0.06)  # 6x the budget on both windows
    fast, slow = ev.burns("r")
    assert fast == pytest.approx(6.0) and slow == pytest.approx(6.0)
    assert ev.states()["r"] == "warn"
    assert ev.firing() == [("r", "warn")]
    assert ev.values()["rulesWarn"] == 1.0
    assert ev.warn_transitions == 1  # entered warn exactly once


def test_burn_oracle_14p4x_is_page():
    ev = _run_constant_error(0.144)  # the classic page threshold
    fast, slow = ev.burns("r")
    assert fast == pytest.approx(14.4) and slow == pytest.approx(14.4)
    assert ev.states()["r"] == "page"
    assert ev.firing() == [("r", "page")]
    assert ev.values()["rulesPage"] == 1.0
    assert ev.page_transitions == 1


def test_burn_multiwindow_gates_on_both():
    """A short burst burns the fast window hard but not the slow one:
    multi-window alerting must NOT page on it."""
    ev = BurnRateEvaluator(fast_window_s=60.0, slow_window_s=900.0,
                           clock=lambda: 0.0)
    counts = {"good": 0.0, "bad": 0.0}
    ev.add_rule(BurnRule("r", budget=0.01),
                lambda: (counts["good"], counts["bad"]))
    # 15 minutes of clean traffic...
    for t in range(0, 901, 30):
        counts["good"] += 300.0
        ev.tick(now=float(t))
    # ...then one 60 s window of 100% errors
    for t in range(930, 991, 30):
        counts["bad"] += 300.0
        ev.tick(now=float(t))
    fast, slow = ev.burns("r")
    assert fast >= 14.4  # the fast window alone would page
    assert slow < 14.4  # but the slow window hasn't burned through
    assert ev.states()["r"] != "page"


def test_burn_rule_validation():
    with pytest.raises(ValueError):
        BurnRule("bad", budget=0.0)
    with pytest.raises(ValueError):
        BurnRule("bad", budget=1.5)
    with pytest.raises(ValueError):
        BurnRule("bad", budget=0.01, warn_x=20.0, page_x=14.4)
    with pytest.raises(ValueError):
        BurnRateEvaluator(fast_window_s=900.0, slow_window_s=60.0)
    ev = BurnRateEvaluator()
    ev.add_rule(BurnRule("r", budget=0.1), lambda: (1.0, 0.0))
    with pytest.raises(ValueError):
        ev.add_rule(BurnRule("r", budget=0.1), lambda: (1.0, 0.0))


def test_burn_window_scale_compresses_the_drill():
    """window_scale shrinks both windows so a ~seconds drill exercises
    the same closed-form math as the production minutes-scale windows."""
    ev = BurnRateEvaluator(fast_window_s=60.0, slow_window_s=900.0,
                           window_scale=0.01, clock=lambda: 0.0)
    assert ev.fast_window_s == pytest.approx(0.6)
    assert ev.slow_window_s == pytest.approx(9.0)
    counts = {"t": 0.0}

    def src():
        total = counts["t"] * 100.0
        return total * 0.856, total * 0.144

    ev.add_rule(BurnRule("r", budget=0.01), src)
    t = 0.0
    while t <= 18.0:
        counts["t"] = t
        ev.tick(now=t)
        t += 0.3
    assert ev.states()["r"] == "page"


def test_burn_source_exception_skips_rule():
    ev = BurnRateEvaluator(clock=lambda: 0.0)

    def dying():
        raise RuntimeError("source died")

    ev.add_rule(BurnRule("r", budget=0.01), dying)
    ev.tick(now=0.0)  # must not raise
    assert ev.states()["r"] == "ok"


# -- detector determinism + step detection ------------------------------------


def _stream(seed: int = 3) -> list[float]:
    import random

    rng = random.Random(seed)
    base = [rng.gauss(10.0, 0.5) for _ in range(60)]
    return base + [25.0] * 10 + [rng.gauss(10.0, 0.5) for _ in range(20)]


def test_ewma_detector_fires_on_step_and_replays():
    d1 = EwmaDetector(alpha=0.3, z_threshold=6.0)
    d2 = EwmaDetector(alpha=0.3, z_threshold=6.0)
    s = _stream()
    zs1 = [d1.update(x) for x in s]
    zs2 = [d2.update(x) for x in s]
    assert zs1 == zs2  # bit-identical replay
    assert max(zs1[:60]) < 6.0  # quiet during the baseline
    assert zs1[60] > 6.0  # the step fires immediately


def test_mad_detector_seed_replay_and_robustness():
    s = _stream()
    d1, d2, d3 = MadDetector(seed=7), MadDetector(seed=7), MadDetector(seed=8)
    zs1 = [d1.update(x) for x in s]
    zs2 = [d2.update(x) for x in s]
    zs3 = [d3.update(x) for x in s]
    assert zs1 == zs2  # same seed: bit-identical
    assert zs1 != zs3  # different seed: different coin flips
    assert max(abs(z) for z in zs1[:60]) < 6.0
    assert zs1[60] > 6.0  # robust z still catches the step


def test_ewma_warmup_suppresses_early_z():
    d = EwmaDetector(alpha=0.3, z_threshold=1.0, warmup=5)
    assert all(d.update(x) == 0.0 for x in (1.0, 9.0, 1.0, 9.0, 1.0))
    assert d.update(100.0) != 0.0  # past warmup: z flows


def test_detector_bank_consecutive_and_direction():
    bank = DetectorBank(clock=lambda: 0.0)
    vals = {"x": 10.0}
    bank.attach("up-only", lambda: vals["x"],
                EwmaDetector(alpha=0.3, z_threshold=6.0, warmup=2),
                min_consecutive=2, direction="up")
    for _ in range(20):
        bank.tick(now=0.0)
    vals["x"] = 0.0  # huge step DOWN: an up-only series must not fire
    assert bank.tick(now=1.0) == []
    with pytest.raises(ValueError):
        bank.attach("up-only", lambda: 0.0, EwmaDetector())
    with pytest.raises(ValueError):
        bank.attach("bad-dir", lambda: 0.0, EwmaDetector(),
                    direction="sideways")


def test_detector_bank_hold_while_decouples_recovery():
    """A z detector spots the STEP then adapts; hold_while keeps the
    series firing until the underlying condition actually clears."""
    bank = DetectorBank(clock=lambda: 0.0)
    vals = {"x": 10.0}
    cond = {"broken": False}
    bank.attach("s", lambda: vals["x"],
                EwmaDetector(alpha=0.3, z_threshold=6.0, warmup=2),
                min_consecutive=1, opens_incident=True, direction="down",
                hold_while=lambda: cond["broken"])
    for t in range(30):
        assert bank.tick(now=float(t)) == []
    vals["x"] = 0.0
    cond["broken"] = True
    fired = bank.tick(now=30.0)
    assert [d.name for d in fired] == ["s"]
    assert fired[0].opens_incident
    # detector adapts within a few ticks, but the condition persists:
    # hold_while must keep the series firing
    for t in range(31, 50):
        assert [d.name for d in bank.tick(now=float(t))] == ["s"]
    cond["broken"] = False  # actual recovery
    vals["x"] = 10.0
    for _ in range(5):
        out = bank.tick(now=50.0)
    assert out == []
    assert bank.values()["seriesAnomalous"] == 0.0


def test_source_factories():
    class Rep:
        def values(self):
            return {"depth": 7.0}

    src = reporter_key_source(Rep(), "depth")
    assert src() == 7.0
    assert reporter_key_source(Rep(), "missing")() is None

    from handel_tpu.core.trace import LogHistogram

    h = LogHistogram()
    for v in (0.01, 0.02, 0.04):
        h.add(v)
    q = histogram_quantile_source(lambda: h, 0.5)
    assert q() == h.quantile(0.5)
    assert histogram_quantile_source(lambda: None, 0.5)() is None

    t = {"now": 0.0}
    c = {"v": 0.0}
    rate = counter_rate(lambda: c["v"], clock=lambda: t["now"])
    assert rate() is None  # first sample primes
    c["v"], t["now"] = 30.0, 10.0
    assert rate() == pytest.approx(3.0)


# -- incident lifecycle -------------------------------------------------------


def test_incident_open_escalate_close():
    t = {"now": 0.0}
    events: list[tuple[str, int]] = []
    log = IncidentLog(snapshot_fn=lambda: {"cause": "unit-test"},
                      min_hold_s=2.0, cooldown_s=5.0,
                      clock=lambda: t["now"])
    log.add_listener(lambda ev, inc: events.append((ev, inc.id)))

    log.observe([("goodput", "warn")], now=0.0)
    inc = log.current
    assert inc is not None and inc.severity == "warn"
    assert inc.attribution == {"cause": "unit-test"}
    # correlation: a second rule firing attaches, no second incident
    log.observe([("goodput", "warn"), ("tier-gold-p99", "warn")], now=1.0)
    assert log.current is inc and inc.rules == {"goodput", "tier-gold-p99"}
    assert log.opened == 1
    # escalation: a page firing upgrades severity exactly once
    log.observe([("goodput", "page")], now=2.0)
    assert inc.severity == "page" and log.escalated == 1
    log.observe([("goodput", "page")], now=3.0)
    assert log.escalated == 1
    # close only after min_hold_s of continuous quiet
    log.observe([], now=4.0)
    assert log.current is inc  # quiet 0 s: still open
    log.observe([], now=5.0)
    log.observe([], now=6.1)
    assert log.current is None and inc.state == "closed"
    assert log.closed == 1
    assert [e for e, _ in events] == ["open", "escalate", "close"]
    names = [e["event"] for e in inc.timeline]
    assert names == ["open", "correlate", "escalate", "close"]


def test_incident_flap_reopens_within_cooldown():
    t = {"now": 0.0}
    log = IncidentLog(min_hold_s=1.0, cooldown_s=5.0,
                      clock=lambda: t["now"])
    log.observe([("r", "page")], now=0.0)
    first = log.current
    log.observe([], now=1.0)
    log.observe([], now=2.5)
    assert log.current is None and first.state == "closed"
    # refire 2 s after close: inside the cooldown -> REOPEN, same id
    log.observe([("r", "page")], now=4.5)
    assert log.current is first and first.flaps == 1
    assert log.opened == 1 and log.flapped == 1
    log.observe([], now=5.0)
    log.observe([], now=6.5)
    assert log.current is None
    # refire well past the cooldown: a genuinely new incident
    log.observe([("r", "page")], now=60.0)
    assert log.current is not first and log.current.id != first.id
    assert log.opened == 2


def test_incident_quiet_hold_resets_on_refire():
    """Min-hold is CONTINUOUS quiet: a blip mid-hold restarts the clock
    without closing or reopening anything."""
    log = IncidentLog(min_hold_s=2.0, cooldown_s=5.0, clock=lambda: 0.0)
    log.observe([("r", "warn")], now=0.0)
    inc = log.current
    log.observe([], now=1.0)
    log.observe([("r", "warn")], now=2.0)  # blip: hold clock resets
    log.observe([], now=3.0)
    log.observe([], now=4.5)
    assert log.current is inc  # only 1.5 s quiet since the blip
    log.observe([], now=5.1)
    assert log.current is None
    assert inc.flaps == 0  # never closed mid-flap, so no flap counted


def test_incident_report_rebases_timestamps():
    log = IncidentLog(min_hold_s=1.0, clock=lambda: 0.0)
    log.observe([("r", "page")], now=100.0)
    log.observe([], now=101.0)
    log.observe([], now=102.5)
    rep = log.to_report(t0=100.0)
    assert rep["opened"] == 1 and rep["closed"] == 1
    inc = rep["incidents"][0]
    assert inc["opened_at"] == 0.0
    assert inc["closed_at"] == pytest.approx(2.5)
    assert inc["timeline"][0]["at"] == 0.0


def test_incident_trace_instants():
    from handel_tpu.core.trace import FlightRecorder

    rec = FlightRecorder(capacity=256)
    log = IncidentLog(recorder=rec, min_hold_s=1.0, clock=lambda: 0.0)
    log.observe([("r", "warn")], now=0.0)
    log.observe([("r", "page")], now=0.5)
    log.observe([], now=1.0)
    log.observe([], now=2.5)
    names = [e["name"] for e in rec.export()["traceEvents"]
             if e.get("cat") == "incident"]
    assert names == ["incident_open", "incident_escalate", "incident_close"]


# -- the AlertPlane -----------------------------------------------------------


class _Params:
    """Duck-typed AlertParams (obs/ never imports sim/)."""

    enabled = True
    fast_window_s = 0.6
    slow_window_s = 9.0
    window_scale = 1.0
    page_x = 14.4
    warn_x = 6.0
    z_threshold = 6.0
    ewma_alpha = 0.3
    min_consecutive = 1
    seed = 0
    min_hold_s = 0.5
    cooldown_s = 2.0
    tick_interval_s = 0.05


def _drilled_plane():
    """An AlertPlane driven through a synthetic region-kill drill with a
    manual clock; returns (plane, clock dict)."""
    t = {"now": 0.0}
    plane = AlertPlane.from_params(_Params(), clock=lambda: t["now"])
    health = {"regions": 3.0}
    plane.detectors.attach(
        "region-health", lambda: health["regions"],
        EwmaDetector(alpha=0.3, z_threshold=6.0),
        min_consecutive=1, opens_incident=True, direction="down",
        hold_while=lambda: health["regions"] < 3.0,
    )
    plane.add_context("unhealthy_regions",
                      lambda: ["us-east"] if health["regions"] < 3.0 else [])
    counts = {"good": 0.0, "bad": 0.0}
    plane.evaluator.add_rule(
        BurnRule("goodput", budget=0.05),
        lambda: (counts["good"], counts["bad"]),
    )
    return plane, t, health, counts


def test_alert_plane_drill_opens_attributes_and_closes():
    plane, t, health, counts = _drilled_plane()
    while t["now"] < 3.0:  # healthy baseline
        counts["good"] += 5.0
        assert plane.tick() == []
        t["now"] += 0.05
    health["regions"] = 2.0  # the kill
    kill_t = t["now"]
    opened_at = None
    while t["now"] < kill_t + 2.0:
        counts["good"] += 5.0
        plane.tick()
        if plane.incidents.current is not None and opened_at is None:
            opened_at = t["now"]
        t["now"] += 0.05
    assert opened_at is not None
    assert opened_at - kill_t <= 0.2  # bounded detection latency
    inc = plane.incidents.current
    assert inc.attribution["unhealthy_regions"] == ["us-east"]
    assert any(s["series"] == "region-health"
               for s in inc.attribution["top_anomalous"])
    health["regions"] = 3.0  # recovery
    recover_t = t["now"]
    while t["now"] < recover_t + 2.0:
        counts["good"] += 5.0
        plane.tick()
        t["now"] += 0.05
    assert plane.incidents.current is None
    assert plane.incidents.opened == 1  # exactly one incident, now closed
    assert inc.state == "closed"


def test_alert_plane_metrics_families_and_alerts_endpoint():
    from handel_tpu.core.metrics import (
        MetricsRegistry,
        MetricsServer,
        parse_exposition,
    )

    plane, t, health, counts = _drilled_plane()
    counts["good"] = 100.0
    plane.tick()
    t["now"] += 0.05
    plane.tick()
    reg = MetricsRegistry()
    plane.register_metrics(reg)
    fams = parse_exposition(reg.exposition())
    for name in (
        "handel_alerts_rules_total",
        "handel_alerts_eval_ticks_ct",
        "handel_alerts_series_total",
        "handel_alerts_firings_ct",
        "handel_incidents_incidents_open",
        "handel_incidents_opened_ct",
    ):
        assert name in fams, sorted(fams)
    # labeled rows ride the rule / series dimensions
    labels = {l.get("rule") for l, _ in
              fams["handel_alerts_burn_fast"]["samples"]}
    assert labels == {"goodput"}
    series = {l.get("series") for l, _ in
              fams["handel_alerts_last_z"]["samples"]}
    assert series == {"region-health"}
    # gauge-vs-counter is declared, never guessed
    assert fams["handel_alerts_rules_total"]["type"] == "gauge"
    assert fams["handel_alerts_eval_ticks_ct"]["type"] == "counter"
    assert fams["handel_incidents_incidents_open"]["type"] == "gauge"

    srv = MetricsServer(reg, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://{srv.address}/alerts", timeout=3
        ) as r:
            payload = json.loads(r.read())
        assert payload["open"] is False
        assert "goodput" in payload["rules"]
        assert "region-health" in payload["series"]
        assert payload["incidents"] == []
    finally:
        srv.stop()


def test_alerts_endpoint_unwired_is_501():
    from handel_tpu.core.metrics import MetricsRegistry, MetricsServer

    srv = MetricsServer(MetricsRegistry(), port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{srv.address}/alerts", timeout=3
            )
        assert ei.value.code == 501
    finally:
        srv.stop()


# -- [alerts] config ----------------------------------------------------------


def test_alerts_config_round_trip(tmp_path):
    from handel_tpu.sim.config import AlertParams, SimConfig, dump_config
    from handel_tpu.sim.config import load_config

    cfg = SimConfig()
    assert cfg.alerts == AlertParams()  # enabled by default
    cfg.alerts.window_scale = 0.02
    cfg.alerts.z_threshold = 8.0
    cfg.alerts.min_hold_s = 1.5
    path = tmp_path / "alerts.toml"
    path.write_text(dump_config(cfg))
    loaded = load_config(str(path))
    assert loaded.alerts.window_scale == 0.02
    assert loaded.alerts.z_threshold == 8.0
    assert loaded.alerts.min_hold_s == 1.5
    assert loaded.alerts.page_x == 14.4  # untouched default survives


def test_alerts_config_validation(tmp_path):
    from handel_tpu.sim.config import load_config

    bad = tmp_path / "bad.toml"
    bad.write_text("[alerts]\nfast_window_s = 900.0\nslow_window_s = 60.0\n")
    with pytest.raises(ValueError):
        load_config(str(bad))
    bad.write_text("[alerts]\nwarn_x = 20.0\npage_x = 14.4\n")
    with pytest.raises(ValueError):
        load_config(str(bad))
    bad.write_text("[alerts]\ngoodput_slo = 1.5\n")
    with pytest.raises(ValueError):
        load_config(str(bad))


# -- control wiring -----------------------------------------------------------


def test_autoscaler_incident_nudge_waives_cooldown():
    from handel_tpu.lifecycle.autoscaler import LaneAutoscaler

    class _Svc:
        fill_sum = 0.0
        fill_launches = 0

        class plane:
            lanes: list = []

        def queue_depth(self):
            return 0

    sc = LaneAutoscaler(_Svc(), engine_factory=lambda: None,
                        cooldown_s=3600.0)
    assert sc.values()["incidentNudgesCt"] == 0.0
    sc.notify_incident("breaker-storm")
    assert sc.incident_nudges == 1 and sc._repair_first


def test_breaker_transition_counter_and_callback():
    from handel_tpu.utils.breaker import CircuitBreaker

    seen: list[tuple[str, str]] = []
    t = {"now": 0.0}
    b = CircuitBreaker(threshold=2, cooldown_s=10.0,
                       clock=lambda: t["now"],
                       on_transition=lambda p, n: seen.append((p, n)))
    assert b.state == "closed" and b.transitions == 0
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()  # threshold: closed -> open
    assert b.state == "open"
    t["now"] = 11.0  # cooldown elapsed: open -> half-open (observed lazily)
    assert b.allow()
    b.record_success()  # half-open -> closed
    assert b.state == "closed"
    assert seen == [("closed", "open"), ("open", "half-open"),
                    ("half-open", "closed")]
    assert b.transitions == 3


def test_frontdoor_markdown_counter():
    from handel_tpu.sim.config import FederationParams
    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.service.federation import Federation

    fed = Federation(FederationParams(), scheme=FakeScheme())
    assert fed.values()["markdownCt"] == 0.0
    region = fed.region_names()[0]
    fed.front_door.mark(region, False)
    assert fed.front_door.markdowns == 1
    fed.front_door.mark(region, False)  # dedup: still-down is no new mark
    assert fed.front_door.markdowns == 1
    fed.front_door.mark(region, True)
    fed.front_door.mark(region, False)
    assert fed.values()["markdownCt"] == 2.0


# -- the chaos drill end to end (short in-process load run) -------------------


@pytest.mark.slow
def test_load_drill_exactly_one_attributed_incident(tmp_path):
    """The acceptance drill in miniature: a ~6 s open-loop run with a
    mid-run region kill opens exactly one incident, attributes it to the
    killed region, and closes it after recovery; the clean control run
    opens zero."""
    from handel_tpu.sim.config import (
        AlertParams,
        FederationParams,
        LoadParams,
    )
    from handel_tpu.sim.load import run_load

    lp = LoadParams(rate_sps=6.0, duration_s=6.0, nodes=6, seed=11,
                    deadline_s=8.0)
    fp = FederationParams(kill_region="us-east", kill_at_frac=0.35,
                          recover_at_frac=0.65)
    ap = AlertParams(window_scale=0.01, min_hold_s=0.5, cooldown_s=2.0,
                     tick_interval_s=0.1)
    report = asyncio.run(
        run_load(lp, fp, str(tmp_path / "drill"), alert_p=ap)
    )
    al = report["alerts"]
    assert al is not None
    incidents = al["report"]["incidents"]
    assert len(incidents) == 1, incidents
    inc = incidents[0]
    assert inc["state"] == "closed"
    assert "us-east" in inc["attribution"]["unhealthy_regions"]
    assert report["detection_latency_ms"] > 0.0
    assert report["detection_latency_ms"] < 2000.0
    assert report["false_positive_rate"] == 0.0
    assert os.path.exists(tmp_path / "drill" / "incident_report.json")

    # clean control: no kill -> zero incidents, zero false positives
    fp2 = FederationParams()
    report2 = asyncio.run(
        run_load(lp, fp2, str(tmp_path / "clean"), alert_p=ap)
    )
    assert report2["alerts"]["report"]["opened"] == 0
    assert report2["false_positive_rate"] == 0.0

"""Mesh latency plane & dual-mode scheduling (parallel/mesh_plane.py +
the mesh-lane integration in parallel/plane.py and batch_verifier.py).
Host-math engines only — no jax, no kernels: the sharded-kernel side of
the latency plane lives in tests/test_sharding.py and the MULTICHIP
smoke; here we pin the POLICY (which launch group rides the whole mesh),
the scheduler integration (pick vs pick_mesh, fallbacks, breaker
semantics), and the observability surfaces (mode counters, telemetry,
`sim watch` mode column)."""

import asyncio

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.test_harness import FakeScheme
from handel_tpu.models.fake import FakePublic, FakeSignature
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.parallel.mesh_plane import (
    MODE_LATENCY,
    MODE_THROUGHPUT,
    HostMeshDevice,
    ModePolicy,
    enable_latency_plane,
    host_mesh_engine,
)
from handel_tpu.parallel.plane import DevicePlane
from handel_tpu.service.fairness import TIERS
from handel_tpu.utils.breaker import CircuitBreaker

PKS = [FakePublic(True) for _ in range(16)]


class _Engine:
    batch_size = 64

    def __init__(self):
        self.dispatched = 0

    def dispatch_multi(self, items):
        self.dispatched += 1
        return [True] * len(items)

    def fetch(self, handle):
        return handle


def _req(tag: int, ok: bool = True, n: int = 16):
    bs = BitSet(n)
    bs.set(tag % n, True)
    return (bs, FakeSignature(ok))


# -- ModePolicy ----------------------------------------------------------


def test_mode_policy_routes_by_size_backlog_and_tier():
    pol = ModePolicy(small_batch_max=64, max_queue_depth=128)
    gold, bronze = TIERS["gold"], TIERS["bronze"]
    # small + shallow + gold -> latency
    assert pol.pick_mode(8, 0, gold, 64) == MODE_LATENCY
    # too big for the policy cap -> throughput
    assert pol.pick_mode(65, 0, gold, 128) == MODE_THROUGHPUT
    # too big for the MESH ENGINE's batch even if under the cap
    assert pol.pick_mode(16, 0, gold, 8) == MODE_THROUGHPUT
    # deep backlog -> throughput (K independent lanes beat one fast lane)
    assert pol.pick_mode(8, 129, gold, 64) == MODE_THROUGHPUT
    # tier not entitled to the mesh -> throughput
    assert pol.pick_mode(8, 0, bronze, 64) == MODE_THROUGHPUT
    assert pol.pick_mode(8, 0, TIERS["standard"], 64) == MODE_THROUGHPUT


def test_mode_policy_accepts_tier_names_and_custom_tiers():
    pol = ModePolicy(latency_tiers=("gold", "silver"))
    assert pol.pick_mode(4, 0, "silver", 64) == MODE_LATENCY
    assert pol.pick_mode(4, 0, TIERS["silver"], 64) == MODE_LATENCY
    assert pol.pick_mode(4, 0, "bronze", 64) == MODE_THROUGHPUT


# -- plane scheduling ----------------------------------------------------


def test_pick_never_returns_mesh_lane():
    plane = DevicePlane([_Engine(), _Engine()])
    mesh_lane = plane.add_lane(_Engine(), mesh=True)
    for _ in range(8):
        assert plane.pick() is not mesh_lane
    assert plane.mesh_lanes() == [mesh_lane]
    assert plane.values()["meshLanes"] == 1.0


def test_pick_mesh_only_returns_free_admissible_mesh_lane():
    plane = DevicePlane([_Engine()])
    br = CircuitBreaker(cooldown_s=600.0)
    mesh_lane = plane.add_lane(_Engine(), breaker=br, mesh=True)
    assert plane.pick_mesh() is mesh_lane
    assert plane.mesh_picks == 1
    # busy mesh lane -> None (the caller falls back to throughput)
    mesh_lane.dispatching = ["x"]
    assert plane.pick_mesh() is None
    mesh_lane.dispatching = None
    # breaker-open mesh lane -> None, and the census reflects it
    for _ in range(br.threshold):
        br.record_failure()
    assert plane.pick_mesh() is None
    assert plane.values()["meshLanesAvailable"] == 0.0
    assert plane.values()["meshLanes"] == 1.0


def test_remove_lane_guards_last_throughput_lane():
    plane = DevicePlane([_Engine()])
    plane.add_lane(_Engine(), mesh=True)
    with pytest.raises(ValueError, match="throughput"):
        plane.remove_lane(plane.lanes[0])
    # removing the mesh lane instead is fine
    plane.remove_lane(plane.lanes[1])
    assert plane.mesh_lanes() == []


def test_mesh_only_plane_throughput_pool_falls_back():
    """A plane built purely of mesh lanes must not deadlock the collector:
    the throughput pool degrades to the whole admissible set."""
    plane = DevicePlane([_Engine()])
    plane.lanes[0].mesh = True
    assert plane.throughput_pool() == plane.lanes
    assert plane.pick() is plane.lanes[0]


def test_lane_mode_metric_row():
    plane = DevicePlane([_Engine()])
    mesh_lane = plane.add_lane(_Engine(), mesh=True)
    assert plane.lanes[0].values()["mode"] == 0.0
    assert mesh_lane.values()["mode"] == 1.0
    assert "mode" in plane.labeled_gauge_keys()


def test_plane_batch_size_ignores_mesh_lane():
    """The collector's drain width must stay the THROUGHPUT batch: a
    small-batch mesh engine must not shrink it."""

    class _Small(_Engine):
        batch_size = 8

    plane = DevicePlane([_Engine()])
    plane.add_lane(_Small(), mesh=True)
    assert plane.batch_size == 64


# -- HostMeshDevice ------------------------------------------------------


def test_host_mesh_device_verdicts_and_counters():
    scheme = FakeScheme()
    eng = HostMeshDevice(
        scheme.constructor, batch_size=8, devices=4,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    items = [
        (b"m", PKS, *_req(i, ok=(i != 3))) for i in range(6)
    ]
    got = eng.fetch(eng.dispatch_multi(items))
    assert got == [True, True, True, False, True, True]
    assert eng.mesh_launches == 1 and eng.mesh_candidates == 6
    # shard merge must preserve item order at every devices count
    eng1 = HostMeshDevice(
        scheme.constructor, batch_size=8, devices=1,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    assert eng1.dispatch_multi(items) == got


def test_host_mesh_device_epoch_parity():
    eng = host_mesh_engine(
        FakeScheme().constructor, devices=2, per_candidate_ms=0.0,
        collective_ms=0.0,
    )
    with pytest.raises(RuntimeError, match="stage_registry"):
        eng.activate_staged()
    assert eng.stage_registry(PKS) == len(PKS)
    assert eng.registry_stagings == 1
    assert eng.activate_staged() == 1
    assert eng.epoch == 1


# -- service integration -------------------------------------------------


def _mesh_service(
    mesh_eng,
    lanes: int = 2,
    policy: ModePolicy | None = None,
    mesh_breaker: CircuitBreaker | None = None,
):
    plane = DevicePlane([_Engine() for _ in range(lanes)])
    svc = BatchVerifierService(plane, max_delay_ms=0.1)
    enable_latency_plane(
        svc, mesh_eng, policy=policy or ModePolicy(small_batch_max=8),
        breaker=mesh_breaker,
    )
    svc.queue.set_tier("gold0", "gold")
    return svc, plane


def test_gold_small_group_rides_mesh_lane():
    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=4,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, plane = _mesh_service(mesh_eng)

    async def go():
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(b"gold", PKS, [_req(i)], session="gold0")
                    for i in range(8)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert mesh_eng.mesh_launches >= 1
    assert vals["modeLatencyLaunches"] >= 1.0
    assert vals["meshFallbacks"] == 0.0
    assert vals["meshLaunches"] >= 1.0
    # the throughput lanes carried nothing
    assert all(l.engine.dispatched == 0 for l in plane.lanes if not l.mesh)


def test_standard_tier_group_stays_on_lanes():
    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=4,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, plane = _mesh_service(mesh_eng)

    async def go():
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(b"bulk", PKS, [_req(i)], session="std")
                    for i in range(8)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert mesh_eng.mesh_launches == 0
    assert vals["modeLatencyLaunches"] == 0.0
    assert vals["modeThroughputLaunches"] >= 1.0
    assert sum(l.engine.dispatched for l in plane.lanes if not l.mesh) >= 1


def test_oversized_gold_group_stays_on_lanes():
    """Gold entitlement does not override the size gate: a group bigger
    than the mesh engine's batch rides the throughput path."""
    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=4,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, plane = _mesh_service(
        mesh_eng, policy=ModePolicy(small_batch_max=64)
    )

    async def go():
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(b"big", PKS, [_req(i)], session="gold0")
                    for i in range(24)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert mesh_eng.mesh_launches == 0
    assert vals["modeThroughputLaunches"] >= 1.0


def test_breaker_open_mesh_lane_degrades_to_throughput():
    """An open mesh breaker makes latency mode unavailable — groups fall
    back to the lanes (counted), never to failover."""
    br = CircuitBreaker(cooldown_s=600.0)
    for _ in range(br.threshold):
        br.record_failure()
    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=4,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, plane = _mesh_service(mesh_eng, mesh_breaker=br)

    async def go():
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(b"gold", PKS, [_req(i)], session="gold0")
                    for i in range(8)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert mesh_eng.mesh_launches == 0
    assert vals["meshFallbacks"] >= 1.0
    assert vals["meshLanesAvailable"] == 0.0
    assert vals["failoverBatches"] == 0.0
    assert sum(l.engine.dispatched for l in plane.lanes if not l.mesh) >= 1


def test_service_gauge_keys_and_values_expose_mode_counters():
    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=2,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, _ = _mesh_service(mesh_eng)
    try:
        vals = svc.values()
        for key in (
            "modeLatencyLaunches", "modeThroughputLaunches",
            "meshFallbacks", "meshLanes", "meshLanesAvailable",
            "meshPicks", "meshLaunches",
        ):
            assert key in vals, key
        assert vals["meshLanes"] == 1.0
        assert {"meshLanes", "meshLanesAvailable"} <= svc.gauge_keys()
    finally:
        svc.stop()


def test_device_telemetry_reports_mesh_census():
    from handel_tpu.parallel.telemetry import DeviceTelemetry

    mesh_eng = HostMeshDevice(
        FakeScheme().constructor, batch_size=8, devices=2,
        per_candidate_ms=0.0, collective_ms=0.0,
    )
    svc, _ = _mesh_service(mesh_eng)
    try:
        tel = DeviceTelemetry(service=svc)
        vals = tel.values()
        assert vals["meshLanes"] == 1.0
        assert vals["meshLanesAvailable"] == 1.0
        assert {"meshLanes", "meshLanesAvailable"} <= tel.gauge_keys()
    finally:
        svc.stop()


def test_mesh_knobs_roundtrip_and_cluster_attaches_lane(tmp_path):
    """[service] mesh_devices/mesh_batch_size flow through load_config and
    dump_config, and a cluster built with them serves a run with one mesh
    lane beside the throughput lanes."""
    from handel_tpu.service.driver import MultiSessionCluster
    from handel_tpu.sim.config import dump_config, load_config

    p = tmp_path / "sim.toml"
    p.write_text(
        "[sim]\nnodes = 8\n\n[service]\nsessions = 2\ndevices = 2\n"
        "mesh_devices = 4\nmesh_batch_size = 8\n"
    )
    cfg = load_config(str(p))
    assert cfg.service.mesh_devices == 4
    assert cfg.service.mesh_batch_size == 8
    dumped = dump_config(cfg)
    assert "mesh_devices = 4" in dumped and "mesh_batch_size = 8" in dumped
    # absent keys keep the latency plane off
    p.write_text("[sim]\nnodes = 8\n\n[service]\nsessions = 1\n")
    assert load_config(str(p)).service.mesh_devices == 0

    cluster = MultiSessionCluster(
        2, 8, devices=2, mesh_devices=4, mesh_batch_size=8,
        tier_cycle=("gold",),
    )
    try:
        plane = cluster.service.plane
        assert len(plane.mesh_lanes()) == 1
        assert len(plane.throughput_pool()) == 2
        assert plane.mesh_lanes()[0].engine.mesh_devices == 4
        out = asyncio.run(cluster.run(timeout=60.0))
        assert out["completed"] == 2
    finally:
        cluster.stop()


def test_watch_renders_mode_column_and_mesh_summary():
    """sim watch devices block: per-lane mode column plus the mesh summary
    line fed by the mode counters."""
    from handel_tpu.sim.watch_cli import aggregate, parse_exposition, render

    text = (
        'handel_device_verifier_launches{device="0"} 5\n'
        'handel_device_verifier_mode{device="0"} 0\n'
        'handel_device_verifier_launches{device="2"} 3\n'
        'handel_device_verifier_fill_ratio{device="2"} 0.75\n'
        'handel_device_verifier_mode{device="2"} 1\n'
        "handel_device_verifier_mesh_lanes 1\n"
        "handel_device_verifier_mesh_launches 3\n"
        "handel_device_verifier_mode_latency_launches 3\n"
        "handel_device_verifier_mode_throughput_launches 5\n"
        "handel_device_verifier_mesh_fallbacks 1\n"
    )
    model = aggregate([parse_exposition(text)])
    assert model["devices"]["2"]["mode"] == 1.0
    assert model["mesh_lanes"] == 1.0
    assert model["mesh_launches"] == 3.0
    assert model["mode_latency"] == 3.0
    assert model["mode_throughput"] == 5.0
    assert model["mesh_fallbacks"] == 1.0
    out = render(model, ["x"], 1, 1)
    assert "mode mesh" in out
    assert "mode lane" in out
    assert "1 mesh" in out
    assert "latency 3" in out or "3 latency" in out

"""Real-crypto protocol integration: BN254 BLS end-to-end over the in-process
network (reference model: bn256/cf/bn256_test.go:13-37, a 37-node cluster;
smaller here because the pure-Python oracle backend is ~100ms/verify — the
JAX and C++ backends run the larger configs).
"""

import asyncio

import pytest

from handel_tpu.core.config import Config
from handel_tpu.core.crypto import verify_multisignature
from handel_tpu.core.test_harness import LocalCluster
from handel_tpu.models.bn254 import BN254Scheme

MSG = b"hello world"


@pytest.mark.slow
def test_bn254_end_to_end():
    scheme = BN254Scheme()

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=60.0)
            return cluster, res
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(MSG, sig, cluster.registry, scheme.constructor)


@pytest.mark.slow
def test_bn254_jax_device_end_to_end():
    """The protocol with verification ON THE DEVICE PATH: an 8-node cluster
    whose Constructor.batch_verify runs the batched aggregation +
    product-of-pairings launch (models/bn254_jax.py) — the wiring the whole
    framework exists for (VERDICT r1 item 2)."""
    from handel_tpu.models.bn254_jax import BN254JaxScheme

    scheme = BN254JaxScheme(batch_size=8)

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            return cluster, await cluster.wait_complete_success(timeout=900.0)
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(
            MSG, sig, cluster.registry, scheme.constructor
        )


@pytest.mark.slow
def test_bls12_381_jax_device_end_to_end():
    """Same protocol wiring on the second device curve (bls12-381-jax)."""
    from handel_tpu.models.bls12_381_jax import BLS12381JaxScheme

    scheme = BLS12381JaxScheme(batch_size=8)

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            return cluster, await cluster.wait_complete_success(timeout=900.0)
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(
            MSG, sig, cluster.registry, scheme.constructor
        )

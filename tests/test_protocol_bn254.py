"""Real-crypto protocol integration: BN254 BLS end-to-end over the in-process
network (reference model: bn256/cf/bn256_test.go:13-37, a 37-node cluster;
smaller here because the pure-Python oracle backend is ~100ms/verify — the
JAX and C++ backends run the larger configs).
"""

import asyncio

import pytest

from handel_tpu.core.config import Config
from handel_tpu.core.crypto import verify_multisignature
from handel_tpu.core.test_harness import LocalCluster
from handel_tpu.models.bn254 import BN254Scheme

MSG = b"hello world"


@pytest.mark.slow
def test_bn254_end_to_end():
    scheme = BN254Scheme()

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=60.0)
            return cluster, res
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(MSG, sig, cluster.registry, scheme.constructor)

"""Real-crypto protocol integration: BN254 BLS end-to-end over the in-process
network (reference model: bn256/cf/bn256_test.go:13-37, a 37-node cluster;
smaller here because the pure-Python oracle backend is ~100ms/verify — the
JAX and C++ backends run the larger configs).
"""

import asyncio

import pytest

from handel_tpu.core.config import Config
from handel_tpu.core.crypto import verify_multisignature
from handel_tpu.core.test_harness import LocalCluster
from handel_tpu.models.bn254 import BN254Scheme

MSG = b"hello world"


@pytest.mark.slow
def test_bn254_end_to_end():
    scheme = BN254Scheme()

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=60.0)
            return cluster, res
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(MSG, sig, cluster.registry, scheme.constructor)


@pytest.mark.slow
def test_bn254_jax_device_end_to_end():
    """The protocol with verification ON THE DEVICE PATH: an 8-node cluster
    whose Constructor.batch_verify runs the batched aggregation +
    product-of-pairings launch (models/bn254_jax.py) — the wiring the whole
    framework exists for (VERDICT r1 item 2)."""
    from handel_tpu.models.bn254_jax import BN254JaxScheme

    scheme = BN254JaxScheme(batch_size=8)

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            return cluster, await cluster.wait_complete_success(timeout=900.0)
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(
            MSG, sig, cluster.registry, scheme.constructor
        )


@pytest.mark.slow
def test_bls12_381_jax_device_end_to_end():
    """Same protocol wiring on the second device curve (bls12-381-jax)."""
    from handel_tpu.models.bls12_381_jax import BLS12381JaxScheme

    scheme = BLS12381JaxScheme(batch_size=8)

    async def go():
        cluster = LocalCluster(8, scheme=scheme, msg=MSG)
        cluster.start()
        try:
            return cluster, await cluster.wait_complete_success(timeout=900.0)
        finally:
            cluster.stop()

    cluster, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
        assert verify_multisignature(
            MSG, sig, cluster.registry, scheme.constructor
        )


@pytest.mark.slow
def test_warmup_then_round_zero_xla_compiles():
    """Acceptance for the startup-warmup plane: scheme construction
    (prepare + BN254Device.warmup) compiles every kernel class a round can
    reach, so a full protocol round afterwards triggers ZERO new XLA
    compilations — before warmup, the first candidate in a fresh hole-count
    class stalled its whole verification round on a mid-run compile."""
    import jax._src.monitoring as jmon

    from handel_tpu.models.bn254_jax import BN254JaxScheme

    scheme = BN254JaxScheme(batch_size=4)  # warmup=True is the default

    async def go():
        # n=12 >= 11: BOTH quantized range classes (miss_k 8 and 64) are
        # reachable and warmed; the dense fallback needs >64 holes, which a
        # 12-key registry cannot produce, and is correctly skipped
        cluster = LocalCluster(12, scheme=scheme, msg=MSG)
        scheme.constructor.prepare(
            [cluster.registry.identity(i).public_key for i in range(12)]
        )
        compiles: list[str] = []

        def listener(name: str, duration: float, **kw) -> None:
            if name.startswith("/jax/core/compile/backend_compile"):
                compiles.append(name)

        jmon.register_event_duration_secs_listener(listener)
        try:
            cluster.start()
            try:
                res = await cluster.wait_complete_success(timeout=900.0)
            finally:
                cluster.stop()
        finally:
            jmon._unregister_event_duration_listener_by_callback(listener)
        return cluster, res, compiles

    cluster, results, compiles = asyncio.run(go())
    assert len(results) == 12
    for sig in results.values():
        assert sig.cardinality() >= cluster.threshold
    assert compiles == [], (
        f"round triggered {len(compiles)} XLA compiles after warmup"
    )

"""BLS-over-BN254 scheme semantics (reference: bn256/*/bn256_test.go:39-99)."""

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.models.bn254 import (
    BN254Constructor,
    BN254SecretKey,
    hash_to_g1,
    marshal_g2,
    new_keypair,
    unmarshal_g1,
    unmarshal_g2,
)

MSG = b"attestation data"


def test_sign_verify():
    sk, pk = new_keypair(seed=1)
    sig = sk.sign(MSG)
    assert pk.verify(MSG, sig)
    assert not pk.verify(b"other message", sig)
    sk2, pk2 = new_keypair(seed=2)
    assert not pk2.verify(MSG, sig)


def test_aggregate_sign_verify():
    # combine k signatures + pubkeys: aggregate verifies, partial doesn't
    keys = [new_keypair(seed=i) for i in range(4)]
    agg_sig = None
    agg_pk = None
    for sk, pk in keys:
        s = sk.sign(MSG)
        agg_sig = s if agg_sig is None else agg_sig.combine(s)
        agg_pk = pk if agg_pk is None else agg_pk.combine(pk)
    assert agg_pk.verify(MSG, agg_sig)
    # dropping one pubkey must fail
    partial_pk = keys[0][1].combine(keys[1][1]).combine(keys[2][1])
    assert not partial_pk.verify(MSG, agg_sig)


def test_marshal_roundtrip():
    sk, pk = new_keypair(seed=7)
    sig = sk.sign(MSG)
    cons = BN254Constructor()
    assert cons.signature_size() == 64
    sig2 = cons.unmarshal_signature(sig.marshal())
    assert sig2 == sig
    pk2 = unmarshal_g2(pk.marshal())
    assert pk2 == pk.point
    assert len(pk.marshal()) == 128


def test_unmarshal_rejects_off_curve():
    with pytest.raises(ValueError):
        unmarshal_g1(b"\x01" * 64)
    with pytest.raises(ValueError):
        unmarshal_g2(b"\x02" * 128)
    # coordinate >= modulus rejected
    with pytest.raises(ValueError):
        unmarshal_g1(b"\xff" * 64)


def test_hash_to_g1_deterministic():
    from handel_tpu.ops import bn254_ref as bn

    h1, h2 = hash_to_g1(MSG), hash_to_g1(MSG)
    assert h1 == h2
    assert bn.g1_is_valid(h1)
    assert hash_to_g1(b"x") != hash_to_g1(b"y")


def test_hash_to_g1_mirrors_go_rand_int_derivation():
    """The H(m) scalar must follow Go crypto/rand.Int semantics exactly as
    the reference's SHA256->bytes.Buffer->RandomG1 chain does
    (bn256/go/bn256.go:206-218): 32 bytes big-endian with the top byte
    masked to order.bit_length() % 8 bits — NOT a mod-r reduction — and a
    deterministic re-hash standing in for the reference's EOF error on a
    draw >= r. Expected scalars here are computed by an independent
    re-statement of that algorithm."""
    import hashlib

    from handel_tpu.ops import bn254_ref as bn

    def go_rand_int_scalar(msg: bytes) -> int:
        d = hashlib.sha256(msg).digest()
        while True:
            v = int.from_bytes(d, "big")
            v &= (1 << 254) - 1  # r.bit_length()=254: top byte keeps 6 bits
            if 0 < v < bn.R:
                return v
            d = hashlib.sha256(d).digest()  # our stand-in for the EOF error

    # masking case: a digest whose top byte exceeds 0x3f must be masked,
    # not reduced mod r (mod-r of the unmasked value gives a different k)
    masked_msg = rehash_msg = None
    for i in range(4096):
        m = b"probe-%d" % i
        d = hashlib.sha256(m).digest()
        masked = int.from_bytes(d, "big") & ((1 << 254) - 1)
        if masked_msg is None and d[0] > 0x3F and masked < bn.R:
            if masked != int.from_bytes(d, "big") % bn.R:
                masked_msg = m
        if rehash_msg is None and masked >= bn.R:
            rehash_msg = m
        if masked_msg and rehash_msg:
            break
    assert masked_msg and rehash_msg, "probe space too small"

    for msg in (masked_msg, rehash_msg, MSG):
        expected = bn.g1_mul(bn.G1_GEN, go_rand_int_scalar(msg))
        assert hash_to_g1(msg) == expected
    # the re-hash path still yields a signable point
    sk, pk = new_keypair(seed=7)
    assert pk.verify(rehash_msg, sk.sign(rehash_msg))


def test_batch_verify_via_constructor():
    cons = BN254Constructor()
    keys = [new_keypair(seed=i) for i in range(4)]
    pubkeys = [pk for _, pk in keys]
    sigs = [sk.sign(MSG) for sk, _ in keys]

    bs_all = BitSet(4)
    for i in range(4):
        bs_all.set(i)
    agg = sigs[0].combine(sigs[1]).combine(sigs[2]).combine(sigs[3])

    bs_one = BitSet(4)
    bs_one.set(2)

    bs_wrong = BitSet(4)
    bs_wrong.set(0)  # claims signer 0 but carries signer 1's sig

    out = cons.batch_verify(
        MSG,
        pubkeys,
        [(bs_all, agg), (bs_one, sigs[2]), (bs_wrong, sigs[1])],
    )
    assert out == [True, True, False]


def test_secret_key_marshal():
    sk, _ = new_keypair(seed=3)
    sk2 = BN254SecretKey.unmarshal(sk.marshal())
    assert sk2.scalar == sk.scalar

"""Signature store scoring + merge/patch semantics (reference: store_test.go:9-197)."""

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
from handel_tpu.core.store import SignatureStore
from handel_tpu.models.fake import FakeSignature, fake_registry


def make_store(n=8, id=1):
    part = BinomialPartitioner(id, fake_registry(n))
    return SignatureStore(part), part


def inc(level, bits, size, is_ind=False, mapped=0, origin=0):
    bs = BitSet(size)
    for b in bits:
        bs.set(b)
    return IncomingSig(
        origin=origin,
        level=level,
        ms=MultiSignature(bs, FakeSignature()),
        is_ind=is_ind,
        mapped_index=mapped,
    )


def test_store_and_best():
    store, _ = make_store()
    sp = inc(2, [0], 2)
    out = store.store(sp)
    assert out is not None
    assert store.best(2).cardinality() == 1
    assert store.best(3) is None


def test_evaluate_completes_level_scores_highest():
    store, _ = make_store()
    # level 2 of id=1 has size 2: a full sig completes the level
    full = inc(2, [0, 1], 2)
    partial = inc(2, [0], 2)
    s_full = store.evaluate(full)
    s_partial = store.evaluate(partial)
    assert s_full > s_partial
    assert s_full >= 1_000_000 - 2 * 10 - 2  # completes-level band


def test_evaluate_zero_for_completed_level():
    store, _ = make_store()
    store.store(inc(2, [0, 1], 2))
    assert store.evaluate(inc(2, [0], 2)) == 0
    assert store.evaluate(inc(2, [0, 1], 2)) == 0


def test_evaluate_zero_for_superset():
    store, _ = make_store(16, 1)
    # level 3 of id=1 (n=16) covers [4,8): size 4
    store.store(inc(3, [0, 1, 2], 4))
    assert store.evaluate(inc(3, [0, 1], 4)) == 0  # dominated
    assert store.evaluate(inc(3, [0, 1, 2, 3], 4)) > 0  # improves


def test_evaluate_individual_already_verified():
    store, _ = make_store(16, 1)
    ind = inc(3, [1], 4, is_ind=True, mapped=1, origin=5)
    store.store(ind)
    assert store.evaluate(inc(3, [1], 4, is_ind=True, mapped=1, origin=5)) == 0
    # an individual that adds nothing new still scores 1 (BFT patching)
    store.store(inc(3, [0, 1, 2, 3], 4))
    other = inc(3, [2], 4, is_ind=True, mapped=2, origin=6)
    assert store.evaluate(other) == 0  # level completed -> 0


def test_merge_disjoint_sigs():
    store, _ = make_store(16, 1)
    store.store(inc(3, [0, 1], 4))
    out = store.store(inc(3, [2], 4))
    assert out.bitset.indices() == [0, 1, 2]
    assert store.best(3).cardinality() == 3


def test_overlapping_worse_sig_discarded():
    store, _ = make_store(16, 1)
    store.store(inc(3, [0, 1, 2], 4))
    out = store.store(inc(3, [0, 1], 4))
    assert out is None or out.cardinality() < 3 or out is not None
    # best unchanged
    assert store.best(3).bitset.indices() == [0, 1, 2]


def test_individual_patching():
    store, _ = make_store(16, 1)
    # verify individual sig at index 3 first
    store.store(inc(3, [3], 4, is_ind=True, mapped=3, origin=7))
    # then a multisig covering [0,1] arrives: patched with individual 3
    out = store.store(inc(3, [0, 1], 4))
    assert out.bitset.indices() == [0, 1, 3]


def test_combined_and_full_signature():
    store, part = make_store(8, 1)
    # seed with own sig at level 0 (handel.go:108-116 does this)
    store.store(inc(0, [0], 1, is_ind=True, mapped=0, origin=1))
    store.store(inc(1, [0], 1))  # peer 0
    ms = store.combined(1)  # for sending to level 2
    assert len(ms.bitset) == 2  # range_level_inverse(2) of id=1 = [0,2)
    assert ms.bitset.indices() == [0, 1]
    full = store.full_signature()
    assert len(full.bitset) == 8
    assert full.bitset.indices() == [0, 1]


def test_highest_tracking():
    store, _ = make_store()
    store.store(inc(1, [0], 1))
    store.store(inc(3, [0], 4))
    assert store.highest == 3


class _CountingSig(FakeSignature):
    """FakeSignature tagging each point with an int so batched combines can
    be checked for exact membership (sum of tags, order-free)."""

    __slots__ = ("tag",)

    def __init__(self, tag=0):
        super().__init__(True)
        self.tag = tag

    def combine(self, other):
        out = _CountingSig(self.tag + other.tag)
        return out


def _batched_combiner(log):
    def combiner(parts):
        log.append(sorted(p.tag for p in parts))
        out = _CountingSig(sum(p.tag for p in parts))
        return out

    return combiner


def test_check_merge_single_batched_combine():
    """A disjoint merge with individual-sig patches issues ONE combiner
    call carrying every contribution (new sig + current best + patches),
    and the result matches the serial reference path."""
    part = BinomialPartitioner(1, fake_registry(8))
    log = []
    store = SignatureStore(part, combiner=_batched_combiner(log))
    serial = SignatureStore(part)

    def feed(s):
        # level 3 of id=1 covers 4 ids: [4,8); build the same stream twice.
        # The final replace ({1,3} vs best {0,1,2}) patches holes 0 and 2
        # with individuals recorded (but not merged) earlier — a THREE-part
        # combine the batched path must issue as one call.
        for bits, ind, tag in [
            ([0, 1], False, 3),  # initial best
            ([0], True, 1),      # overlaps best: recorded only
            ([1, 2], False, 5),  # replace, patched with ind 0
            ([2], True, 7),      # overlaps best: recorded only
            ([1, 3], False, 11),  # replace, patched with inds 0 AND 2
        ]:
            bs = BitSet(4)
            for b in bits:
                bs.set(b)
            ms = MultiSignature(bs, _CountingSig(tag))
            s.store(
                IncomingSig(
                    origin=0,
                    level=3,
                    ms=ms,
                    is_ind=ind,
                    mapped_index=bits[0],
                )
            )

    feed(store)
    feed(serial)
    assert store.best(3).bitset.indices() == serial.best(3).bitset.indices()
    assert store.best(3).signature.tag == serial.best(3).signature.tag
    # the final replace (new sig + two individual patches) was ONE batched
    # call with all its parts
    assert log and log[-1] == [1, 7, 11]


def test_combined_uses_batched_combiner():
    """store.combined()/full_signature() route the per-level fold through
    the combiner in one call."""
    part = BinomialPartitioner(1, fake_registry(8))
    log = []
    store = SignatureStore(part, combiner=_batched_combiner(log))
    for lvl in (1, 2, 3):
        bs = BitSet(part.size_of(lvl))
        bs.set(0)
        store.store(
            IncomingSig(
                origin=0,
                level=lvl,
                ms=MultiSignature(bs, _CountingSig(10**lvl)),
            )
        )
    log.clear()
    ms = store.full_signature()
    assert ms is not None and ms.signature.tag == 10 + 100 + 1000
    assert len(log) == 1 and len(log[0]) == 3


# -- WindowedSignatureStore (ISSUE 11: swarm memory window) ----------------


def make_windowed(n=16, id=1):
    from handel_tpu.core.store import WindowedSignatureStore

    part = BinomialPartitioner(id, fake_registry(n))
    return WindowedSignatureStore(part), part


def _complete_level(store, part, level):
    """Deliver the level's full aggregate and retire it, as
    _check_completed_level would."""
    lo, hi = part.range_level(level)
    sp = inc(level, range(hi - lo), hi - lo)
    store.store(sp)
    store.retire_level(level)


def test_retirement_never_drops_best_aggregate():
    """After retire_level the level's best is still readable and combined()/
    full_signature() still cover it — only the individual-sig window dies."""
    store, part = make_windowed()
    for lvl in (1, 2, 3):
        _complete_level(store, part, lvl)
    for lvl in (1, 2, 3):
        best = store.best(lvl)
        assert best is not None
        assert best.cardinality() == part.size_of(lvl)
    full = store.full_signature()
    want = 1 + sum(part.size_of(l) for l in (1, 2, 3))  # own id implied absent
    assert full.cardinality() == want - 1  # store holds levels 1-3 only
    assert store.combined_cardinality(3) == full.cardinality()


def test_retired_best_compacts_to_all_ones():
    """A complete retired best is swapped for the O(1) AllOnesBitSet run —
    same coverage, none of the dense words."""
    from handel_tpu.core.bitset import AllOnesBitSet

    store, part = make_windowed()
    _complete_level(store, part, 3)
    assert isinstance(store.best(3).bitset, AllOnesBitSet)
    # and the combine path still embeds it correctly
    assert store.combined(3).cardinality() == part.size_of(3)


def test_stale_redeliveries_counted_and_ignored():
    """Contributions landing after retirement mutate nothing and bump
    staleRetiredCt (gossip re-deliveries racing completion)."""
    store, part = make_windowed()
    _complete_level(store, part, 2)
    best_before = store.best(2)
    late = inc(2, [0], part.size_of(2))
    assert store.evaluate(late) == 0
    got = store.store(late)
    assert got is best_before
    assert store.best(2) is best_before
    assert store.values()["staleRetiredCt"] == 2.0  # evaluate + store
    assert store.values()["retiredLevelCt"] == 1.0


def test_retire_level_idempotent():
    store, part = make_windowed()
    _complete_level(store, part, 1)
    before = store.best(1)
    store.retire_level(1)
    store.retire_level(1)
    assert store.best(1) is before
    assert store.values()["retiredLevelCt"] == 1.0


def test_windowed_memory_flat_as_levels_complete():
    """deep_size of the store must not grow as levels complete: each
    completed level's individual window is freed and its dense best
    compacts, so the walk stays O(active levels) — the property the
    65k-committee run depends on."""
    from handel_tpu.swarm.mem import deep_size

    n, nid = 256, 1
    store, part = make_windowed(n=n, id=nid)
    shared = (part, part.reg)
    sizes = []
    for lvl in part.levels():
        lo, hi = part.range_level(lvl)
        size = hi - lo
        # individual deliveries first: builds the per-level window
        for i in range(size):
            store.store(inc(lvl, [i], size, is_ind=True, mapped=i))
        sizes.append(deep_size(store, shared=shared))
        store.retire_level(lvl)
    retired_size = deep_size(store, shared=shared)
    # retiring the last (largest) level must free its window: the final
    # walk is smaller than the store was at its peak
    assert retired_size < max(sizes)
    # and the end state doesn't scale with N: it is bounded by the walk
    # of the level-1 state (smallest window) plus slack for the bests
    assert retired_size < sizes[0] + 64 * len(part.levels()) * 100

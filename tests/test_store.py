"""Signature store scoring + merge/patch semantics (reference: store_test.go:9-197)."""

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
from handel_tpu.core.store import SignatureStore
from handel_tpu.models.fake import FakeSignature, fake_registry


def make_store(n=8, id=1):
    part = BinomialPartitioner(id, fake_registry(n))
    return SignatureStore(part), part


def inc(level, bits, size, is_ind=False, mapped=0, origin=0):
    bs = BitSet(size)
    for b in bits:
        bs.set(b)
    return IncomingSig(
        origin=origin,
        level=level,
        ms=MultiSignature(bs, FakeSignature()),
        is_ind=is_ind,
        mapped_index=mapped,
    )


def test_store_and_best():
    store, _ = make_store()
    sp = inc(2, [0], 2)
    out = store.store(sp)
    assert out is not None
    assert store.best(2).cardinality() == 1
    assert store.best(3) is None


def test_evaluate_completes_level_scores_highest():
    store, _ = make_store()
    # level 2 of id=1 has size 2: a full sig completes the level
    full = inc(2, [0, 1], 2)
    partial = inc(2, [0], 2)
    s_full = store.evaluate(full)
    s_partial = store.evaluate(partial)
    assert s_full > s_partial
    assert s_full >= 1_000_000 - 2 * 10 - 2  # completes-level band


def test_evaluate_zero_for_completed_level():
    store, _ = make_store()
    store.store(inc(2, [0, 1], 2))
    assert store.evaluate(inc(2, [0], 2)) == 0
    assert store.evaluate(inc(2, [0, 1], 2)) == 0


def test_evaluate_zero_for_superset():
    store, _ = make_store(16, 1)
    # level 3 of id=1 (n=16) covers [4,8): size 4
    store.store(inc(3, [0, 1, 2], 4))
    assert store.evaluate(inc(3, [0, 1], 4)) == 0  # dominated
    assert store.evaluate(inc(3, [0, 1, 2, 3], 4)) > 0  # improves


def test_evaluate_individual_already_verified():
    store, _ = make_store(16, 1)
    ind = inc(3, [1], 4, is_ind=True, mapped=1, origin=5)
    store.store(ind)
    assert store.evaluate(inc(3, [1], 4, is_ind=True, mapped=1, origin=5)) == 0
    # an individual that adds nothing new still scores 1 (BFT patching)
    store.store(inc(3, [0, 1, 2, 3], 4))
    other = inc(3, [2], 4, is_ind=True, mapped=2, origin=6)
    assert store.evaluate(other) == 0  # level completed -> 0


def test_merge_disjoint_sigs():
    store, _ = make_store(16, 1)
    store.store(inc(3, [0, 1], 4))
    out = store.store(inc(3, [2], 4))
    assert out.bitset.indices() == [0, 1, 2]
    assert store.best(3).cardinality() == 3


def test_overlapping_worse_sig_discarded():
    store, _ = make_store(16, 1)
    store.store(inc(3, [0, 1, 2], 4))
    out = store.store(inc(3, [0, 1], 4))
    assert out is None or out.cardinality() < 3 or out is not None
    # best unchanged
    assert store.best(3).bitset.indices() == [0, 1, 2]


def test_individual_patching():
    store, _ = make_store(16, 1)
    # verify individual sig at index 3 first
    store.store(inc(3, [3], 4, is_ind=True, mapped=3, origin=7))
    # then a multisig covering [0,1] arrives: patched with individual 3
    out = store.store(inc(3, [0, 1], 4))
    assert out.bitset.indices() == [0, 1, 3]


def test_combined_and_full_signature():
    store, part = make_store(8, 1)
    # seed with own sig at level 0 (handel.go:108-116 does this)
    store.store(inc(0, [0], 1, is_ind=True, mapped=0, origin=1))
    store.store(inc(1, [0], 1))  # peer 0
    ms = store.combined(1)  # for sending to level 2
    assert len(ms.bitset) == 2  # range_level_inverse(2) of id=1 = [0,2)
    assert ms.bitset.indices() == [0, 1]
    full = store.full_signature()
    assert len(full.bitset) == 8
    assert full.bitset.indices() == [0, 1]


def test_highest_tracking():
    store, _ = make_store()
    store.store(inc(1, [0], 1))
    store.store(inc(3, [0], 4))
    assert store.highest == 3


class _CountingSig(FakeSignature):
    """FakeSignature tagging each point with an int so batched combines can
    be checked for exact membership (sum of tags, order-free)."""

    __slots__ = ("tag",)

    def __init__(self, tag=0):
        super().__init__(True)
        self.tag = tag

    def combine(self, other):
        out = _CountingSig(self.tag + other.tag)
        return out


def _batched_combiner(log):
    def combiner(parts):
        log.append(sorted(p.tag for p in parts))
        out = _CountingSig(sum(p.tag for p in parts))
        return out

    return combiner


def test_check_merge_single_batched_combine():
    """A disjoint merge with individual-sig patches issues ONE combiner
    call carrying every contribution (new sig + current best + patches),
    and the result matches the serial reference path."""
    part = BinomialPartitioner(1, fake_registry(8))
    log = []
    store = SignatureStore(part, combiner=_batched_combiner(log))
    serial = SignatureStore(part)

    def feed(s):
        # level 3 of id=1 covers 4 ids: [4,8); build the same stream twice.
        # The final replace ({1,3} vs best {0,1,2}) patches holes 0 and 2
        # with individuals recorded (but not merged) earlier — a THREE-part
        # combine the batched path must issue as one call.
        for bits, ind, tag in [
            ([0, 1], False, 3),  # initial best
            ([0], True, 1),      # overlaps best: recorded only
            ([1, 2], False, 5),  # replace, patched with ind 0
            ([2], True, 7),      # overlaps best: recorded only
            ([1, 3], False, 11),  # replace, patched with inds 0 AND 2
        ]:
            bs = BitSet(4)
            for b in bits:
                bs.set(b)
            ms = MultiSignature(bs, _CountingSig(tag))
            s.store(
                IncomingSig(
                    origin=0,
                    level=3,
                    ms=ms,
                    is_ind=ind,
                    mapped_index=bits[0],
                )
            )

    feed(store)
    feed(serial)
    assert store.best(3).bitset.indices() == serial.best(3).bitset.indices()
    assert store.best(3).signature.tag == serial.best(3).signature.tag
    # the final replace (new sig + two individual patches) was ONE batched
    # call with all its parts
    assert log and log[-1] == [1, 7, 11]


def test_combined_uses_batched_combiner():
    """store.combined()/full_signature() route the per-level fold through
    the combiner in one call."""
    part = BinomialPartitioner(1, fake_registry(8))
    log = []
    store = SignatureStore(part, combiner=_batched_combiner(log))
    for lvl in (1, 2, 3):
        bs = BitSet(part.size_of(lvl))
        bs.set(0)
        store.store(
            IncomingSig(
                origin=0,
                level=lvl,
                ms=MultiSignature(bs, _CountingSig(10**lvl)),
            )
        )
    log.clear()
    ms = store.full_signature()
    assert ms is not None and ms.signature.tag == 10 + 100 + 1000
    assert len(log) == 1 and len(log[0]) == 3

"""BN254 scalar ground truth: field tower, curve groups, pairing properties.

Reference test model: bn256/go/bn256_test.go + bn256/cf/bn256_test.go
(sign/verify/combine/marshal round-trips), plus the algebraic properties the
Go tests get for free from their audited dependency — here they must be
proven: tower inverses, bilinearity, fast-vs-naive final exponentiation.
"""

import random

import pytest

from handel_tpu.ops import bn254_ref as bn

rng = random.Random(1234)


def rand_fp():
    return rng.randrange(bn.P)


def rand_f2():
    return (rand_fp(), rand_fp())


def rand_f6():
    return (rand_f2(), rand_f2(), rand_f2())


def rand_f12():
    return (rand_f6(), rand_f6())


def test_f2_field_axioms():
    for _ in range(10):
        a, b, c = rand_f2(), rand_f2(), rand_f2()
        assert bn.f2_mul(a, bn.f2_add(b, c)) == bn.f2_add(
            bn.f2_mul(a, b), bn.f2_mul(a, c)
        )
        assert bn.f2_mul(a, b) == bn.f2_mul(b, a)
        assert bn.f2_sqr(a) == bn.f2_mul(a, a)
        if a != bn.F2_ZERO:
            assert bn.f2_mul(a, bn.f2_inv(a)) == bn.F2_ONE


def test_f6_field_axioms():
    for _ in range(5):
        a, b = rand_f6(), rand_f6()
        assert bn.f6_mul(a, b) == bn.f6_mul(b, a)
        assert bn.f6_mul(a, bn.F6_ONE) == a
        assert bn.f6_mul(a, bn.f6_inv(a)) == bn.F6_ONE
        # v^3 == xi: multiplying three times by v equals multiplying by xi
        threev = bn.f6_mul_v(bn.f6_mul_v(bn.f6_mul_v(a)))
        xi_a = tuple(bn.f2_mul_xi(c) for c in a)
        assert threev == xi_a


def test_f12_field_axioms():
    for _ in range(3):
        a, b = rand_f12(), rand_f12()
        assert bn.f12_mul(a, b) == bn.f12_mul(b, a)
        assert bn.f12_mul(a, bn.f12_inv(a)) == bn.F12_ONE
        assert bn.f12_sqr(a) == bn.f12_mul(a, a)


def test_frobenius_is_p_power():
    a = rand_f12()
    assert bn.f12_frobenius(a) == bn.f12_pow(a, bn.P)


def test_frobenius_conj_is_p6():
    # x^(p^6) == conjugate for any Fp12 element
    a = rand_f12()
    f = a
    for _ in range(6):
        f = bn.f12_frobenius(f)
    assert f == bn.f12_conj(a)


def test_generators_valid():
    assert bn.g1_is_valid(bn.G1_GEN)
    assert bn.g2_is_valid(bn.G2_GEN)
    assert bn.g1_mul(bn.G1_GEN, bn.R) is None
    assert bn.g2_mul(bn.G2_GEN, bn.R) is None


def test_group_ops():
    p2 = bn.g1_add(bn.G1_GEN, bn.G1_GEN)
    p3 = bn.g1_add(p2, bn.G1_GEN)
    assert p3 == bn.g1_mul(bn.G1_GEN, 3)
    assert bn.g1_add(p3, bn.g1_neg(p3)) is None
    assert bn.g1_add(None, p2) == p2
    q5 = bn.g2_mul(bn.G2_GEN, 5)
    assert q5 == bn.g2_add(bn.g2_mul(bn.G2_GEN, 2), bn.g2_mul(bn.G2_GEN, 3))


def test_pairing_bilinear():
    a, b = rng.randrange(1, 10**9), rng.randrange(1, 10**9)
    e = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    assert e != bn.F12_ONE
    lhs = bn.pairing(bn.g2_mul(bn.G2_GEN, b), bn.g1_mul(bn.G1_GEN, a))
    assert lhs == bn.f12_pow(e, a * b)
    # e(P, Q)^r == 1 (GT has order r)
    assert bn.f12_pow(e, bn.R) == bn.F12_ONE


def test_fast_final_exp_matches_naive():
    f = bn.miller_loop(bn.g2_mul(bn.G2_GEN, 7), bn.g1_mul(bn.G1_GEN, 11))
    assert bn.final_exponentiation(f) == bn.final_exponentiation_naive(f)


def _twist_point_outside_subgroup():
    # find a point on E'(Fp2) NOT in the order-r subgroup (E' has a large
    # cofactor, so almost any solved-for point qualifies)
    for x0 in range(1, 50):
        x = (x0, 0)
        rhs = bn.f2_add(bn.f2_mul(bn.f2_sqr(x), x), bn.TWIST_B)
        y = bn.f2_sqrt(rhs)
        if y is None:
            continue
        pt = (x, y)
        assert bn.pt_is_on_curve(bn.F2_OPS, pt, bn.TWIST_B)
        if bn.pt_mul(bn.F2_OPS, pt, bn.R) is not None:
            return pt
    raise AssertionError("no out-of-subgroup twist point found")


def test_rogue_g2_point_rejected():
    # regression: pt_mul must not reduce the scalar mod R, else the subgroup
    # check [R]P == O is vacuously true and rogue keys pass validation
    rogue = _twist_point_outside_subgroup()
    assert not bn.g2_is_valid(rogue)


def test_f2_sqrt():
    for _ in range(5):
        a = rand_f2()
        sq = bn.f2_sqr(a)
        root = bn.f2_sqrt(sq)
        assert root is not None and bn.f2_sqr(root) == sq


def test_pairing_check_product():
    p, q = bn.G1_GEN, bn.G2_GEN
    assert bn.pairing_check([(p, q), (bn.g1_neg(p), q)])
    assert not bn.pairing_check([(p, q), (p, q)])
    # e(aP, Q) * e(-P, aQ) == 1
    a = 424242
    assert bn.pairing_check(
        [(bn.g1_mul(p, a), q), (bn.g1_neg(p), bn.g2_mul(q, a))]
    )

"""BLS12-381 scalar pairing + scheme tests.

Mirrors the per-curve test shape of the reference (bn256/*/bn256_test.go:
sign/verify/combine/marshal + small end-to-end), plus the pairing-math
property tests that pin the M-twist line placement and the hard-part
identity (3·hard = (z-1)^2 (z+p)(z^2+p^2-1) + 3).
"""

import random

import pytest

from handel_tpu.core.crypto import verify_multisignature
from handel_tpu.models.bls12_381 import (
    BLS12381Scheme,
    new_keypair,
    unmarshal_g1,
    unmarshal_g2,
)
from handel_tpu.ops import bls12_381_ref as bls


def test_hard_part_identity():
    hard = (bls.P**4 - bls.P**2 + 1) // bls.R
    assert 3 * hard == (bls.Z - 1) ** 2 * (bls.Z + bls.P) * (
        bls.Z**2 + bls.P**2 - 1
    ) + 3


def test_generators_valid():
    assert bls.g1_is_valid(bls.G1_GEN)
    assert bls.g2_is_valid(bls.G2_GEN)


def test_fast_final_exp_is_cube_of_naive():
    f = bls.miller_loop(bls.G2_GEN, bls.G1_GEN)
    e = bls.final_exponentiation_naive(f)
    cube = bls.f12_mul(bls.f12_mul(e, e), e)
    assert bls.final_exponentiation(f) == cube
    assert e != bls.F12_ONE  # non-degenerate


def test_bilinearity():
    rng = random.Random(3)
    k, l = rng.randrange(1, bls.R), rng.randrange(1, bls.R)
    lhs = bls.pairing(bls.g2_mul(bls.G2_GEN, l), bls.g1_mul(bls.G1_GEN, k))
    rhs = bls.f12_pow(bls.pairing(bls.G2_GEN, bls.G1_GEN), k * l % bls.R)
    assert lhs == rhs


def test_sign_verify_combine():
    msg = b"hello bls12-381"
    sk1, pk1 = new_keypair(seed=1)
    sk2, pk2 = new_keypair(seed=2)
    s1, s2 = sk1.sign(msg), sk2.sign(msg)
    assert pk1.verify(msg, s1)
    assert not pk2.verify(msg, s1)
    agg_sig = s1.combine(s2)
    agg_pk = pk1.combine(pk2)
    assert agg_pk.verify(msg, agg_sig)
    assert not agg_pk.verify(b"other", agg_sig)


def test_marshal_roundtrip():
    sk, pk = new_keypair(seed=7)
    sig = sk.sign(b"m")
    assert unmarshal_g1(sig.marshal()) == sig.point
    assert unmarshal_g2(pk.marshal()) == pk.point
    with pytest.raises(ValueError):
        unmarshal_g1(b"\xff" * 96)


def test_scheme_registry_dispatch():
    from handel_tpu.models.registry import new_scheme

    s = new_scheme("bls12-381")
    assert isinstance(s, BLS12381Scheme)
    sk, pk = s.keygen(3)
    assert s.unmarshal_public(pk.marshal()) == pk
    assert s.unmarshal_secret(sk.marshal()).scalar == sk.scalar


@pytest.mark.slow
def test_protocol_e2e_bls12_381():
    """Small aggregation run on the in-process network with real BLS12-381
    (tier-3 analogue of bn256/cf/bn256_test.go:13-37)."""
    import asyncio

    from handel_tpu.core.test_harness import run_cluster

    results = asyncio.run(
        run_cluster(5, timeout=120.0, scheme=BLS12381Scheme())
    )
    assert len(results) == 5
    for sig in results.values():
        assert sig.cardinality() >= 3

"""Simulation harness tests.

Tier-4 of the reference test strategy (SURVEY.md §4): TestMainLocalHost
(simul/main_test.go:17-60) spawns real processes over real sockets with the
sync barrier and the monitor, and asserts success + a results CSV. Plus unit
tests for allocator invariants (allocator_test.go:16), registry CSV
round-trip (parser_test.go:48), sync barrier (sync_test.go:8), and stats.
"""

import asyncio
import csv
import os

import pytest

from handel_tpu.sim.allocator import RoundRobin, RoundRandomOffline
from handel_tpu.sim.config import HandelParams, RunConfig, SimConfig, dump_config, load_config
from handel_tpu.sim.keys import (
    generate_nodes,
    read_registry_csv,
    registry_from_records,
    secret_of,
    write_registry_csv,
)
from handel_tpu.sim.monitor import Monitor, Sink, Stats
from handel_tpu.sim.platform import LocalhostPlatform, free_ports
from handel_tpu.sim.sync import STATE_START, SyncMaster, SyncSlave
from handel_tpu.models.fake import FakeScheme


def test_allocator_invariants():
    for alloc_cls in (RoundRobin, RoundRandomOffline):
        alloc = alloc_cls().allocate(40, 2, 4, failing=10)
        assert len(alloc) == 40
        assert sum(1 for s in alloc.values() if not s.active) == 10
        assert {s.process for s in alloc.values()} == set(range(8))


def test_registry_csv_roundtrip(tmp_path):
    scheme = FakeScheme()
    records = generate_nodes(scheme, [f"127.0.0.1:{4000+i}" for i in range(5)])
    path = str(tmp_path / "reg.csv")
    write_registry_csv(path, records)
    back = read_registry_csv(path)
    assert [(r.id, r.address) for r in back] == [
        (r.id, r.address) for r in records
    ]
    reg = registry_from_records(back, scheme)
    assert reg.size() == 5
    sk = secret_of(back[3], scheme)
    assert sk.id == 3


def test_sync_barrier():
    async def go():
        (port,) = [free_ports(1)[0]]
        master = SyncMaster(port, expected=3)
        await master.start()
        slaves = [SyncSlave(f"127.0.0.1:{port}", i) for i in range(3)]
        for s in slaves:
            await s.start()
        await asyncio.gather(
            master.wait_all(STATE_START, 10.0),
            *(s.signal_and_wait(STATE_START, 10.0) for s in slaves),
        )
        master.stop()
        for s in slaves:
            s.stop()

    asyncio.run(go())


def test_monitor_stats(tmp_path):
    async def go():
        (port,) = free_ports(1)
        mon = Monitor(port)
        await mon.start()
        sink = Sink(f"127.0.0.1:{port}")
        for v in (1.0, 3.0):
            sink.record("sigen", {"wall": v})
        await asyncio.sleep(0.2)
        mon.stop()
        sink.close()
        return mon.stats

    stats = asyncio.run(go())
    cols = stats.columns()
    assert "sigen_wall_avg" in cols
    row = dict(zip(cols, stats.row()))
    assert row["sigen_wall_avg"] == 2.0
    assert row["sigen_wall_min"] == 1.0 and row["sigen_wall_max"] == 3.0
    path = str(tmp_path / "stats.csv")
    stats.write_csv(path)
    assert os.path.exists(path)


def test_config_toml_roundtrip(tmp_path):
    from handel_tpu.sim.config import HostSpec

    cfg = SimConfig(
        scheme="fake",
        mesh_devices=4,
        master_ip="10.0.0.9",
        base_port=21000,
        hosts=[HostSpec(connect="ssh:u@h1", ip="10.0.0.2", python="python3")],
        runs=[RunConfig(nodes=12, threshold=7, failing=2, processes=3,
                        handel=HandelParams(period_ms=5.0))],
    )
    path = tmp_path / "sim.toml"
    path.write_text(dump_config(cfg))
    back = load_config(str(path))
    assert back.scheme == "fake"
    assert back.mesh_devices == 4
    assert back.master_ip == "10.0.0.9" and back.base_port == 21000
    assert back.hosts == cfg.hosts
    assert back.runs[0].nodes == 12
    assert back.runs[0].handel.period_ms == 5.0
    assert back.runs[0].resolved_threshold() == 7


@pytest.mark.parametrize("scheme,nodes,processes,failing", [
    ("fake", 8, 2, 0),
    ("fake", 16, 4, 3),
])
def test_localhost_platform(tmp_path, scheme, nodes, processes, failing):
    """TestMainLocalHost equivalent: real processes, UDP, barrier, monitor."""
    threshold = (nodes - failing) // 2 + 1
    cfg = SimConfig(
        network="udp",
        scheme=scheme,
        max_timeout_s=60.0,
        runs=[
            RunConfig(
                nodes=nodes,
                threshold=threshold,
                failing=failing,
                processes=processes,
            )
        ],
    )

    async def go():
        plat = LocalhostPlatform(cfg, str(tmp_path))
        return await plat.start_run(0)

    res = asyncio.run(go())
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok
    assert os.path.exists(res.csv_path)
    with open(res.csv_path) as f:
        rows = list(csv.reader(f))
    header = rows[0]
    assert "sigen_wall_avg" in header
    assert any("net_sentBytes" in h for h in header)


def test_remote_platform_two_hosts(tmp_path):
    """The multi-host platform (sim/remote.py, the aws.go analog) with two
    localhost-as-remote hosts: the package is packed + shipped into each
    host's staging dir, node processes run FROM the shipped copies on
    separately-launched "hosts", and the orchestrator's barriers + monitor
    produce the same stats CSV as the localhost platform."""
    from handel_tpu.sim.config import HostSpec
    from handel_tpu.sim.platform import run_simulation

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        max_timeout_s=60.0,
        hosts=[
            HostSpec(connect="local", workdir=str(tmp_path / "hostA")),
            HostSpec(connect="local", workdir=str(tmp_path / "hostB")),
        ],
        runs=[RunConfig(nodes=8, threshold=5, processes=1)],
    )
    results = asyncio.run(
        run_simulation(cfg, str(tmp_path / "out"), platform="remote")
    )
    res = results[0]
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok
    # deployment really happened: both hosts got the package + run files
    for host in ("hostA", "hostB"):
        assert (tmp_path / host / "handel_tpu" / "sim" / "node.py").exists()
        assert (tmp_path / host / "registry_0.csv").exists()
    # two hosts -> two node processes (one per host), each with 4 nodes
    assert len(res.outputs) == 2
    with open(res.csv_path) as f:
        header = list(csv.reader(f))[0]
    assert "sigen_wall_avg" in header


@pytest.mark.slow
def test_remote_platform_rpc_verifier(tmp_path, monkeypatch):
    """The batch-plane RPC (parallel/rpc_verifier.py): host A is flagged
    `device = true`, so its node process serves the shared
    BatchVerifierService over TCP and host B's chip-less process verifies
    every candidate through it — the fleet topology where one accelerator
    host serves all others (BASELINE.json north_star). Asserts the run
    completes AND that host B actually shipped candidates over the link
    (rpc counters on the monitor plane)."""
    from handel_tpu.sim.config import HostSpec
    from handel_tpu.sim.platform import run_simulation

    monkeypatch.setenv("HANDEL_TPU_PLATFORM", "cpu")
    cfg = SimConfig(
        network="udp",
        scheme="bn254-jax",
        batch_size=8,
        shared_verifier=True,
        max_timeout_s=900.0,
        hosts=[
            HostSpec(
                connect="local", workdir=str(tmp_path / "hostA"), device=True
            ),
            HostSpec(connect="local", workdir=str(tmp_path / "hostB")),
        ],
        runs=[
            RunConfig(
                nodes=8,
                threshold=5,
                processes=1,
                handel=HandelParams(period_ms=50.0, timeout_ms=200.0),
            )
        ],
    )
    results = asyncio.run(
        run_simulation(cfg, str(tmp_path / "out"), platform="remote")
    )
    res = results[0]
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok
    rows = list(csv.DictReader(open(res.csv_path)))
    # host B's process sent candidates over the link; host A served them
    assert float(rows[0]["device_rpc_rpcSentCandidates_sum"]) > 0
    assert float(rows[0]["device_rpcserve_rpcServedCandidates_sum"]) > 0
    assert float(rows[0]["device_rpc_rpcLinkErrors_sum"]) == 0


def test_localhost_platform_base_port(tmp_path):
    """With base_port set the localhost platform assigns node i the fixed
    port base_port + i instead of probing — probing holds two fds per
    port simultaneously, which trips the fd limit at committee sizes like
    16384 (the 16k capture's failure mode)."""
    import csv as _csv

    from handel_tpu.sim.platform import run_simulation

    base = 13500  # below the 16000+ fixed ranges used by capture TOMLs
    cfg = SimConfig(
        network="udp",
        scheme="fake",
        base_port=base,
        max_timeout_s=60.0,
        runs=[RunConfig(nodes=8, threshold=5, processes=2)],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    assert results[0].ok
    with open(str(tmp_path / "registry_0.csv")) as f:
        rows = list(_csv.reader(f))
    assert [r[1] for r in rows] == [
        f"127.0.0.1:{base + i}" for i in range(8)
    ]


def test_port_plan_validates_bounds():
    """A base_port without room for the reserved -2/-3 slots or whose
    range runs past 65535 must fail immediately, not as a barrier stall
    after max_timeout_s (port 0/negative/out-of-range binds misbehave
    deep inside node processes)."""
    import pytest

    from handel_tpu.sim.platform import port_plan

    with pytest.raises(ValueError):
        port_plan(SimConfig(base_port=2), 8)
    with pytest.raises(ValueError):
        port_plan(SimConfig(base_port=65530), 8)
    node_ports, master, monitor, verifier = port_plan(
        SimConfig(base_port=18000), 8
    )
    assert node_ports == list(range(18000, 18008))
    assert (master, monitor, verifier) == (17998, 17999, 17997)


def test_preflight_ports_detects_conflict():
    """The fixed-plan pre-flight fails fast with the conflicting port
    named when something already holds one."""
    import socket

    import pytest

    from handel_tpu.sim.platform import free_ports, preflight_ports

    port = free_ports(1)[0]
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", port))
    try:
        with pytest.raises(OSError, match=str(port)):
            preflight_ports([port])
    finally:
        s.close()
    preflight_ports([port])  # released: now clean


def test_localhost_platform_bn254_real_crypto(tmp_path):
    """Small run with real BN254 host crypto end-to-end over real sockets."""
    cfg = SimConfig(
        network="udp",
        scheme="bn254",
        max_timeout_s=120.0,
        runs=[RunConfig(nodes=4, threshold=3, processes=2)],
    )

    async def go():
        plat = LocalhostPlatform(cfg, str(tmp_path))
        return await plat.start_run(0)

    res = asyncio.run(go())
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok


def test_standalone_master_with_node_processes(tmp_path):
    """Multi-host form: a standalone master process (sim/master.py,
    reference simul/master/main.go:36-118) + node processes connecting to
    it over sockets, stats CSV written at END."""
    import asyncio
    import sys

    from handel_tpu.models.registry import new_scheme
    from handel_tpu.sim import keys as simkeys
    from handel_tpu.sim.config import SimConfig, RunConfig, dump_config
    from handel_tpu.sim.platform import free_ports

    async def go():
        n = 4
        cfg = SimConfig(network="udp", scheme="fake", runs=[RunConfig(nodes=n)])
        scheme = new_scheme("fake")
        ports = free_ports(n + 2)
        addrs = [f"127.0.0.1:{p}" for p in ports[:n]]
        recs = simkeys.generate_nodes(scheme, addrs)
        reg_path = str(tmp_path / "reg.csv")
        simkeys.write_registry_csv(reg_path, recs)
        cfg_path = str(tmp_path / "cfg.toml")
        with open(cfg_path, "w") as f:
            f.write(dump_config(cfg))
        csv_path = str(tmp_path / "stats.csv")
        import os

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "PYTHONPATH": repo_root}
        master = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "handel_tpu.sim.master",
            "--port", str(ports[n]), "--monitor-port", str(ports[n + 1]),
            "--expected", str(n), "--csv", csv_path, "--timeout", "60",
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE,
            env=env,
        )
        node = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "handel_tpu.sim.node",
            "--config", cfg_path, "--registry", reg_path,
            "--master", f"127.0.0.1:{ports[n]}",
            "--monitor", f"127.0.0.1:{ports[n+1]}",
            "--run", "0", "--ids", ",".join(map(str, range(n))),
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE,
            env=env,
        )
        (m_out, m_err), (n_out, n_err) = await asyncio.wait_for(
            asyncio.gather(master.communicate(), node.communicate()), 90
        )
        assert master.returncode == 0, m_err.decode()
        assert node.returncode == 0, n_err.decode()
        assert b"END released" in m_out
        with open(csv_path) as f:
            header = f.readline()
        assert "sigen_wall" in header

    asyncio.run(go())


def test_stats_percentile_filter():
    """DataFilter drops samples above the configured percentile before
    aggregation (stats.go DataFilter)."""
    from handel_tpu.sim.monitor import DataFilter, Stats

    stats = Stats(data_filter=DataFilter({"lat_wall": 50.0}))
    for v in (1.0, 2.0, 3.0, 100.0):
        stats.update("lat_wall", v)
        stats.update("other", v)
    row = dict(zip(stats.columns(), stats.row()))
    assert row["lat_wall_max"] <= 3.0  # outlier filtered
    assert row["other_max"] == 100.0  # unconfigured key passes through


def test_evaluator_knob_roundtrip(tmp_path):
    cfg = SimConfig(
        scheme="fake",
        runs=[RunConfig(nodes=8, handel=HandelParams(evaluator="fifo"))],
    )
    path = tmp_path / "sim.toml"
    path.write_text(dump_config(cfg))
    back = load_config(str(path))
    assert back.runs[0].handel.evaluator == "fifo"
    from handel_tpu.core.processing import FifoProcessing

    c = back.runs[0].handel.to_config(5, seed=1)
    assert c.new_processing is FifoProcessing


@pytest.mark.slow
def test_localhost_platform_256_nodes(tmp_path):
    """Reference-scale single-host run: 256 nodes, 8 processes, 99%
    threshold. Regression for the free_ports ephemeral-range race that
    deadlocked runs past ~128 sockets (platform.py free_ports)."""
    from handel_tpu.sim.platform import run_simulation

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        max_timeout_s=120.0,
        runs=[
            RunConfig(
                nodes=256,
                threshold=254,
                processes=8,
                handel=HandelParams(period_ms=50.0, timeout_ms=100.0),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    assert results[0].ok, [e.decode(errors="replace")[-2000:] for _, e in results[0].outputs]
    rows = list(csv.DictReader(open(results[0].csv_path)))
    assert float(rows[0]["nodes"]) == 256
    assert float(rows[0]["sigen_wall_avg"]) > 0


@pytest.mark.slow
def test_localhost_platform_2000_nodes_invariant(tmp_path):
    """Reference-scale nightly tier: 2000 nodes, 99% threshold, fake crypto
    (handel_test.go:71-84 scale + simul/plots/csv N=2000 rows). Asserts the
    protocol-convergence invariant instead of eyeballing it: signatures
    checked per node lands in the reference's band (~60/node at N=2000-4000,
    handel_0failing_99thr.csv: 61.8) — pacing knobs match the captured
    1024-node run (one shared CPU core: 200 ms period, slow timeouts)."""
    from handel_tpu.sim.platform import run_simulation

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        # one shared core: 2000 asyncio nodes start up + converge slowly;
        # the barrier window must absorb both (the 1024-node run needed
        # ~1/3 of this)
        max_timeout_s=2400.0,
        runs=[
            RunConfig(
                nodes=2000,
                threshold=1980,
                processes=4,
                # pacing matters for the INVARIANT, not just wall time: the
                # period must be long enough for the starved core to drain a
                # whole round's traffic, or every resend round re-verifies
                # incrementally-improved aggregates and sigs-checked scales
                # with (wall/period) instead of staying ~60 (a 200 ms period
                # here measured 229 checked over a 33-minute crawl)
                handel=HandelParams(period_ms=1000.0, timeout_ms=2000.0),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    assert results[0].ok, [
        e.decode(errors="replace")[-2000:] for _, e in results[0].outputs
    ]
    rows = list(csv.DictReader(open(results[0].csv_path)))
    assert float(rows[0]["nodes"]) == 2000
    checked = float(rows[0]["sigs_sigCheckedCt_avg"])
    # the invariant: log-structured aggregation, NOT O(N) flooding. The
    # reference averages 61.8 at N=4000 / 99%; the captured 1024-node run
    # measured 59.0. Band kept generous for scheduler jitter.
    assert 30.0 <= checked <= 120.0, f"sigs checked/node = {checked}"


@pytest.mark.slow
def test_localhost_platform_bn254_jax_shared_verifier(tmp_path, monkeypatch):
    """Simulation with verification on the device path: scheme bn254-jax +
    the shared BatchVerifierService fusing co-located nodes' requests into
    one launch per batch (sim/node.py scheme.constructor.Device dispatch).
    Node subprocesses force the CPU backend via HANDEL_TPU_PLATFORM (a downed
    TPU tunnel would otherwise hang jax init in every child)."""
    from handel_tpu.sim.platform import run_simulation

    monkeypatch.setenv("HANDEL_TPU_PLATFORM", "cpu")
    cfg = SimConfig(
        network="udp",
        scheme="bn254-jax",
        batch_size=8,
        shared_verifier=True,
        max_timeout_s=900.0,
        runs=[
            RunConfig(
                nodes=8,
                threshold=5,
                processes=1,
                handel=HandelParams(period_ms=20.0),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    assert results[0].ok, [
        e.decode(errors="replace")[-2000:] for _, e in results[0].outputs
    ]
    rows = list(csv.DictReader(open(results[0].csv_path)))
    assert float(rows[0]["sigs_sigCheckedCt_avg"]) > 0


@pytest.mark.slow
def test_localhost_platform_mesh_sharded_verifier(tmp_path, monkeypatch):
    """Simulation with the verification plane sharded over a device mesh:
    the `mesh_devices` TOML knob routes the shared BatchVerifierService's
    BN254Device through the shard_map kernels (parallel/sharding.py) on
    virtual CPU devices forced inside the node subprocess (sim/node.py)."""
    from handel_tpu.sim.platform import run_simulation

    monkeypatch.setenv("HANDEL_TPU_PLATFORM", "cpu")
    cfg = SimConfig(
        network="udp",
        scheme="bn254-jax",
        batch_size=8,
        shared_verifier=True,
        mesh_devices=4,  # 8-node registry: divisible; candidates pad
        max_timeout_s=900.0,
        runs=[
            RunConfig(
                nodes=8,
                threshold=5,
                processes=1,
                handel=HandelParams(period_ms=20.0),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    assert results[0].ok, [
        e.decode(errors="replace")[-2000:] for _, e in results[0].outputs
    ]
    rows = list(csv.DictReader(open(results[0].csv_path)))
    assert float(rows[0]["sigs_sigCheckedCt_avg"]) > 0

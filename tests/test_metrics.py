"""Live telemetry plane tests (ISSUE 5): exposition format, health/readiness
transitions, registry scrapes over a traced LocalCluster, port hygiene,
explicit gauge declarations, and the bench regression gate."""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from handel_tpu.core.metrics import (  # noqa: E402
    MetricsRegistry,
    MetricsServer,
    is_gauge_key,
    merged_histogram,
    metric_name,
    parse_exposition,
    snake,
)
from handel_tpu.core.test_harness import LocalCluster  # noqa: E402
from handel_tpu.core.trace import FlightRecorder, LogHistogram  # noqa: E402

import bench_check  # noqa: E402  (scripts/bench_check.py)


def _get(addr: str, path: str, timeout: float = 3.0):
    """(status, body) even for non-2xx replies."""
    try:
        with urllib.request.urlopen(
            f"http://{addr}{path}", timeout=timeout
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- naming + classification --------------------------------------------------


def test_snake_and_metric_name():
    assert snake("msgSentCt") == "msg_sent_ct"
    assert snake("levelCompleteS") == "level_complete_s"
    assert snake("dedupHitRate") == "dedup_hit_rate"
    assert snake("xlaCompileCt") == "xla_compile_ct"
    assert metric_name("sigs", "msgSentCt") == "handel_sigs_msg_sent_ct"
    assert (
        metric_name("device_verifier", "breakerState")
        == "handel_device_verifier_breaker_state"
    )


def test_gauge_classification_explicit_then_suffix():
    # explicit declaration wins even without a magic suffix...
    assert is_gauge_key("bestCardinality", {"bestCardinality"})
    # ...and the suffix heuristic stays as fallback only
    assert is_gauge_key("dedupHitRate", None)
    assert is_gauge_key("breakerState", set())
    assert not is_gauge_key("msgSentCt", set())


# -- exposition golden --------------------------------------------------------


def test_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("handel_test_events", "events seen")
    g = reg.gauge("handel_test_depth", "queue depth")
    h = reg.histogram("handel_test_latency_s")
    c.inc()
    c.inc(2)
    g.set(7)
    for v in (0.001, 0.002, 0.002, 0.5):
        h.observe(v)

    text = reg.exposition()
    lines = text.splitlines()
    assert "# TYPE handel_test_events counter" in lines
    assert "# HELP handel_test_events events seen" in lines
    assert "# TYPE handel_test_depth gauge" in lines
    assert "# TYPE handel_test_latency_s histogram" in lines
    assert "handel_test_events 3.0" in lines
    assert "handel_test_depth 7.0" in lines
    # histogram carries cumulative buckets, +Inf, _sum and _count
    assert any(
        l.startswith("handel_test_latency_s_bucket{le=") for l in lines
    )
    assert 'handel_test_latency_s_bucket{le="+Inf"} 4.0' in lines
    assert any(l.startswith("handel_test_latency_s_count") for l in lines)
    assert any(l.startswith("handel_test_latency_s_sum") for l in lines)
    # exactly one TYPE header per family
    assert sum(1 for l in lines if l.startswith("# TYPE")) == len(
        {l.split()[2] for l in lines if l.startswith("# TYPE")}
    )

    fams = parse_exposition(text)
    assert fams["handel_test_events"]["type"] == "counter"
    assert fams["handel_test_events"]["samples"][0][1] == 3.0
    assert fams["handel_test_latency_s"]["type"] == "histogram"
    rebuilt = merged_histogram(fams, "handel_test_latency_s")
    assert rebuilt is not None and rebuilt.count == 4
    # quantiles survive the round trip to within the log-bucket error
    assert rebuilt.quantile(0.5) == pytest.approx(
        h.hist.quantile(0.5), rel=0.25
    )


def test_histogram_exposition_roundtrip_exact_quantiles():
    """`sim watch` quantile-reconstruction bias fix (ISSUE 19 satellite):
    the exposition carries the observed min/max as _min/_max pseudo-
    samples, so a merged histogram's quantile() matches the original
    EXACTLY — not just to within the log-bucket error — because the
    clamp to [lo, hi] uses the true observed extrema, not bucket edges."""
    import random

    rng = random.Random(7)
    h = LogHistogram()
    for _ in range(500):
        h.add(rng.lognormvariate(-3.0, 1.2))

    class Rep:
        def histograms(self):
            return {"verifyLatencyS": h}

    reg = MetricsRegistry()
    reg.register_histograms("sigs", Rep())
    fams = parse_exposition(reg.exposition())
    rebuilt = merged_histogram(fams, "handel_sigs_verify_latency_s")
    assert rebuilt is not None and rebuilt.count == h.count
    assert rebuilt.lo == h.lo and rebuilt.hi == h.hi
    for q in (0.001, 0.5, 0.9, 0.99, 0.999):
        assert rebuilt.quantile(q) == h.quantile(q), q

    # single-sample edge case: the reconstruction must return the sample
    h1 = LogHistogram()
    h1.add(0.00103)

    class Rep1:
        def histograms(self):
            return {"verifyLatencyS": h1}

    reg1 = MetricsRegistry()
    reg1.register_histograms("sigs", Rep1())
    fams1 = parse_exposition(reg1.exposition())
    r1 = merged_histogram(fams1, "handel_sigs_verify_latency_s")
    assert r1.quantile(0.5) == h1.quantile(0.5) == 0.00103


def test_obs_plane_declares_every_gauge():
    """ISSUE 19 satellite: every obs/ reporter key classifies explicitly
    — a declared gauge or a *Ct counter — so the metrics plane never
    falls back to the suffix heuristic on the alerts/incidents families."""
    from handel_tpu.obs import BurnRateEvaluator, DetectorBank, IncidentLog

    for rep in (BurnRateEvaluator(), DetectorBank(), IncidentLog()):
        vals = rep.values()
        gauges = rep.gauge_keys()
        assert gauges <= set(vals), type(rep).__name__
        for key in vals:
            assert key in gauges or key.endswith("Ct"), (
                f"{type(rep).__name__}.{key} is neither a declared gauge "
                f"nor a *Ct counter — the suffix heuristic would guess"
            )
        # labeled planes declare explicitly too, and never call a
        # counter a gauge
        for key in rep.labeled_gauge_keys():
            assert not key.endswith("Ct"), (
                f"{type(rep).__name__} labeled gauge {key} looks like "
                f"a counter"
            )


def test_reporter_collector_uses_gauge_keys():
    class Rep:
        def values(self):
            return {"fooCt": 3.0, "liveLanes": 5.0}

        def gauge_keys(self):
            return {"liveLanes"}  # no magic suffix — explicit only

    reg = MetricsRegistry()
    reg.register_values("sigs", Rep(), labels={"node": "2"})
    fams = parse_exposition(reg.exposition())
    assert fams["handel_sigs_foo_ct"]["type"] == "counter"
    assert fams["handel_sigs_live_lanes"]["type"] == "gauge"
    labels, v = fams["handel_sigs_live_lanes"]["samples"][0]
    assert labels["node"] == "2" and v == 5.0


def test_scrape_survives_dying_reporter():
    class Dying:
        def values(self):
            raise RuntimeError("reporter died")

    reg = MetricsRegistry()
    reg.register_values("sigs", Dying())
    reg.gauge("handel_ok_gauge").set(1)
    fams = parse_exposition(reg.exposition())
    assert "handel_ok_gauge" in fams
    assert reg.scrape_errors >= 1


# -- health + readiness -------------------------------------------------------


def test_healthz_readyz_transition_warmup_and_breaker():
    from handel_tpu.utils.breaker import CircuitBreaker

    state = {"warmed": False}
    breaker = CircuitBreaker(threshold=1, cooldown_s=3600)
    reg = MetricsRegistry()
    reg.add_readiness("scheme_warmed", lambda: state["warmed"])
    reg.add_readiness("breaker_closed", lambda: breaker.state != "open")
    srv = MetricsServer(reg, port=0).start()
    try:
        addr = srv.address
        assert _get(addr, "/healthz")[0] == 200  # alive from the start
        code, body = _get(addr, "/readyz")
        assert code == 503
        checks = json.loads(body)["checks"]
        assert checks == {"scheme_warmed": False, "breaker_closed": True}

        breaker.record_failure()  # forces the breaker open
        state["warmed"] = True  # warmup done, but breaker now open
        code, body = _get(addr, "/readyz")
        assert code == 503
        assert json.loads(body)["checks"]["breaker_closed"] is False

        breaker.record_success()  # device recovered
        code, body = _get(addr, "/readyz")
        assert code == 200 and json.loads(body)["ready"] is True

        assert _get(addr, "/nope")[0] == 404
    finally:
        srv.stop()


def test_debug_profile_endpoint():
    reg = MetricsRegistry()
    srv = MetricsServer(reg, port=0).start()
    try:
        # no profiler wired: 501, never a crash
        req = urllib.request.Request(
            f"http://{srv.address}/debug/profile?seconds=0.1", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=3)
        assert ei.value.code == 501

        captured = []
        srv.set_profiler(lambda s: captured.append(s) or "/tmp/prof_dir")
        with urllib.request.urlopen(req, timeout=3) as r:
            out = json.loads(r.read())
        assert out["trace"] == "/tmp/prof_dir"
        assert captured == [0.1]
    finally:
        srv.stop()


# -- registry scrape over a traced LocalCluster -------------------------------


class _StubDevice:
    batch_size = 8

    def dispatch(self, msg, reqs):
        return len(reqs)

    def fetch(self, handle):
        return [True] * handle


def test_traced_localcluster_scrape():
    """The acceptance-shaped run: a traced 8-node in-process cluster with a
    shared verifier service serves >= 20 metric families spanning the
    sigs / net / penalty / device_verifier planes, and /readyz flips only
    after the cluster starts."""
    from handel_tpu.parallel.batch_verifier import BatchVerifierService

    async def run():
        svc = BatchVerifierService(_StubDevice(), max_delay_ms=0.1)
        rec = FlightRecorder(capacity=1 << 14)
        cluster = LocalCluster(
            8, recorder=rec, metrics_port=0, verifier_service=svc
        )
        addr = cluster.metrics_server.address
        assert _get(addr, "/healthz")[0] == 200
        assert _get(addr, "/readyz")[0] == 503  # not started yet
        cluster.start()
        assert _get(addr, "/readyz")[0] == 200
        finals = await cluster.wait_complete_success(10)
        assert len(finals) == 8
        code, text = _get(addr, "/metrics")
        assert code == 200
        svc.stop()
        cluster.stop()
        return text, cluster

    text, cluster = asyncio.run(run())
    fams = parse_exposition(text)
    handel_fams = {n for n in fams if n.startswith("handel_")}
    assert len(handel_fams) >= 20, sorted(handel_fams)
    planes = {n.split("_")[1] for n in handel_fams}
    assert {"sigs", "net", "penalty", "device", "metrics"} <= planes
    assert any(n.startswith("handel_device_verifier_") for n in fams)

    # per-node labels survive: 8 samples for a sigs counter
    sent = fams["handel_sigs_msg_sent_ct"]["samples"]
    assert len(sent) == 8
    assert {l["node"] for l, _ in sent} == {str(i) for i in range(8)}
    # scraped totals agree with the live reporters
    assert sum(v for _, v in sent) == sum(
        h.values()["msgSentCt"] for h in cluster.handels.values()
    )
    # histogram plane made it through with real observations
    wave = merged_histogram(fams, "handel_sigs_level_complete_s")
    assert wave is not None and wave.count >= 8
    # after stop() the endpoint is down (zero leaked sockets)
    with pytest.raises((urllib.error.URLError, OSError)):
        urllib.request.urlopen(
            f"http://{cluster.metrics_server.address}/healthz", timeout=0.5
        )


def test_metrics_disabled_is_fully_off():
    cluster = LocalCluster(4)
    assert cluster.metrics is None and cluster.metrics_server is None
    # the sim platform allocates zero ports when metrics = false
    from handel_tpu.sim.config import SimConfig, dump_config, load_config
    from handel_tpu.sim.platform import metrics_port_plan

    cfg = SimConfig()
    assert cfg.metrics is False  # off by default, like trace
    assert metrics_port_plan(cfg, nodes=8, nprocs=2) == []
    # TOML round trip for the new keys
    cfg.metrics = True
    cfg.metrics_linger_s = 1.5
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".toml", delete=False) as f:
        f.write(dump_config(cfg))
        path = f.name
    try:
        loaded = load_config(path)
        assert loaded.metrics is True
        assert loaded.metrics_linger_s == 1.5
    finally:
        os.unlink(path)


def test_metrics_port_plan_hygiene():
    """Per-process ports never collide with the node block or the
    master/monitor/verifier slots below base_port."""
    from handel_tpu.sim.config import SimConfig
    from handel_tpu.sim.platform import metrics_port_plan, port_plan

    cfg = SimConfig(metrics=True, base_port=21000)
    nodes = 16
    node_ports, master_p, monitor_p, verifier_p = port_plan(cfg, nodes)
    mports = metrics_port_plan(cfg, nodes, nprocs=4)
    assert len(mports) == len(set(mports)) == 4
    taken = set(node_ports) | {master_p, monitor_p, verifier_p}
    assert not (set(mports) & taken)
    # ephemeral plan: real, distinct, bindable ports
    cfg2 = SimConfig(metrics=True)
    mports2 = metrics_port_plan(cfg2, nodes, nprocs=3)
    assert len(set(mports2)) == 3


# -- explicit gauges through the monitor plane --------------------------------


class _CaptureSink:
    def __init__(self):
        self.recorded = {}

    def record(self, name, values):
        self.recorded.setdefault(name, {}).update(values)


def test_counterio_honors_declared_gauges():
    from handel_tpu.sim.monitor import CounterIO

    class Rep:
        def __init__(self):
            self.base = {"evCt": 10.0, "liveLanes": 4.0, "hitRate": 0.5}

        def values(self):
            return dict(self.base)

        def gauge_keys(self):
            return {"liveLanes"}

    sink = _CaptureSink()
    rep = Rep()
    cio = CounterIO(sink, "sigs", rep)
    rep.base = {"evCt": 25.0, "liveLanes": 6.0, "hitRate": 0.8}
    cio.record()
    got = sink.recorded["sigs"]
    assert got["evCt"] == 15.0  # counter: delta'd against the base
    assert got["liveLanes"] == 6.0  # declared gauge: recorded as-is
    assert got["hitRate"] == 0.8  # suffix fallback still catches Rate


def test_stats_declare_gauge():
    from handel_tpu.sim.monitor import Stats

    s = Stats()
    s.declare("sigen_wall")
    s.declare("verifier_liveLanes", gauge=True)
    assert s.is_gauge("verifier_liveLanes")
    assert not s.is_gauge("sigen_wall")
    assert s.is_gauge("anything_dedupHitRate")  # suffix fallback intact
    assert s.gauge_keys() == {"verifier_liveLanes"}
    # declared keys still pin the NaN schema
    assert "verifier_liveLanes_avg" in s.columns()


# -- device telemetry ---------------------------------------------------------


def test_device_telemetry_values_shape():
    """The collector reports every key with jax absent-or-present and never
    imports jax itself (a scrape must not initialize a backend)."""
    from handel_tpu.parallel.telemetry import DeviceTelemetry

    tel = DeviceTelemetry(service=None)
    vals = tel.values()
    for key in (
        "xlaCompileCt", "liveArrays", "liveArrayBytes", "memBytesInUse",
        "dispatchQueueDepth", "inflightLaunches", "breakerState",
    ):
        assert key in vals
    assert tel.gauge_keys() <= set(vals)
    assert not is_gauge_key("xlaCompileCt", tel.gauge_keys())
    assert is_gauge_key("dispatchQueueDepth", tel.gauge_keys())


# -- watch dashboard ----------------------------------------------------------


def test_watch_aggregate_and_render():
    from handel_tpu.sim import watch_cli

    class Node:
        def __init__(self, levels, sent):
            self._levels = levels
            self._sent = sent

        def values(self):
            return {
                "levelsCompletedCt": float(self._levels),
                "bestCardinality": 6.0,
                "msgSentCt": float(self._sent),
            }

        def gauge_keys(self):
            return {"bestCardinality"}

        def histograms(self):
            h = LogHistogram()
            h.add(0.01)
            h.add(0.04)
            return {"levelCompleteS": h}

    reg = MetricsRegistry()
    for i, lv in enumerate((3, 3, 2, 1)):
        n = Node(lv, 10 * (i + 1))
        reg.register_values("sigs", n, labels={"node": str(i)})
        reg.register_histograms("sigs", n, labels={"node": str(i)})
    fams = parse_exposition(reg.exposition())
    model = watch_cli.aggregate([fams])
    assert model["nodes"] == 4
    assert model["levels"] == {"0": 3.0, "1": 3.0, "2": 2.0, "3": 1.0}
    assert model["wave_p50"] is not None
    frame = watch_cli.render(model, ["127.0.0.1:1"], up=1, tick=3)
    assert "aggregation wave (4 nodes reporting)" in frame
    assert "level  1 complete" in frame
    assert "4/4" in frame  # every node finished level 1
    assert "2/4" in frame  # two nodes reached level 3


def test_watch_discovers_endpoints(tmp_path):
    from handel_tpu.sim import watch_cli

    (tmp_path / "metrics_ports.json").write_text(
        json.dumps({"run": 0, "addresses": {"0": "127.0.0.1:9100",
                                            "1": "127.0.0.1:9101"}})
    )
    (tmp_path / "metrics_5.addr").write_text("127.0.0.1:9102\n")
    eps = watch_cli.discover_endpoints(str(tmp_path))
    assert eps == ["127.0.0.1:9100", "127.0.0.1:9101", "127.0.0.1:9102"]


# -- bench regression gate ----------------------------------------------------


def _bench_rec(value, backend="tpu", metric="4096sig_batch_verify_p50_ms",
               **extra):
    return {"metric": metric, "value": value, "unit": "ms",
            "backend": backend, **extra}


def test_bench_check_improvement_and_ok():
    history = [_bench_rec(v) for v in (100.0, 104.0, 98.0)]
    report = bench_check.detect_regressions(history, _bench_rec(90.0))
    assert not report["regressions"]
    assert report["improved"][0]["metric"] == "4096sig_batch_verify_p50_ms"
    # within threshold: ok, not a regression
    report = bench_check.detect_regressions(history, _bench_rec(110.0))
    assert not report["regressions"] and report["ok"]


def test_bench_check_flags_25pct_regression():
    history = [_bench_rec(v) for v in (100.0, 104.0, 98.0)]
    report = bench_check.detect_regressions(history, _bench_rec(125.0))
    assert len(report["regressions"]) == 1
    entry = report["regressions"][0]
    assert entry["backend"] == "tpu"
    assert entry["degradation"] == pytest.approx(0.25, abs=0.01)
    # higher-is-better direction: a dropping dedup rate regresses too
    history = [_bench_rec(100.0, dedup_hit_rate=0.9) for _ in range(3)]
    fresh = _bench_rec(100.0, dedup_hit_rate=0.5)
    report = bench_check.detect_regressions(history, fresh)
    assert any(e["metric"] == "dedup_hit_rate"
               for e in report["regressions"])


def test_bench_check_skips_cross_backend():
    """A TPU-persisted history must never judge a CPU-fallback number."""
    history = [_bench_rec(v, backend="tpu") for v in (100.0, 101.0, 99.0)]
    fresh = _bench_rec(
        500.0, backend="cpu", metric="4096sig_batch_verify_p50_ms"
    )
    report = bench_check.detect_regressions(history, fresh)
    assert not report["regressions"]
    assert report["skipped"]
    assert "cross-backend" in report["skipped"][0]["reason"]


def test_bench_check_ignores_forced_and_invalid():
    rec = _bench_rec(5.0, forced_shape=True)
    assert bench_check.extract_metrics(rec) == {}
    wrapped = {"n": 3, "rc": 0, "parsed": None}
    assert bench_check.normalize(wrapped) is None
    assert bench_check.normalize({"n": 1, "parsed": _bench_rec(7.0)})[
        "value"
    ] == 7.0


def test_bench_check_cli_gate_and_dry_run(tmp_path):
    for i, v in enumerate((100.0, 102.0, 98.0)):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"n": i, "rc": 0, "parsed": _bench_rec(v)})
        )
    fresh = tmp_path / "bench_tpu.json"
    fresh.write_text(json.dumps(_bench_rec(130.0)))
    argv = [
        "--history", str(tmp_path / "BENCH_*.json"),
        "--fresh", str(fresh),
    ]
    assert bench_check.main(argv) == 1  # 30% regression: gate fails
    assert bench_check.main(argv + ["--dry-run"]) == 0
    fresh.write_text(json.dumps(_bench_rec(101.0)))
    assert bench_check.main(argv) == 0
    # missing fresh artifact: hard error unless dry-run
    argv_missing = ["--history", str(tmp_path / "BENCH_*.json"),
                    "--fresh", str(tmp_path / "nope.json")]
    assert bench_check.main(argv_missing) == 2
    assert bench_check.main(argv_missing + ["--dry-run"]) == 0


def test_bench_probe_short_circuit(monkeypatch):
    """CPU-pinned env or BENCH_SKIP_PROBE skips the ~8.5 min probe backoff;
    the forced-outage test hook keeps priority over both."""
    sys.path.insert(0, REPO)
    import bench

    monkeypatch.delenv("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL", raising=False)
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert bench._probe_short_circuit() == "JAX_PLATFORMS selects cpu"
    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    assert bench._probe_short_circuit() is None  # tpu first: probe needed
    monkeypatch.setenv("BENCH_SKIP_PROBE", "1")
    assert bench._probe_short_circuit() == "BENCH_SKIP_PROBE=1"
    monkeypatch.setenv("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    assert bench._probe_short_circuit() is None  # outage hook owns the path


def test_bench_probe_verdict_cached_per_host(monkeypatch, tmp_path):
    """An unreachable-backend verdict persists to the host-local cache, so
    the ~8.5 min retry ladder replays once per TTL, not once per run
    (BENCH_r05 tail). A reachable verdict never short-circuits (the tunnel
    can drop between runs), and the forced-outage hook never writes the
    cache (a test run must not poison real ones)."""
    sys.path.insert(0, REPO)
    import time as _time

    import bench

    cache = tmp_path / "probe_verdict.json"
    monkeypatch.setenv("HANDEL_TPU_PROBE_CACHE", str(cache))
    monkeypatch.delenv("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL", raising=False)

    assert bench._cached_probe_failure() is None  # no cache yet
    bench._record_probe_verdict(False)
    age = bench._cached_probe_failure()
    assert age is not None and age < 60.0
    # a fresh failure verdict short-circuits the whole ladder
    monkeypatch.setenv("HANDEL_TPU_PROBE_BUDGET_S", "0.01")
    assert bench._probe_with_retries() is False

    bench._record_probe_verdict(True)
    assert bench._cached_probe_failure() is None  # success never cached-skips

    # stale failure verdict: re-probe (here the 0-budget ladder re-records)
    cache.write_text(json.dumps(
        {"reachable": False, "checked_at": _time.time() - 7200}
    ))
    assert bench._cached_probe_failure() is None

    # the forced-outage hook returns False without touching the cache
    cache.unlink()
    monkeypatch.setenv("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL", "1")
    assert bench._probe_with_retries() is False
    assert not cache.exists()

    # corrupt cache is ignored, not fatal
    cache.write_text("{nope")
    assert bench._cached_probe_failure() is None


def test_bench_probe_cache_path_survives_tmpdir_churn(monkeypatch, tmp_path):
    """The default probe-cache path must NOT live in tempfile.gettempdir():
    drivers point TMPDIR at a fresh per-round directory, so a verdict
    written there evaporates between rounds and the ~8.5 min ladder
    replays every round of an outage (BENCH_r05 — the PR 12 cache existed
    but was never found again). The default is keyed to the stable
    per-user cache root instead, so two rounds with different TMPDIRs
    resolve the SAME file."""
    sys.path.insert(0, REPO)
    import importlib
    import tempfile

    import bench

    monkeypatch.delenv("HANDEL_TPU_PROBE_CACHE", raising=False)
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "cache-root"))
    monkeypatch.setenv("TMPDIR", str(tmp_path / "round-a"))
    (tmp_path / "round-a").mkdir()
    (tmp_path / "round-b").mkdir()
    tempfile.tempdir = None  # force gettempdir() to re-read TMPDIR
    try:
        path_a = bench._probe_cache_path()
        monkeypatch.setenv("TMPDIR", str(tmp_path / "round-b"))
        tempfile.tempdir = None
        path_b = bench._probe_cache_path()
    finally:
        tempfile.tempdir = None
    assert path_a == path_b, "probe verdict must survive TMPDIR churn"
    assert str(tmp_path / "cache-root") in path_a
    # and recording actually creates the (previously absent) cache dir
    bench._record_probe_verdict(False)
    assert bench._cached_probe_failure() is not None


def test_bench_cached_unreachable_skips_ladder_to_cpu_fallback(tmp_path):
    """End to end: a fresh cached 'unreachable' verdict makes bench.py skip
    the retry ladder entirely and drop straight to the CPU fallback path,
    re-emitting the persisted TPU artifact (source == "persisted") — the
    outage round costs seconds, not ~8.5 min of backoff."""
    sys.path.insert(0, REPO)
    import subprocess
    import time as _time

    cache = tmp_path / "probe_verdict.json"
    cache.write_text(json.dumps(
        {"reachable": False, "checked_at": _time.time()}
    ))
    artifact = tmp_path / "bench_tpu.json"
    artifact.write_text(json.dumps({
        "metric": "4096sig_batch_verify_p50_ms", "value": 101.3,
        "unit": "ms", "vs_baseline": 8.88, "backend": "tpu",
        "captured_at": "2026-08-01T00:00:00Z",
    }))
    env = os.environ.copy()
    env.pop("JAX_PLATFORMS", None)  # probe path must actually be consulted
    env.pop("HANDEL_TPU_PLATFORM", None)
    env.pop("BENCH_SKIP_PROBE", None)
    env.pop("HANDEL_TPU_BENCH_FORCE_PROBE_FAIL", None)
    env["HANDEL_TPU_PROBE_CACHE"] = str(cache)
    env["HANDEL_TPU_BENCH_ARTIFACT"] = str(artifact)
    env["HANDEL_TPU_BENCH_FP_ARTIFACT"] = str(tmp_path / "fp.json")
    # ladder bait: were the cache ignored, the budget still bounds the run,
    # but the assertions below would see retry chatter / a probe attempt
    env["HANDEL_TPU_PROBE_BUDGET_S"] = "30"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert "host cache says unreachable" in r.stderr
    assert "retrying in" not in r.stderr  # no ladder
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["source"] == "persisted"
    assert line["backend"] == "tpu"
    assert line["value"] == 101.3


def test_bench_check_dedupes_persisted_reemits():
    cap = "2026-01-01T00:00:00Z"
    recs = [
        _bench_rec(100.0, captured_at=cap),
        _bench_rec(100.0, source="persisted", captured_at=cap),
        _bench_rec(100.0, source="persisted", captured_at=cap),
    ]
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        for i, r in enumerate(recs):
            with open(os.path.join(d, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump({"n": i, "rc": 0, "parsed": r}, f)
        hist = bench_check.load_history(os.path.join(d, "BENCH_*.json"))
    assert len(hist) == 1  # one capture, not three


# -- localhost platform end to end --------------------------------------------


def test_sim_metrics_end_to_end(tmp_path):
    """A 2-process localhost run with `metrics = true` serves /metrics and
    /readyz on every node process (distinct allocated ports, plan written
    to the run dir), and the endpoints are gone after the run."""
    from handel_tpu.sim.config import RunConfig, SimConfig, dump_config
    from handel_tpu.sim.platform import run_simulation
    from handel_tpu.sim import watch_cli

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        metrics=True,
        metrics_linger_s=3.0,
        max_timeout_s=30.0,
        runs=[RunConfig(nodes=8, threshold=8, processes=2)],
    )
    workdir = str(tmp_path / "run")

    async def run_and_scrape():
        task = asyncio.create_task(run_simulation(cfg, workdir))
        plan_path = os.path.join(workdir, "metrics_ports.json")
        deadline = time.monotonic() + 25
        scraped = {}
        ready_codes = {}
        while time.monotonic() < deadline and not task.done():
            eps = watch_cli.discover_endpoints(workdir)
            if len(eps) >= 2:
                for addr in eps:
                    got = await asyncio.to_thread(watch_cli.scrape, addr)
                    if got is None:
                        continue
                    # the server comes up before the node registers its
                    # reporters — keep re-scraping until this endpoint is
                    # warm, or the first boot-window scrape freezes a
                    # 3-family snapshot the assertions below reject
                    fams = {n for n in got[0] if n.startswith("handel_")}
                    if len(fams) < 20:
                        continue
                    scraped[addr] = got
                    code, _ = await asyncio.to_thread(
                        _get, addr, "/readyz"
                    )
                    ready_codes[addr] = code
                if len(scraped) >= 2:
                    break
            await asyncio.sleep(0.2)
        results = await task
        assert os.path.exists(plan_path)
        return results, scraped, ready_codes

    results, scraped, ready_codes = asyncio.run(run_and_scrape())
    assert len(results) == 1 and results[0].ok, results[0].outputs
    assert len(scraped) == 2, "both node processes must serve /metrics"
    assert set(ready_codes.values()) == {200}
    for fams, _text in scraped.values():
        handel_fams = {n for n in fams if n.startswith("handel_")}
        assert len(handel_fams) >= 20
        assert any(n.startswith("handel_sigs_") for n in handel_fams)
        assert any(n.startswith("handel_net_") for n in handel_fams)
        assert any(n.startswith("handel_penalty_") for n in handel_fams)
    # distinct ports per process
    with open(os.path.join(workdir, "metrics_ports.json")) as f:
        plan = json.load(f)
    addrs = list(plan["addresses"].values())
    assert len(addrs) == len(set(addrs)) == 2
    # endpoints die with the run
    for addr in addrs:
        with pytest.raises((urllib.error.URLError, OSError)):
            urllib.request.urlopen(f"http://{addr}/healthz", timeout=0.5)

"""Multi-tenant aggregation service tests (handel_tpu/service/).

Coverage per ISSUE 7's satellite list: session lifecycle transitions
(spawn/threshold/expire), eviction under the live-session cap,
deficit-round-robin starvation resistance (hot tenant + 15 cold tenants
all make progress), per-tenant dedup isolation (the same aggregate in two
sessions is NOT cross-deduped), per-launch fill-ratio accounting, the
session-labeled metrics plane, and the 2-process multi-session e2e through
the `sim serve` driver.
"""

from __future__ import annotations

import asyncio
import threading
import time

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.metrics import MetricsRegistry, parse_exposition
from handel_tpu.core.penalty import SessionScorers
from handel_tpu.core.store import VerifiedAggCache
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.service import (
    STATE_DONE,
    STATE_EXPIRED,
    STATE_RUNNING,
    AdmissionRefused,
    SessionManager,
    TenantQueue,
)
from handel_tpu.service.driver import (
    HostDevice,
    MultiSessionCluster,
    merge_summaries,
    run_service,
)
from handel_tpu.sim.config import (
    ServiceParams,
    SimConfig,
    dump_config,
    load_config,
)


def run(coro):
    return asyncio.run(coro)


class _Sig:
    """Marshal-able stand-in signature with identity-distinct bytes."""

    def __init__(self, tag: int = 0):
        self.tag = tag

    def marshal(self) -> bytes:
        return self.tag.to_bytes(4, "big")


def _req(tag: int, n: int = 16):
    bs = BitSet(n)
    bs.set(tag % n, True)
    return (bs, _Sig(tag))


class StubDevice:
    """Single-message device (no dispatch_multi): per-msg launch groups."""

    batch_size = 16

    def __init__(self, gate: threading.Event | None = None):
        self.dispatched = 0
        self.lanes: list[int] = []
        self.gate = gate

    def dispatch(self, msg, reqs):
        if self.gate is not None:
            self.gate.wait(5.0)
        self.dispatched += 1
        self.lanes.append(len(reqs))
        return len(reqs)

    def fetch(self, handle):
        return [True] * handle


class MultiStubDevice:
    """dispatch_multi-capable stub: whole mixed batches as one launch."""

    def __init__(self, batch_size: int = 16, launch_s: float = 0.0):
        self.batch_size = batch_size
        self.launch_s = launch_s
        self.dispatched = 0
        self.lanes: list[int] = []

    def dispatch_multi(self, items):
        if self.launch_s:
            time.sleep(self.launch_s)
        self.dispatched += 1
        self.lanes.append(len(items))
        return [True] * len(items)

    def fetch(self, handle):
        return handle


# -- TenantQueue: deficit round robin ----------------------------------------


def test_drr_single_tenant_fifo():
    q = TenantQueue(quantum=4)
    for i in range(10):
        assert q.push("a", i)
    assert q.take(6) == [0, 1, 2, 3, 4, 5]
    assert q.take(10) == [6, 7, 8, 9]
    assert len(q) == 0


def test_drr_fair_share_across_tenants():
    q = TenantQueue(quantum=2)
    for i in range(6):
        q.push("a", f"a{i}")
        q.push("b", f"b{i}")
    got = q.take(8)
    # quantum-2 alternation: neither tenant gets more than quantum ahead
    assert got == ["a0", "a1", "b0", "b1", "a2", "a3", "b2", "b3"]


def test_drr_hot_tenant_cannot_starve_cold():
    """Hot session + 15 cold sessions: every cold tenant's work drains
    within two 64-lane takes while the hot backlog waits its turns."""
    q = TenantQueue(quantum=8)
    for i in range(2000):
        q.push("hot", ("hot", i))
    for c in range(15):
        for i in range(8):
            q.push(f"cold{c}", (f"cold{c}", i))
    first = q.take(64)
    second = q.take(64)
    served = first + second
    cold_served = [it for it in served if it[0] != "hot"]
    assert len(cold_served) == 15 * 8, "a cold tenant was starved"
    hot_served = [it for it in served if it[0] == "hot"]
    # the hot tenant still progresses (no lockout), just fairly
    assert 0 < len(hot_served) <= 2 * 8
    assert q.depth("hot") == 2000 - len(hot_served)


def test_drr_deficit_continues_across_takes():
    """A lane budget exhausted mid-quantum must not reset whose turn it
    is: the head tenant finishes its quantum on the next take."""
    q = TenantQueue(quantum=4)
    for i in range(8):
        q.push("a", f"a{i}")
        q.push("b", f"b{i}")
    assert q.take(2) == ["a0", "a1"]
    # a's quantum (4) is half spent; it continues before b starts
    assert q.take(4) == ["a2", "a3", "b0", "b1"]


def test_tenant_bound_refuses_push():
    q = TenantQueue(quantum=4, max_pending=3)
    assert all(q.push("a", i) for i in range(3))
    assert not q.push("a", 99)
    assert q.refused == 1
    assert q.push("b", 0)  # other tenants unaffected


def test_drop_tenant_returns_items():
    q = TenantQueue()
    q.push("a", 1)
    q.push("b", 2)
    assert q.drop_tenant("a") == [1]
    assert q.depths() == {"b": 1}
    assert q.take(4) == [2]


# -- service: per-tenant dedup isolation + fill accounting -------------------


def test_per_tenant_dedup_isolation():
    """The same aggregate content in two sessions is TWO verifications;
    within one session the second copy is a cache hit."""

    async def go():
        svc = BatchVerifierService(StubDevice(), max_delay_ms=0.1)
        await svc.verify(b"m", [], [_req(1)], session="A")
        await svc.verify(b"m", [], [_req(1)], session="B")  # not cross-dedup
        await svc.verify(b"m", [], [_req(1)], session="A")  # intra-session hit
        svc.stop()
        return svc

    svc = run(go())
    assert svc.device.dispatched == 2
    assert svc.cache.hits == 1
    assert svc.tenant_dedup_hits == {"A": 1}


def test_forget_session_drops_state_and_fails_queued():
    async def go():
        gate = threading.Event()
        gate.set()
        svc = BatchVerifierService(StubDevice(gate=gate), max_delay_ms=0.1)
        await svc.verify(b"m", [], [_req(1)], session="A")  # cached verdict
        # gate the device so the NEXT batch parks the collector in its
        # dispatch executor, leaving later requests in the tenant queue
        gate.clear()
        blocker = asyncio.ensure_future(
            svc.verify(b"mC", [], [_req(9)], session="C")
        )
        await asyncio.sleep(0.05)
        t_a = asyncio.ensure_future(
            svc.verify(b"m", [], [_req(2)], session="A")
        )
        t_b = asyncio.ensure_future(
            svc.verify(b"m", [], [_req(3)], session="B")
        )
        await asyncio.sleep(0.02)
        assert any(k[0] == "A" for k in svc.cache._map)
        dropped = svc.forget_session("A")
        cache_clean = not any(k[0] == "A" for k in svc.cache._map)
        gate.set()
        with pytest.raises(RuntimeError, match="evicted"):
            await asyncio.wait_for(t_a, 2.0)
        assert await asyncio.wait_for(t_b, 2.0) == [True]
        assert await asyncio.wait_for(blocker, 2.0) == [True]
        svc.stop()
        return svc, dropped, cache_clean

    svc, dropped, cache_clean = run(go())
    assert dropped == 1
    assert cache_clean, "A's cached verdicts survived the evict"
    assert "A" not in svc.tenant_candidates


def test_launch_fill_ratio_coalesced():
    """4 sessions' 4 candidates each fill one 16-lane launch end to end."""

    async def go():
        svc = BatchVerifierService(MultiStubDevice(16), max_delay_ms=5.0)
        results = await asyncio.gather(
            *(
                svc.verify(
                    f"m{s}".encode(),
                    [],
                    [_req(s * 10 + i) for i in range(4)],
                    session=f"s{s}",
                )
                for s in range(4)
            )
        )
        svc.stop()
        return svc, results

    svc, results = run(go())
    assert all(r == [True] * 4 for r in results)
    assert svc.device.dispatched == 1
    assert svc.fill_launches == 1
    assert svc.values()["launchFillRatio"] == 1.0
    assert svc.values()["lastLaunchFill"] == 1.0
    assert svc.coalesced_launches == 1


def test_single_msg_device_groups_by_msg():
    """Without dispatch_multi, distinct messages still split (pre-service
    behavior), and each split launch records its own fill."""

    async def go():
        svc = BatchVerifierService(StubDevice(), max_delay_ms=5.0)
        await asyncio.gather(
            svc.verify(b"m1", [], [_req(1)], session="A"),
            svc.verify(b"m2", [], [_req(2)], session="B"),
        )
        svc.stop()
        return svc

    svc = run(go())
    assert svc.device.dispatched == 2
    assert svc.fill_launches == 2
    assert svc.coalesced_launches == 0
    assert svc.values()["launchFillRatio"] == pytest.approx(1 / 16)


def test_admission_bound_fails_future_immediately():
    async def go():
        svc = BatchVerifierService(
            MultiStubDevice(4, launch_s=0.05),
            max_delay_ms=0.1,
            max_pending_per_session=2,
        )
        reqs = [_req(i) for i in range(8)]
        with pytest.raises(RuntimeError, match="queue full"):
            await svc.verify(b"m", [], reqs, session="hot")
        vals = svc.values()
        svc.stop()
        return vals

    vals = run(go())
    assert vals["admissionRefused"] >= 1


# -- service: hot tenant vs cold tenants under load --------------------------


def test_service_hot_session_no_starvation():
    """500 hot candidates + 15 cold sessions x 4: every cold session
    resolves while most of the hot backlog is still queued."""

    async def go():
        svc = BatchVerifierService(
            MultiStubDevice(64, launch_s=0.002),
            max_delay_ms=0.5,
            quantum=8,
        )
        hot = [
            asyncio.ensure_future(
                svc.verify(b"hot", [], [_req(i, 1024)], session="hot")
            )
            for i in range(500)
        ]
        await asyncio.sleep(0)  # hot backlog enqueues first
        cold = [
            asyncio.ensure_future(
                svc.verify(
                    f"c{c}".encode(),
                    [],
                    [_req(c * 100 + i, 1024) for i in range(4)],
                    session=f"cold{c}",
                )
            )
            for c in range(15)
        ]
        await asyncio.wait_for(asyncio.gather(*cold), 10.0)
        hot_unresolved = sum(1 for f in hot if not f.done())
        await asyncio.wait_for(asyncio.gather(*hot), 20.0)
        svc.stop()
        return hot_unresolved

    hot_unresolved = run(go())
    # all cold done while the hot tenant still holds most of its backlog
    assert hot_unresolved > 250, (
        f"cold tenants waited for the hot backlog ({hot_unresolved} left)"
    )


# -- dedup cache scope drops --------------------------------------------------


def test_cache_drop_scope_plain_and_tuple():
    c = VerifiedAggCache()
    ms_key_a = ("A", b"m", b"w", b"s")
    ms_key_b = ("B", b"m", b"w", b"s")
    node_key = (("A", 3), b"w", b"s")
    plain_key = (3, b"w", b"s")
    for k in (ms_key_a, ms_key_b, node_key, plain_key):
        c.put(k, True)
    assert c.drop_scope("A") == 2
    assert ms_key_b in c._map and plain_key in c._map
    assert ms_key_a not in c._map and node_key not in c._map


# -- per-session penalty keying ----------------------------------------------


def test_session_scorers_isolated_and_dropped():
    scorers = SessionScorers()
    a = scorers.for_session("A")
    b = scorers.for_session("B")
    assert a is not b
    assert scorers.for_session("A") is a
    for _ in range(10):
        a.report(7)
    assert a.banned(7) and not b.banned(7)
    assert scorers.labeled_values()["A"]["peersBanned"] == 1.0
    assert scorers.drop("A")
    assert scorers.for_session("A") is not a  # fresh trust domain


def test_session_scorers_bounded():
    scorers = SessionScorers(capacity=2)
    s1 = scorers.for_session("s1")
    scorers.for_session("s2")
    scorers.for_session("s3")  # evicts s1 (LRU)
    assert len(scorers) == 2
    assert scorers.evicted == 1
    assert scorers.for_session("s1") is not s1


# -- session lifecycle --------------------------------------------------------


def test_session_lifecycle_spawn_running_threshold():
    async def go():
        svc = BatchVerifierService(MultiStubDevice(32), max_delay_ms=0.2)
        mgr = SessionManager(service=svc, max_sessions=4)
        s = mgr.spawn(8)
        assert s.state == "spawned"
        mgr.start(s.sid)
        assert s.state == STATE_RUNNING
        await mgr.wait_all(20.0)
        svc.stop()
        return mgr, s

    mgr, s = run(go())
    assert s.state == STATE_DONE
    assert s.completion_s() is not None and s.completion_s() > 0
    assert mgr.completed_ct == 1
    assert mgr.values()["sessionCompletionP50S"] > 0
    # tenant state released at completion
    assert s.sid not in mgr.service.tenant_candidates


def test_session_expires_at_ttl():
    async def go():
        mgr = SessionManager(max_sessions=2, session_ttl_s=0.3)
        # threshold 8 over a committee with one offline node: unreachable
        s = mgr.spawn(8, threshold=8, offline=(3,))
        mgr.start(s.sid)
        await mgr.wait_all(10.0)
        return mgr, s

    mgr, s = run(go())
    assert s.state == STATE_EXPIRED
    assert mgr.expired_ct == 1 and mgr.completed_ct == 0


def test_admission_cap_refuses_then_evicts_finished():
    async def go():
        mgr = SessionManager(max_sessions=2)
        s1 = mgr.spawn(4)
        mgr.spawn(4)
        # both live: a third spawn is refused outright
        with pytest.raises(AdmissionRefused):
            mgr.spawn(4)
        assert mgr.refused_ct == 1
        # finish s1: still HELD (results retained) — the next spawn at the
        # cap reclaims exactly that slot by evicting the finished session
        mgr.start(s1.sid)
        await mgr.wait_all(10.0)
        assert s1.state == STATE_DONE
        assert s1.sid in mgr.sessions
        s3 = mgr.spawn(4)
        assert s1.sid not in mgr.sessions
        assert s3.sid in mgr.sessions
        # both held sessions live again: refuse
        with pytest.raises(AdmissionRefused):
            mgr.spawn(4)
        return mgr, s1

    mgr, s1 = run(go())
    assert (s1.sid, STATE_DONE, s1.completion_s()) in list(mgr.retired)


def test_evict_vs_threshold_same_tick_settles_once():
    """The evict-vs-threshold race: evicting a session in the same event-loop
    tick its threshold future resolves must settle the session exactly once
    — never both completed AND evicted — with no late `_finish` after the
    eviction, and must still `forget_session` the tenant's shared-plane
    state. Deterministic via a hand-held completion future: the watcher is
    parked on it, then resolution and eviction happen with no await between
    them."""

    async def go():
        svc = BatchVerifierService(MultiStubDevice(32), max_delay_ms=0.2)
        forgotten: list[str] = []
        orig_forget = svc.forget_session
        svc.forget_session = lambda sid: (forgotten.append(sid),
                                          orig_forget(sid))[1]
        mgr = SessionManager(service=svc, max_sessions=4)

        # interleaving A: future resolves, evict lands BEFORE the watcher
        # gets to run — the session must settle as evicted, not completed
        s = mgr.spawn(4)
        gate = asyncio.get_running_loop().create_future()
        s.cluster.wait_complete_success = lambda ttl: gate
        mgr.start(s.sid)
        await asyncio.sleep(0)  # watcher parks on the gate
        gate.set_result({})  # threshold reached...
        assert mgr.evict(s.sid)  # ...and evicted, same tick, no await between
        await asyncio.sleep(0.01)  # any stray watcher wakeup fires here

        # interleaving B: the watcher settles DONE first, the evict of the
        # still-held finished session lands in the same tick — terminal
        # state must stick and the second tenant release must be idempotent
        s2 = mgr.spawn(4)
        gate2 = asyncio.get_running_loop().create_future()
        s2.cluster.wait_complete_success = lambda ttl: gate2
        mgr.start(s2.sid)
        await asyncio.sleep(0)
        gate2.set_result({})
        await asyncio.sleep(0)  # watcher runs _finish(DONE)
        assert s2.state == STATE_DONE
        assert mgr.evict(s2.sid)  # held-but-finished: bookkeeping only
        await asyncio.sleep(0.01)
        svc.stop()
        return mgr, s, s2, forgotten

    mgr, s, s2, forgotten = run(go())
    assert s.state == "evicted"
    assert s2.state == STATE_DONE  # eviction never rewrites a terminal state
    # each session settled exactly once: A evicted, B completed
    assert mgr.evicted_ct == 1 and mgr.completed_ct == 1
    assert mgr.expired_ct == 0
    assert s.sid not in mgr.sessions and s2.sid not in mgr.sessions
    # tenant state released for both (idempotent on B's double release)
    assert forgotten.count(s.sid) == 1
    assert forgotten.count(s2.sid) >= 1
    assert s.sid not in mgr.tiers and s2.sid not in mgr.tiers
    states = {sid: state for sid, state, _ in mgr.retired}
    assert states[s.sid] == "evicted" and states[s2.sid] == STATE_DONE


def test_evict_running_session():
    async def go():
        svc = BatchVerifierService(MultiStubDevice(32), max_delay_ms=0.2)
        mgr = SessionManager(service=svc, max_sessions=4)
        s = mgr.spawn(16)
        mgr.start(s.sid)
        await asyncio.sleep(0.01)
        assert mgr.evict(s.sid)
        svc.stop()
        return mgr, s

    mgr, s = run(go())
    assert s.state == "evicted"
    assert mgr.evicted_ct == 1
    assert s.sid not in mgr.sessions


# -- session-labeled metrics plane -------------------------------------------


def test_labeled_metrics_carry_session_dimension():
    async def go():
        cluster = MultiSessionCluster(
            2, 8, batch_size=32, metrics_port=0
        )
        summary = await cluster.run(30.0)
        text = cluster.metrics.exposition()
        cluster.stop()
        return summary, text

    summary, text = run(go())
    assert summary["completed"] == 2
    fams = parse_exposition(text)
    pending = fams.get("handel_service_pending")
    assert pending is not None and pending["type"] == "gauge"
    sids = {lb.get("session") for lb, _ in pending["samples"]}
    assert len(sids) == 2
    assert fams["handel_service_sessions_completed"]["samples"][0][1] == 2.0
    # every completed session reports the terminal state + its completion
    # latency on the labeled plane
    states = [v for _, v in fams["handel_service_state"]["samples"]]
    assert states == [2.0, 2.0]  # threshold-reached
    assert all(
        v > 0 for _, v in fams["handel_service_completion_s"]["samples"]
    )
    fill = fams["handel_device_verifier_launch_fill_ratio"]
    assert fill["type"] == "gauge"


def test_registry_labeled_values_collector_unit():
    class R:
        def labeled_values(self):
            return {"a": {"depth": 3.0, "hits": 1.0}}

        def gauge_keys(self):
            return {"depth"}

    reg = MetricsRegistry()
    reg.register_labeled_values("svc", R(), label="session")
    fams = parse_exposition(reg.exposition())
    assert fams["handel_svc_depth"]["type"] == "gauge"
    assert fams["handel_svc_hits"]["type"] == "counter"
    labels, v = fams["handel_svc_depth"]["samples"][0]
    assert labels["session"] == "a" and v == 3.0


# -- drivers ------------------------------------------------------------------


def test_multi_session_cluster_all_reach_threshold():
    async def go():
        cluster = MultiSessionCluster(4, 8, batch_size=32)
        try:
            return await cluster.run(30.0), cluster
        finally:
            cluster.stop()

    (summary, cluster) = run(go())
    assert summary["completed"] == 4 and summary["expired"] == 0
    assert summary["aggregates_per_s"] > 0
    assert summary["coalesced_launches"] > 0
    assert 0 < summary["launch_fill_ratio"] <= 1.0
    # per-session dedup never crossed tenants: every session completed with
    # its OWN message, so any cross-dedup would have corrupted verdicts
    assert cluster.service.values()["dedupHitRate"] >= 0


def test_host_device_verdicts_honest():
    """HostDevice must verify, not rubber-stamp: an invalid fake sig in
    one lane fails only that lane."""
    from handel_tpu.core.test_harness import FakeScheme
    from handel_tpu.models.fake import FakePublic, FakeSignature

    scheme = FakeScheme()
    dev = HostDevice(scheme.constructor, batch_size=8)
    pks = [FakePublic(True) for _ in range(4)]
    good, bad = BitSet(4), BitSet(4)
    good.set(0, True)
    bad.set(1, True)
    verdicts = dev.fetch(
        dev.dispatch_multi(
            [
                (b"m1", pks, good, FakeSignature(True)),
                (b"m2", pks, bad, FakeSignature(False)),
            ]
        )
    )
    assert verdicts == [True, False]


def test_serve_driver_two_processes(tmp_path):
    """2-process multi-session e2e: the `sim serve` fleet path."""
    cfg = SimConfig(
        scheme="fake",
        service=ServiceParams(
            sessions=4, nodes=8, processes=2, session_ttl_s=30.0,
            batch_size=32,
        ),
        max_timeout_s=60.0,
    )
    summary = run(run_service(cfg, str(tmp_path)))
    assert summary["ok"]
    assert summary["workers"] == 2
    assert summary["completed"] == 4
    assert (tmp_path / "service_summary.json").exists()


def test_merge_summaries_weighting():
    a = {
        "sessions": 2, "nodes_per_session": 8, "completed": 2, "expired": 0,
        "wall_s": 1.0, "aggregates_per_s": 2.0, "session_p50_s": 0.2,
        "session_p99_s": 0.5, "verifier_launches": 10,
        "verifier_candidates": 100, "coalesced_launches": 5,
        "launch_fill_ratio": 0.5, "dedup_hit_rate": 0.5,
        "admission_refused": 0,
    }
    b = dict(a, wall_s=2.0, session_p99_s=0.9, verifier_launches=30,
             launch_fill_ratio=0.9, verifier_candidates=300,
             dedup_hit_rate=0.7)
    m = merge_summaries([a, b])
    assert m["sessions"] == 4 and m["completed"] == 4
    assert m["wall_s"] == 2.0
    assert m["session_p99_s"] == 0.9  # worst worker
    assert m["launch_fill_ratio"] == pytest.approx(0.8)  # launch-weighted
    assert m["aggregates_per_s"] == pytest.approx(4.0)


def test_service_toml_round_trip(tmp_path):
    cfg = SimConfig(
        scheme="fake",
        service=ServiceParams(
            sessions=64, nodes=128, processes=4, max_sessions=64,
            session_ttl_s=300.0, quantum=16, max_pending_per_session=2048,
            batch_size=128, spawn_stagger_ms=5.0, period_ms=20.0,
        ),
    )
    p = tmp_path / "serve.toml"
    p.write_text(dump_config(cfg))
    got = load_config(str(p)).service
    assert got == cfg.service
    # default config: service mode off
    q = tmp_path / "plain.toml"
    q.write_text(dump_config(SimConfig()))
    assert not load_config(str(q)).service.enabled()


def test_soak_toml_round_trip(tmp_path):
    from handel_tpu.sim.config import SoakParams

    cfg = SimConfig(
        soak=SoakParams(
            duration_s=12.0, nodes=8, concurrency=4, devices=3,
            max_lanes=6, queue_capacity=512, tiers="gold,bronze",
            swap_at_frac=0.3, lane_loss_at_frac=0.7,
        ),
    )
    p = tmp_path / "soak.toml"
    p.write_text(dump_config(cfg))
    assert load_config(str(p)).soak == cfg.soak
    # a default config dumps no [soak] table and loads back to defaults
    q = tmp_path / "plain.toml"
    q.write_text(dump_config(SimConfig()))
    assert "[soak]" not in q.read_text()
    assert load_config(str(q)).soak == SoakParams()


# -- sim watch session rows ---------------------------------------------------


def test_watch_renders_session_rows():
    from handel_tpu.sim.watch_cli import aggregate, render

    text = "\n".join(
        [
            "# TYPE handel_service_state gauge",
            'handel_service_state{session="s1"} 1',
            'handel_service_state{session="s2"} 2',
            "# TYPE handel_service_pending gauge",
            'handel_service_pending{session="s1"} 40',
            'handel_service_pending{session="s2"} 0',
            "# TYPE handel_service_nodes_done gauge",
            'handel_service_nodes_done{session="s1"} 3',
            'handel_service_nodes_done{session="s2"} 8',
            "# TYPE handel_service_nodes gauge",
            'handel_service_nodes{session="s1"} 8',
            'handel_service_nodes{session="s2"} 8',
            "# TYPE handel_service_sessions_live gauge",
            "handel_service_sessions_live 1",
            "# TYPE handel_service_sessions_completed counter",
            "handel_service_sessions_completed 1",
        ]
    )
    model = aggregate([parse_exposition(text)])
    assert model["sessions"]["s1"]["pending"] == 40.0
    frame = render(model, ["x"], 1, 1)
    assert "sessions" in frame
    assert "running" in frame and "done" in frame
    # top-K orders by pending: the hot session leads
    assert frame.index("s1") < frame.index("s2")


def test_watch_renders_lifecycle_row():
    from handel_tpu.sim.watch_cli import aggregate, render

    text = "\n".join(
        [
            "# TYPE handel_device_verifier_epoch gauge",
            "handel_device_verifier_epoch 2",
            "# TYPE handel_device_verifier_quiesce_ct counter",
            "handel_device_verifier_quiesce_ct 2",
            "# TYPE handel_device_verifier_last_quiesce_stall_ms gauge",
            "handel_device_verifier_last_quiesce_stall_ms 65.2",
            "# TYPE handel_device_verifier_admission_shed counter",
            "handel_device_verifier_admission_shed 12",
            "# TYPE handel_device_verifier_shed_rate gauge",
            "handel_device_verifier_shed_rate 0.03",
            "# TYPE handel_device_verifier_lanes_added counter",
            "handel_device_verifier_lanes_added 3",
            "# TYPE handel_device_verifier_lanes_removed counter",
            "handel_device_verifier_lanes_removed 1",
        ]
    )
    model = aggregate([parse_exposition(text)])
    assert model["epoch"] == 2.0 and model["shed_rate"] == 0.03
    frame = render(model, ["x"], 1, 1)
    assert "lifecycle epoch 2" in frame
    assert "65.2ms" in frame and "lanes +3/-1" in frame
    # no lifecycle plane scraped -> the row stays absent entirely
    bare = aggregate([parse_exposition("")])
    assert "lifecycle" not in render(bare, ["x"], 1, 1)

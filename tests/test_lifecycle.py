"""Production lifecycle control plane tests (handel_tpu/lifecycle/).

Coverage per ISSUE 12: epoch registry rotation (stage/quiesce/flip with
zero dropped futures, epoch-versioned dedup keys, session versioning),
verify-plane elasticity (live attach, graceful drain, breaker-open
replacement, depth/fill scaling with cooldown), SLO-driven admission
(global shed bound, tier-weighted DRR, per-tier quantiles), critical-path
autotuning (dominance hysteresis over stages_ms, clamps), and the
controller loop tying them together. The full drill runs in CI as
`scripts/soak_smoke.py`; these are the deterministic unit/integration
pieces.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.lifecycle import (
    CriticalPathAutotuner,
    EpochManager,
    LaneAutoscaler,
    LifecycleController,
)
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.parallel.plane import DevicePlane
from handel_tpu.service import SessionManager, TenantQueue
from handel_tpu.service.driver import HostDevice
from handel_tpu.service.fairness import TIERS, SloTier


def run(coro):
    return asyncio.run(coro)


class _Sig:
    def __init__(self, tag: int = 0):
        self.tag = tag

    def marshal(self) -> bytes:
        return self.tag.to_bytes(4, "big")


def _req(tag: int, n: int = 16):
    bs = BitSet(n)
    bs.set(tag % n, True)
    return (bs, _Sig(tag))


class StubEngine:
    """dispatch_multi stub with the epoch-rotation protocol."""

    def __init__(self, batch_size: int = 16, launch_s: float = 0.0):
        self.batch_size = batch_size
        self.launch_s = launch_s
        self.dispatched = 0
        self.epoch = 0
        self._staged = None
        self.fail = False

    def stage_registry(self, registry_pubkeys, build_prefix: bool = True):
        self._staged = list(registry_pubkeys)
        return len(self._staged)

    def activate_staged(self):
        if self._staged is None:
            raise RuntimeError("no staged registry")
        self._staged = None
        self.epoch += 1
        return self.epoch

    def dispatch_multi(self, items):
        if self.fail:
            raise RuntimeError("chip gone")
        if self.launch_s:
            time.sleep(self.launch_s)
        self.dispatched += 1
        return [True] * len(items)

    def fetch(self, handle):
        return handle


# -- quiesce + epoch rotation -------------------------------------------------


def test_quiesce_runs_fn_with_plane_idle():
    async def go():
        svc = BatchVerifierService(StubEngine(launch_s=0.01), max_delay_ms=0.2)
        futs = [
            asyncio.ensure_future(
                svc.verify(b"m", [], [_req(i)], session="s")
            )
            for i in range(8)
        ]
        await asyncio.sleep(0.005)  # some launches in flight
        seen = {}

        def fn():
            seen["idle"] = svc._plane_idle()

        stall = await svc.quiesce_and(fn)
        await asyncio.gather(*futs)
        svc.stop()
        return seen, stall, svc

    seen, stall, svc = run(go())
    assert seen["idle"] is True
    assert stall >= 0.0
    assert svc.quiesce_ct == 1
    assert svc.values()["lastQuiesceStallMs"] == pytest.approx(stall * 1e3)


def test_quiesce_before_start_runs_fn_directly():
    async def go():
        svc = BatchVerifierService(StubEngine())
        called = []
        stall = await svc.quiesce_and(lambda: called.append(1))
        return called, stall

    called, stall = run(go())
    assert called == [1] and stall == 0.0


def test_epoch_rotation_zero_drops_and_versioned_dedup():
    """Work submitted before, during, and after a rotation all resolves;
    the same aggregate re-verifies after the flip (epoch is in the dedup
    key) instead of replaying the old epoch's verdict."""

    async def go():
        eng = StubEngine(launch_s=0.002)
        svc = BatchVerifierService(eng, max_delay_ms=0.2)
        mgr = SessionManager(service=svc, max_sessions=4)
        em = EpochManager(svc, mgr)

        before = [
            asyncio.ensure_future(
                svc.verify(b"m", [], [_req(i)], session="s")
            )
            for i in range(6)
        ]
        await asyncio.sleep(0.001)
        dispatched_epoch0 = eng.dispatched
        stall = await em.rotate([f"pk{i}" for i in range(8)])
        after = [
            asyncio.ensure_future(
                svc.verify(b"m", [], [_req(i)], session="s")
            )
            for i in range(6)
        ]
        r_before = await asyncio.gather(*before)
        r_after = await asyncio.gather(*after)
        svc.stop()
        return eng, svc, mgr, em, stall, dispatched_epoch0, r_before, r_after

    eng, svc, mgr, em, stall, d0, r_before, r_after = run(go())
    assert all(r == [True] for r in r_before + r_after)
    assert svc.epoch == 1 and mgr.epoch == 1 and em.epoch == 1
    assert eng.epoch == 1 and eng._staged is None
    assert em.rotations == 1 and stall >= 0.0
    # the identical aggregates re-dispatched under the new epoch: the old
    # epoch's cached verdicts were NOT replayed across the flip
    assert eng.dispatched > d0
    vals = em.values()
    assert vals["epochRotations"] == 1.0
    assert vals["lastEpochSwapStallMs"] == pytest.approx(stall * 1e3)


def test_commit_without_stage_raises():
    async def go():
        svc = BatchVerifierService(StubEngine())
        em = EpochManager(svc)
        with pytest.raises(RuntimeError, match="no staged rotation"):
            await em.commit_rotation()

    run(go())


def test_sessions_spawn_under_current_epoch():
    svc = BatchVerifierService(StubEngine())
    mgr = SessionManager(service=svc, max_sessions=4)
    mgr.epoch = 3
    s = mgr.spawn(4)
    assert s.epoch == 3
    # the epoch rides every node Config into dedup keys + trace spans
    assert all(h.c.epoch == 3 for h in s.cluster.handels.values())


def test_host_device_epoch_protocol():
    dev = HostDevice(None)
    assert dev.stage_registry(["a", "b"]) == 2
    assert dev.activate_staged() == 1
    with pytest.raises(RuntimeError):
        dev.activate_staged()


# -- plane elasticity ---------------------------------------------------------


def test_attach_lane_live_dispatches():
    async def go():
        # batch_size 2: 12 candidates split into 6 launch groups, so the
        # least-loaded scheduler has real work to spread onto the new lane
        svc = BatchVerifierService(
            StubEngine(batch_size=2, launch_s=0.005), max_delay_ms=0.1
        )
        svc.start()
        eng2 = StubEngine(batch_size=2, launch_s=0.005)
        lane = svc.attach_lane(eng2)  # wired live, mid-service
        futs = [
            asyncio.ensure_future(
                svc.verify(f"m{i}".encode(), [], [_req(i)], session="s")
            )
            for i in range(12)
        ]
        await asyncio.gather(*futs)
        svc.stop()
        return svc, lane, eng2

    svc, lane, eng2 = run(go())
    assert len(svc.plane) == 2 and lane.index == 1
    assert eng2.dispatched > 0, "attached lane never dispatched"
    assert svc.plane.values()["lanesAdded"] == 1.0


def test_drain_lane_graceful_and_last_lane_protected():
    async def go():
        plane = DevicePlane([StubEngine(), StubEngine()])
        svc = BatchVerifierService(plane, max_delay_ms=0.1)
        await svc.verify(b"m", [], [_req(1)], session="s")
        lane = svc.plane.lanes[1]
        clean = await svc.drain_lane(lane)
        # remaining work still verifies on the surviving lane
        r = await svc.verify(b"m2", [], [_req(2)], session="s")
        with pytest.raises(ValueError, match="last lane"):
            svc.plane.remove_lane(svc.plane.lanes[0])
        svc.stop()
        return svc, clean, r

    svc, clean, r = run(go())
    assert clean is True and r == [True]
    assert len(svc.plane) == 1
    assert svc.plane.values()["lanesRemoved"] == 1.0


def test_draining_lane_not_scheduled():
    plane = DevicePlane([StubEngine(), StubEngine()])
    plane.lanes[0].draining = True
    assert plane.allowed() == [plane.lanes[1]]
    assert plane.pick() is plane.lanes[1]


def test_autoscaler_replaces_breaker_open_lane():
    async def go():
        plane = DevicePlane([StubEngine(), StubEngine()])
        svc = BatchVerifierService(plane, max_delay_ms=0.1)
        svc.start()
        scaler = LaneAutoscaler(
            svc, engine_factory=StubEngine, min_lanes=2, max_lanes=4
        )
        broken = svc.plane.lanes[0]
        while broken.breaker.state != "open":
            broken.breaker.record_failure()
        out = await scaler.tick()
        r = await svc.verify(b"m", [], [_req(1)], session="s")
        svc.stop()
        return svc, scaler, broken, out, r

    svc, scaler, broken, out, r = run(go())
    assert scaler.lanes_replaced == 1
    assert broken not in svc.plane.lanes
    assert len(svc.plane) == 2  # attach-first: never below the floor
    assert r == [True]
    assert any("replaced" in a for a in out["actions"])


def test_autoscaler_grows_on_depth_and_respects_cooldown():
    async def go():
        svc = BatchVerifierService(StubEngine(), max_delay_ms=0.1)
        svc.start()
        now = [0.0]
        scaler = LaneAutoscaler(
            svc,
            engine_factory=StubEngine,
            min_lanes=1,
            max_lanes=3,
            scale_up_depth=1,
            cooldown_s=10.0,
            clock=lambda: now[0],
        )
        fut = asyncio.get_running_loop().create_future()
        svc.queue.push("t", ("t", b"m", [], _req(1)[0], _req(1)[1], fut))
        await scaler.tick()
        lanes_after_first = len(svc.plane)
        await scaler.tick()  # inside cooldown: no growth
        lanes_after_second = len(svc.plane)
        now[0] = 20.0
        await scaler.tick()  # cooldown expired, depth still high
        fut.cancel()
        svc.queue.drop_tenant("t")
        svc.stop()
        return svc, scaler, lanes_after_first, lanes_after_second

    svc, scaler, l1, l2 = run(go())
    assert l1 == 2 and l2 == 2 and len(svc.plane) == 3
    assert scaler.lanes_grown == 2


def test_autoscaler_shrinks_idle_plane_to_floor():
    async def go():
        plane = DevicePlane([StubEngine(), StubEngine(), StubEngine()])
        svc = BatchVerifierService(plane, max_delay_ms=0.1)
        svc.start()
        now = [0.0]
        scaler = LaneAutoscaler(
            svc,
            engine_factory=StubEngine,
            min_lanes=2,
            max_lanes=4,
            scale_down_depth=8,
            cooldown_s=1.0,
            clock=lambda: now[0],
        )
        now[0] = 2.0
        await scaler.tick()  # idle + empty: shrink one
        now[0] = 4.0
        await scaler.tick()  # at the floor: hold
        svc.stop()
        return svc, scaler

    svc, scaler = run(go())
    assert len(svc.plane) == 2 and scaler.lanes_shrunk == 1


# -- SLO admission ------------------------------------------------------------


def _item(tag: int, tenant: str = "t"):
    bs, sig = _req(tag)
    return (tenant, b"m", [], bs, sig, None)


def test_tenant_queue_sheds_at_capacity():
    q = TenantQueue(quantum=4, max_pending=100, capacity=4)
    for i in range(4):
        assert q.push("a", _item(i))
    assert not q.push("a", _item(9))  # at global capacity: shed
    assert q.shed == 1 and q.shed_rate() == pytest.approx(1 / 5)
    q.take(4)
    assert q.push("a", _item(10))  # drained: admits again


def test_tier_shed_ladder_bronze_before_gold():
    q = TenantQueue(quantum=4, max_pending=100, capacity=10)
    q.set_tier("b", "bronze")  # shed_at 0.60 -> refuses at depth 6
    q.set_tier("g", "gold")  # shed_at 0.98 -> refuses at depth 9
    for i in range(6):
        assert q.push("g", _item(i))
    assert not q.push("b", _item(100)), "bronze admitted past its shed point"
    assert q.push("g", _item(101)), "gold shed too early"
    assert q.shed == 1


def test_tier_weight_scales_drr_quantum():
    q = TenantQueue(quantum=2, max_pending=100)
    q.set_tier("g", "gold")  # weight 4 -> 8 credits per visit
    for i in range(8):
        q.push("g", _item(i, "g"))
        q.push("s", _item(100 + i, "s"))
    batch = q.take(12)
    by_tenant = {}
    for it in batch:
        by_tenant[it[0]] = by_tenant.get(it[0], 0) + 1
    assert by_tenant["g"] == 8 and by_tenant["s"] == 4


def test_drop_tenant_releases_tier_and_total():
    q = TenantQueue(quantum=4, max_pending=100, capacity=8)
    q.set_tier("a", "gold")
    for i in range(8):
        q.push("a", _item(i))
    assert len(q.drop_tenant("a")) == 8
    assert q.tier_of("a").name == "standard"
    # global depth released: a full capacity's worth admits again
    for i in range(8):
        assert q.push("b", _item(i))


def test_tier_registry_shapes():
    assert set(TIERS) == {"gold", "silver", "bronze", "standard"}
    assert TIERS["gold"].weight > TIERS["bronze"].weight
    assert TIERS["gold"].p99_target_s < TIERS["bronze"].p99_target_s
    assert isinstance(TIERS["gold"], SloTier)


def test_manager_tier_quantiles_against_targets():
    async def go():
        svc = BatchVerifierService(StubEngine(32), max_delay_ms=0.2)
        mgr = SessionManager(service=svc, max_sessions=8)
        for i in range(2):
            s = mgr.spawn(8, tier="gold")
            mgr.start(s.sid)
        await mgr.wait_all(20.0)
        svc.stop()
        return mgr

    mgr = run(go())
    tq = mgr.tier_quantiles()
    assert tq["gold"]["completed"] == 2.0
    assert 0 < tq["gold"]["p99_s"] <= tq["gold"]["target_s"]
    assert tq["gold"]["met"] == 1.0
    # tier mapping released at completion, latency bucket retained
    assert mgr.tiers == {}


# -- critical-path autotuning -------------------------------------------------


def _report(**stages):
    return {"stages_ms": stages}


def test_autotuner_queue_dominance_shrinks_window():
    svc = BatchVerifierService(StubEngine())
    tuner = CriticalPathAutotuner(svc, patience=2)
    d0 = svc.max_delay
    assert tuner.observe(_report(queue=80.0, device=10.0, net=5.0)) == ""
    assert svc.max_delay == d0  # hysteresis: one report is noise
    action = tuner.observe(_report(queue=80.0, device=10.0, net=5.0))
    assert "max_delay" in action and svc.max_delay < d0
    assert tuner.adjustments == 1


def test_autotuner_device_dominance_grows_window_with_clamp():
    svc = BatchVerifierService(StubEngine())
    tuner = CriticalPathAutotuner(svc, patience=1, max_delay_s=0.004)
    for _ in range(20):
        tuner.observe(_report(queue=5.0, device=90.0, net=5.0))
    assert svc.max_delay == pytest.approx(0.004)  # clamped at the ceiling


def test_autotuner_net_dominance_raises_inflight():
    svc = BatchVerifierService(StubEngine())
    tuner = CriticalPathAutotuner(svc, patience=1, max_inflight_cap=4)
    base = svc.max_inflight
    for _ in range(10):
        tuner.observe(_report(queue=5.0, device=5.0, net=90.0))
    assert svc.max_inflight == 4 > base


def test_autotuner_streak_resets_on_stage_change():
    svc = BatchVerifierService(StubEngine())
    tuner = CriticalPathAutotuner(svc, patience=2)
    tuner.observe(_report(queue=90.0, device=5.0))
    tuner.observe(_report(device=90.0, queue=5.0))
    tuner.observe(_report(queue=90.0, device=5.0))
    assert tuner.adjustments == 0  # no stage held dominance twice running


def test_autotuner_ignores_empty_and_unattributed_reports():
    svc = BatchVerifierService(StubEngine())
    tuner = CriticalPathAutotuner(svc, patience=1)
    assert tuner.observe(None) == ""
    assert tuner.observe({}) == ""
    # verify/merge dominance is not actionable by the collector window
    assert tuner.observe(_report(verify=95.0, queue=1.0, device=1.0)) == ""
    assert svc.max_delay == 2.0 / 1e3 and tuner.adjustments == 0


# -- controller ---------------------------------------------------------------


def test_controller_ticks_compose_and_survive_bad_reports():
    async def go():
        svc = BatchVerifierService(StubEngine(), max_delay_ms=0.1)
        svc.start()
        scaler = LaneAutoscaler(svc, engine_factory=StubEngine, min_lanes=1)
        calls = [0]

        def bad_source():
            calls[0] += 1
            raise OSError("report missing")

        ctl = LifecycleController(
            svc,
            autoscaler=scaler,
            autotuner=CriticalPathAutotuner(svc),
            epoch_manager=EpochManager(svc),
            report_source=bad_source,
            interval_s=0.01,
        )
        ctl.start()
        with pytest.raises(RuntimeError, match="already started"):
            ctl.start()
        await asyncio.sleep(0.08)
        await ctl.stop()
        ticks = ctl.ticks
        await ctl.stop()  # idempotent
        svc.stop()
        return ctl, ticks, calls[0]

    ctl, ticks, calls = run(go())
    assert ticks >= 3 and calls >= 3  # broken source never killed the loop
    vals = ctl.values()
    assert vals["lifecycleTicks"] == float(ticks)
    # merged telemetry surface spans all three sub-planes
    assert {"lanesReplaced", "autotuneAdjustments", "epochRotations"} <= set(
        vals
    )
    assert "fillSignal" in ctl.gauge_keys()


def test_service_values_carry_lifecycle_keys():
    svc = BatchVerifierService(StubEngine(), queue_capacity=8)
    vals = svc.values()
    for key in ("epoch", "quiesceCt", "lastQuiesceStallMs", "shedRate",
                "admissionShed"):
        assert key in vals, key
    assert {"epoch", "lastQuiesceStallMs", "shedRate"} <= svc.gauge_keys()

"""Test configuration.

Tests never require the real TPU: JAX runs on CPU with 8 virtual devices so
sharding/mesh tests exercise real multi-device code paths
(xla_force_host_platform_device_count, see task spec / SURVEY.md §7).

The environment may pre-register an experimental TPU platform plugin at
interpreter startup (a sitecustomize that calls
`jax.config.update("jax_platforms", ...)`), which overrides the JAX_PLATFORMS
environment variable — so setting the env var is NOT enough. The shared
helper (handel_tpu/utils/jaxenv.py) re-overrides through the config API,
which wins over any earlier update, and clears any already-initialized
backends so the CPU selection actually engages.
This must run before any test imports jax-dependent modules.
"""

import os

# force CPU even if the caller exported HANDEL_TPU_PLATFORM=tpu: test
# correctness must be checkable on any chip-less machine
os.environ["HANDEL_TPU_PLATFORM"] = "cpu"

from handel_tpu.utils.jaxenv import apply_platform_env

apply_platform_env(default="cpu", force_host_device_count=8)

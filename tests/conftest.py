"""Test configuration.

Tests never require the real TPU: JAX runs on CPU with 8 virtual devices so
sharding/mesh tests exercise real multi-device code paths
(xla_force_host_platform_device_count, see task spec / SURVEY.md §7).
This must run before any `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

"""Test configuration.

Tests never require the real TPU: JAX runs on CPU with 8 virtual devices so
sharding/mesh tests exercise real multi-device code paths
(xla_force_host_platform_device_count, see task spec / SURVEY.md §7).

The environment may pre-register an experimental TPU platform plugin at
interpreter startup (a sitecustomize that calls
`jax.config.update("jax_platforms", ...)`), which overrides the JAX_PLATFORMS
environment variable — so setting the env var is NOT enough. We re-override
through the config API, which wins over any earlier update, and clear any
already-initialized backends so the CPU selection actually engages.
This must run before any test imports jax-dependent modules.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: pairing-sized graphs take tens of seconds to
# compile on CPU the first time; reruns hit the disk cache
jax.config.update("jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():  # a plugin already built a backend set
    from jax.extend.backend import clear_backends

    clear_backends()

"""Fault injection + resilience: ChaosNetwork, adversarial rounds, failover.

The tentpole integration contracts (ISSUE 3):
  * a 16-node round with 2 invalid-signer adversaries and 10% seeded packet
    loss completes to threshold (fake + bn254 schemes, CPU), and
  * a BN254-style device failure mid-run trips the verifier circuit breaker
    and fails over to the host reference verifier with the round still
    completing (breaker/failover counters > 0).

Unit layers: seeded determinism of the chaos fault pattern, per-fault
counters, TOML plumbing for the chaos section and the adversary matrix, and
the localhost-platform adversarial smoke run. The long adversarial sweep is
slow-tier.
"""

import asyncio
import csv
import random

import pytest

from handel_tpu.core.identity import Identity
from handel_tpu.core.net import Packet
from handel_tpu.network.chaos import ChaosConfig, ChaosNetwork


class RecordingNet:
    """Minimal inner transport: remembers every (address, packet) delivery."""

    def __init__(self):
        self.delivered = []
        self.listeners = []

    def send(self, identities, packet):
        for ident in identities:
            self.delivered.append((ident.address, packet))

    def register_listener(self, listener):
        self.listeners.append(listener)

    def values(self):
        return {"innerSent": float(len(self.delivered))}


def ident(i):
    return Identity(i, f"peer-{i}", None)


def packet(i=0, payload=b"\x00\x08\xaa" + b"\x01" * 8):
    return Packet(origin=i, level=1, multisig=payload)


def test_chaos_config_validates_rates():
    with pytest.raises(ValueError):
        ChaosConfig(drop_rate=1.5).validate()
    with pytest.raises(ValueError):
        ChaosConfig(corrupt_rate=-0.1).validate()
    ChaosConfig(drop_rate=1.0, reorder_rate=0.0).validate()
    assert not ChaosConfig().any()
    assert ChaosConfig(delay_rate=0.1).any()


def test_chaos_drop_is_seeded_and_per_link():
    """The same seed reproduces the same fault pattern; different seeds (or
    links) fault independently."""

    def pattern(seed):
        inner = RecordingNet()
        net = ChaosNetwork(inner, ChaosConfig(drop_rate=0.5, seed=seed))
        for k in range(64):
            net.send([ident(0), ident(1)], packet(k))
        return [addr for addr, _ in inner.delivered], net.dropped

    a, dropped_a = pattern(7)
    b, _ = pattern(7)
    c, _ = pattern(8)
    assert a == b  # deterministic
    assert a != c  # seed-dependent
    assert 0 < dropped_a < 128  # some but not all of 2*64 deliveries


def test_chaos_corruption_flips_payload_bytes():
    inner = RecordingNet()
    net = ChaosNetwork(inner, ChaosConfig(corrupt_rate=1.0, seed=3))
    original = packet()
    net.send([ident(0)], original)
    assert net.corrupted == 1
    (_, delivered), = inner.delivered
    assert delivered is not original  # corrupts a copy
    assert delivered.multisig != original.multisig
    assert len(delivered.multisig) == len(original.multisig)
    assert original.multisig == b"\x00\x08\xaa" + b"\x01" * 8  # untouched


def test_chaos_duplicate_and_counters():
    inner = RecordingNet()
    net = ChaosNetwork(inner, ChaosConfig(duplicate_rate=1.0, seed=1))
    net.send([ident(0)], packet())
    assert net.duplicated == 1
    assert len(inner.delivered) == 2
    vals = net.values()
    assert vals["chaosDuplicated"] == 1.0
    assert vals["innerSent"] == 2.0  # inner counters merged


def test_chaos_reorder_releases_after_next_send():
    async def go():
        inner = RecordingNet()
        net = ChaosNetwork(inner, ChaosConfig(reorder_rate=0.5, seed=0))
        first, second = packet(1, b"\x00\x08\xaa" + b"A" * 8), packet(
            2, b"\x00\x08\xaa" + b"B" * 8
        )
        for _ in range(32):  # enough traffic to trigger holds at rate 0.5
            net.send([ident(0)], first)
            net.send([ident(0)], second)
        # whatever the seeded pattern chose, every packet must eventually
        # arrive (flush timer covers a held packet with no successor)
        await asyncio.sleep(0.1)
        assert len(inner.delivered) == 64  # nothing lost to reordering
        assert net.reordered > 0

    asyncio.run(go())


def test_chaos_delay_defers_delivery():
    async def go():
        inner = RecordingNet()
        net = ChaosNetwork(
            inner, ChaosConfig(delay_rate=1.0, delay_ms=20.0, seed=2)
        )
        net.send([ident(0)], packet())
        assert inner.delivered == []  # not yet
        await asyncio.sleep(0.08)
        assert len(inner.delivered) == 1
        assert net.delayed == 1

    asyncio.run(go())


# -- the acceptance integration round ---------------------------------------


def _adversarial_round(scheme=None, n=16, threshold=9, timeout=30.0):
    from handel_tpu.core.test_harness import LocalCluster

    async def go():
        cluster = LocalCluster(
            n,
            scheme=scheme,
            threshold=threshold,
            adversaries={n - 1: "invalid_signer", n - 2: "invalid_signer"},
            chaos=ChaosConfig(drop_rate=0.10, seed=42),
        )
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=timeout)
        finally:
            cluster.stop()
        return cluster, res

    return asyncio.run(go())


def test_adversarial_round_fake_16_nodes():
    """16 honest-majority nodes + 2 invalid signers + 10% seeded loss reach
    threshold; adversary contributions never enter a final signature."""
    cluster, res = _adversarial_round()
    assert len(res) == 14
    for sig in res.values():
        assert sig.cardinality() >= 9
        assert not sig.bitset.get(15) and not sig.bitset.get(14)
    # at least one honest node caught and attributed a bad signature
    fails = sum(h.proc.sig_verify_failed for h in cluster.handels.values())
    reports = sum(
        h.scorer.reports for h in cluster.handels.values() if h.scorer
    )
    assert fails > 0 and reports > 0


def test_adversarial_round_bn254_real_crypto():
    """Same adversarial round over real BN254 host crypto (smaller committee
    to stay in the fast tier): forged signatures fail real pairing checks."""
    from handel_tpu.models.bn254 import BN254Scheme

    cluster, res = _adversarial_round(
        scheme=BN254Scheme(), n=8, threshold=5, timeout=60.0
    )
    assert len(res) == 6
    for sig in res.values():
        assert sig.cardinality() >= 5
        assert not sig.bitset.get(7) and not sig.bitset.get(6)
    fails = sum(h.proc.sig_verify_failed for h in cluster.handels.values())
    assert fails > 0


def test_device_failover_midrun():
    """A verifier device that dies mid-run trips the circuit breaker and
    fails over to the host reference verifier; the round still completes
    and the breaker/failover counters prove the path was taken."""
    from handel_tpu.core.config import Config
    from handel_tpu.core.test_harness import FakeScheme, LocalCluster
    from handel_tpu.parallel.batch_verifier import BatchVerifierService

    scheme = FakeScheme()
    pubs = {}

    class DyingDevice:
        """BN254Device-shaped stub: verifies host-side for `good` launches,
        then raises like a lost accelerator on every later dispatch."""

        batch_size = 8

        def __init__(self, good):
            self.good = good
            self.launches = 0

        def dispatch(self, msg, reqs):
            if self.launches >= self.good:
                raise RuntimeError("device lost: simulated XLA failure")
            self.launches += 1
            return scheme.constructor.batch_verify(msg, pubs["k"], reqs)

        def fetch(self, handle):
            return handle

    def host_fallback(msg, reqs):
        return scheme.constructor.batch_verify(msg, pubs["k"], reqs)

    async def go():
        service = BatchVerifierService(
            DyingDevice(good=2),
            fallback=host_fallback,
            backoff_base_s=0.005,
            backoff_cap_s=0.02,
        )

        def cfg_factory(i):
            c = Config()
            c.rand = random.Random(5 + i)
            c.verifier = service.verify
            return c

        cluster = LocalCluster(
            16, threshold=9, scheme=scheme, config_factory=cfg_factory
        )
        pubs["k"] = cluster.registry.public_keys()
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=30.0)
        finally:
            cluster.stop()
            service.stop()
        return service, res

    service, res = asyncio.run(go())
    assert len(res) == 16
    vals = service.values()
    assert vals["breakerOpenCt"] > 0
    assert vals["failoverBatches"] > 0 and vals["failoverCandidates"] > 0
    assert vals["verifierLaunches"] > 0  # the device did work before dying


def test_failover_without_fallback_still_fails_futures():
    """No fallback configured: a dead device fails the verify futures (the
    pre-breaker contract BatchProcessing's requeue depends on)."""
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.models.fake import FakeSignature

    class DeadDevice:
        batch_size = 4

        def dispatch(self, msg, reqs):
            raise RuntimeError("dead")

        def fetch(self, handle):
            raise AssertionError("unreachable")

    async def go():
        service = BatchVerifierService(
            DeadDevice(), backoff_base_s=0.001, backoff_cap_s=0.002
        )
        bs = BitSet(4)
        bs.set(1)
        with pytest.raises(RuntimeError):
            await service.verify(b"m", [], [(bs, FakeSignature())])
        service.stop()
        assert service.values()["breakerState"] in (0.5, 1.0)

    asyncio.run(go())


def test_constructor_level_host_failover():
    """The per-node default-verifier path (no shared service): a device that
    cannot even prepare — e.g. XLA compile failure — makes
    BN254JaxConstructor.batch_verify fall back to the inherited host-side
    serial verify with correct verdicts, and the breaker opens."""
    from handel_tpu.models.bn254 import BN254Scheme
    from handel_tpu.models.bn254_jax import BN254JaxConstructor

    class BrokenDeviceConstructor(BN254JaxConstructor):
        def _device_of(self, pubkeys):
            raise RuntimeError("XLA compile failed: simulated")

    host = BN254Scheme()
    keys = [host.keygen(i) for i in range(4)]
    pubkeys = [pk for _, pk in keys]
    cons = BrokenDeviceConstructor(batch_size=4, warmup=False)

    from handel_tpu.core.bitset import BitSet

    bs = BitSet(4)
    bs.set(0)
    bs.set(2)
    agg = keys[0][0].sign(b"m").combine(keys[2][0].sign(b"m"))
    forged = keys[1][0].sign(b"other")
    for _ in range(3):  # three batches: breaker threshold reached
        verdicts = cons.batch_verify(b"m", pubkeys, [(bs, agg), (bs, forged)])
        assert verdicts == [True, False]  # host fallback verdicts are real
    assert cons.failover_batches == 3
    assert cons.breaker.state in ("open", "half-open")
    # request bugs are NOT device failures: they propagate, untouched
    with pytest.raises(ValueError):
        BN254JaxConstructor(batch_size=4, warmup=False).batch_verify(
            b"m", pubkeys, [(BitSet(9), agg)]
        )


def test_breaker_recloses_after_probe_success():
    from handel_tpu.parallel.batch_verifier import CircuitBreaker

    t = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_s=5.0, clock=lambda: t[0])
    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.allow()  # one failure: still closed
    br.record_failure()
    assert br.state == "open" and not br.allow()
    t[0] = 6.0
    assert br.state == "half-open" and br.allow()  # cooldown elapsed: probe
    br.record_failure()  # probe failed: re-open, no new open_count
    assert br.state == "open" and br.open_count == 1
    t[0] = 12.0
    br.record_success()  # probe succeeded: fully closed
    assert br.state == "closed" and br.allow()


# -- sim plumbing ------------------------------------------------------------


def test_chaos_and_adversaries_toml_roundtrip(tmp_path):
    from handel_tpu.sim.config import (
        AdversaryParams,
        RunConfig,
        SimConfig,
        dump_config,
        load_config,
    )

    cfg = SimConfig(
        scheme="fake",
        chaos=ChaosConfig(drop_rate=0.1, corrupt_rate=0.05, seed=9),
        runs=[
            RunConfig(
                nodes=16,
                threshold=9,
                adversaries=AdversaryParams(
                    invalid_signer=2, flooder=1, flood_pps=50.0
                ),
            )
        ],
    )
    path = tmp_path / "sim.toml"
    path.write_text(dump_config(cfg))
    back = load_config(str(path))
    assert back.chaos == cfg.chaos
    assert back.runs[0].adversaries == cfg.runs[0].adversaries
    assert back.runs[0].adversaries.total() == 3


def test_localhost_platform_adversarial_chaos_run(tmp_path):
    """run_node_process builds the adversaries and wraps transports in
    ChaosNetwork from the TOML matrix: real processes, UDP, seeded loss,
    one invalid signer — the run completes and the chaos/byzantine counters
    ride the monitor CSV."""
    from handel_tpu.sim.config import AdversaryParams, RunConfig, SimConfig
    from handel_tpu.sim.platform import run_simulation

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        max_timeout_s=120.0,  # generous: CI cores are shared and slow
        chaos=ChaosConfig(drop_rate=0.05, seed=11),
        runs=[
            RunConfig(
                nodes=8,
                threshold=5,
                processes=2,
                adversaries=AdversaryParams(invalid_signer=1),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    res = results[0]
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok
    rows = list(csv.DictReader(open(res.csv_path)))
    assert float(rows[0]["adversaries"]) == 1.0
    assert float(rows[0]["net_chaosDropped_sum"]) > 0
    # somebody verified (and rejected) the forged contribution
    assert float(rows[0]["sigs_sigVerifyFailed_sum"]) > 0


@pytest.mark.slow
def test_real_bn254_device_failover_midrun():
    """The literal acceptance wiring: a REAL BN254Device (JAX kernels on
    CPU) whose dispatch is severed mid-run — the shared BatchVerifierService
    trips its breaker and completes the round through the host reference
    verifier."""
    from handel_tpu.core.config import Config
    from handel_tpu.core.crypto import Constructor, verify_multisignature
    from handel_tpu.core.test_harness import LocalCluster
    from handel_tpu.models.bn254_jax import BN254JaxScheme
    from handel_tpu.parallel.batch_verifier import BatchVerifierService

    scheme = BN254JaxScheme(batch_size=4)
    msg = b"hello world"

    async def go():
        # keygen is seeded per index, so these ARE the cluster's keys
        pubkeys = [scheme.keygen(i)[1] for i in range(8)]
        device = scheme.constructor.prepare(pubkeys)

        real_dispatch = device.dispatch
        seen = {"n": 0}

        def dying_dispatch(m, reqs):
            seen["n"] += 1
            if seen["n"] > 2:  # two good launches, then the device is gone
                raise RuntimeError("device lost: simulated mid-run failure")
            return real_dispatch(m, reqs)

        device.dispatch = dying_dispatch

        def host_fallback(m, reqs):
            return Constructor.batch_verify(scheme.constructor, m, pubkeys, reqs)

        service = BatchVerifierService(
            device,
            fallback=host_fallback,
            backoff_base_s=0.005,
            backoff_cap_s=0.02,
        )

        def cfg_factory(i):
            c = Config()
            c.rand = random.Random(31 + i)
            c.verifier = service.verify
            return c

        cluster = LocalCluster(
            8, scheme=scheme, msg=msg, config_factory=cfg_factory
        )
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=900.0)
        finally:
            cluster.stop()
            service.stop()
        return cluster, service, res

    cluster, service, results = asyncio.run(go())
    assert len(results) == 8
    for sig in results.values():
        assert verify_multisignature(
            msg, sig, cluster.registry, scheme.constructor
        )
    vals = service.values()
    assert vals["breakerOpenCt"] > 0
    assert vals["failoverCandidates"] > 0


@pytest.mark.slow
def test_adversarial_sweep_64_nodes(tmp_path):
    """The long adversarial sweep: 64 nodes, mixed roles (4 invalid signers,
    2 stale replayers, 1 flooder), loss + corruption + duplication — the
    protocol still reaches a 51% threshold on every honest node."""
    from handel_tpu.sim.config import (
        AdversaryParams,
        HandelParams,
        RunConfig,
        SimConfig,
    )
    from handel_tpu.sim.platform import run_simulation

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        max_timeout_s=300.0,
        chaos=ChaosConfig(
            drop_rate=0.10,
            corrupt_rate=0.05,
            duplicate_rate=0.05,
            seed=1234,
        ),
        runs=[
            RunConfig(
                nodes=64,
                threshold=33,
                processes=4,
                adversaries=AdversaryParams(
                    invalid_signer=4,
                    stale_replayer=2,
                    flooder=1,
                    flood_pps=100.0,
                ),
                handel=HandelParams(period_ms=50.0, timeout_ms=100.0),
            )
        ],
    )
    results = asyncio.run(run_simulation(cfg, str(tmp_path)))
    res = results[0]
    assert res.ok, [e.decode(errors="replace")[-2000:] for _, e in res.outputs]
    rows = list(csv.DictReader(open(res.csv_path)))
    assert float(rows[0]["adversaries"]) == 7.0
    assert float(rows[0]["net_chaosCorrupted_sum"]) > 0
    assert float(rows[0]["sigs_peerPenaltyReports_sum"]) > 0

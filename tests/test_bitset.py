"""BitSet semantics + wire format (reference: bitset_test.go)."""

import pytest

from handel_tpu.core.bitset import BitSet


def test_set_get_cardinality():
    bs = BitSet(70)
    assert bs.cardinality() == 0 and bs.none()
    bs.set(0)
    bs.set(69)
    bs.set(64)
    assert bs.cardinality() == 3
    assert bs.get(0) and bs.get(69) and bs.get(64)
    assert not bs.get(1)
    bs.set(64, False)
    assert bs.cardinality() == 2
    with pytest.raises(IndexError):
        bs.get(70)
    with pytest.raises(IndexError):
        bs.set(70)


def test_algebra():
    a, b = BitSet(10), BitSet(10)
    a.set(1), a.set(3)
    b.set(3), b.set(5)
    assert a.or_(b).indices() == [1, 3, 5]
    assert a.and_(b).indices() == [3]
    assert a.xor(b).indices() == [1, 5]
    assert a.or_(b).is_superset(a)
    assert not a.is_superset(b)
    assert a.intersection_cardinality(b) == 1
    with pytest.raises(ValueError):
        a.or_(BitSet(11))


def test_next_set_indices():
    bs = BitSet(130)
    for i in (0, 64, 129):
        bs.set(i)
    assert bs.next_set(0) == 0
    assert bs.next_set(1) == 64
    assert bs.next_set(65) == 129
    assert bs.next_set(129) == 129
    assert bs.indices() == [0, 64, 129]
    empty = BitSet(16)
    assert empty.next_set(0) is None


def test_wire_roundtrip():
    for n in (1, 7, 8, 9, 64, 65, 100):
        bs = BitSet(n)
        for i in range(0, n, 3):
            bs.set(i)
        data = bs.marshal()
        out, used = BitSet.unmarshal(data)
        assert used == len(data)
        assert out == bs


def test_unmarshal_clamps_overflow_bits():
    # a malicious peer setting padding bits beyond the declared length must
    # not corrupt cardinality (bitset.go unmarshal semantics)
    bs = BitSet(4)
    bs.set(0)
    data = bytearray(bs.marshal())
    data[-1] |= 0xF0  # set bits 4..7, beyond the 4-bit length
    out, _ = BitSet.unmarshal(bytes(data))
    assert out.cardinality() == 1


def test_mask_bool():
    bs = BitSet(5)
    bs.set(2)
    mask = bs.mask_bool(8)
    assert mask.tolist() == [False, False, True, False, False, False, False, False]

"""JAX pairing kernels vs the scalar oracle.

Validates the batched Miller loop + final exponentiation (ops/pairing.py)
bit-exactly against ops/bn254_ref.py (VERDICT r1 item 1: >= random vectors
matching `bn254_ref.pairing`, bilinearity, masked lanes, product check), all
on CPU (tests/conftest.py forces the CPU platform).

Shapes are kept identical across tests (B=4 lanes) so each graph compiles
once into the persistent cache; first run is compile-heavy, reruns are fast.
"""

import random

import jax
import pytest

# slow tier: XLA-compile-bound (pairing graphs, minutes each cold) — runs in
# test-slow/test-all (nightly/CI); the fast tier keeps the oracle +
# protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BN254Curves
from handel_tpu.ops.pairing import BN254Pairing

B = 4  # lane count shared by every test


@pytest.fixture(scope="module", params=["cios", "rns"])
def stack(request):
    """Both Field backends through the SAME oracle assertions. The rns
    param auto-enables the residue-resident pairing (ops/pairing.py):
    the Miller loop and final exponentiation stay residue planes, with
    CRT reconstruction only at the line boundaries — so these tests gate
    the resident form bit-exactly against the scalar oracle."""
    curves = BN254Curves(backend=request.param)
    return curves, BN254Pairing(curves)


def _pack_pairs(curves, g1s, g2s):
    xp = curves.F.pack([p[0] for p in g1s])
    yp = curves.F.pack([p[1] for p in g1s])
    xq = curves.T.f2_pack([q[0] for q in g2s])
    yq = curves.T.f2_pack([q[1] for q in g2s])
    return (xp, yp), (xq, yq)


def _rand_points(seed):
    rng = random.Random(seed)
    ks = [rng.randrange(1, bn.R) for _ in range(B)]
    ls = [rng.randrange(1, bn.R) for _ in range(B)]
    g1s = [bn.g1_mul(bn.G1_GEN, k) for k in ks]
    g2s = [bn.g2_mul(bn.G2_GEN, l) for l in ls]
    return ks, ls, g1s, g2s


def test_miller_loop_matches_oracle(stack):
    curves, pr = stack
    _, _, g1s, g2s = _rand_points(1)
    p, q = _pack_pairs(curves, g1s, g2s)
    f = jax.jit(lambda p, q: pr.miller_loop(p, q))(p, q)
    got = curves.T.f12_unpack(f)
    exp = [bn.miller_loop_projective(q_, p_) for p_, q_ in zip(g1s, g2s)]
    assert got == exp


def test_pairing_matches_oracle_and_bilinear(stack):
    curves, pr = stack
    ks, ls, g1s, g2s = _rand_points(1)
    p, q = _pack_pairs(curves, g1s, g2s)
    jit_pairing = jax.jit(lambda p, q: pr.pairing(p, q))
    f = jit_pairing(p, q)
    got = curves.T.f12_unpack(f)
    exp = [bn.pairing(q_, p_) for p_, q_ in zip(g1s, g2s)]
    assert got == exp
    # bilinearity through the oracle: e([k]G1, [l]G2) == e(G1, G2)^(k*l)
    base = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    for k, l, val in zip(ks, ls, got):
        assert val == bn.f12_pow(base, k * l % bn.R)


def test_masked_lanes_give_identity(stack):
    import jax.numpy as jnp

    curves, pr = stack
    _, _, g1s, g2s = _rand_points(2)
    p, q = _pack_pairs(curves, g1s, g2s)
    mask = jnp.asarray([True, False, True, False])
    f = jax.jit(lambda p, q, m: pr.miller_loop(p, q, m))(p, q, mask)
    got = curves.T.f12_unpack(f)
    assert got[1] == bn.F12_ONE and got[3] == bn.F12_ONE
    assert got[0] == bn.miller_loop_projective(g2s[0], g1s[0])


def test_pairing_check_bls_verify(stack):
    """The batched product check accepts valid BLS pairs and rejects a
    corrupted signature — the shape used by batch_verify
    (bn256/go/bn256.go:82-94 as one product check)."""
    import jax.numpy as jnp

    curves, pr = stack
    rng = random.Random(7)
    msg_scalar = rng.randrange(1, bn.R)
    h = bn.g1_mul(bn.G1_GEN, msg_scalar)  # H(m)
    sks = [rng.randrange(1, bn.R) for _ in range(2)]
    pks = [bn.g2_mul(bn.G2_GEN, sk) for sk in sks]
    sigs = [bn.g1_mul(h, sk) for sk in sks]
    bad_sig = bn.g1_mul(h, sks[1] + 1)  # candidate 1 corrupted

    # 2 candidates x 2 pairs, chunk-major: [h, h, -s0, -bad]
    g1s = [h, h, bn.g1_neg(sigs[0]), bn.g1_neg(bad_sig)]
    g2s = [pks[0], pks[1], bn.G2_GEN, bn.G2_GEN]
    p, q = _pack_pairs(curves, g1s, g2s)
    mask = jnp.ones((B,), bool)
    ok = jax.jit(lambda p, q, m: pr.pairing_check(p, q, m, 2))(p, q, mask)
    assert list(map(bool, ok)) == [True, False]

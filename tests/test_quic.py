"""Secure session transport tests.

Reference model: network/quic/net_test.go (two endpoints exchange a packet
over TLS sessions) and sessionmanager_test.go:29-92 (concurrent dials to one
peer share a single session).
"""

import asyncio
import importlib.util

import pytest

# the TLS handshake paths mint a self-signed certificate through the
# `cryptography` package; on images without it (this container ships none,
# and the image is sealed — no pip install) those tests are gated, not
# failed. The session-manager semantics below run regardless.
needs_cryptography = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="cryptography package not installed (sealed image)",
)

from handel_tpu.core.identity import Identity
from handel_tpu.core.net import Packet
from handel_tpu.network.quic import (
    QUICNetwork,
    SessionManager,
    new_insecure_test_config,
)
from tests.test_network import ChanListener, _free_ports, _mk_packet


@needs_cryptography
def test_two_node_exchange_tls():
    async def go():
        p1, p2 = _free_ports(2)
        a = QUICNetwork(f"127.0.0.1:{p1}")
        b = QUICNetwork(f"127.0.0.1:{p2}")
        la, lb = ChanListener(), ChanListener()
        a.register_listener(la)
        b.register_listener(lb)
        await a.start()
        await b.start()
        try:
            a.send([Identity(1, f"127.0.0.1:{p2}", None)], _mk_packet(7))
            got = await asyncio.wait_for(lb.packets.get(), 5)
            assert got.origin == 7 and got.multisig == b"\x01\x02\x03"
            b.send([Identity(0, f"127.0.0.1:{p1}", None)], _mk_packet(9))
            got2 = await asyncio.wait_for(la.packets.get(), 5)
            assert got2.origin == 9
            # session reuse: a second send rides the cached session
            a.send([Identity(1, f"127.0.0.1:{p2}", None)], _mk_packet(8))
            got3 = await asyncio.wait_for(lb.packets.get(), 5)
            assert got3.origin == 8
            assert a.values()["sentPackets"] == 2.0
        finally:
            a.stop()
            b.stop()

    asyncio.run(go())


def test_session_manager_dedups_concurrent_dials():
    """sessionmanager_test.go:29-92: N concurrent sends to one peer must
    produce exactly one dial."""

    dials = 0

    class FakeWriter:
        def is_closing(self):
            return False

        def close(self):
            pass

    async def dialer(addr):
        nonlocal dials
        dials += 1
        await asyncio.sleep(0.05)  # keep the dial in flight
        from handel_tpu.network.quic import _Session

        return _Session(FakeWriter())

    async def go():
        mgr = SessionManager(dialer)
        sessions = await asyncio.gather(
            *(mgr.session("peer:1") for _ in range(8))
        )
        assert dials == 1
        assert all(s is sessions[0] for s in sessions)

    asyncio.run(go())


def test_session_manager_dial_failure_propagates():
    async def dialer(addr):
        raise OSError("refused")

    async def go():
        mgr = SessionManager(dialer)
        with pytest.raises(OSError):
            await mgr.session("peer:2")
        # a later attempt re-dials (failure isn't cached)
        with pytest.raises(OSError):
            await mgr.session("peer:2")

    asyncio.run(go())


@needs_cryptography
def test_insecure_config_roundtrip():
    server_ctx, client_ctx = new_insecure_test_config()
    import ssl

    assert server_ctx.protocol == ssl.PROTOCOL_TLS_SERVER
    assert client_ctx.verify_mode == ssl.CERT_NONE

"""Fast-tier RNS backend unit checks (ops/rns.py + the ops/fp.py seam).

Compile-cheap by design — the heavy property suites (full parametrized
mul/inv/pow round-trips, pairing-line boundary chains) are slow-tier in
tests/test_fp_jax.py; this file keeps tier-1 coverage of the backend seam,
the basis construction invariants, the float-assisted exact reduction, and
one small-batch bit-exactness pass so a broken RNS kernel cannot reach CI's
slow tier unnoticed. scripts/rns_smoke.py wraps the same surface for the
CI gate.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field
from handel_tpu.ops.rns import RnsField


@pytest.fixture(scope="module")
def F():
    return Field(bn.P, backend="rns")


def test_backend_seam():
    """Field(backend=...) construction contract: "rns" redirects to
    RnsField, "cios"/None stay Field, junk raises, subclass construction
    is never hijacked."""
    assert type(Field(bn.P, backend="rns")) is RnsField
    assert Field(bn.P, backend="rns").backend == "rns"
    assert type(Field(bn.P, backend="cios")) is Field
    assert type(Field(bn.P)) is Field
    assert Field(bn.P).backend == "cios"
    with pytest.raises(ValueError):
        Field(bn.P, backend="mxu")
    with pytest.raises(ValueError):
        RnsField(bn.P, backend="cios")
    # direct subclass construction still works
    assert RnsField(bn.P).backend == "rns"


def test_basis_invariants(F):
    """Every bound the kernel's int32 exactness argument rests on, asserted
    on the constructed bases (generic over p — BLS12-381 covered in the
    slow tier)."""
    import math

    assert F.M >= 4 * F.p
    assert F.MB > 2 * (F.kA + 1) * F.p
    assert F.mr > F.kB + 1
    ms = F.mA + F.mB + [F.mr]
    assert len(set(ms)) == len(ms)
    assert all(m < (1 << 13) for m in ms)
    assert math.gcd(F.M, F.MB * F.mr) == 1
    # the Montgomery constant is M, not R — pack/unpack self-consistency
    assert F.mont_r == F.M % F.p
    assert F.mont_r2 == F.mont_r * F.mont_r % F.p
    # full 16n-bit positional range reconstructs exactly (CRT range)
    assert (1 << (16 * F.nlimbs)) <= F.MB


def test_mod_rows_exact(F):
    """The float-assisted reduction is integer-exact over its whole stated
    domain edge: v near 2^30 and v near 0, across every modulus in play."""
    m_np = np.array(F.mA + F.mB + [F.mr], np.int32)
    minv = (1.0 / m_np.astype(np.float64)).astype(np.float32)
    rng = np.random.default_rng(5)
    vs = np.concatenate([
        rng.integers(0, 1 << 30, (64,)),
        (1 << 30) - 1 - np.arange(8),
        np.arange(8),
    ]).astype(np.int32)
    for i, m in enumerate(m_np):
        got = np.asarray(
            F._mod_rows(jnp.asarray(vs), jnp.int32(int(m)),
                        jnp.float32(float(minv[i])))
        )
        assert np.array_equal(got, vs % m), f"inexact mod {m}"


def test_small_batch_bit_exact(F):
    """One jitted RNS mul at batch 8: canonical boundary values bitwise
    equal to the CIOS kernel's (the backend bit-exactness contract)."""
    Fc = Field(bn.P, use_pallas=False)
    rng = np.random.default_rng(17)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(6)]
    xs += [0, bn.P - 1]
    ys = list(reversed(xs))
    got = F.unpack(jax.jit(F.mul)(F.pack(xs), F.pack(ys)))
    assert got == [x * y % bn.P for x, y in zip(xs, ys)]
    plain_r = F.pack(xs, mont=False)
    plain_c = Fc.pack(xs, mont=False)
    assert np.array_equal(np.asarray(plain_r), np.asarray(plain_c))
    out_r = F.from_mont(F.mul(F.to_mont(plain_r), F.to_mont(plain_r)))
    out_c = Fc.from_mont(Fc.mul(Fc.to_mont(plain_c), Fc.to_mont(plain_c)))
    assert np.array_equal(np.asarray(out_r), np.asarray(out_c))


def test_int8_plane_lowering_bit_identical(F):
    """The int8-planes MXU lowering of the constant contractions is
    bit-identical to the int32 single-dot lowering."""
    rng = np.random.default_rng(23)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(8)]
    a, b = F.pack(xs), F.pack(list(reversed(xs)))
    base = np.asarray(F.mul(a, b))
    flipped = F.int8_dots
    try:
        F.int8_dots = not flipped
        assert np.array_equal(np.asarray(F.mul(a, b)), base)
    finally:
        F.int8_dots = flipped


def test_config_plumbing_to_field():
    """TOML fp_backend -> SimConfig -> scheme kwargs -> Curves -> Field:
    the end-to-end selector path, without any device warmup."""
    from handel_tpu.models.registry import new_scheme
    from handel_tpu.sim.config import dump_config, load_config

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "cfg.toml")
        with open(path, "w") as f:
            f.write('scheme = "bn254-jax"\nfp_backend = "rns"\n')
        cfg = load_config(path)
        assert cfg.fp_backend == "rns"
        assert 'fp_backend = "rns"' in dump_config(cfg)
        bad = os.path.join(d, "bad.toml")
        with open(bad, "w") as f:
            f.write('fp_backend = "vpu"\n')
        with pytest.raises(ValueError):
            load_config(bad)
    sch = new_scheme(
        "bn254-jax", batch_size=4, mesh_devices=1, fp_backend="rns",
        warmup=False,
    )
    assert sch.constructor.curves.F.backend == "rns"
    assert type(sch.constructor.curves.F) is RnsField
    # default stays the CIOS oracle
    sch_c = new_scheme("bn254-jax", batch_size=4, warmup=False)
    assert sch_c.constructor.curves.F.backend == "cios"

"""Fast-tier RNS backend unit checks (ops/rns.py + the ops/fp.py seam).

Compile-cheap by design — the heavy property suites (full parametrized
mul/inv/pow round-trips, pairing-line boundary chains) are slow-tier in
tests/test_fp_jax.py; this file keeps tier-1 coverage of the backend seam,
the basis construction invariants, the float-assisted exact reduction, and
one small-batch bit-exactness pass so a broken RNS kernel cannot reach CI's
slow tier unnoticed. scripts/rns_smoke.py wraps the same surface for the
CI gate.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field
from handel_tpu.ops.rns import RnsField


@pytest.fixture(scope="module")
def F():
    return Field(bn.P, backend="rns")


def test_backend_seam():
    """Field(backend=...) construction contract: "rns" redirects to
    RnsField, "cios"/None stay Field, junk raises, subclass construction
    is never hijacked."""
    assert type(Field(bn.P, backend="rns")) is RnsField
    assert Field(bn.P, backend="rns").backend == "rns"
    assert type(Field(bn.P, backend="cios")) is Field
    assert type(Field(bn.P)) is Field
    assert Field(bn.P).backend == "cios"
    with pytest.raises(ValueError):
        Field(bn.P, backend="mxu")
    with pytest.raises(ValueError):
        RnsField(bn.P, backend="cios")
    # direct subclass construction still works
    assert RnsField(bn.P).backend == "rns"


def test_basis_invariants(F):
    """Every bound the kernel's int32 exactness argument rests on, asserted
    on the constructed bases (generic over p — BLS12-381 covered in the
    slow tier)."""
    import math

    assert F.M >= 4 * F.p
    assert F.MB > 2 * (F.kA + 1) * F.p
    assert F.mr > F.kB + 1
    ms = F.mA + F.mB + [F.mr]
    assert len(set(ms)) == len(ms)
    assert all(m < (1 << 13) for m in ms)
    assert math.gcd(F.M, F.MB * F.mr) == 1
    # the Montgomery constant is M, not R — pack/unpack self-consistency
    assert F.mont_r == F.M % F.p
    assert F.mont_r2 == F.mont_r * F.mont_r % F.p
    # full 16n-bit positional range reconstructs exactly (CRT range)
    assert (1 << (16 * F.nlimbs)) <= F.MB


def test_mod_rows_exact(F):
    """The float-assisted reduction is integer-exact over its whole stated
    domain edge: v near 2^30 and v near 0, across every modulus in play."""
    m_np = np.array(F.mA + F.mB + [F.mr], np.int32)
    minv = (1.0 / m_np.astype(np.float64)).astype(np.float32)
    rng = np.random.default_rng(5)
    vs = np.concatenate([
        rng.integers(0, 1 << 30, (64,)),
        (1 << 30) - 1 - np.arange(8),
        np.arange(8),
    ]).astype(np.int32)
    for i, m in enumerate(m_np):
        got = np.asarray(
            F._mod_rows(jnp.asarray(vs), jnp.int32(int(m)),
                        jnp.float32(float(minv[i])))
        )
        assert np.array_equal(got, vs % m), f"inexact mod {m}"


def test_small_batch_bit_exact(F):
    """One jitted RNS mul at batch 8: canonical boundary values bitwise
    equal to the CIOS kernel's (the backend bit-exactness contract)."""
    Fc = Field(bn.P, use_pallas=False)
    rng = np.random.default_rng(17)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(6)]
    xs += [0, bn.P - 1]
    ys = list(reversed(xs))
    got = F.unpack(jax.jit(F.mul)(F.pack(xs), F.pack(ys)))
    assert got == [x * y % bn.P for x, y in zip(xs, ys)]
    plain_r = F.pack(xs, mont=False)
    plain_c = Fc.pack(xs, mont=False)
    assert np.array_equal(np.asarray(plain_r), np.asarray(plain_c))
    out_r = F.from_mont(F.mul(F.to_mont(plain_r), F.to_mont(plain_r)))
    out_c = Fc.from_mont(Fc.mul(Fc.to_mont(plain_c), Fc.to_mont(plain_c)))
    assert np.array_equal(np.asarray(out_r), np.asarray(out_c))


def test_int8_plane_lowering_bit_identical(F):
    """The int8-planes MXU lowering of the constant contractions is
    bit-identical to the int32 single-dot lowering."""
    rng = np.random.default_rng(23)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(8)]
    a, b = F.pack(xs), F.pack(list(reversed(xs)))
    base = np.asarray(F.mul(a, b))
    flipped = F.int8_dots
    try:
        F.int8_dots = not flipped
        assert np.array_equal(np.asarray(F.mul(a, b)), base)
    finally:
        F.int8_dots = flipped


def test_config_plumbing_to_field():
    """TOML fp_backend -> SimConfig -> scheme kwargs -> Curves -> Field:
    the end-to-end selector path, without any device warmup."""
    from handel_tpu.models.registry import new_scheme
    from handel_tpu.sim.config import dump_config, load_config

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "cfg.toml")
        with open(path, "w") as f:
            f.write('scheme = "bn254-jax"\nfp_backend = "rns"\n')
        cfg = load_config(path)
        assert cfg.fp_backend == "rns"
        assert 'fp_backend = "rns"' in dump_config(cfg)
        bad = os.path.join(d, "bad.toml")
        with open(bad, "w") as f:
            f.write('fp_backend = "vpu"\n')
        with pytest.raises(ValueError):
            load_config(bad)
    sch = new_scheme(
        "bn254-jax", batch_size=4, mesh_devices=1, fp_backend="rns",
        warmup=False,
    )
    assert sch.constructor.curves.F.backend == "rns"
    assert type(sch.constructor.curves.F) is RnsField
    # default stays the CIOS oracle
    sch_c = new_scheme("bn254-jax", batch_size=4, warmup=False)
    assert sch_c.constructor.curves.F.backend == "cios"


# -- residue-resident value form (residue-resident pairing) -------------------


def test_resident_closure_invariants(F):
    """Construction-time bounds the resident exactness argument rests on:
    base A holds 2^RES_MUL_LOG2 * p of head-room (so fused tower chains
    never overflow the Montgomery-quotient tolerance), the quotient row
    count keeps the int32 discipline, and the broadcast constants
    (Montgomery one, subtract offsets) are the right residues."""
    assert F.M >= (1 << F.RES_MUL_LOG2) * F.p
    assert F.kA + 1 <= 64
    m_all = [int(m) for m in F._m_all]
    assert list(F._one_res) == [(F.M % F.p) % m for m in m_all]
    assert F._off_res.shape == (F.RES_MAX_BLOG + 1, F.k_all)
    for s in (0, 7, F.RES_MAX_BLOG):
        assert list(F._off_res[s]) == [(F.p << s) % m for m in m_all]


def test_resident_ops_bit_exact(F):
    """Seeded chain through every resident primitive — mul, add, sub (with
    offset), refresh — against python ints, reconstructed ONCE at the end;
    plus the from_resident boundary bit-identical to canonical limbs."""
    A = F.resident()
    rng = np.random.default_rng(16)
    xs = [int.from_bytes(rng.bytes(32), "little") % bn.P for _ in range(6)]
    xs += [0, bn.P - 1]
    ys = list(reversed(xs))
    a, b = A.pack(xs), A.pack(ys)
    # c = x*y (bound 6); d = c + x (7); e = d - y + off (8); f = e * c (6)
    c = A.mul(a, b)
    d = A.add(c, a)
    e = A.sub(d, b, 7)
    f = A.mul(e, A.refresh(c))
    got = A.unpack(f)
    want = [
        (x * y % bn.P + x - y) * (x * y % bn.P) % bn.P
        for x, y in zip(xs, ys)
    ]
    assert got == want
    # boundary limbs bit-identical to a straight canonical pack
    limbs = F.from_resident(f)
    assert np.array_equal(np.asarray(limbs), np.asarray(F.pack(got)))


def test_resident_adapter_contracts(F):
    """The contracts the tower relies on: sub/neg demand a static blog
    literal inside the offset table; eq/is_zero are refused (positional
    boundaries by definition); constant() embeds without counting a
    conversion; select keeps the int32 residue dtype."""
    A = F.resident()
    a, b = A.pack([3, 5]), A.pack([1, 2])
    with pytest.raises(ValueError):
        F.sub_resident(a, b, None)
    with pytest.raises(ValueError):
        F.sub_resident(a, b, F.RES_MAX_BLOG + 1)
    with pytest.raises(RuntimeError):
        A.eq(a, b)
    with pytest.raises(RuntimeError):
        A.is_zero(a)
    before = F.conversion_counts()["total"]
    one = A.constant(1, 2)
    assert F.conversion_counts()["total"] == before
    assert one.dtype == jnp.int32 and one.shape == (F.k_all, 2)
    assert A.unpack(A.mul(a, one)) == [3, 5]  # Montgomery identity
    sel = A.select(jnp.asarray([True, False]), a, b)
    assert sel.dtype == jnp.int32 and A.unpack(sel) == [3, 2]


def test_resident_conversion_counters(F):
    """to/from_resident count one boundary crossing each at trace time;
    the legacy positional mul models its inherent round trip as one of
    each per call."""
    A = F.resident()
    F.reset_conversion_counts()
    a = A.pack([7, 11])
    assert F.conversion_counts() == {
        "to_resident": 1, "from_resident": 0, "total": 1,
    }
    A.mul(a, a)  # resident ops never convert
    assert F.conversion_counts()["total"] == 1
    A.unpack(a)
    assert F.conversion_counts() == {
        "to_resident": 1, "from_resident": 1, "total": 2,
    }
    F.reset_conversion_counts()
    x = F.pack([7, 11])
    F.mul(x, x)
    assert F.conversion_counts() == {
        "to_resident": 1, "from_resident": 1, "total": 2,
    }
    F.reset_conversion_counts()


def test_resident_pairing_knob():
    """BN254Pairing residency: auto-on for an rns Field, off for cios, and
    an explicit resident=True on a positional backend is refused with the
    fix named."""
    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.ops.pairing import BN254Pairing

    assert BN254Pairing(BN254Curves(backend="rns")).resident
    assert not BN254Pairing(BN254Curves(backend="cios")).resident
    with pytest.raises(ValueError, match="rns"):
        BN254Pairing(BN254Curves(backend="cios"), resident=True)


def test_resident_config_knob_roundtrip():
    """TOML `rns_resident` -> SimConfig -> dump_config round trip, with
    the default on; the fp_backend validation error names the choices."""
    from handel_tpu.sim.config import dump_config, load_config

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "cfg.toml")
        with open(path, "w") as f:
            f.write('fp_backend = "rns"\nrns_resident = false\n')
        cfg = load_config(path)
        assert cfg.rns_resident is False
        assert "rns_resident = false" in dump_config(cfg)
        with open(path, "w") as f:
            f.write('fp_backend = "rns"\n')
        cfg = load_config(path)
        assert cfg.rns_resident is True
        assert "rns_resident = true" in dump_config(cfg)
        bad = os.path.join(d, "bad.toml")
        with open(bad, "w") as f:
            f.write('fp_backend = "vpu"\n')
        with pytest.raises(ValueError, match="cios.*rns"):
            load_config(bad)


def test_resident_sharding_rule():
    """Resident residue planes are batch-last like positional limb banks:
    a `res_`-named operand shards its trailing axis with the registry."""
    from handel_tpu.parallel.sharding import (
        P,
        launch_partition_rules,
        match_partition_rules,
    )

    specs = match_partition_rules(
        launch_partition_rules("dp"),
        ["reg_x0", "res_f12_c0", "resident_acc", "mask", "sig_x"],
    )
    assert specs["res_f12_c0"] == P(None, "dp")
    assert specs["resident_acc"] == P(None, "dp")
    assert specs["reg_x0"] == P(None, "dp")
    assert specs["mask"] == P("dp", None)
    assert specs["sig_x"] == P()

"""JAX tower field ops vs the scalar oracle (bn254_ref)."""

import random

import jax
import pytest

# slow tier: XLA-compile-bound (tower arithmetic graphs) — runs in
# test-slow/test-all (nightly/CI); the fast tier keeps the oracle +
# protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field
from handel_tpu.ops.tower import Tower

rng = random.Random(7)
B = 4


@pytest.fixture(scope="module", params=["cios", "rns"])
def T(request):
    """Both Field backends through the SAME oracle assertions. The rns
    param runs the RESIDENT tower (ops/rns.py ResidentRns adapter +
    Tower.as_resident): values stay residue planes across every tower op
    and reconstruct through the CRT only at the unpack boundary — the
    form the pairing rides (residue-resident pairing)."""
    if request.param == "rns":
        return Tower(Field(bn.P, backend="rns")).as_resident()
    return Tower(Field(bn.P, use_pallas=False))


def rand_f2s(k=B):
    return [(rng.randrange(bn.P), rng.randrange(bn.P)) for _ in range(k)]


def rand_f12s(k=B):
    return [
        (
            (rand_f2s(1)[0], rand_f2s(1)[0], rand_f2s(1)[0]),
            (rand_f2s(1)[0], rand_f2s(1)[0], rand_f2s(1)[0]),
        )
        for _ in range(k)
    ]


def test_f2_mul_sqr_inv(T):
    xs, ys = rand_f2s(), rand_f2s()
    ax, ay = T.f2_pack(xs), T.f2_pack(ys)
    assert T.f2_unpack(jax.jit(T.f2_mul)(ax, ay)) == [
        bn.f2_mul(x, y) for x, y in zip(xs, ys)
    ]
    assert T.f2_unpack(jax.jit(T.f2_sqr)(ax)) == [bn.f2_sqr(x) for x in xs]
    assert T.f2_unpack(jax.jit(T.f2_inv)(ax)) == [bn.f2_inv(x) for x in xs]
    # blog=0: freshly packed operands are canonical (< p); the resident
    # backend demands the literal, positional backends ignore it
    assert T.f2_unpack(jax.jit(lambda a: T.f2_mul_xi(a, 0))(ax)) == [
        bn.f2_mul_xi(x) for x in xs
    ]


def test_f2_mul_fp(T):
    xs = rand_f2s()
    ss = [rng.randrange(bn.P) for _ in range(B)]
    out = jax.jit(T.f2_mul_fp)(T.f2_pack(xs), T.F.pack(ss))
    assert T.f2_unpack(out) == [bn.f2_scalar(x, s) for x, s in zip(xs, ss)]


def test_f12_mul_matches_oracle(T):
    xs, ys = rand_f12s(), rand_f12s()
    ax, ay = T.f12_pack(xs), T.f12_pack(ys)
    got = T.f12_unpack(jax.jit(T.f12_mul)(ax, ay))
    want = [bn.f12_mul(x, y) for x, y in zip(xs, ys)]
    assert got == want


def test_f12_inv_conj(T):
    xs = rand_f12s(2)
    ax = T.f12_pack(xs)
    got = T.f12_unpack(jax.jit(T.f12_inv)(ax))
    assert got == [bn.f12_inv(x) for x in xs]
    assert T.f12_unpack(T.f12_conj(ax, 0)) == [bn.f12_conj(x) for x in xs]


def test_f12_frobenius(T):
    xs = rand_f12s(2)
    ax = T.f12_pack(xs)
    assert T.f12_unpack(jax.jit(T.f12_frobenius)(ax)) == [
        bn.f12_frobenius(x) for x in xs
    ]
    assert T.f12_unpack(jax.jit(T.f12_frobenius2)(ax)) == [
        bn.f12_frobenius2(x) for x in xs
    ]


def test_f12_pow_u(T):
    xs = rand_f12s(1)
    ax = T.f12_pack(xs)
    got = T.f12_unpack(jax.jit(T.f12_pow_u)(ax))
    assert got == [bn.f12_pow(x, bn.U) for x in xs]


@pytest.mark.parametrize("window", [1, 4])
def test_f12_pow_const_windowed_and_unroll(T, window):
    """Small exponents keep all lowerings compile-cheap on CPU: the digit
    scan at BOTH window widths (window=1 bit scan — the CPU default — and
    window=4 table+gather — the accelerator production path, pinned
    explicitly per ADVICE r5 #2 so CPU CI keeps oracle-checking it) and the
    static unroll (the flag offered to co-located deployments) must agree
    with the oracle — untaken branches would otherwise rot untested."""
    xs = rand_f12s(2)
    ax = T.f12_pack(xs)
    for e in (3, 16, 0x1D, 0x113):
        want = [bn.f12_pow(x, e) for x in xs]
        windowed = T.f12_unpack(
            jax.jit(lambda a, e=e: T.f12_pow_const(a, e, window=window))(ax)
        )
        assert windowed == want, f"windowed e={e:#x} w={window}"
        unrolled = T.f12_unpack(
            jax.jit(lambda a, e=e: T.f12_pow_const(a, e, unroll=True))(ax)
        )
        assert unrolled == want, f"unroll e={e:#x}"


def test_f6_mul_v_and_select(T):
    import jax.numpy as jnp

    xs = rand_f12s(2)
    ax = T.f12_pack(xs)
    mask = jnp.asarray([True, False])
    sel = T.f12_select(mask, ax, T.f12_one(2))
    got = T.f12_unpack(sel)
    assert got[0] == xs[0]
    assert got[1] == bn.F12_ONE
    if not getattr(T.F, "is_resident", False):
        # residue-plane equality is a boundary op: the resident adapter
        # refuses F.eq by contract (compare after from_resident instead)
        eq = T.f12_eq(ax, ax)
        assert eq.tolist() == [True, True]


def test_cyclotomic_square_matches_generic(T):
    """Granger-Scott cyclotomic squaring agrees with the generic f12 square
    (and the scalar oracle) on GT elements, where it is valid."""
    vals = []
    for _ in range(3):
        q = bn.g2_mul(bn.G2_GEN, rng.randrange(1, bn.R))
        p = bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R))
        vals.append(bn.pairing(q, p))
    a = T.f12_pack(vals)
    assert T.f12_unpack(T.f12_cyclo_sqr(a)) == [bn.f12_mul(v, v) for v in vals]
    assert T.f12_unpack(T.f12_pow_u(a, cyclo=True)) == [
        bn.f12_pow(v, bn.U) for v in vals
    ]

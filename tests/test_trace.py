"""Flight-recorder + trace-pipeline tests (ISSUE 4).

Covers: the bounded ring and its disabled-mode overhead budget (<1 us per
span call — the contract that lets the hooks live in the hot path
permanently), Packet.sent_ts wire transport, the end-to-end traced
LocalCluster (every contribution's recv -> queue -> verify -> merge chain
reconstructable with >= 95% wall coverage), and the trace-analysis CLI.
"""

import asyncio
import json
import os
import time

import pytest

from handel_tpu.core.net import Packet
from handel_tpu.core.test_harness import run_cluster
from handel_tpu.core.trace import FlightRecorder, LogHistogram, merge_traces
from handel_tpu.sim import trace_cli


# -- ring mechanics ----------------------------------------------------------


def test_ring_bound_and_order():
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.span(f"s{i}", float(i), float(i) + 0.5, tid=1)
    evs = rec.events()
    assert len(evs) == 8
    assert rec.dropped == 12
    # oldest events were overwritten; the survivors are the newest, in order
    assert [e[0] for e in evs] == [f"s{i}" for i in range(12, 20)]
    assert rec.values()["traceDropped"] == 12.0


def test_export_chrome_shape():
    rec = FlightRecorder(capacity=16, pid=7)
    rec.name_thread(3, "node-3")
    rec.span("verify", 1.0, 1.002, tid=3, cat="pipeline", args={"origin": 5})
    rec.instant("level_complete", ts=1.01, tid=3, args={"level": 2})
    ex = rec.export()
    assert ex["traceEvents"]
    meta = [e for e in ex["traceEvents"] if e["ph"] == "M"]
    assert meta[0]["args"]["name"] == "node-3"
    span = next(e for e in ex["traceEvents"] if e["ph"] == "X")
    assert span["pid"] == 7 and span["tid"] == 3
    assert span["ts"] == pytest.approx(1.0e6)
    assert span["dur"] == pytest.approx(2000.0, rel=1e-6)
    inst = next(e for e in ex["traceEvents"] if e["ph"] == "i")
    assert inst["args"]["level"] == 2
    json.dumps(ex)  # serializable as-is


def test_disabled_overhead_below_1us():
    """The acceptance budget: with tracing disabled, a span hook costs under
    1 us — so the per-contribution instrumentation (a handful of calls)
    stays compiled into the hot path unconditionally."""
    rec = FlightRecorder(capacity=8, enabled=False)
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        rec.span("recv", 0.0, 0.0, tid=1)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 1e-6, f"disabled span() costs {per_call * 1e9:.0f} ns"
    assert rec.events() == []  # nothing recorded


def test_merge_traces_sorts_by_ts():
    a = FlightRecorder(pid=1)
    b = FlightRecorder(pid=2)
    a.span("x", 2.0, 3.0)
    b.span("y", 1.0, 2.0)
    merged = merge_traces([a.export(), b.export()])
    names = [e["name"] for e in merged["traceEvents"] if e["ph"] == "X"]
    assert names == ["y", "x"]


def test_flow_events_export_chrome_shape():
    """Flow links (ph s/t/f) share an id and bind to the enclosing slice
    (bp: "e" on t/f) — the Chrome/Perfetto contract that draws one arrow
    per contribution across process rows."""
    rec = FlightRecorder(capacity=16, pid=3)
    rec.span("send", 1.0, 1.001, tid=1, cat="pipeline")
    rec.flow("contrib", 0xBEEF, "s", 1.0, tid=1)
    rec.span("recv", 1.002, 1.003, tid=2, cat="pipeline")
    rec.flow("contrib", 0xBEEF, "t", 1.003, tid=2)
    rec.flow("contrib", 0xBEEF, "f", 1.004, tid=2)
    ex = rec.export()
    flows = [e for e in ex["traceEvents"] if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert all(e["id"] == 0xBEEF for e in flows)
    assert all(e["name"] == "contrib" for e in flows)
    assert "bp" not in flows[0]
    assert flows[1]["bp"] == "e" and flows[2]["bp"] == "e"
    json.dumps(ex)


def test_flow_disabled_is_noop():
    rec = FlightRecorder(capacity=8, enabled=False)
    rec.flow("contrib", 7, "s", 1.0)
    assert rec.events() == []


def test_merge_traces_applies_clock_offset():
    """A per-process clockOffset (seconds to add to land on the master's
    clock) shifts every non-metadata event at merge, so cross-process
    arrows point forward in time."""
    a = FlightRecorder(pid=1)
    b = FlightRecorder(pid=2)
    a.name_thread(0, "a")
    a.span("send", 1.0, 1.001, tid=0)
    b.span("recv", 1.0, 1.002, tid=0)
    b.clock_offset = 0.5  # b's clock runs half a second behind the master
    merged = merge_traces([a.export(), b.export()])
    spans = {
        (e["pid"], e["name"]): e["ts"]
        for e in merged["traceEvents"]
        if e["ph"] == "X"
    }
    assert spans[(1, "send")] == pytest.approx(1.0e6)
    assert spans[(2, "recv")] == pytest.approx(1.5e6)
    # metadata rows are clock-independent and must not shift
    meta = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    assert all(e.get("ts", 0) == 0 for e in meta)


def test_span_rate_gauge_in_values():
    rec = FlightRecorder(capacity=64)
    for i in range(10):
        rec.span("s", float(i), float(i) + 0.1)
    vals = rec.values()
    assert vals["traceEvents"] == 10.0
    assert vals["traceSpanRate"] > 0.0
    assert "traceSpanRate" in rec.gauge_keys()


def test_sync_slave_offset_sample_keeps_min_rtt():
    """The NTP-style estimator keeps the minimum-RTT sample (tightest
    ±rtt/2 error bound) and rejects backwards clocks."""
    from handel_tpu.sim.sync import SyncSlave

    s = SyncSlave("127.0.0.1:0", 1)
    now = time.time()
    s._offset_sample(now - 0.010, now - 0.005 + 0.3)  # rtt ~10ms, offset ~.3
    assert s.clock_rtt == pytest.approx(0.010, abs=0.005)
    first = s.clock_offset
    assert first == pytest.approx(0.3, abs=0.01)
    # a noisier (larger-rtt) sample must not displace the kept one
    s._offset_sample(now - 0.200, now + 1.0)
    assert s.clock_offset == first
    # a tighter sample wins
    s.clock_rtt = 1.0
    s._offset_sample(time.time() - 1e-4, time.time() + 0.25)
    assert s.clock_offset == pytest.approx(0.25, abs=0.01)
    # negative rtt (clock stepped back) is discarded
    before = s.clock_offset, s.clock_rtt
    s._offset_sample(time.time() + 5.0, 0.0)
    assert (s.clock_offset, s.clock_rtt) == before


# -- wire transport of the cross-node stamp ----------------------------------


def test_packet_sent_ts_roundtrip():
    p = Packet(origin=3, level=2, multisig=b"ms", individual_sig=b"i",
               sent_ts=1234.5678)
    q = Packet.decode(p.encode())
    assert q.sent_ts == pytest.approx(1234.5678)
    assert (q.origin, q.level, q.multisig, q.individual_sig) == (
        3, 2, b"ms", b"i",
    )


def test_packet_corrupt_sent_ts_degrades_to_zero():
    import struct

    p = Packet(origin=1, level=1, multisig=b"m", sent_ts=float("inf"))
    assert Packet.decode(p.encode()).sent_ts == 0.0
    wire = bytearray(Packet(origin=1, level=1, multisig=b"m").encode())
    # force a NaN into the stamp field (bytes 9-16 of the header)
    wire[9:17] = struct.pack(">d", float("nan"))
    assert Packet.decode(bytes(wire)).sent_ts == 0.0


# -- end-to-end traced cluster ----------------------------------------------


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """One traced 16-node LocalCluster run shared by the e2e assertions."""
    rec = FlightRecorder(capacity=1 << 16)
    finals = asyncio.run(run_cluster(16, recorder=rec))
    d = tmp_path_factory.mktemp("trace")
    rec.dump(str(d / "trace_0.json"))
    return rec, finals, str(d)


def test_traced_cluster_exports_pipeline_spans(traced_run):
    rec, finals, _ = traced_run
    assert len(finals) == 16
    names = {e[0] for e in rec.events()}
    for span in ("recv", "queue", "verify", "merge", "net_transit"):
        assert span in names, f"missing {span} spans"
    assert "level_complete" in names


def test_traced_contribution_coverage(traced_run):
    """Acceptance: spans cover >= 95% of a sampled contribution's
    recv -> merge wall time (and the median chain stays attributable)."""
    _, _, d = traced_run
    events = trace_cli.load_traces([d])
    chains = trace_cli.contribution_chains(events)
    assert chains, "no complete contribution chains reconstructed"
    cov = sorted(c["coverage"] for c in chains.values())
    assert cov[-1] >= 0.95, f"best chain coverage {cov[-1]:.1%}"
    assert cov[len(cov) // 2] >= 0.80, f"median coverage {cov[len(cov) // 2]:.1%}"
    # every chain decomposes into the pipeline stages
    sample = next(iter(chains.values()))
    assert {"recv", "queue", "verify", "merge"} <= set(sample["stages"])


def test_level_timeline_is_monotonic(traced_run):
    _, _, d = traced_run
    events = trace_cli.load_traces([d])
    wave = trace_cli.level_timeline(events)
    assert wave, "no level_complete events"
    for lvl, (first, med, last) in wave.items():
        assert first <= med <= last
    # higher levels complete no earlier than level 1 started (the wave moves up)
    firsts = [wave[lvl][0] for lvl in sorted(wave)]
    assert firsts == sorted(firsts)


def test_traced_cluster_flow_linkage(traced_run):
    """Every traced contribution's recv resolves its packet span id back to
    a send span — in-process, linkage must be total."""
    _, _, d = traced_run
    events = trace_cli.load_traces([d])
    frac, linked, total = trace_cli.flow_linkage(events)
    assert total > 0
    assert frac >= 0.95, f"flow linkage {frac:.1%} ({linked}/{total})"


def test_critical_path_covers_time_to_threshold(traced_run):
    """Acceptance: the backwards walk from the first threshold_reached
    instant yields ONE causal chain whose spans cover >= 90% of the wall
    time-to-threshold, with per-stage attribution."""
    _, _, d = traced_run
    events = trace_cli.load_traces([d])
    cp = trace_cli.critical_path(events)
    assert cp is not None, "no threshold_reached anchor"
    assert cp["chain"], "empty causal chain"
    assert cp["wall_ms"] > 0
    assert cp["coverage"] >= 0.90, f"coverage {cp['coverage']:.1%}"
    names = {e["name"] for e in cp["chain"]}
    # the chain decomposes into the pipeline stages, net hops included
    assert {"recv", "verify", "merge", "net_transit"} <= names
    assert cp["hops"] >= 1
    # stage attribution is sane: non-negative, and no stage alone exceeds
    # the wall (adjacent chain spans may overlap, so the SUM can slightly)
    assert all(v >= 0.0 for v in cp["stages_ms"].values())
    assert max(cp["stages_ms"].values()) <= cp["wall_ms"] * 1.001
    # the chain is causally ordered: event starts never move backwards
    starts = [e["t_ms"] for e in cp["chain"]]
    assert starts == sorted(starts)


def test_build_report_is_bench_record(traced_run):
    """trace_report.json rides the bench_check gate: record-shaped
    (metric/value/backend) with every side metric extractable."""
    import sys

    _, _, d = traced_run
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    try:
        import bench_check
    finally:
        sys.path.pop(0)
    exports = trace_cli.load_exports([d])
    events = merge_traces(exports)["traceEvents"]
    report = trace_cli.build_report(events, exports)
    assert report["backend"] == "trace"
    assert report["metric"] == "trace_time_to_threshold_s"
    assert report["value"] > 0
    got = bench_check.extract_metrics(report)
    for key in ("time_to_threshold_s", "critical_path_coverage",
                "flow_linkage", "lane_occupancy"):
        assert (key, "trace") in got, f"{key} not extracted by bench_check"
    assert got[("critical_path_coverage", "trace")] >= 0.90
    json.dumps(report)


def test_trace_cli_smoke(traced_run, tmp_path, capsys):
    _, _, d = traced_run
    merged = str(tmp_path / "merged.json")
    report = str(tmp_path / "trace_report.json")
    assert trace_cli.main(
        [d, "--merged", merged, "--top", "3",
         "--critical-path", "--report", report]
    ) == 0
    out = capsys.readouterr().out
    assert "aggregation wave" in out
    assert "slowest-span attribution" in out
    assert "contribution chains" in out
    assert "critical path to threshold" in out
    with open(merged) as f:
        data = json.load(f)
    assert len(data["traceEvents"]) > 0
    with open(report) as f:
        rep = json.load(f)
    assert rep["backend"] == "trace" and rep["critical_path"]["chain"]


def test_trace_cli_plot(traced_run, tmp_path):
    pytest.importorskip("matplotlib")
    _, _, d = traced_run
    png = str(tmp_path / "wave.png")
    assert trace_cli.main([d, "--plot", png]) == 0
    assert os.path.getsize(png) > 0


def test_untraced_cluster_has_no_recorder_cost_path():
    """Default config: recorder is None — the protocol still converges and
    per-node histograms (always-on distributional plane) are populated."""
    async def go():
        from handel_tpu.core.test_harness import LocalCluster

        cluster = LocalCluster(8)
        cluster.start()
        try:
            await cluster.wait_complete_success(10.0)
        finally:
            cluster.stop()
        h = next(iter(cluster.handels.values()))
        assert h.rec is None
        hists = h.histograms()
        assert hists["levelCompleteS"].count > 0
        assert hists["verifyLatencyS"].count > 0
        assert hists["queueWaitS"].count > 0

    asyncio.run(go())


def test_localhost_platform_traced_run(tmp_path):
    """The full subprocess path: `trace = true` makes every node process
    record a flight recorder and dump Chrome JSON into the run's trace dir;
    the stats CSV carries the _p50/_p90/_p99 columns for the
    level-completion and device-verify latency keys (acceptance criteria)."""
    import csv

    from handel_tpu.sim.config import RunConfig, SimConfig
    from handel_tpu.sim.platform import LocalhostPlatform

    cfg = SimConfig(
        network="udp",
        scheme="fake",
        trace=True,
        max_timeout_s=60.0,
        runs=[RunConfig(nodes=8, threshold=5, processes=2)],
    )

    async def go():
        plat = LocalhostPlatform(cfg, str(tmp_path))
        return await plat.start_run(0)

    res = asyncio.run(go())
    if not res.ok:
        for out, err in res.outputs:
            print(out.decode(errors="replace"))
            print(err.decode(errors="replace"))
    assert res.ok
    # one dump per node process, each a valid non-empty Chrome trace
    dumps = sorted(os.listdir(res.trace_dir))
    assert len(dumps) == 2
    exports = trace_cli.load_exports([res.trace_dir])
    events = merge_traces(exports)["traceEvents"]
    assert len(events) > 0
    assert trace_cli.level_timeline(events)  # the wave is reconstructable
    chains = trace_cli.contribution_chains(events)
    assert chains
    assert max(c["coverage"] for c in chains.values()) >= 0.95
    # cross-process causality (acceptance): >= 95% of traced recvs resolve
    # their packet span id to the sending process's send span
    frac, linked, total = trace_cli.flow_linkage(events)
    assert total > 0
    assert frac >= 0.95, f"cross-process flow linkage {frac:.1%} ({linked}/{total})"
    # each process dump carries a clock-offset estimate from the sync
    # handshake; on one host the skew must be tiny (well under a second)
    offsets = [float(ex.get("clockOffset", 0.0) or 0.0) for ex in exports]
    assert len(offsets) == 2
    assert all(abs(o) < 1.0 for o in offsets), f"clock offsets {offsets}"
    # the merged trace yields a critical path across processes
    cp = trace_cli.critical_path(events)
    assert cp is not None and cp["chain"]
    # distribution columns next to the classic stats
    rows = list(csv.DictReader(open(res.csv_path)))
    for key in ("levelCompleteS", "verifyLatencyS", "queueWaitS"):
        for s in ("p50", "p90", "p99"):
            assert float(rows[0][f"sigs_{key}_{s}"]) > 0.0
    assert float(rows[0]["sigs_levelCompleteS_n"]) > 0.0


def test_histogram_quantile_accuracy():
    """LogHistogram quantiles land within one bucket (<= 19% relative) of
    the exact sample quantiles, clamped to the observed range."""
    import random

    rng = random.Random(7)
    h = LogHistogram()
    samples = [rng.uniform(1e-4, 2.0) for _ in range(5000)]
    for s in samples:
        h.add(s)
    samples.sort()
    for q in (0.5, 0.9, 0.99):
        exact = samples[int(q * len(samples)) - 1]
        est = h.quantile(q)
        assert est == pytest.approx(exact, rel=0.25)
    assert h.quantile(0.99) >= h.quantile(0.5)
    assert h.lo <= h.quantile(0.5) <= h.hi

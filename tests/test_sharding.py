"""Mesh-sharded verification plane (parallel/sharding.py) on the 8
virtual CPU devices from conftest.

The scaling axis of this framework is the pairing/aggregation batch
(SURVEY.md §5.7): the registry shards over the mesh for the masked G2
segment-sum (shard_map partial sums + all_gather + log-depth point-add
tree) and candidates shard for the product-of-pairings check — the device
analog of the loop the reference runs serially per signature
(processing.go:355-361, bn256/cf/bn256.go:86-98). These tests cover the
raw kernels (incl. non-divisible padding) and the wired path:
`BN254Device(mesh_devices=8).batch_verify` end to end.
"""

import random

import numpy as np
import pytest

N_DEV = 8


def _keys(n, seed=7):
    from handel_tpu import native as nat
    from handel_tpu.ops import bn254_ref as bn

    rng = random.Random(seed)
    # small scalars keep host keygen fast; device cost is magnitude-free
    sks = [rng.randrange(1, 1 << 30) for _ in range(n)]
    pks = nat.g2_mul_batch([bn.G2_GEN] * n, sks)
    return sks, pks


def test_mesh_requires_enough_devices():
    import jax

    from handel_tpu.parallel.sharding import make_mesh

    assert len(jax.devices()) >= N_DEV  # conftest contract
    with pytest.raises(ValueError, match="devices"):
        make_mesh(len(jax.devices()) + 1)


def test_sharded_masked_sum_matches_dense_nondivisible():
    """Registry-sharded masked G2 sum == single-device masked sum, on a
    registry size that does NOT divide over the mesh (the padding path)."""
    import jax.numpy as jnp

    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.parallel.sharding import make_mesh, sharded_masked_sum_g2

    n_reg, batch = 20, 8  # 20 % 8 == 4 -> padded to 24
    curves = BN254Curves()
    T, g2 = curves.T, curves.g2
    _, pks = _keys(n_reg)
    reg_x = T.f2_pack([p[0] for p in pks])
    reg_y = T.f2_pack([p[1] for p in pks])
    rng = np.random.default_rng(3)
    mask = rng.random((n_reg, batch)) < 0.5
    mask[:, 0] = False  # one all-empty candidate: must come back infinity

    mesh = make_mesh(N_DEV)
    fn = sharded_masked_sum_g2(curves, mesh, n_reg, batch)
    agg = fn(reg_x[0], reg_x[1], reg_y[0], reg_y[1], jnp.asarray(mask))

    tile = lambda a: jnp.repeat(a, batch, axis=1)
    P2 = g2.from_affine(
        (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
    )
    want = g2.masked_sum(P2, jnp.asarray(mask.reshape(-1)), n_reg)

    got_inf = np.asarray(g2.is_infinity(agg))
    want_inf = np.asarray(g2.is_infinity(want))
    np.testing.assert_array_equal(got_inf, want_inf)
    assert got_inf[0]  # the empty candidate
    gx, gy, _ = g2.to_affine(agg)
    wx, wy, _ = g2.to_affine(want)
    for g, w in ((gx, wx), (gy, wy)):
        for c in range(2):
            np.testing.assert_array_equal(
                np.asarray(g[c])[:, ~got_inf], np.asarray(w[c])[:, ~want_inf]
            )


def test_sharded_masked_sum_tiny_registry_empty_shards():
    """Boundary shape: 5 keys over 8 devices (pads to 8 — shards 5..7 are
    pure padding) and a 2-candidate batch (fewer lanes than devices). The
    padding lanes must contribute nothing and the tiny batch must still
    match the single-device masked sum."""
    import jax.numpy as jnp

    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.parallel.sharding import make_mesh, sharded_masked_sum_g2

    n_reg, batch = 5, 2
    curves = BN254Curves()
    T, g2 = curves.T, curves.g2
    _, pks = _keys(n_reg, seed=13)
    reg_x = T.f2_pack([p[0] for p in pks])
    reg_y = T.f2_pack([p[1] for p in pks])
    mask = np.zeros((n_reg, batch), dtype=bool)
    mask[:3, 0] = True  # candidate 0: keys {0,1,2}
    mask[4, 1] = True  # candidate 1: a single key

    mesh = make_mesh(N_DEV)
    fn = sharded_masked_sum_g2(curves, mesh, n_reg, batch)
    agg = fn(reg_x[0], reg_x[1], reg_y[0], reg_y[1], jnp.asarray(mask))

    tile = lambda a: jnp.repeat(a, batch, axis=1)
    P2 = g2.from_affine(
        (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
    )
    want = g2.masked_sum(P2, jnp.asarray(mask.reshape(-1)), n_reg)
    assert not np.asarray(g2.is_infinity(agg)).any()
    gx, gy, _ = g2.to_affine(agg)
    wx, wy, _ = g2.to_affine(want)
    for g, w in ((gx, wx), (gy, wy)):
        for c in range(2):
            np.testing.assert_array_equal(np.asarray(g[c]), np.asarray(w[c]))


def test_commit_registry_sharded_pads_edge_and_places():
    """The resident-registry commit (commit_registry_sharded): width padded
    to the device multiple with edge replication (a real point, so padded
    lanes never hit the point-at-infinity special case), original columns
    intact, arrays placed under the mesh's (None, dp) sharding."""
    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.parallel.sharding import (
        commit_registry_sharded,
        make_mesh,
    )

    n_reg = 5  # pads to 8: 3 padded columns
    curves = BN254Curves()
    T = curves.T
    _, pks = _keys(n_reg, seed=17)
    reg_x = T.f2_pack([p[0] for p in pks])
    reg_y = T.f2_pack([p[1] for p in pks])

    mesh = make_mesh(N_DEV)
    (rx0, rx1), (ry0, ry1) = commit_registry_sharded(
        mesh, reg_x, reg_y, n_reg
    )
    for got, src in ((rx0, reg_x[0]), (rx1, reg_x[1]),
                     (ry0, reg_y[0]), (ry1, reg_y[1])):
        assert got.shape[1] == N_DEV  # 5 -> 8
        np.testing.assert_array_equal(
            np.asarray(got)[:, :n_reg], np.asarray(src)
        )
        # edge mode: every padded column replicates the last real key
        for pad_col in range(n_reg, N_DEV):
            np.testing.assert_array_equal(
                np.asarray(got)[:, pad_col], np.asarray(src)[:, -1]
            )
        shards = {d.id for d in got.sharding.device_set}
        assert len(shards) == N_DEV  # spread over the whole mesh


def test_launch_partition_rules_route_operands():
    """The latency-plane partition table (launch_partition_rules): mesh-
    resident banks shard the point axis, the per-launch mask shards its
    registry-major rows, per-candidate operands stay replicated — and the
    first-match search covers every spelling a launch stages."""
    from jax.sharding import PartitionSpec as P

    from handel_tpu.parallel.sharding import (
        launch_partition_rules,
        match_partition_rules,
    )

    specs = match_partition_rules(
        launch_partition_rules(),
        ["reg_x", "reg_y", "prefix", "mask", "sig_x", "sig_y",
         "valid", "lo", "hi", "miss_idx", "r_bits", "group_oh", "g_occ"],
    )
    for name in ("reg_x", "reg_y", "prefix"):
        assert specs[name] == P(None, "dp"), name
    assert specs["mask"] == P("dp", None)
    for name in ("sig_x", "sig_y", "valid", "lo", "hi", "miss_idx"):
        assert specs[name] == P(), name
    # RLC scalar-side operands are candidate-axis-last and must stay
    # replicated — the mask row rule must not capture them.
    for name in ("r_bits", "group_oh", "g_occ"):
        assert specs[name] == P(), name
    # a table without the catch-all terminal must refuse unknown operands
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(((r"^reg", P(None, "dp")),), ["mask"])


def test_make_shard_fns_place_by_rule():
    """make_shard_fns: rule-matched placement fns produce arrays already
    laid out in the launch sharding — registry split over the point axis,
    mask over its rows, replicated operands on every device."""
    from handel_tpu.parallel.sharding import (
        launch_partition_rules,
        make_mesh,
        make_shard_fns,
        match_partition_rules,
    )

    mesh = make_mesh(N_DEV)
    put = make_shard_fns(
        mesh,
        match_partition_rules(
            launch_partition_rules(), ["reg_x", "mask", "sig_x"]
        ),
    )
    reg = put["reg_x"](np.zeros((4, 16), np.uint32))
    mask = put["mask"](np.zeros((16, 4), bool))
    sig = put["sig_x"](np.zeros((4, 4), np.uint32))
    assert len(reg.sharding.device_set) == N_DEV
    assert reg.sharding.shard_shape(reg.shape) == (4, 2)  # point axis split
    assert mask.sharding.shard_shape(mask.shape) == (2, 4)  # row axis split
    assert sig.sharding.is_fully_replicated


@pytest.mark.parametrize("k", [1, 2, 8])
def test_sharded_masked_sum_matches_dense_across_mesh_widths(k):
    """K ∈ {1, 2, 8}: the registry-sharded masked sum must equal the dense
    single-device oracle bit-exactly at every mesh width — K=1 is the
    degenerate whole-mesh-is-one-chip shape, K ∈ {2, 8} leave an
    edge-padded final shard (11 % 2 == 1, 11 % 8 == 3)."""
    import jax.numpy as jnp

    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.parallel.sharding import make_mesh, sharded_masked_sum_g2

    n_reg, batch = 11, 4
    curves = BN254Curves()
    T, g2 = curves.T, curves.g2
    _, pks = _keys(n_reg, seed=31)
    reg_x = T.f2_pack([p[0] for p in pks])
    reg_y = T.f2_pack([p[1] for p in pks])
    rng = np.random.default_rng(5)
    mask = rng.random((n_reg, batch)) < 0.5
    mask[:, 2] = False  # one empty candidate per width

    fn = sharded_masked_sum_g2(curves, make_mesh(k), n_reg, batch)
    agg = fn(reg_x[0], reg_x[1], reg_y[0], reg_y[1], jnp.asarray(mask))

    tile = lambda a: jnp.repeat(a, batch, axis=1)
    P2 = g2.from_affine(
        (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
    )
    want = g2.masked_sum(P2, jnp.asarray(mask.reshape(-1)), n_reg)
    got_inf = np.asarray(g2.is_infinity(agg))
    np.testing.assert_array_equal(
        got_inf, np.asarray(g2.is_infinity(want))
    )
    assert got_inf[2]
    gx, gy, _ = g2.to_affine(agg)
    wx, wy, _ = g2.to_affine(want)
    for g, w in ((gx, wx), (gy, wy)):
        for c in range(2):
            np.testing.assert_array_equal(
                np.asarray(g[c])[:, ~got_inf],
                np.asarray(w[c])[:, ~got_inf],
            )


def test_sharded_masked_sum_preplaced_padded_mask():
    """The latency-plane staging path (BN254Device._run_plan dense class):
    a mask pre-padded to the device multiple and pre-placed by partition
    rule must keep its shards (the pad-skip branch in
    sharded_masked_sum_g2) and produce the exact aggregates of the
    replicated unpadded call."""
    import jax
    import jax.numpy as jnp

    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.parallel.sharding import (
        launch_partition_rules,
        make_mesh,
        make_shard_fns,
        match_partition_rules,
        sharded_masked_sum_g2,
    )

    n_reg, batch = 20, 8  # pads to 24: the final shard is half padding
    pad_n = (-n_reg) % N_DEV
    curves = BN254Curves()
    T, g2 = curves.T, curves.g2
    _, pks = _keys(n_reg, seed=37)
    reg_x = T.f2_pack([p[0] for p in pks])
    reg_y = T.f2_pack([p[1] for p in pks])
    rng = np.random.default_rng(11)
    mask = rng.random((n_reg, batch)) < 0.5

    mesh = make_mesh(N_DEV)
    fn = sharded_masked_sum_g2(curves, mesh, n_reg, batch)
    put = make_shard_fns(
        mesh, match_partition_rules(launch_partition_rules(), ["mask"])
    )
    placed = put["mask"](np.pad(mask, ((0, pad_n), (0, 0))))
    assert placed.sharding.shard_shape(placed.shape) == (
        (n_reg + pad_n) // N_DEV, batch,
    )
    got = fn(reg_x[0], reg_x[1], reg_y[0], reg_y[1], placed)
    want = fn(reg_x[0], reg_x[1], reg_y[0], reg_y[1], jnp.asarray(mask))
    for a, b in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 2, 8])
def test_sharded_pairing_check_matches_oracle_across_mesh_widths(k):
    """K ∈ {1, 2, 8}: the candidate-sharded Miller loop + final
    exponentiation product check must agree with the scalar reference
    oracle bit-exactly at every mesh width — K=8 pads the 4-candidate
    batch with masked lanes (4 % 8), K=2 splits it 2/2, K=1 is the dense
    single-device graph itself."""
    import jax.numpy as jnp

    from handel_tpu.ops import bn254_ref as bn
    from handel_tpu.ops.curve import BN254Curves
    from handel_tpu.ops.pairing import BN254Pairing
    from handel_tpu.parallel.sharding import make_mesh, sharded_pairing_check

    groups = 4
    curves = BN254Curves()
    pr = BN254Pairing(curves)
    rng = random.Random(41 + k)
    h = bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R))  # H(m)
    sks = [rng.randrange(1, bn.R) for _ in range(groups)]
    pks = [bn.g2_mul(bn.G2_GEN, sk) for sk in sks]
    sigs = [bn.g1_mul(h, sk) for sk in sks]
    sigs[2] = bn.g1_mul(h, sks[2] + 1)  # candidate 2 forged

    def pack1(pts):
        return (
            curves.F.pack([p[0] for p in pts]),
            curves.F.pack([p[1] for p in pts]),
        )

    def pack2(pts):
        return (
            curves.T.f2_pack([q[0] for q in pts]),
            curves.T.f2_pack([q[1] for q in pts]),
        )

    # pair 0: e(H, pk_j); pair 1: e(-sig_j, G2) — BLS verify as one product
    ps = (pack1([h] * groups), pack1([bn.g1_neg(s) for s in sigs]))
    qs = (pack2(pks), pack2([bn.G2_GEN] * groups))
    mask = np.ones((groups,), bool)
    mask[3] = False  # one masked-out lane: must come back False

    fn = sharded_pairing_check(pr, make_mesh(k), groups)
    got = [bool(v) for v in np.asarray(fn(ps, qs, jnp.asarray(mask)))]

    # dense single-device oracle: the scalar reference product per candidate
    want = []
    for j in range(groups):
        prod = bn.f12_mul(
            bn.pairing(pks[j], h),
            bn.pairing(bn.G2_GEN, bn.g1_neg(sigs[j])),
        )
        want.append(bool(mask[j]) and prod == bn.F12_ONE)
    assert got == want == [True, True, False, False]


@pytest.mark.slow
def test_device_batch_verify_sharded():
    """The wired path: BN254Device(mesh_devices=8).batch_verify — valid
    candidates pass, a forged signature fails — over a registry that doesn't
    divide over the mesh. (Agreement with the single-device engine is
    implied: the same oracle-built batch must come back all-True except the
    forgery, which tests/test_bn254_device.py already pins for the
    single-device kernels.)"""
    from handel_tpu import native as nat
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature, hash_to_g1
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops import bn254_ref as bn

    n_reg, C = 50, 16  # 50 % 8 == 2
    sks, pks = _keys(n_reg)
    msg = b"sharded-verify"
    h = hash_to_g1(msg)

    rng = random.Random(11)
    requests = []
    for j in range(6):
        # scattered signer sets (hole count far over MISS_CAP) force the
        # dense masked-sum kernel — the sharded-sum path under test
        signers = sorted(rng.sample(range(n_reg), n_reg // 2))
        bs = BitSet(n_reg)
        for i in signers:
            bs.set(i, True)
        agg_sk = sum(sks[i] for i in signers) % bn.R
        sig_pt = nat.g1_mul(h, agg_sk)
        if j == 3:  # forge one: wrong scalar
            sig_pt = nat.g1_mul(h, (agg_sk + 1) % bn.R)
        requests.append((bs, BN254Signature(sig_pt)))

    reg = [BN254PublicKey(p) for p in pks]
    sharded = BN254Device(reg, batch_size=C, mesh_devices=N_DEV)
    assert sharded.mesh is not None
    got = sharded.batch_verify(msg, requests)
    assert got == [True, True, True, False, True, True]


@pytest.mark.slow
def test_sharded_pipeline_reference_scale():
    """The dryrun pipeline at reference-like size: 1030-key registry
    (pads over 8 devices), 32 candidates, one wired batch_verify launch.
    Matches the headline regime of the reference's 4000-node scenario
    (README.md:32-33) scaled to a CI-tolerable registry."""
    from handel_tpu import native as nat
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature, hash_to_g1
    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops import bn254_ref as bn

    # batch_size 16 shares the device test's executable geometry (32
    # candidates -> two launches of the same compiled kernels)
    n_reg, C, n_cand = 1030, 16, 32
    sks, pks = _keys(n_reg, seed=23)
    msg = b"pipeline-1030"
    h = hash_to_g1(msg)

    rng = random.Random(29)
    requests = []
    for j in range(n_cand):
        # contiguous partitioner-style ranges with a few holes: the
        # prefix-table range kernel path, under the sharded pairing check
        size = rng.choice([64, 128, 256])
        lo = rng.randrange(0, n_reg - size)
        holes = set(rng.sample(range(lo, lo + size), rng.randrange(0, 5)))
        bs = BitSet(n_reg)
        signers = [i for i in range(lo, lo + size) if i not in holes]
        for i in signers:
            bs.set(i, True)
        agg_sk = sum(sks[i] for i in signers) % bn.R
        requests.append((bs, BN254Signature(nat.g1_mul(h, agg_sk))))

    device = BN254Device(
        [BN254PublicKey(p) for p in pks], batch_size=C, mesh_devices=N_DEV
    )
    assert device.batch_verify(msg, requests) == [True] * n_cand

"""Fleet-of-chips verify plane (parallel/plane.py + the per-lane service
pipeline): scheduling, degradation, per-device metrics rows, and the
`devices` config knob. Host-math engines only — no jax, no kernels."""

import asyncio

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.models.fake import FakePublic, FakeSignature
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.parallel.plane import DeviceLane, DevicePlane, host_plane
from handel_tpu.utils.breaker import CircuitBreaker


class _Engine:
    batch_size = 4

    def __init__(self):
        self.dispatched = 0

    def dispatch_multi(self, items):
        self.dispatched += 1
        return [True] * len(items)

    def fetch(self, handle):
        return handle


def _plane(k, breakers=None):
    return DevicePlane([_Engine() for _ in range(k)], breakers=breakers)


def _req(tag: int, n: int = 16):
    bs = BitSet(n)
    bs.set(tag % n, True)
    return (bs, FakeSignature(True))


PKS = [FakePublic(True) for _ in range(16)]


def test_pick_prefers_idle_lane():
    plane = _plane(3)
    # lane 0 busy dispatching, lane 1 has one launch awaiting fetch
    plane.lanes[0].dispatching = ["x"]
    plane.lanes[1].fetching = ["y"]
    lane = plane.pick()
    assert lane is plane.lanes[2]  # the only zero-load lane
    assert plane.idle_violations == 0


def test_pick_least_loaded_then_lowest_index():
    plane = _plane(3)
    plane.lanes[0].fetching = ["a"]
    plane.lanes[1].fetching = ["b"]
    # all free for dispatch, loads 1/1/1 after giving lane 2 one too
    plane.lanes[2].fetching = ["c"]
    assert plane.pick() is plane.lanes[0]  # tie -> lowest index


def test_pick_skips_breaker_open_lane():
    breakers = [CircuitBreaker(cooldown_s=600.0) for _ in range(2)]
    plane = _plane(2, breakers=breakers)
    for _ in range(breakers[0].threshold):
        breakers[0].record_failure()
    assert plane.pick() is plane.lanes[1]
    assert plane.values()["devicesAvailable"] == 1.0
    assert len(plane.allowed()) == 1


def test_pick_none_when_all_occupied():
    plane = _plane(2)
    for lane in plane.lanes:
        lane.dispatching = ["x"]
    assert plane.pick() is None


def test_host_cost_sums_over_engines():
    plane = _plane(2)
    for i, lane in enumerate(plane.lanes):
        lane.engine.host_pack_ms = 2.0 + i
        lane.engine.host_pack_launches = 1 + i
        lane.engine.host_dispatch_ms = 10.0
        lane.engine.host_dispatch_launches = 2
    hc = plane.host_cost()
    assert hc["pack_ms"] == 5.0
    assert hc["pack_launches"] == 3.0
    assert hc["dispatch_ms"] == 20.0
    assert hc["dispatch_launches"] == 4.0


def test_labeled_values_one_row_per_device():
    plane = _plane(3)
    plane.lanes[1].launches = 4
    plane.lanes[1].fill_sum = 3.0
    rows = plane.labeled_values()
    assert set(rows) == {"0", "1", "2"}
    assert rows["1"]["launches"] == 4.0
    assert rows["1"]["fillRatio"] == 0.75
    assert plane.labeled_gauge_keys() <= set(rows["0"])


def test_plane_requires_engines_and_matched_breakers():
    with pytest.raises(ValueError, match="at least one"):
        DevicePlane([])
    with pytest.raises(ValueError, match="1:1"):
        DevicePlane([_Engine()], breakers=[])


def test_lane_values_shape():
    lane = DeviceLane(0, _Engine())
    vals = lane.values()
    assert vals["breakerState"] == 0.0
    assert vals["load"] == 0.0


def test_service_fleet_uses_every_lane():
    """A flood of distinct aggregates over a 4-lane plane must reach every
    lane (least-loaded spreads; no lane starves) and keep the scheduler
    audit clean."""
    plane = _plane(4)

    async def go():
        svc = BatchVerifierService(plane, max_delay_ms=0.1)
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(
                        i.to_bytes(2, "big"), PKS, [_req(i)], session="s"
                    )
                    for i in range(64)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert all(lane.engine.dispatched >= 1 for lane in plane.lanes)
    assert vals["devicesTotal"] == 4.0
    assert vals["schedIdleViolations"] == 0.0
    assert sum(lane.launches for lane in plane.lanes) == vals[
        "verifierLaunches"
    ]


def test_service_fleet_degrades_to_healthy_lanes():
    """Breaker-open on one lane: the run completes on the others and the
    tripped lane never dispatches."""
    breakers = [CircuitBreaker(cooldown_s=600.0) for _ in range(3)]
    plane = _plane(3, breakers=breakers)
    for _ in range(breakers[1].threshold):
        breakers[1].record_failure()

    async def go():
        svc = BatchVerifierService(plane, max_delay_ms=0.1)
        try:
            out = await asyncio.gather(
                *(
                    svc.verify(
                        i.to_bytes(2, "big"), PKS, [_req(i)], session="s"
                    )
                    for i in range(24)
                )
            )
            return out, svc.values()
        finally:
            svc.stop()

    out, vals = asyncio.run(go())
    assert all(v == [True] for v in out)
    assert plane.lanes[1].engine.dispatched == 0
    assert plane.lanes[0].engine.dispatched >= 1
    assert plane.lanes[2].engine.dispatched >= 1
    assert vals["devicesAvailable"] == 2.0
    assert vals["failoverBatches"] == 0.0


def test_single_device_wrap_keeps_identities():
    """A bare engine (no plane) wraps into a plane of 1 and the legacy
    `service.device` / `service.breaker` surfaces stay the lane's."""
    eng = _Engine()
    br = CircuitBreaker()
    svc = BatchVerifierService(eng, breaker=br)
    assert len(svc.plane) == 1
    assert svc.device is eng
    assert svc.breaker is br
    assert svc.plane.lanes[0].breaker is br


def test_host_plane_builds_k_host_devices():
    from handel_tpu.core.test_harness import FakeScheme

    plane = host_plane(FakeScheme().constructor, 3, batch_size=8)
    assert len(plane) == 3
    assert plane.batch_size == 8


def test_devices_knob_roundtrip(tmp_path):
    """[service] devices flows through load_config and dump_config."""
    from handel_tpu.sim.config import dump_config, load_config

    p = tmp_path / "sim.toml"
    p.write_text(
        "[sim]\nnodes = 8\n\n[service]\nsessions = 2\ndevices = 4\n"
    )
    cfg = load_config(str(p))
    assert cfg.service.devices == 4
    dumped = dump_config(cfg)
    assert "devices = 4" in dumped
    # default stays 1 when the key is absent
    p.write_text("[sim]\nnodes = 8\n")
    assert load_config(str(p)).service.devices == 1


def test_watch_aggregates_device_rows():
    """sim watch: `device`-labeled families aggregate into per-device rows
    and render as a devices block."""
    from handel_tpu.sim.watch_cli import aggregate, parse_exposition, render

    text = (
        'handel_device_verifier_launches{device="0"} 5\n'
        'handel_device_verifier_launches{device="1"} 7\n'
        'handel_device_verifier_fill_ratio{device="1"} 0.5\n'
        'handel_device_verifier_inflight{device="1"} 2\n'
        'handel_device_verifier_breaker_state{device="0"} 1\n'
    )
    model = aggregate([parse_exposition(text)])
    assert model["devices"]["1"]["launches"] == 7.0
    assert model["devices"]["1"]["fill"] == 0.5
    assert model["devices"]["0"]["breaker"] == 1.0
    out = render(model, ["x"], 1, 1)
    assert "dev   1" in out
    assert "breaker open" in out

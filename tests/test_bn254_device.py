"""BN254Device batch-verify kernels: prefix-table range path vs dense path.

The range kernel (prefix[hi] - prefix[lo] - missing patch) must agree with
the masked tree-sum kernel and with host-side verification for every signer-
set shape: full ranges, ranges with holes, scattered sets, invalid sigs,
empty/padded lanes. Reference semantics: processing.go:342-368 verify +
crypto.go:126-134 pubkey aggregation.
"""

import random

import pytest

# slow tier: XLA-compile-bound (device verify kernels) — runs in
# test-slow/test-all (nightly/CI); the fast tier keeps the oracle +
# protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.core.bitset import BitSet
from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature, hash_to_g1
from handel_tpu.models.bn254_jax import BN254Device
from handel_tpu.ops import bn254_ref as bn
from handel_tpu import native as nat

N = 8
C = 4
MSG = b"device kernel test"


@pytest.fixture(scope="module")
def setup():
    rng = random.Random(99)
    sks = [rng.randrange(1, bn.R) for _ in range(N)]
    pks = [BN254PublicKey(nat.g2_mul(bn.G2_GEN, sk)) for sk in sks]
    device = BN254Device(pks, batch_size=C)
    h = hash_to_g1(MSG)
    return device, sks, h


def _request(sks, h, signers, corrupt=False):
    bs = BitSet(N)
    for i in signers:
        bs.set(i, True)
    agg = sum(sks[i] for i in signers) % bn.R
    if corrupt:
        agg = (agg + 1) % bn.R
    return (bs, BN254Signature(nat.g1_mul(h, agg)))


def test_range_candidates(setup):
    device, sks, h = setup
    reqs = [
        _request(sks, h, range(0, 8)),          # full registry
        _request(sks, h, range(2, 6)),          # inner range
        _request(sks, h, [0, 1, 3, 4], False),  # hole at 2
        _request(sks, h, range(1, 5), corrupt=True),
    ]
    assert device.batch_verify(MSG, reqs) == [True, True, True, False]


def test_scattered_and_single(setup):
    device, sks, h = setup
    reqs = [
        _request(sks, h, [0, 7]),   # hull with 6 holes (patched)
        _request(sks, h, [5]),      # single signer
        _request(sks, h, [1, 2, 6], corrupt=True),
        _request(sks, h, [3, 4]),
    ]
    assert device.batch_verify(MSG, reqs) == [True, True, False, True]


def test_range_and_dense_paths_agree(setup):
    device, sks, h = setup
    rng = random.Random(5)
    reqs = []
    for _ in range(C):
        signers = sorted(rng.sample(range(N), rng.randrange(1, N + 1)))
        reqs.append(_request(sks, h, signers, corrupt=rng.random() < 0.5))
    expect = device.batch_verify(MSG, reqs)  # dispatches to the range path

    # force the dense kernel on identical requests
    import numpy as np
    import jax.numpy as jnp

    mask = np.zeros((N, C), dtype=bool)
    sig_pts = []
    valid = np.zeros((C,), dtype=bool)
    for j, (bs, sig) in enumerate(reqs):
        idx = list(bs.indices())
        mask[idx, j] = True
        valid[j] = True
        sig_pts.append(sig.point)
    F = device.curves.F
    dense = device._kernel(
        device._reg_x,
        device._reg_y,
        jnp.asarray(mask.reshape(-1)),
        F.pack([p[0] for p in sig_pts]),
        F.pack([p[1] for p in sig_pts]),
        *device._h_point(MSG),
        jnp.asarray(valid),
    )
    assert [bool(v) for v in dense] == expect


def test_empty_and_padded_lanes(setup):
    device, sks, h = setup
    empty = BitSet(N)
    reqs = [
        (empty, BN254Signature(bn.G1_GEN)),  # no signers -> invalid lane
        _request(sks, h, range(0, 3)),
    ]
    assert device.batch_verify(MSG, reqs) == [False, True]


def test_prefix_table_matches_host(setup):
    """prefix[i] must equal the host sum of the first i keys."""
    device, sks, h = setup
    pref_pts = []
    (x0, x1), (y0, y1), inf = device._prefix
    import numpy as np

    T = device.curves.T
    xs = T.f2_unpack((x0, x1))
    ys = T.f2_unpack((y0, y1))
    infs = np.asarray(inf)
    acc = None
    for i in range(N + 1):
        if infs[i]:
            assert acc is None or i == 0
        else:
            assert (xs[i], ys[i]) == acc, f"prefix slot {i}"
        if i < N:
            acc = nat.g2_add(acc, nat.g2_mul(bn.G2_GEN, sks[i]))


def test_dispatch_multi_per_lane_messages(setup):
    """One launch whose lanes carry DIFFERENT messages (the multi-tenant
    service's cross-session coalescing, dispatch_multi): every lane's
    pairing check runs against ITS message's H(m) — a valid aggregate
    claimed under the wrong message must fail its lane."""
    device, sks, h = setup
    msg2 = b"second tenant message"
    h2 = hash_to_g1(msg2)
    good_m1 = _request(sks, h, range(0, 3))
    good_m2 = _request(sks, h2, range(3, 6))
    # a third lane back on msg1 (messages interleave across lanes)
    good_m1b = _request(sks, h, [6, 7])
    # valid aggregate for MSG placed in a msg2 lane: must fail
    wrong_msg = _request(sks, h, [1, 2])
    verdicts = device.fetch(
        device.dispatch_multi(
            [
                (MSG, None, *good_m1),
                (msg2, None, *good_m2),
                (MSG, None, *good_m1b),
                (msg2, None, *wrong_msg),
            ]
        )
    )
    assert verdicts == [True, True, True, False]
    assert device.multi_msg_launches == 1
    # uniform-message batches keep the ordinary dispatch path (no extra
    # kernel variant, cached (L, 1) h)
    before = device.multi_msg_launches
    verdicts = device.fetch(
        device.dispatch_multi(
            [(MSG, None, *good_m1), (MSG, None, *good_m1b)]
        )
    )
    assert verdicts == [True, True]
    assert device.multi_msg_launches == before


def test_warmup_multi_msg_compiles_variant(setup):
    """warmup(multi_msg=True) pre-compiles the per-lane-h range variant so
    a service's first coalesced launch never stalls on XLA."""
    device, sks, h = setup
    n_before = device.multi_msg_launches
    launches = device.warmup(multi_msg=True)
    assert launches >= 4
    assert device.multi_msg_launches == n_before + 1
    assert device.host_pack_launches == 0  # warmup resets host counters

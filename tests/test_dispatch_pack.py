"""Vectorized launch packing: equivalence with the per-candidate oracle.

The device packer (`BN254Device._pack_requests`) builds every launch input —
range bounds, missing-signer patch, dense mask, packed signature limbs —
with array-at-once numpy ops over the batch. It must be BIT-IDENTICAL to
the old per-candidate loop (`_pack_requests_loop`, kept as the oracle) for
every signer-set shape: contiguous ranges, ranges with holes in both
quantization classes, scattered sets past the MISS_CAP, empty bitsets,
point-less signatures, and partial batches.

Fast tier: packing is pure host numpy — nothing here compiles a kernel.
"""

import random

import numpy as np
import pytest

from handel_tpu import native as nat
from handel_tpu.core.bitset import BitSet
from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature
from handel_tpu.models.bn254_jax import BN254Device
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field

N = 130  # > MISS_CAP + 3 so the dense fallback class is reachable
C = 8


@pytest.fixture(scope="module", params=["per_candidate", "rlc"])
def device(request):
    """Both batch-check modes (models/rlc.py): launch packing is shared
    between the per-candidate and RLC launch classes, so every equivalence
    property below must hold identically under either device mode."""
    rng = random.Random(11)
    sks = [rng.randrange(1, 1 << 20) for _ in range(N)]
    pks = [BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * N, sks)]
    return BN254Device(pks, batch_size=C, batch_check=request.param)


def _rand_request(rng, kind):
    bs = BitSet(N)
    if kind == "empty":
        return (bs, BN254Signature(bn.G1_GEN))
    if kind == "nosig":
        for i in rng.sample(range(N), 5):
            bs.set(i, True)
        return (bs, object())  # no .point: lane must be masked out
    max_holes = {"range8": 9, "range64": 60, "dense": None}[kind]
    size = rng.randrange(1, N)
    lo = rng.randrange(0, N - size + 1)
    n_holes = rng.randrange(0, size if max_holes is None else min(size, max_holes))
    holes = set(rng.sample(range(lo, lo + size), n_holes))
    holes.discard(lo)  # keep the hull anchored so hole counts stay exact
    holes.discard(lo + size - 1)
    for i in range(lo, lo + size):
        if i not in holes:
            bs.set(i, True)
    return (bs, BN254Signature(bn.G1_GEN))


def _mask_of(plan):
    """Dense candidate mask of a plan in (n, C) layout, whichever source
    the plan carries: the loop oracle's host-built `mask`, or the
    vectorized plan's packed `words` (the device-transfer source — the
    kernel unpacks it on device with the same bit semantics)."""
    if plan.mask is not None:
        return np.asarray(plan.mask)
    bits = np.unpackbits(
        np.asarray(plan.words).view(np.uint8),
        axis=1,
        count=N,
        bitorder="little",
    ).view(np.bool_)
    return (bits & np.asarray(plan.valid)[:, None]).T


def _assert_plans_equal(a, b, ctx):
    assert a.kind == b.kind, ctx
    assert a.miss_k == b.miss_k, ctx
    for f in ("lo", "hi", "miss_idx", "miss_ok", "valid"):
        x, y = getattr(a, f), getattr(b, f)
        assert (x is None) == (y is None), (ctx, f)
        if x is not None:
            x, y = np.asarray(x), np.asarray(y)
            assert x.dtype == y.dtype, (ctx, f, x.dtype, y.dtype)
            assert x.shape == y.shape and (x == y).all(), (ctx, f)
    if a.kind == "dense":
        ma, mb = _mask_of(a), _mask_of(b)
        assert ma.shape == mb.shape and (ma == mb).all(), (ctx, "mask")
    for f in ("sig_x", "sig_y"):
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert x.dtype == y.dtype and (x == y).all(), (ctx, f)


_SNAP_FIELDS = ("lo", "hi", "miss_idx", "miss_ok", "words", "mask", "valid",
                "sig_x", "sig_y")


def _snap(plan):
    """Deep-copy a plan out of its staging views."""
    return plan._replace(
        **{
            f: np.asarray(getattr(plan, f)).copy()
            for f in _SNAP_FIELDS
            if getattr(plan, f) is not None
        }
    )


def test_pack_requests_matches_loop_property(device):
    """Random batches across all request shapes: the vectorized packer and
    the per-candidate loop must produce bit-identical device inputs."""
    rng = random.Random(23)
    kinds = ["empty", "nosig", "range8", "range64", "dense"]
    for trial in range(120):
        reqs = [
            _rand_request(rng, rng.choice(kinds))
            for _ in range(rng.randrange(1, C + 1))
        ]
        vec = _snap(device._pack_requests(reqs))
        loop = device._pack_requests_loop(reqs)
        _assert_plans_equal(vec, loop, trial)


def test_pack_requests_rotation_boundary_property(device):
    """The double-buffered staging contract: across streams of consecutive
    launches, a plan's views must stay bit-identical to the loop oracle
    until the rotation wraps back onto its staging set — i.e. plan k is
    still valid while plan k+1 is packed, and is only invalidated by plan
    k + stage_sets. Verification is deliberately DEFERRED one launch: plan
    k is checked against the oracle after pack k+1 ran, unsnapshotted, so
    any buffer sharing between adjacent launches would corrupt it."""
    rng = random.Random(41)
    kinds = ["empty", "nosig", "range8", "range64", "dense"]
    assert device.stage_sets >= 2  # the contract under test
    for trial in range(25):
        streams = [
            [
                _rand_request(rng, rng.choice(kinds))
                for _ in range(rng.randrange(1, C + 1))
            ]
            for _ in range(3 + trial % 3)  # >= 3 consecutive launches
        ]
        prev = None  # (reqs, live unsnapshotted plan)
        for reqs in streams:
            plan = device._pack_requests(reqs)
            if prev is not None:
                # the PREVIOUS plan's views survived this pack (other set)
                _assert_plans_equal(
                    _snap(prev[1]),
                    device._pack_requests_loop(prev[0]),
                    trial,
                )
            prev = (reqs, plan)
        _assert_plans_equal(
            _snap(prev[1]), device._pack_requests_loop(prev[0]), trial
        )


def test_pack_requests_class_selection(device):
    """The two range quantization classes and the dense fallback trigger at
    the same thresholds as the old loop: <=8 holes -> miss_k=8, <=64 ->
    miss_k=64, >64 -> dense."""
    sig = BN254Signature(bn.G1_GEN)

    def req_with_holes(n_holes):
        bs = BitSet(N)
        width = n_holes + 2
        for i in range(width):
            bs.set(i, True)
        for i in range(1, 1 + n_holes):
            bs.set(i, False)
        return (bs, sig)

    for n_holes, kind, miss_k in ((0, "range", 8), (8, "range", 8),
                                  (9, "range", 64), (64, "range", 64),
                                  (65, "dense", 0)):
        plan = device._pack_requests([req_with_holes(n_holes)])
        assert (plan.kind, plan.miss_k) == (kind, miss_k), n_holes


def test_pack_requests_rejects_wrong_length(device):
    bs = BitSet(N + 1)
    bs.set(0, True)
    with pytest.raises(ValueError, match="bitset length"):
        device._pack_requests([(bs, BN254Signature(bn.G1_GEN))])
    with pytest.raises(ValueError, match="bitset length"):
        device._pack_requests_loop([(bs, BN254Signature(bn.G1_GEN))])


def test_field_pack_batch_matches_pack():
    """The array-at-once limb packer is bit-identical to the per-element
    reference for random field elements, in and out of Montgomery form."""
    F = Field(bn.P)
    rng = random.Random(7)
    xs = [rng.randrange(0, bn.P) for _ in range(64)] + [0, 1, bn.P - 1]
    for mont in (True, False):
        a = np.asarray(F.pack(xs, mont=mont))
        b = np.asarray(F.pack_batch(xs, mont=mont))
        assert a.dtype == b.dtype and a.shape == b.shape
        assert (a == b).all()


def test_batch_verify_bounds_dispatch_window(device, monkeypatch):
    """batch_verify never runs more than MAX_DISPATCH_AHEAD chunks ahead of
    the fetch cursor (ADVICE r5 #3: an unbounded window kept every chunk's
    upload buffers resident on device simultaneously)."""
    in_flight = {"now": 0, "max": 0}
    serial = iter(range(1000))

    def fake_dispatch(msg, reqs):
        in_flight["now"] += 1
        in_flight["max"] = max(in_flight["max"], in_flight["now"])
        return ("h", next(serial), len(reqs))

    def fake_fetch(handle):
        in_flight["now"] -= 1
        return [True] * handle[2]

    monkeypatch.setattr(device, "dispatch", fake_dispatch)
    monkeypatch.setattr(device, "fetch", fake_fetch)
    bs = BitSet(N)
    bs.set(0, True)
    reqs = [(bs, BN254Signature(bn.G1_GEN))] * (C * 12)
    out = device.batch_verify(b"m", reqs)
    assert len(out) == C * 12
    assert in_flight["max"] <= device.MAX_DISPATCH_AHEAD
    assert in_flight["now"] == 0


def test_batch_check_mode_validated_and_routed(device):
    """The device carries its validated check mode; rlc-mode dispatch
    returns the rlc handle shape without compiling anything when the
    launch has at most one valid candidate (no combined pre-launch)."""
    assert device.batch_check in ("per_candidate", "rlc")
    with pytest.raises(ValueError, match="per_candidate.*rlc"):
        BN254Device(
            [BN254PublicKey(bn.G2_GEN)], batch_size=1, batch_check="bogus"
        )
    if device.batch_check != "rlc":
        return
    bs = BitSet(N)  # empty bitset: candidate invalid, nothing pre-launched
    handle = device.dispatch(b"m", [(bs, BN254Signature(bn.G1_GEN))])
    assert handle[0] == "rlc" and handle[3] is None
    assert device.fetch(handle) == [False]

"""JAX curve ops vs the scalar oracle (tests mirror the role of the
reference's bn256 sign/combine unit tests, bn256/*/bn256_test.go:39-99)."""

import random

import numpy as np
import pytest

# slow tier: XLA-compile-bound (curve op graphs) — runs in
# test-slow/test-all (nightly/CI); the fast tier keeps the oracle +
# protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BN254Curves

random.seed(0xC04FE)


@pytest.fixture(scope="module")
def curves():
    return BN254Curves()


def _rand_g1(k=None):
    k = k if k is not None else random.randrange(1, bn.R)
    return bn.g1_mul(bn.G1_GEN, k)


def _rand_g2(k=None):
    k = k if k is not None else random.randrange(1, bn.R)
    return bn.g2_mul(bn.G2_GEN, k)


def test_g1_add_batch(curves):
    B = 8
    ps = [_rand_g1() for _ in range(B)]
    qs = [_rand_g1() for _ in range(B)]
    # exercise the complete-formula corner cases in-lane
    qs[0] = ps[0]  # doubling
    qs[1] = bn.g1_neg(ps[1])  # inverse -> infinity
    ps[2] = None  # left identity
    qs[3] = None  # right identity
    out = curves.g1.add(curves.pack_g1(ps), curves.pack_g1(qs))
    got = curves.unpack_g1(out)
    want = [bn.g1_add(p, q) for p, q in zip(ps, qs)]
    assert got == want


def test_g2_add_batch(curves):
    B = 6
    ps = [_rand_g2() for _ in range(B)]
    qs = [_rand_g2() for _ in range(B)]
    qs[0] = ps[0]
    qs[1] = bn.g2_neg(ps[1])
    ps[2] = None
    out = curves.g2.add(curves.pack_g2(ps), curves.pack_g2(qs))
    got = curves.unpack_g2(out)
    want = [bn.g2_add(p, q) for p, q in zip(ps, qs)]
    assert got == want


def test_g1_scalar_mul(curves):
    ks = [1, 2, 3, random.randrange(bn.R), bn.R - 1, 0, 7, 1 << 200]
    P = curves.pack_g1([bn.G1_GEN] * len(ks))
    bits = curves.scalar_bits(ks)
    got = curves.unpack_g1(curves.g1.scalar_mul(P, bits))
    want = [bn.g1_mul(bn.G1_GEN, k) for k in ks]
    assert got == want


def test_g2_scalar_mul(curves):
    ks = [1, 5, random.randrange(bn.R), 0]
    P = curves.pack_g2([bn.G2_GEN] * len(ks))
    bits = curves.scalar_bits(ks)
    got = curves.unpack_g2(curves.g2.scalar_mul(P, bits))
    want = [bn.g2_mul(bn.G2_GEN, k) for k in ks]
    assert got == want


@pytest.mark.parametrize("n", [1, 2, 3, 7, 16])
def test_g1_sum_points(curves, n):
    b = 4
    pts = [[_rand_g1() for _ in range(b)] for _ in range(n)]
    pts[0][0] = None  # infinity inside the tree
    flat = [p for block in pts for p in block]
    P = curves.pack_g1(flat)
    got = curves.unpack_g1(curves.g1.sum_points(P, n))
    want = []
    for j in range(b):
        acc = None
        for i in range(n):
            acc = bn.g1_add(acc, pts[i][j])
        want.append(acc)
    assert got == want


def test_g2_masked_sum(curves):
    n, b = 8, 2
    pts = [[_rand_g2() for _ in range(b)] for _ in range(n)]
    mask = np.array([bool(random.getrandbits(1)) for _ in range(n * b)])
    flat = [p for block in pts for p in block]
    P = curves.pack_g2(flat)
    import jax.numpy as jnp

    got = curves.unpack_g2(curves.g2.masked_sum(P, jnp.asarray(mask), n))
    want = []
    for j in range(b):
        acc = None
        for i in range(n):
            if mask[i * b + j]:
                acc = bn.g2_add(acc, pts[i][j])
        want.append(acc)
    assert got == want


def test_eq_and_on_curve(curves):
    ps = [_rand_g1() for _ in range(4)] + [None]
    P = curves.pack_g1(ps)
    # P == P (incl. infinity lane)
    assert bool(np.asarray(curves.g1.eq(P, P)).all())
    # scaled projective coordinates still equal
    two = curves.F.constant(2, len(ps))
    P2 = tuple(curves.F.mul(c, two) for c in P)
    assert bool(np.asarray(curves.g1.eq(P, P2)).all())
    assert bool(np.asarray(curves.g1.on_curve(P)).all())
    bad = (P[1], P[0], P[2])  # swap x/y: not on curve (generic points)
    assert not np.asarray(curves.g1.on_curve(bad))[:4].any()


def test_g2_on_curve(curves):
    qs = [_rand_g2() for _ in range(3)] + [None]
    Q = curves.pack_g2(qs)
    assert bool(np.asarray(curves.g2.on_curve(Q)).all())

"""Geo-federation + open-loop load tests (ISSUE 18).

The failure lattice for service/federation.py and sim/load.py: RTT
lookup against the planet presets, deterministic nearest-first routing,
capped-exponential retry backoff, spill-over with one region down,
bounded attributed shed with every region refusing, recovery
re-admission through the probe map and the epoch path, `[load]` /
`[federation]` TOML round-trips, the `sim watch` federation row, the
seeded arrival models, and a short end-to-end LoadRun with the kill
drill — plus a regression for the shared report-check specs
(sim/report_checks.py) the soak and federation reports both stamp.
"""

from __future__ import annotations

import asyncio

import pytest

from handel_tpu.core.metrics import MetricsRegistry, parse_exposition
from handel_tpu.network.geo import GeoConfig
from handel_tpu.scenario.planets import planet_preset
from handel_tpu.service.federation import Federation, RegionShedding
from handel_tpu.sim.config import (
    FederationParams,
    LoadParams,
    SimConfig,
    dump_config,
    load_config,
)


def run(coro):
    return asyncio.run(coro)


def _fast_params(**kw) -> FederationParams:
    """CI-speed federation: tiny RTTs, tiny retry waits, small registry."""
    base = dict(
        planet="planet-3region-fast",
        retry_base_ms=5.0,
        retry_cap_ms=20.0,
        probe_interval_s=0.05,
        session_ttl_s=10.0,
        registry=16,
        trace_capacity=1 << 12,
    )
    base.update(kw)
    return FederationParams(**base)


# -- satellite 1: the public RTT lookup --------------------------------------


def test_geo_rtt_lookup_matches_presets():
    for planet in ("planet-3region", "planet-5region"):
        regions, rtt = planet_preset(planet)
        geo = GeoConfig(regions=regions, rtt_ms=rtt).validate()
        for i, a in enumerate(regions):
            for j, b in enumerate(regions):
                # by name, by index, and mixed all read the same cell
                assert geo.rtt(a, b) == rtt[i][j]
                assert geo.rtt(i, j) == rtt[i][j]
                assert geo.rtt(a, j) == rtt[i][j]
                # the presets are symmetric matrices
                assert geo.rtt(a, b) == geo.rtt(b, a)


def test_geo_rtt_lookup_validation():
    regions, rtt = planet_preset("planet-3region")
    geo = GeoConfig(regions=regions, rtt_ms=rtt).validate()
    with pytest.raises(ValueError, match="unknown region"):
        geo.rtt("atlantis", "eu-west")
    with pytest.raises(ValueError, match="out of range"):
        geo.rtt(0, 7)
    with pytest.raises(ValueError, match="out of range"):
        geo.rtt(-1, 0)


# -- routing + backoff --------------------------------------------------------


def test_route_order_nearest_first_and_deterministic():
    fed = Federation(_fast_params())
    fd = fed.front_door
    # planet-3region-fast RTTs: eu<->us 8ms, eu<->ap 22ms, us<->ap 17ms
    assert fd.route_order("eu-west") == ["eu-west", "us-east", "ap-east"]
    assert fd.route_order("us-east") == ["us-east", "eu-west", "ap-east"]
    assert fd.route_order("ap-east") == ["ap-east", "us-east", "eu-west"]
    # a second build from the same params routes identically
    fed2 = Federation(_fast_params())
    for origin in fed.region_names():
        assert (fed.front_door.route_order(origin)
                == fed2.front_door.route_order(origin))
    # marking a region down removes it; marking up restores the order
    fd.mark("us-east", False)
    assert fd.route_order("eu-west") == ["eu-west", "ap-east"]
    fd.mark("us-east", True)
    assert fd.route_order("eu-west") == ["eu-west", "us-east", "ap-east"]


def test_backoff_capped_exponential():
    fed = Federation(
        _fast_params(retry_base_ms=50.0, retry_cap_ms=400.0)
    )
    fd = fed.front_door
    assert [fd.backoff_ms(a) for a in range(6)] == [
        50.0, 100.0, 200.0, 400.0, 400.0, 400.0
    ]


# -- the failure lattice ------------------------------------------------------


def test_spillover_when_nearest_region_down():
    async def go():
        fed = Federation(_fast_params())
        fed.start()
        try:
            fed.kill_region("eu-west")
            outcome, s, plane, _ = await fed.submit(
                "eu-west", nodes=4, tier="gold", seed=1
            )
            assert outcome == "admitted"
            # spilled to the next region by RTT from eu-west
            assert plane.name == "us-east"
            assert fed.front_door.spillovers == 1
            assert plane.spill_in == 1
            # the misroute marked the dead region down passively —
            # no probe round needed
            assert fed.front_door.health["eu-west"] is False
            while not s.finished:
                await asyncio.sleep(0.01)
        finally:
            await fed.stop()

    run(go())


def test_all_regions_dead_fails_with_attribution():
    async def go():
        p = _fast_params(retry_budget=2)
        fed = Federation(p)
        fed.start()
        try:
            for name in fed.region_names():
                fed.kill_region(name)
            outcome, s, plane, attempts = await fed.submit(
                "us-east", nodes=4, tier="gold", seed=2
            )
            assert outcome == "failed" and s is None and plane is None
            assert attempts == p.retry_budget
            assert fed.front_door.failures == 1
            assert fed.front_door.retries == p.retry_budget
        finally:
            await fed.stop()

    run(go())


def test_all_regions_shedding_classified_as_shed(monkeypatch):
    async def go():
        p = _fast_params(retry_budget=2)
        fed = Federation(p)
        fed.start()
        try:
            monkeypatch.setattr(
                "handel_tpu.service.federation.RegionPlane.shedding",
                lambda self, tier: True,
            )
            outcome, s, _, attempts = await fed.submit(
                "ap-east", nodes=4, tier="bronze", seed=3
            )
            # every region at its shed bound through the whole retry
            # budget is a SHED, not a failure — bounded, attributed
            assert outcome == "shed" and s is None
            assert attempts == p.retry_budget
            assert fed.front_door.sheds == 1
            assert fed.front_door.failures == 0
        finally:
            await fed.stop()

    run(go())


def test_region_shed_bound_refuses_session(monkeypatch):
    fed = Federation(_fast_params())
    plane = fed.by_name["eu-west"]
    monkeypatch.setattr(
        type(plane.cluster.service.queue), "__len__", lambda self: 10**6
    )
    with pytest.raises(RegionShedding):
        plane.admit(nodes=4, tier="gold", seed=4)
    assert plane.sheds == 1


def test_kill_recover_readmission_via_epoch_path():
    async def go():
        fed = Federation(_fast_params())
        fed.start()
        try:
            fd = fed.front_door
            assert fed.epoch == 0
            fed.kill_region("ap-east")
            fd.probe_now()
            assert fd.health["ap-east"] is False
            assert "ap-east" not in fd.route_order("ap-east")
            assert fed.values()["regionsHealthy"] == 2.0

            stall = await fed.recover_region("ap-east")
            assert stall >= 0.0
            # the rejoin IS an epoch rotation: every healthy region
            # flipped together and the federation epoch advanced
            assert fed.epoch == 1
            for plane in fed.planes:
                assert plane.cluster.manager.epoch == 1
            fd.probe_now()
            assert fd.health["ap-east"] is True
            assert fd.route_order("ap-east")[0] == "ap-east"
            # and the revived region ADMITS again
            outcome, s, plane, _ = await fed.submit(
                "ap-east", nodes=4, tier="gold", seed=5
            )
            assert outcome == "admitted" and plane.name == "ap-east"
            while not s.finished:
                await asyncio.sleep(0.01)
        finally:
            await fed.stop()

    run(go())


def test_kill_returns_interrupted_live_sids():
    async def go():
        fed = Federation(_fast_params())
        fed.start()
        try:
            outcome, s, plane, _ = await fed.submit(
                "eu-west", nodes=64, tier="gold", seed=6
            )
            assert outcome == "admitted" and plane.name == "eu-west"
            live = fed.kill_region("eu-west")
            assert s.sid in live
            assert plane.stats()["regionHealthy"] == 0.0
            assert plane.stats()["sessionsLive"] == 0.0
        finally:
            await fed.stop()

    run(go())


# -- TOML round-trips ---------------------------------------------------------


def test_load_federation_toml_round_trip(tmp_path):
    cfg = SimConfig()
    cfg.load = LoadParams(
        rate_sps=7.5, duration_s=33.0, model="diurnal", seed=9,
        nodes=12, deadline_s=4.0, tiers="gold,silver",
        diurnal_amplitude=0.3, diurnal_period_s=20.0,
    )
    cfg.federation = FederationParams(
        planet="planet-3region-fast", devices=2, batch_size=16,
        queue_capacity=128, kill_region="us-east",
        kill_at_frac=0.25, recover_at_frac=0.5,
        retry_base_ms=10.0, retry_cap_ms=80.0, retry_budget=3,
    )
    path = tmp_path / "load.toml"
    path.write_text(dump_config(cfg))
    back = load_config(str(path))
    assert back.load == cfg.load
    assert back.federation == cfg.federation


def test_load_toml_validation(tmp_path):
    bad_model = tmp_path / "bad_model.toml"
    bad_model.write_text("[load]\nrate_sps = 1.0\nmodel = \"lunar\"\n")
    with pytest.raises(ValueError, match="load.model"):
        load_config(str(bad_model))
    bad_kill = tmp_path / "bad_kill.toml"
    bad_kill.write_text(
        "[federation]\nkill_region = \"us-east\"\n"
        "kill_at_frac = 0.8\nrecover_at_frac = 0.4\n"
    )
    with pytest.raises(ValueError, match="kill_at_frac"):
        load_config(str(bad_kill))
    bad_retry = tmp_path / "bad_retry.toml"
    bad_retry.write_text(
        "[federation]\nretry_base_ms = 100.0\nretry_cap_ms = 10.0\n"
    )
    with pytest.raises(ValueError, match="retry_cap_ms"):
        load_config(str(bad_retry))


# -- satellite 2: the `sim watch` federation row ------------------------------


def test_watch_federation_row():
    from handel_tpu.sim import watch_cli

    fed = Federation(_fast_params())
    fed.by_name["us-east"].killed = True
    reg = MetricsRegistry()
    reg.register_values("federation", fed)
    reg.register_labeled_values(
        "federation", fed, label="region",
        gauges=fed.labeled_gauge_keys(),
    )
    fams = parse_exposition(reg.exposition())
    model = watch_cli.aggregate([fams])
    assert model["fed_regions_total"] == 3.0
    assert model["fed_regions_healthy"] == 2.0
    assert set(model["regions"]) == {"eu-west", "us-east", "ap-east"}
    assert model["regions"]["us-east"]["healthy"] == 0.0
    frame = watch_cli.render(model, ["127.0.0.1:1"], up=1, tick=1)
    assert "federation  regions 2/3 healthy" in frame
    assert "us-east DOWN" in frame
    assert "eu-west up" in frame


# -- arrival models -----------------------------------------------------------


def test_arrival_offsets_seeded_and_in_window():
    from handel_tpu.sim.load import arrival_offsets

    p = LoadParams(rate_sps=20.0, duration_s=10.0, seed=3)
    a = arrival_offsets(p)
    assert a == arrival_offsets(p)  # same seed, same clock
    assert a != arrival_offsets(
        LoadParams(rate_sps=20.0, duration_s=10.0, seed=4)
    )
    assert all(0.0 <= t < p.duration_s for t in a)
    assert a == sorted(a)
    # LLN at 200 expected arrivals: within a loose band
    assert 120 < len(a) < 300


def test_rate_at_models():
    from handel_tpu.sim.load import peak_rate, rate_at

    diurnal = LoadParams(
        rate_sps=10.0, model="diurnal", diurnal_amplitude=0.5,
        diurnal_period_s=40.0,
    )
    assert rate_at(diurnal, 0.0) == pytest.approx(10.0)
    assert rate_at(diurnal, 10.0) == pytest.approx(15.0)  # sin peak
    assert rate_at(diurnal, 30.0) == pytest.approx(5.0)  # trough
    assert peak_rate(diurnal) == pytest.approx(15.0)

    burst = LoadParams(
        rate_sps=10.0, model="burst", burst_every_s=10.0,
        burst_x=4.0, burst_len_s=2.0,
    )
    assert rate_at(burst, 1.0) == pytest.approx(40.0)  # inside the window
    assert rate_at(burst, 5.0) == pytest.approx(10.0)  # between bursts
    assert rate_at(burst, 11.5) == pytest.approx(40.0)  # next window
    assert peak_rate(burst) == pytest.approx(40.0)


def test_burst_model_concentrates_arrivals():
    from handel_tpu.sim.load import arrival_offsets

    p = LoadParams(
        rate_sps=10.0, duration_s=40.0, model="burst", seed=11,
        burst_every_s=10.0, burst_x=6.0, burst_len_s=2.0,
    )
    a = arrival_offsets(p)
    in_burst = sum(1 for t in a if (t % 10.0) < 2.0)
    # burst windows are 20% of the wall but 6x the rate: they must carry
    # well over half the arrivals
    assert in_burst / len(a) > 0.5


# -- end-to-end: a short open-loop run with the kill drill --------------------


def test_load_run_e2e_with_kill_drill(tmp_path):
    from handel_tpu.sim.load import run_load

    lp = LoadParams(
        rate_sps=6.0, duration_s=6.0, nodes=4, seed=2, deadline_s=5.0
    )
    fp = _fast_params(
        kill_region="us-east", kill_at_frac=0.3, recover_at_frac=0.6,
        # the session spans of even a short run outnumber the smoke ring;
        # keep the early kill instants resident for the trace assertions
        trace_capacity=1 << 16,
    )
    report = run(run_load(lp, fp, str(tmp_path)))
    assert report["ok"], report["checks"]
    fed = report["federation"]
    assert fed["unaccounted"] == 0 and fed["unresolved"] == 0
    assert fed["arrivals"] == (
        fed["completed"] + fed["shed"] + fed["failed"] + fed["expired"]
    )
    kill = fed["kill"]
    assert kill["killed_at_s"] is not None
    assert kill["unhealthy_detected_s"] >= kill["killed_at_s"]
    assert kill["recovery_s"] is not None
    assert kill["post_recovery_completed"] > 0
    # SIDE_METRICS keys sit flat on the record for bench_check
    for key in ("open_loop_p99_s", "region_recovery_s", "spillover_rate"):
        assert isinstance(report[key], (int, float))
    assert (tmp_path / "federation_report.json").exists()
    assert (tmp_path / "trace_federation.json").exists()
    # the trace carries region-tagged federation spans for
    # `sim trace --critical-path` attribution
    import json

    events = json.loads(
        (tmp_path / "trace_federation.json").read_text()
    )["traceEvents"]
    fed_events = {
        e["name"] for e in events if e.get("cat") == "federation"
    }
    assert "region_kill" in fed_events
    assert "region_recover" in fed_events
    assert "frontdoor_route" in fed_events


# -- the shared report-check specs (rode-along refactor) ----------------------


def test_report_checks_helper():
    from handel_tpu.sim.report_checks import (
        Check,
        assert_checks,
        attach,
        evaluate,
    )

    checks = [
        Check("has_x", lambda r: r.get("x", 0) > 0, lambda r: "x > 0"),
        Check("has_y", lambda r: "y" in r, lambda r: "y present"),
    ]
    good = attach({"x": 1, "y": 2}, checks)
    assert good["checks"] == {"has_x": True, "has_y": True}
    assert good["ok"] is True
    assert_checks(good, checks)

    bad = attach({"x": 0}, checks)
    assert bad["ok"] is False
    assert evaluate(bad, checks) == {"has_x": False, "has_y": False}
    with pytest.raises(AssertionError, match="has_x"):
        assert_checks(bad, checks)


def test_federation_checks_vacuous_without_kill():
    from handel_tpu.sim.report_checks import FEDERATION_CHECKS, evaluate

    report = {
        "shed_rate": 0.0,
        "federation": {
            "unaccounted": 0, "unresolved": 0, "spillovers": 0,
            "shed_ceiling": 0.15, "tiers": {"gold": {"met": 1.0}},
            "kill": None,
        },
    }
    got = evaluate(report, FEDERATION_CHECKS)
    # no kill drill configured: the kill-lattice checks pass vacuously,
    # the always-on invariants still bind
    assert all(got.values()), got

"""Real-transport tests: two sockets exchanging packets.

Reference model: network/udp/net_test.go:12-31 and tcp/net_test.go:12-36 (two
endpoints, one packet each way), plus counter assertions for the byte-counting
decorator (counter_encoding.go).
"""

import asyncio

import pytest

from handel_tpu.core.identity import Identity
from handel_tpu.core.net import Packet
from handel_tpu.network import (
    BinaryEncoding,
    CounterEncoding,
    TCPNetwork,
    UDPNetwork,
)


class ChanListener:
    def __init__(self):
        self.packets: asyncio.Queue = asyncio.Queue()

    def new_packet(self, packet: Packet) -> None:
        self.packets.put_nowait(packet)


def _mk_packet(origin: int) -> Packet:
    return Packet(origin=origin, level=3, multisig=b"\x01\x02\x03", individual_sig=b"\x09")


def _free_ports(n: int) -> list[int]:
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.parametrize("net_cls", [UDPNetwork, TCPNetwork])
def test_two_node_exchange(net_cls):
    async def go():
        p1, p2 = _free_ports(2)
        a = net_cls(f"127.0.0.1:{p1}", encoding=CounterEncoding())
        b = net_cls(f"127.0.0.1:{p2}", encoding=CounterEncoding())
        la, lb = ChanListener(), ChanListener()
        a.register_listener(la)
        b.register_listener(lb)
        await a.start()
        await b.start()
        try:
            ident_b = Identity(1, f"127.0.0.1:{p2}", None)
            ident_a = Identity(0, f"127.0.0.1:{p1}", None)
            a.send([ident_b], _mk_packet(0))
            got = await asyncio.wait_for(lb.packets.get(), 5.0)
            assert got.origin == 0 and got.multisig == b"\x01\x02\x03"
            b.send([ident_a], _mk_packet(1))
            got = await asyncio.wait_for(la.packets.get(), 5.0)
            assert got.origin == 1 and got.individual_sig == b"\x09"
            # give fire-and-forget counters a beat to settle
            await asyncio.sleep(0.05)
            assert a.values()["sentPackets"] >= 1
            assert a.values()["rcvdPackets"] >= 1
            assert a.values()["sentBytes"] > 0
            assert b.values()["rcvdBytes"] > 0
        finally:
            a.stop()
            b.stop()

    asyncio.run(go())


def test_udp_malformed_datagram_ignored():
    async def go():
        (p1,) = _free_ports(1)
        a = UDPNetwork(f"127.0.0.1:{p1}")
        lst = ChanListener()
        a.register_listener(lst)
        await a.start()
        try:
            import socket

            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.sendto(b"\x00", ("127.0.0.1", p1))  # too short to parse
            s.close()
            # follow with a valid packet; the bad one must not kill dispatch
            b = UDPNetwork(f"127.0.0.1:{_free_ports(1)[0]}")
            await b.start()
            b.send([Identity(1, f"127.0.0.1:{p1}", None)], _mk_packet(7))
            got = await asyncio.wait_for(lst.packets.get(), 5.0)
            assert got.origin == 7
            b.stop()
        finally:
            a.stop()

    asyncio.run(go())


def test_counter_encoding_standalone():
    enc = CounterEncoding(BinaryEncoding())
    pkt = _mk_packet(5)
    wire = enc.encode(pkt)
    back = enc.decode(wire)
    assert back.origin == 5
    v = enc.values()
    assert v["sentBytes"] == len(wire) == v["rcvdBytes"]


def test_examples_demo_udp():
    """The network/examples demo: every peer hears from every other
    (network/examples/start.go:35-85)."""
    import asyncio

    from handel_tpu.network.examples import run_demo

    heard = asyncio.run(run_demo(3, "udp"))
    for i, origins in heard.items():
        assert origins == {j for j in range(3) if j != i}

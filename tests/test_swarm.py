"""Virtual-node swarm runtime (ISSUE 11 tentpole: handel_tpu/swarm/)."""

import asyncio
import json
from types import SimpleNamespace

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.net import Packet
from handel_tpu.swarm.driver import (
    SwarmHost,
    _split,
    fake_committee,
    merge_summaries,
)
from handel_tpu.swarm.pager import PagedDevice, RegistryPager
from handel_tpu.swarm.router import SwarmRouter
from handel_tpu.swarm.vnode import SWARM_DEDUP_SCOPE, build_vnode


def run(coro):
    return asyncio.run(coro)


class _Sink:
    def __init__(self):
        self.got = []

    def new_packet(self, p):
        self.got.append(p)


def _idents(*ids):
    return [SimpleNamespace(id=i) for i in ids]


# -- router ----------------------------------------------------------------


def test_router_local_short_circuit_shares_one_packet():
    async def go():
        r = SwarmRouter(block=16)
        sinks = {i: _Sink() for i in (0, 1, 2)}
        for i, s in sinks.items():
            r.register(i, s)
        p = Packet(origin=5, level=1, multisig=b"\x00\x08\xff")
        r.route(_idents(0, 1, 2), p)
        await asyncio.sleep(0)  # call_soon drains on the next loop turn
        for s in sinks.values():
            assert len(s.got) == 1
            assert s.got[0] is p  # the SAME object, no encode/decode
        v = r.values()
        assert v["swarmLocalDelivered"] == 3.0
        assert v["swarmUdpSent"] == 0.0

    run(go())


def test_router_unknown_recipient_counted_not_raised():
    async def go():
        r = SwarmRouter(block=16)  # no ports, no socket
        r.route(_idents(99), Packet(origin=0, level=1, multisig=b""))
        assert r.values()["swarmUnknownRecipient"] == 1.0

    run(go())


def test_router_udp_cross_process_frame():
    """Two routers on real sockets: a packet for the other block rides the
    shared socket with the recipient-id frame and decodes on arrival."""
    from handel_tpu.sim.platform import free_ports

    async def go():
        ports = free_ports(2)
        a = SwarmRouter(block=4, ports=ports)
        b = SwarmRouter(block=4, ports=ports)
        await a.open(ports[0])
        await b.open(ports[1])
        try:
            sink = _Sink()
            b.register(5, sink)  # id 5 // block 4 -> process 1
            p = Packet(origin=0, level=2, multisig=b"\x00\x08\x0f")
            a.route(_idents(5), p)
            for _ in range(50):
                if sink.got:
                    break
                await asyncio.sleep(0.01)
            assert len(sink.got) == 1
            q = sink.got[0]
            assert (q.origin, q.level, q.multisig) == (0, 2, p.multisig)
            assert a.values()["swarmUdpSent"] == 1.0
            assert b.values()["swarmUdpRcvd"] == 1.0
        finally:
            a.close()
            b.close()

    run(go())


def test_router_bad_datagrams_dropped_and_counted():
    r = SwarmRouter(block=4)
    r._on_datagram(b"\x00")  # shorter than the frame header
    r._on_datagram(b"\x00\x00\x00\x63junk")  # unknown recipient 99
    r.register(1, _Sink())
    r._on_datagram(b"\x00\x00\x00\x01\xff")  # undecodable Packet payload
    v = r.values()
    assert v["swarmUdpRcvdBad"] == 2.0
    assert v["swarmUnknownRecipient"] == 1.0


# -- registry pager --------------------------------------------------------


def test_pager_touched_chunks_from_words():
    pager = RegistryPager(chunk_bits=6, budget_chunks=8)  # 64 ids per chunk
    bs = BitSet(512)
    bs.set(0)
    bs.set(70)  # chunk 1
    bs.set(511)  # chunk 7
    assert pager.touched_chunks(bs) == {0, 1, 7}


def test_pager_lru_eviction_and_hits():
    committed = []
    pager = RegistryPager(
        chunk_bits=6, budget_chunks=2,
        on_commit=lambda lo, hi: committed.append((lo, hi)),
    )
    pager.ensure({0, 1})
    pager.ensure({0})  # hit, refreshes 0
    pager.ensure({2})  # evicts 1 (LRU), not 0
    assert pager.resident_chunks() == 2
    assert committed == [(0, 64), (64, 128), (128, 192)]
    v = pager.values()
    assert v["pageHits"] == 1.0
    assert v["pagesCommitted"] == 3.0
    assert v["pageEvictions"] == 1.0


def test_paged_device_pages_before_launch():
    class _Engine:
        batch_size = 4

        def __init__(self):
            self.launched = []

        def dispatch_multi(self, items):
            self.launched.append(len(items))
            return "h"

        def fetch(self, handle):
            return [True]

    eng = _Engine()
    pager = RegistryPager(chunk_bits=6, budget_chunks=4)
    dev = PagedDevice(eng, pager)
    bs = BitSet(256)
    bs.set(100)
    assert dev.dispatch_multi([(b"m", None, bs, None)]) == "h"
    assert dev.fetch("h") == [True]
    assert eng.launched == [1]
    assert pager.resident_chunks() == 1  # chunk 1 (ids 64-127)


# -- share splitting / summary merge ---------------------------------------


def test_split_contiguous_shares():
    assert _split(10, 3) == [4, 3, 3]
    assert _split(8, 2) == [4, 4]
    assert _split(3, 5) == [1, 1, 1, 0, 0]
    assert sum(_split(65536, 7)) == 65536


def test_merge_summaries():
    base = {
        "threshold": 3, "vnode_bytes_mean": 100.0, "stale_retired_ct": 0,
        "retired_level_ct": 2, "verifier_launches": 1,
        "verifier_candidates": 2, "dedup_hits": 0,
        "swarmLocalDelivered": 10.0, "swarmUdpSent": 0.0,
        "swarmUdpRcvd": 0.0, "swarmUdpBytesSent": 0.0,
        "pagesCommitted": 1.0, "pageHits": 0.0,
    }
    parts = [
        {**base, "identities": 4, "completed": 4, "rss_bytes": 1000,
         "ttt_max_s": 1.0, "wall_s": 2.0, "ttt_p50_s": 0.5,
         "ttt_p90_s": 0.8},
        {**base, "identities": 4, "completed": 3, "rss_bytes": 1000,
         "ttt_max_s": 2.0, "wall_s": 2.5, "ttt_p50_s": 0.6,
         "ttt_p90_s": 0.9},
    ]
    m = merge_summaries(parts)
    assert m["swarm_identities"] == 8
    assert m["completed"] == 7
    assert m["ok"] is False
    assert m["mem_bytes_per_identity"] == 250.0
    assert m["swarm_time_to_threshold_s"] == 2.0
    assert json.dumps(m)  # JSON-serializable whole


# -- vnode wiring ----------------------------------------------------------


def test_build_vnode_swarm_wiring():
    """The knobs the memory budget depends on: windowed store, shared rand,
    no shuffling, member-id session over a committee-wide dedup scope."""
    import random

    from handel_tpu.core.store import WindowedSignatureStore
    from handel_tpu.core.timeout import TimerWheel
    from handel_tpu.parallel.batch_verifier import BatchVerifierService
    from handel_tpu.service.driver import HostDevice

    async def go():
        registry, secrets = fake_committee(16)
        from handel_tpu.models.fake import FakeConstructor

        cons = FakeConstructor()
        router = SwarmRouter(block=16)
        wheel = TimerWheel(tick_s=0.01)
        service = BatchVerifierService(HostDevice(cons, batch_size=4))
        shared = random.Random(0)
        v = build_vnode(
            registry.identity(3), secrets[3], registry, cons, b"m",
            router, wheel, service,
            threshold=9, update_period=0.05, level_timeout=0.05,
            shared_rand=shared, fast_path=2,
        )
        h = v.handel
        assert h.c.session == "3"
        assert h.c.disable_shuffling is True
        assert h.c.fast_path == 2
        assert h.c.rand is shared
        assert isinstance(h.store, WindowedSignatureStore)
        assert h.scorer is None or not h.c.penalize_peers
        assert router.local.get(3) is h  # listener registered under our id
        service.stop()

    run(go())


def test_swarm_dedup_scope_shared():
    assert SWARM_DEDUP_SCOPE == "swarm"


# -- end-to-end single-process host ----------------------------------------


def test_swarm_host_small_committee_completes():
    async def go():
        host = SwarmHost(64, 0, 64, update_period=0.5)
        s = await host.run(timeout=30.0)
        assert s["completed"] == 64
        assert s["identities"] == 64
        assert s["ttt_max_s"] > 0.0
        assert s["retired_level_ct"] > 0
        assert s["swarmLocalDelivered"] > 0
        assert s["swarmUdpSent"] == 0.0  # single process: all local
        return s

    run(go())


def test_swarm_host_traced_run_streams_report(tmp_path):
    from handel_tpu.sim.trace_cli import stream_report

    async def go():
        host = SwarmHost(
            32, 0, 32, update_period=0.5, trace=True, trace_capacity=1 << 15
        )
        s = await host.run(timeout=30.0)
        assert s["completed"] == 32
        path = host.recorder.dump(str(tmp_path / "swarm_trace_0.json"))
        rep = stream_report([path], top_k=3)
        assert rep["events"] > 0
        assert rep["time_to_threshold_s"] >= 0.0
        assert rep["level_wave"]  # the per-level completion wave
        assert rep["chains"]["count"] > 0

    run(go())


def test_swarm_host_rollup_shape():
    async def go():
        host = SwarmHost(32, 0, 32, update_period=0.5)
        await host.run(timeout=30.0)
        r = host.rollup(top_k=4)
        assert r["vnodes"] == 32
        assert r["unfinished"] == 0
        assert len(r["slowest"]) == 4
        slow = [e["slow_s"] for e in r["slowest"]]
        assert slow == sorted(slow, reverse=True)
        assert "counters" in r and "gauges" in r

    run(go())


# -- barrier release count (sim/sync.py) -----------------------------------


def test_sync_master_small_fleet_needs_everyone():
    """expected=2 must NOT release after one READY: int(2*0.995) floors to
    1 — the ceiling keeps a block from gossiping before its sibling binds."""
    from handel_tpu.sim.sync import STATE_START, SyncMaster

    sent = []
    master = SyncMaster(0, expected=2)
    master._transport = SimpleNamespace(sendto=lambda d, a: sent.append(a))
    master._on_ready(STATE_START, 0, ("127.0.0.1", 1))
    assert not master._event(STATE_START).is_set()
    master._on_ready(STATE_START, 1, ("127.0.0.1", 2))
    assert master._event(STATE_START).is_set()

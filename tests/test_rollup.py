"""Hierarchical roll-up tests (ISSUE 20): LogHistogram merge
order-invariance, two-level (host -> master) merge == flat merge
bit-for-bit including the chunked sparse-wire path, digest boundedness at
4,096 vnodes, delta idempotence under UDP redelivery, the
AlertPlane-from-rollups host-kill drill (exactly one incident with host
attribution), the cardinality cap with its explicit `_overflow` row, the
/fleet endpoint, the `sim watch` fleet block, and the [alerts] config
round trip for the new roll-up knobs."""

from __future__ import annotations

import json
import random
import urllib.error
import urllib.request

import pytest

from handel_tpu.core.metrics import (
    MetricsRegistry,
    MetricsServer,
    parse_exposition,
)
from handel_tpu.core.trace import LogHistogram
from handel_tpu.obs import AlertPlane, BurnRule
from handel_tpu.obs.rollup import (
    MAX_DATAGRAM,
    FleetRollup,
    HostRollup,
    chunk_delta,
    merge_trace_digests,
    trace_digest,
)


def _exact(rng: random.Random) -> float:
    """Values on the 1/1024 grid are exactly representable, so float sums
    are associative and bit-for-bit equality across merge orders holds."""
    return rng.randrange(1, 1 << 20) / 1024.0


# -- satellite 2: merge order-invariance + two-level == flat ------------------


def test_loghistogram_merge_order_invariant():
    rng = random.Random(11)
    parts = []
    for _ in range(8):
        h = LogHistogram()
        for _ in range(rng.randrange(1, 200)):
            h.add(_exact(rng))
        parts.append(h)
    merges = []
    for seed in range(6):
        order = list(range(len(parts)))
        random.Random(seed).shuffle(order)
        m = LogHistogram()
        for i in order:
            m.merge(parts[i])
        merges.append(m)
    ref = merges[0].to_sparse()
    for m in merges[1:]:
        assert m.to_sparse() == ref  # bit-for-bit, not approx


def test_loghistogram_from_sparse_roundtrip():
    h = LogHistogram()
    rng = random.Random(3)
    for _ in range(100):
        h.add(_exact(rng))
    h2 = LogHistogram.from_sparse(h.to_sparse())
    assert h2.to_sparse() == h.to_sparse()
    assert h.copy().to_sparse() == h.to_sparse()


def _mk_surfaces(rng: random.Random, n: int):
    """n vnode-like surfaces sharing one key union (exact values)."""
    out = []
    for _ in range(n):
        out.append((
            {"msgSentCt": _exact(rng), "verifiedCt": _exact(rng),
             "levelRate": _exact(rng)},
            {"levelRate"},
        ))
    return out


def _mk_host(name: str, surfaces, hist_values) -> HostRollup:
    hr = HostRollup(name, clock=lambda: 0.0)
    hr.attach_fold("swarm", lambda: list(surfaces))

    class _Rep:
        def values(self):
            return {"launchesCt": sum(v[0]["msgSentCt"] for v in surfaces)}

        def gauge_keys(self):
            return set()

        def histograms(self):
            h = LogHistogram()
            for v in hist_values:
                h.add(v)
            return {"verifyLatencyS": h}

    hr.attach_reporter("device", _Rep())
    return hr


def test_two_level_merge_equals_flat():
    rng = random.Random(42)
    per_host = [_mk_surfaces(rng, 16) for _ in range(4)]
    per_hist = [[_exact(rng) for _ in range(50)] for _ in range(4)]

    # two-level: one HostRollup per host -> FleetRollup
    fleet = FleetRollup(clock=lambda: 0.0)
    for i in range(4):
        hr = _mk_host(f"h{i}", per_host[i], per_hist[i])
        fleet.ingest_digest(hr.digest())
    two = fleet.merged()

    # flat: every surface folded into ONE HostRollup
    flat_surfaces = [s for hs in per_host for s in hs]
    flat = HostRollup("flat", clock=lambda: 0.0)
    flat.attach_fold("swarm", lambda: list(flat_surfaces))
    fd = flat.digest()

    assert two["counters"]["swarm.msgSentCt"] == fd["counters"][
        "swarm.msgSentCt"]
    assert two["counters"]["swarm.verifiedCt"] == fd["counters"][
        "swarm.verifiedCt"]
    assert two["gauges"]["swarm.levelRate"] == fd["gauges"][
        "swarm.levelRate"]
    # the merged histogram equals a flat merge of the host histograms
    ref = LogHistogram()
    for vals in per_hist:
        for v in vals:
            ref.add(v)
    assert two["hists"]["device.verifyLatencyS"].to_sparse() == (
        ref.to_sparse())


def test_two_level_merge_order_invariant_over_wire():
    """Chunked emission, shuffled + duplicated delivery, any host order:
    the master state is identical to the direct full-digest path."""
    rng = random.Random(7)
    hosts = [
        _mk_host(f"h{i}", _mk_surfaces(rng, 8),
                 [_exact(rng) for _ in range(400)])
        for i in range(3)
    ]
    ref = FleetRollup(clock=lambda: 0.0)
    chunk_sets = []
    for hr in hosts:
        ref.ingest_digest(hr.digest())
        chunk_sets.append(chunk_delta(hr.delta()))
    for seed in range(4):
        srng = random.Random(seed)
        chunks = [c for cs in chunk_sets for c in cs]
        chunks = chunks + srng.sample(chunks, len(chunks) // 2)  # redeliver
        srng.shuffle(chunks)
        fleet = FleetRollup(clock=lambda: 0.0)
        for c in chunks:
            fleet.ingest(json.loads(json.dumps(c)))  # through the wire form
        a, b = fleet.merged(), ref.merged()
        assert a["counters"] == b["counters"]
        assert a["gauges"] == b["gauges"]
        assert {k: h.to_sparse() for k, h in a["hists"].items()} == {
            k: h.to_sparse() for k, h in b["hists"].items()}


def test_sink_chunk_hist_wire_path_reassembles_exactly():
    """The existing sparse-wire chunked path (Sink._chunk_hist): summing
    bucket chunks master-side reassembles the histogram bit-for-bit."""
    from handel_tpu.sim.monitor import _chunk_hist

    h = LogHistogram()
    rng = random.Random(5)
    for _ in range(20000):
        h.add(_exact(rng))
    merged = LogHistogram()
    n_chunks = 0
    for payload in _chunk_hist("node0", "verifyLatencyS", h):
        assert len(json.dumps(payload).encode()) <= MAX_DATAGRAM
        merged.merge_sparse(payload["hists"]["verifyLatencyS"])
        n_chunks += 1
    assert n_chunks >= 1
    assert merged.to_sparse() == h.to_sparse()


# -- satellite 4: digest bounds, idempotence, the drill -----------------------


def test_digest_bounded_at_4096_vnodes():
    """Series count depends on the key union, never the vnode count, and
    every wire chunk respects the UDP budget."""
    counts = {}
    for n in (64, 4096):
        rng = random.Random(9)
        hr = HostRollup(f"host-{n}", clock=lambda: 0.0)
        surfaces = _mk_surfaces(rng, n)
        hr.attach_fold("swarm", lambda: list(surfaces))
        counts[n] = hr.series_count()
        for payload in chunk_delta(hr.delta()):
            assert len(json.dumps(payload).encode()) <= MAX_DATAGRAM
        d = hr.digest()
        assert d["surfaces"] == n
    assert counts[64] == counts[4096] == 3  # O(key-union), not O(vnodes)


def test_delta_redelivery_is_idempotent():
    state = {"v": 0.0}
    hr = HostRollup("h0", clock=lambda: 0.0)
    hr.attach_fold("svc", lambda: [
        ({"workCt": state["v"], "depth": state["v"] / 2.0}, {"depth"})])
    once = FleetRollup(clock=lambda: 0.0)
    twice = FleetRollup(clock=lambda: 0.0)
    for step in range(5):
        state["v"] += 16.0
        chunks = chunk_delta(hr.delta())
        for c in chunks:
            once.ingest(c)
        dup = chunks * 2
        random.Random(step).shuffle(dup)
        for c in dup:
            twice.ingest(c)
    a, b = once.merged(), twice.merged()
    assert a["counters"] == b["counters"]
    assert a["gauges"] == b["gauges"]
    assert twice.stale_drops == 0  # same-seq redelivery is not "stale"


def test_stale_seq_dropped_and_heartbeat_on_quiet_delta():
    state = {"v": 1.0}
    hr = HostRollup("h0", clock=lambda: 0.0)
    hr.attach_fold("svc", lambda: [({"workCt": state["v"]}, set())])
    fleet = FleetRollup(clock=lambda: 0.0)
    first = chunk_delta(hr.delta())
    for c in first:
        fleet.ingest(c, now=1.0)
    state["v"] = 2.0
    for c in chunk_delta(hr.delta()):
        fleet.ingest(c, now=2.0)
    assert fleet.merged()["counters"]["svc.workCt"] == 2.0
    # the stale seq-1 chunk arrives late: dropped, no value regression
    assert fleet.ingest(first[0], now=3.0) is False
    assert fleet.stale_drops == 1
    assert fleet.merged()["counters"]["svc.workCt"] == 2.0
    # an unchanged digest still emits one heartbeat chunk for liveness
    quiet = chunk_delta(hr.delta())
    assert len(quiet) == 1
    assert set(quiet[0]["rollup"]) == {"host", "seq"}
    assert fleet.ingest(quiet[0], now=4.0) is True
    assert fleet.lost_hosts(now=4.1) == []


def test_alert_plane_fed_exclusively_from_rollups_host_kill_drill():
    """The region-kill contract, reproduced purely from roll-ups: one
    lost host -> exactly one incident whose attribution names it, held
    open while lost, closed on recovery."""
    from handel_tpu.sim.config import AlertParams

    t = {"now": 0.0}
    ap = AlertParams(window_scale=0.01, min_hold_s=0.5, cooldown_s=2.0)
    plane = AlertPlane.from_params(ap, clock=lambda: t["now"])
    fleet = FleetRollup(top_k=4, stale_after_s=0.5, clock=lambda: t["now"])
    counts = {f"h{i}": 0.0 for i in range(4)}
    hosts = {}
    for name in counts:
        hr = HostRollup(name, clock=lambda: t["now"])
        hr.attach_fold(
            "svc",
            lambda name=name: [({"goodCt": counts[name], "badCt": 0.0},
                                set())],
        )
        hosts[name] = hr
    fleet.attach_alerts(
        plane,
        burn_rules=[(BurnRule("fleet-goodput", budget=0.05),
                     "svc.goodCt", "svc.badCt")],
    )

    def step(emit=frozenset(counts)):
        for name in counts:
            counts[name] += 5.0
        for name in emit:
            hosts[name].emit(fleet.ingest)
        plane.tick()
        t["now"] += 0.05

    while t["now"] < 2.0:  # healthy baseline: all four hosts report
        step()
    assert plane.incidents.opened == 0
    assert fleet.hosts_up() == 4

    kill_t = t["now"]
    live = frozenset(n for n in counts if n != "h2")
    opened_at = None
    while t["now"] < kill_t + 2.0:  # h2 goes dark -> staleness marks it
        step(emit=live)
        if plane.incidents.current is not None and opened_at is None:
            opened_at = t["now"]
    assert opened_at is not None
    assert opened_at - kill_t <= 1.0  # stale_after_s + a few ticks
    inc = plane.incidents.current
    assert inc.attribution["lost_hosts"] == ["h2"]
    assert inc.attribution["fleet"]["hosts_up"] == 3
    assert fleet.hosts_up() == 3

    recover_t = t["now"]
    while t["now"] < recover_t + 2.0:  # h2 reports again
        step()
    assert fleet.hosts_up() == 4
    assert plane.incidents.current is None
    assert plane.incidents.opened == 1  # exactly one incident, now closed
    assert inc.state == "closed"


def test_trace_digest_bounded_and_merge_keeps_slowest_chain():
    events = []
    for i in range(5000):  # 5000 spans, 3 stages
        stage = ("verify", "pack", "gossip")[i % 3]
        events.append({"ph": "X", "name": stage, "ts": float(i * 10),
                       "dur": 8.0, "pid": 0, "tid": 0})
    d = trace_digest(events)
    assert d["spans"] == 5000
    assert set(d["stages_ms"]) == {"verify", "pack", "gossip"}
    assert len(d["chain_tail"]) <= 8  # bounded, never the raw ring
    slow = dict(d, wall_ms=d["wall_ms"] * 3)
    m = merge_trace_digests([("fast", d), ("slow", slow)])
    assert m["slowest_host"] == "slow"
    assert m["spans"] == 10000
    assert m["stages_ms"]["verify"] == pytest.approx(
        d["stages_ms"]["verify"] * 2)


# -- satellite 1: cardinality governance --------------------------------------


class _ManyRows:
    def __init__(self, n: int):
        self.n = n

    def labeled_values(self):
        return {f"s{i:03d}": {"workCt": float(i + 1), "depth": 2.0}
                for i in range(self.n)}

    def labeled_gauge_keys(self):
        return {"depth"}


def test_labeled_series_cap_overflow_row_preserves_mass():
    reg = MetricsRegistry(series_cap=4)
    reg.register_labeled_values("svc", _ManyRows(10), label="session",
                                gauges={"depth"})
    fams = parse_exposition(reg.exposition())
    rows = {l["session"]: v for l, v in
            fams["handel_svc_work_ct"]["samples"]}
    assert "_overflow" in rows  # never silently truncated
    assert len(rows) == 5  # top-4 by activity + the overflow row
    assert sum(rows.values()) == sum(range(1, 11))  # counter mass intact
    # the activity ranking keeps the hottest rows as distinct series
    assert {"s009", "s008", "s007", "s006"} <= set(rows)
    assert reg.dropped_series == 6
    drop = fams["handel_metrics_rollup_dropped_series_ct"]["samples"]
    assert drop[0][1] == 6.0


def test_series_cap_zero_is_uncapped():
    reg = MetricsRegistry()
    reg.register_labeled_values("svc", _ManyRows(10), label="session",
                                gauges={"depth"})
    fams = parse_exposition(reg.exposition())
    assert len(fams["handel_svc_work_ct"]["samples"]) == 10
    assert reg.dropped_series == 0


# -- /fleet endpoint + handel_fleet_* families --------------------------------


def _small_fleet() -> FleetRollup:
    fleet = FleetRollup(top_k=4, clock=lambda: 0.0)
    for name in ("hostA", "hostB"):
        hr = HostRollup(name, clock=lambda: 0.0)
        hr.attach_fold("svc", lambda: [
            ({"launchesCt": 5.0, "queueDepth": 2.0}, {"queueDepth"})])
        hr.tick()
        hr.emit(fleet.ingest)
    return fleet


def test_fleet_metrics_families_and_endpoint():
    fleet = _small_fleet()
    fleet.mark_lost("hostB")
    reg = MetricsRegistry()
    fleet.register_metrics(reg)
    fams = parse_exposition(reg.exposition())
    for name in ("handel_fleet_hosts_total", "handel_fleet_hosts_up",
                 "handel_fleet_series_total", "handel_fleet_ingests_ct",
                 "handel_fleet_host_up", "handel_fleet_digest_seq"):
        assert name in fams, sorted(fams)
    rows = {l["host"]: v for l, v in
            fams["handel_fleet_host_up"]["samples"]}
    assert rows == {"hostA": 1.0, "hostB": 0.0}
    assert fams["handel_fleet_hosts_up"]["type"] == "gauge"
    assert fams["handel_fleet_ingests_ct"]["type"] == "counter"

    srv = MetricsServer(reg, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://{srv.address}/fleet", timeout=3
        ) as r:
            payload = json.loads(r.read())
        assert payload["hosts_up"] == 1
        assert payload["lost_hosts"] == ["hostB"]
        assert payload["hosts"]["hostA"]["up"] is True
        assert payload["series_total"] == 2
    finally:
        srv.stop()


def test_fleet_endpoint_unwired_is_501():
    srv = MetricsServer(MetricsRegistry(), port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{srv.address}/fleet", timeout=3)
        assert ei.value.code == 501
    finally:
        srv.stop()


# -- satellite 3: the `sim watch` fleet block ---------------------------------


def test_watch_fleet_row():
    from handel_tpu.sim import watch_cli

    fleet = _small_fleet()
    fleet.mark_lost("hostB")
    reg = MetricsRegistry()
    fleet.register_metrics(reg)
    fams = parse_exposition(reg.exposition())
    model = watch_cli.aggregate([fams])
    assert model["fleet_hosts_up"] == 1.0
    assert model["fleet_hosts_total"] == 2.0
    assert set(model["fleet_hosts"]) == {"hostA", "hostB"}
    frame = watch_cli.render(model, ["127.0.0.1:1"], up=1, tick=1)
    assert "fleet    hosts 1/2 up (1 down)" in frame
    assert "series 2" in frame
    assert "hostB DOWN" in frame
    assert "hostA up" in frame
    assert "top anomalous host" in frame


# -- wire-budget contract + [alerts] roll-up knobs ----------------------------


def test_rollup_budget_matches_monitor_sink():
    from handel_tpu.sim import monitor

    assert MAX_DATAGRAM == monitor.MAX_DATAGRAM


def test_rollup_config_round_trip(tmp_path):
    from handel_tpu.sim.config import (
        AlertParams,
        SimConfig,
        dump_config,
        load_config,
    )

    cfg = SimConfig()
    assert cfg.alerts == AlertParams()
    cfg.alerts.series_cap = 512
    cfg.alerts.rollup_top_k = 4
    cfg.alerts.rollup_interval_s = 0.5
    cfg.alerts.rollup_stale_s = 2.5
    path = tmp_path / "rollup.toml"
    path.write_text(dump_config(cfg))
    loaded = load_config(str(path))
    assert loaded.alerts.series_cap == 512
    assert loaded.alerts.rollup_top_k == 4
    assert loaded.alerts.rollup_interval_s == 0.5
    assert loaded.alerts.rollup_stale_s == 2.5


def test_rollup_config_validation(tmp_path):
    from handel_tpu.sim.config import load_config

    for body in (
        "[alerts]\nseries_cap = -1\n",
        "[alerts]\nrollup_top_k = 0\n",
        "[alerts]\nrollup_interval_s = 0.0\n",
        "[alerts]\nrollup_stale_s = -2.0\n",
    ):
        bad = tmp_path / "bad.toml"
        bad.write_text(body)
        with pytest.raises(ValueError):
            load_config(str(bad))

"""Multi-node protocol integration with fake crypto over the in-process network.

Reference model: handel_test.go:30-127 (TestHandelWithFailures,
TestHandelTestNetworkFull — powers of two and not, offline nodes, thresholds),
using the tier-2 strategy from SURVEY.md §4: no real crypto, no real sockets,
and with zero offline nodes the timeout strategy is infinite so any stall is a
real bug.
"""

import asyncio

import pytest

from handel_tpu.core.crypto import verify_multisignature
from handel_tpu.core.test_harness import LocalCluster, run_cluster
from handel_tpu.models.fake import FakeConstructor


def run(coro):
    return asyncio.run(coro)


@pytest.mark.parametrize("n", [2, 4, 8, 13, 32])
def test_full_aggregation(n):
    results = run(run_cluster(n, timeout=15.0))
    assert len(results) == n
    for sig in results.values():
        assert sig.cardinality() >= (n * 51 + 99) // 100


def test_non_power_of_two_large():
    results = run(run_cluster(21, timeout=15.0))
    assert all(s.cardinality() >= 11 for s in results.values())


@pytest.mark.parametrize(
    "n,offline,threshold",
    [
        (8, (1, 5), 6),
        (16, (0, 7, 12), 13),
        (13, (2,), 10),
    ],
)
def test_with_failures(n, offline, threshold):
    async def go():
        cluster = LocalCluster(n, offline=offline, threshold=threshold)
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=20.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == n - len(offline)
    for sig in results.values():
        assert sig.cardinality() >= threshold
        # offline nodes must not appear in the bitset
        for off in offline:
            assert not sig.bitset.get(off)


def test_final_sig_verifies_against_registry():
    async def go():
        cluster = LocalCluster(8)
        cluster.start()
        try:
            res = await cluster.wait_complete_success(timeout=15.0)
            return cluster, res
        finally:
            cluster.stop()

    cluster, results = run(go())
    cons = FakeConstructor()
    for sig in results.values():
        assert verify_multisignature(b"hello world", sig, cluster.registry, cons)


def test_malformed_individual_sig_ignored():
    # regression: wrong-size individual_sig must be rejected as an invalid
    # packet, not crash the listener with a non-ValueError
    from handel_tpu.core.net import Packet

    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.crypto import MultiSignature
    from handel_tpu.models.fake import FakeSignature

    async def go():
        cluster = LocalCluster(8)
        cluster.start()
        h0 = cluster.handels[0]
        # correctly-sized level-3 multisig (4 peers for id 0) so parsing
        # reaches the malformed individual_sig
        bs = BitSet(len(h0.levels[3].nodes))
        bs.set(0)
        good_ms = MultiSignature(bs, FakeSignature()).marshal()
        h0.new_packet(
            Packet(origin=4, level=3, multisig=good_ms, individual_sig=b"\x01\x02")
        )
        try:
            return await cluster.wait_complete_success(timeout=15.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == 8


def test_larger_cluster_slow():
    # reference: TestHandelTestNetworkLarge guarded by testing.Short()
    results = run(run_cluster(64, timeout=30.0))
    assert len(results) == 64


def test_flaky_verifier_requeues():
    """A transiently failing verifier must not lose candidates: errored
    batches are requeued (with a retry cap) and aggregation completes.
    Matches the per-signature error handling intent of processing.go:282-284."""
    import random

    from handel_tpu.core.config import Config

    calls = {"n": 0}
    cons = FakeConstructor()

    async def flaky(msg, pubkeys, requests):
        calls["n"] += 1
        if calls["n"] % 2 == 1:
            raise RuntimeError("transient device error")
        return cons.batch_verify(msg, pubkeys, requests)

    def cfg_factory(i):
        c = Config()
        c.verifier = flaky
        c.rand = random.Random(42 + i)
        return c

    results = run(run_cluster(8, timeout=25.0, config_factory=cfg_factory))
    assert len(results) == 8
    assert calls["n"] > 0


def test_requeue_retry_cap():
    """After max_retries verifier errors a candidate is dropped, not spun on
    forever."""
    import random as _random

    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.crypto import MultiSignature
    from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
    from handel_tpu.core.processing import BatchProcessing
    from handel_tpu.models.fake import FakeSignature

    from handel_tpu.core.identity import ArrayRegistry, Identity
    from handel_tpu.models.fake import FakePublic

    async def go():
        reg = ArrayRegistry(
            [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
        )
        part = BinomialPartitioner(0, reg)
        verified = []

        async def always_fail(msg, pubkeys, requests):
            raise RuntimeError("dead device")

        proc = BatchProcessing(
            part,
            FakeConstructor(),
            b"m",
            [None] * 8,
            type("E", (), {"evaluate": staticmethod(lambda sp: 1)})(),
            verified.append,
            verifier=always_fail,
        )
        proc.start()
        bs = BitSet(1)
        bs.set(0)
        sp = IncomingSig(origin=1, level=1, ms=MultiSignature(bs, FakeSignature()))
        proc.add(sp)
        # let the loop run: 1 initial + max_retries attempts, then drop
        for _ in range(40):
            await asyncio.sleep(0.01)
            if sp.verify_tries > proc.max_retries:
                break
        proc.stop()
        assert sp.verify_tries == proc.max_retries + 1
        assert not verified
        assert all(s.verify_tries <= proc.max_retries for s in proc.pending())

    run(go())


def test_requeue_does_not_starve_queue():
    """A candidate whose batches keep erroring exhausts its retry budget and
    is dropped — while OTHER candidates behind it still get verified (the
    retry cap exists precisely so one poisoned candidate cannot pin the
    queue forever)."""
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.crypto import MultiSignature
    from handel_tpu.core.identity import ArrayRegistry, Identity
    from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
    from handel_tpu.core.processing import BatchProcessing
    from handel_tpu.models.fake import FakePublic, FakeSignature

    async def go():
        reg = ArrayRegistry(
            [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
        )
        part = BinomialPartitioner(0, reg)
        # poisoned is scored highest so it hogs the front of the queue
        scores = {1: 10, 2: 5, 3: 4}
        verified = []
        poison = FakeSignature()

        class Eval:
            def evaluate(self, sp):
                return scores[sp.origin]

        async def poisoned_verifier(msg, pubkeys, requests):
            # the device "errors" on any batch carrying the poisoned sig
            if any(sig is poison for _, sig in requests):
                raise RuntimeError("device chokes on this candidate")
            return [True] * len(requests)

        proc = BatchProcessing(
            part,
            FakeConstructor(),
            b"m",
            [None] * 8,
            Eval(),
            lambda sp: verified.append(sp.origin),
            batch_size=1,  # poisoned candidate rides alone
            verifier=poisoned_verifier,
        )
        proc.start()
        sps = {}
        for origin in (1, 2, 3):
            bs = BitSet(1)
            bs.set(0)
            sig = poison if origin == 1 else FakeSignature()
            sps[origin] = IncomingSig(
                origin=origin, level=1, ms=MultiSignature(bs, sig)
            )
            proc.add(sps[origin])
        for _ in range(100):
            await asyncio.sleep(0.01)
            if len(verified) >= 2 and sps[1].verify_tries > proc.max_retries:
                break
        proc.stop()
        # the healthy candidates completed despite the poisoned front-runner
        assert sorted(verified) == [2, 3]
        # and the poisoned one was dropped after its retry budget
        assert sps[1].verify_tries == proc.max_retries + 1
        assert all(s.origin != 1 for s in proc.pending())

    run(go())


def test_heap_priority_and_lazy_suppression():
    """The priority queue verifies higher-scored candidates first and a
    candidate whose score drops to 0 after enqueue is pruned at dequeue
    (the lazy re-score replacing the reference's whole-queue rescan,
    processing.go:171-220)."""
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.crypto import MultiSignature
    from handel_tpu.core.identity import ArrayRegistry, Identity
    from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
    from handel_tpu.core.processing import BatchProcessing
    from handel_tpu.models.fake import FakePublic, FakeSignature

    async def go():
        reg = ArrayRegistry(
            [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
        )
        part = BinomialPartitioner(0, reg)
        scores = {1: 5, 2: 9, 3: 3}
        verified_order = []

        class Eval:
            def evaluate(self, sp):
                return scores[sp.origin]

        async def record(msg, pubkeys, requests):
            return [True] * len(requests)

        proc = BatchProcessing(
            part,
            FakeConstructor(),
            b"m",
            [None] * 8,
            Eval(),
            lambda sp: verified_order.append(sp.origin),
            batch_size=1,
            verifier=record,
        )
        proc.start()
        for origin in (1, 2, 3):
            bs = BitSet(1)
            bs.set(0)
            proc.add(
                IncomingSig(
                    origin=origin,
                    level=1,
                    ms=MultiSignature(bs, FakeSignature()),
                )
            )
        # origin 2 goes stale before the loop ever runs a step
        scores[2] = 0
        for _ in range(50):
            await asyncio.sleep(0.01)
            if len(verified_order) >= 2:
                break
        proc.stop()
        return verified_order, proc.sig_suppressed

    order, suppressed = run(go())
    assert order == [1, 3]  # priority order among survivors (5 > 3)
    assert suppressed >= 1  # the stale origin-2 entry died at dequeue


def test_heap_rescore_after_score_raise():
    """A queued candidate whose score RISES after a verified publish (e.g.
    jumping into the store's level-completion bracket as indiv_verified
    grows) must be selected before lower-scored entries: the publish marks
    the heap dirty and the next selection rebuilds it with fresh scores.
    Pop-refresh alone would leave the risen entry buried at its stale-low
    key (ADVICE r3)."""
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.core.crypto import MultiSignature
    from handel_tpu.core.identity import ArrayRegistry, Identity
    from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
    from handel_tpu.core.processing import BatchProcessing
    from handel_tpu.models.fake import FakePublic, FakeSignature

    async def go():
        reg = ArrayRegistry(
            [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
        )
        part = BinomialPartitioner(0, reg)
        # A verified first; B buried below C until A's publish raises it
        scores = {1: 10, 2: 3, 3: 4}
        verified_order = []

        class Eval:
            def evaluate(self, sp):
                return scores[sp.origin]

        def on_verified(sp):
            verified_order.append(sp.origin)
            if sp.origin == 1:
                scores[2] = 9  # the store-mutation score raise

        async def ok(msg, pubkeys, requests):
            return [True] * len(requests)

        proc = BatchProcessing(
            part,
            FakeConstructor(),
            b"m",
            [None] * 8,
            Eval(),
            on_verified,
            batch_size=1,
            verifier=ok,
        )
        proc.start()
        for origin in (1, 2, 3):
            bs = BitSet(1)
            bs.set(0)
            proc.add(
                IncomingSig(
                    origin=origin,
                    level=1,
                    ms=MultiSignature(bs, FakeSignature()),
                )
            )
        for _ in range(80):
            await asyncio.sleep(0.01)
            if len(verified_order) >= 3:
                break
        proc.stop()
        return verified_order

    assert run(go()) == [1, 2, 3]  # risen B (9) beats C (4) after rebuild


def test_fifo_processing_cluster():
    """The deprecated arrival-order pipeline (processing.go:380-493) still
    completes aggregation — the A/B counterpart to the evaluator strategy."""
    import random

    from handel_tpu.core.config import Config
    from handel_tpu.core.processing import FifoProcessing

    def cfg_factory(i):
        c = Config()
        c.new_processing = FifoProcessing
        c.rand = random.Random(7 + i)
        return c

    results = run(run_cluster(16, timeout=20.0, config_factory=cfg_factory))
    assert len(results) == 16

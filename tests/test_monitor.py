"""Monitor-plane tests (ISSUE 4 satellites): chunked sink payloads,
histogram round-trip + master-side merge into `_p50/_p90/_p99` columns,
NaN columns for declared-but-unsampled keys, and the warn-once counter on
the reporter plane."""

import asyncio
import csv
import math

import pytest

from handel_tpu.core.trace import LogHistogram
from handel_tpu.sim.monitor import (
    MAX_DATAGRAM,
    HistogramIO,
    Monitor,
    Sink,
    Stats,
    _chunk_hist,
    _chunk_values,
)
from handel_tpu.sim.platform import free_ports


# -- chunking (the oversized-datagram fix) -----------------------------------


def test_chunk_values_respects_datagram_budget():
    import json

    vals = {f"aVeryLongCounterName_{i:04d}": float(i) * 1.234567 for i in range(300)}
    chunks = list(_chunk_values("sigs", vals))
    assert len(chunks) > 1  # this map cannot fit one datagram
    seen = {}
    for c in chunks:
        wire = json.dumps(c).encode()
        assert len(wire) <= MAX_DATAGRAM + 2, f"chunk of {len(wire)} bytes"
        assert c["name"] == "sigs"
        seen.update(c["values"])
    assert seen == vals  # nothing lost, nothing duplicated


def test_chunk_hist_reassembles_exactly():
    import json

    h = LogHistogram()
    h.add(1e-5)
    h.add(10.0)
    # inflate every bucket to a 9-digit count so the sparse map overflows
    # one datagram and the sum/lo/hi chunk protocol is exercised
    for i in range(LogHistogram.NBUCKETS):
        h.counts[i] += 123456789 + i
        h.count += 123456789 + i
    chunks = list(_chunk_hist("sigs", "latS", h))
    assert len(chunks) >= 2
    merged = LogHistogram()
    for c in chunks:
        wire = json.dumps(c).encode()
        assert len(wire) <= MAX_DATAGRAM + 2
        merged.merge_sparse(c["hists"]["latS"])
    assert merged.count == h.count
    assert merged.counts == h.counts
    assert merged.sum == pytest.approx(h.sum)
    assert merged.lo == pytest.approx(h.lo)
    assert merged.hi == pytest.approx(h.hi)


# -- end-to-end sink -> monitor -> stats CSV ---------------------------------


class _HistReporter:
    def __init__(self, values):
        self.h = LogHistogram()
        for v in values:
            self.h.add(v)

    def histograms(self):
        return {"levelCompleteS": self.h}


def test_monitor_roundtrip_chunked_and_hist(tmp_path):
    """Sink -> Monitor -> Stats CSV: a >1-datagram values map arrives whole,
    and two nodes' histograms merge into one distribution whose p50/p90/p99
    columns land in the CSV (the acceptance-criteria schema)."""

    async def go():
        (port,) = free_ports(1)
        mon = Monitor(port)
        await mon.start()
        sink = Sink(f"127.0.0.1:{port}")
        big = {f"counter_{i:04d}": float(i) for i in range(300)}
        sink.record("sigs", big)
        # two "nodes" with disjoint latency populations
        HistogramIO(sink, "sigs", _HistReporter([0.010] * 50)).record()
        HistogramIO(sink, "sigs", _HistReporter([0.100] * 50)).record()
        await asyncio.sleep(0.3)
        mon.stop()
        sink.close()
        return mon.stats

    stats = asyncio.run(go())
    cols = stats.columns()
    row = dict(zip(cols, stats.row()))
    # every chunked key arrived
    for i in range(300):
        assert row[f"sigs_counter_{i:04d}_avg"] == float(i)
    # histogram merge: 100 samples total, p50 near 10 ms, p99 near 100 ms
    assert row["sigs_levelCompleteS_n"] == 100.0
    assert row["sigs_levelCompleteS_p50"] == pytest.approx(0.010, rel=0.25)
    assert row["sigs_levelCompleteS_p99"] == pytest.approx(0.100, rel=0.25)
    assert row["sigs_levelCompleteS_p90"] >= row["sigs_levelCompleteS_p50"]
    path = str(tmp_path / "stats.csv")
    stats.write_csv(path)
    with open(path) as f:
        header = list(csv.reader(f))[0]
    for s in ("p50", "p90", "p99"):
        assert f"sigs_levelCompleteS_{s}" in header


# -- stable schema: declared keys with zero samples --------------------------


def test_declared_key_without_samples_emits_nan_columns(tmp_path):
    stats = Stats(expected=("sigen_wall",))
    stats.update("other", 1.0)
    cols = stats.columns()
    assert "sigen_wall_avg" in cols and "other_avg" in cols
    with pytest.warns(RuntimeWarning, match="sigen_wall"):
        row = dict(zip(cols, stats.row()))
    assert math.isnan(row["sigen_wall_avg"])
    assert row["other_avg"] == 1.0
    # the CSV keeps the column (as "nan"), so downstream schemas stay stable
    path = str(tmp_path / "s.csv")
    with pytest.warns(RuntimeWarning):
        stats.write_csv(path)
    rows = list(csv.DictReader(open(path)))
    assert math.isnan(float(rows[0]["sigen_wall_avg"]))


def test_declared_key_with_samples_is_normal():
    stats = Stats(expected=("sigen_wall",))
    stats.update("sigen_wall", 2.0)
    row = dict(zip(stats.columns(), stats.row()))
    assert row["sigen_wall_avg"] == 2.0


def test_plots_skip_nan_points(tmp_path):
    from handel_tpu.sim.plots import _series

    rows = [
        {"nodes": 8.0, "y": 1.0},
        {"nodes": 16.0, "y": float("nan")},
        {"nodes": 32.0, "y": 3.0},
    ]
    xs, ys = _series(rows, "nodes", "y")
    assert xs == [8.0, 32.0] and ys == [1.0, 3.0]


# -- warn-once counters on the reporter plane --------------------------------


class _CaptureLog:
    def __init__(self):
        self.warns = []
        self.debugs = []

    def warn(self, *a):
        self.warns.append(a)

    def debug(self, *a):
        self.debugs.append(a)


def test_warn_once_counter():
    from handel_tpu.core.report import WarnOnce

    log = _CaptureLog()
    w = WarnOnce(log)
    for _ in range(5):
        w.warn("udp_decode", "boom")
    w.warn("udp_icmp", "nope")
    assert len(log.warns) == 2  # one WARN per distinct reason
    assert len(log.debugs) == 4  # the suppressed repeats
    assert w.total() == 6
    assert w.values() == {"logWarnCt": 6.0}


def test_handel_log_warn_ct_reaches_reporter_plane():
    """Suppressed invalid-packet warnings stay visible as logWarnCt in the
    per-node values() map the `sigs` CounterIO records."""
    from handel_tpu.core.net import Packet
    from handel_tpu.core.test_harness import LocalCluster

    async def go():
        cluster = LocalCluster(8)
        h = cluster.handels[0]
        for _ in range(3):
            h.new_packet(Packet(origin=999, level=1, multisig=b"junk"))
        vals = h.values()
        assert vals["invalidPacketCt"] == 3.0
        assert vals["logWarnCt"] == 3.0

    asyncio.run(go())


def test_udp_log_warn_ct(tmp_path):
    """UDP decode errors count on the logWarnCt plane (warn-once logging)."""
    from handel_tpu.network.udp import UDPNetwork

    async def go():
        (port,) = free_ports(1)
        net = UDPNetwork(f"127.0.0.1:{port}")
        await net.start()
        import socket

        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for _ in range(4):
            s.sendto(b"\x01", ("127.0.0.1", port))
        await asyncio.sleep(0.2)
        vals = net.values()
        net.stop()
        s.close()
        assert vals["decodeErrors"] == 4.0
        assert vals["logWarnCt"] == 4.0

    asyncio.run(go())

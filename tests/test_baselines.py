"""Gossip baseline tests (simul/p2p/test/test.go:23-50 shape)."""

import asyncio

import pytest

from handel_tpu.baselines.gossip import run_gossip
from handel_tpu.core.crypto import verify_multisignature


def test_gossip_full_mesh():
    results = asyncio.run(run_gossip(8, threshold=5, connector="full"))
    assert len(results) == 8
    for ms in results.values():
        assert ms.bitset.cardinality() >= 5


def test_gossip_random_fanout():
    results = asyncio.run(
        run_gossip(10, threshold=6, connector="random", fanout=4)
    )
    assert all(ms.bitset.cardinality() >= 6 for ms in results.values())


def test_gossip_traced_emits_handel_shaped_spans():
    """With a recorder attached the baseline emits the SAME pipeline spans,
    flow links and threshold instant as Handel — so sim trace compares
    baseline-vs-handel like-for-like (ISSUE 10 satellite)."""
    from handel_tpu.core.trace import FlightRecorder

    rec = FlightRecorder(capacity=1 << 15)
    results = asyncio.run(
        run_gossip(8, threshold=5, connector="full", recorder=rec)
    )
    assert len(results) == 8
    events = rec.export()["traceEvents"]
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert {"send", "recv", "verify", "merge", "net_transit"} <= names
    assert any(
        e["ph"] == "i" and e["name"] == "threshold_reached" for e in events
    )
    # flow links resolve: every traced recv's span id has a send start
    from handel_tpu.sim import trace_cli

    frac, linked, total = trace_cli.flow_linkage(events)
    assert total > 0 and frac >= 0.95, f"{linked}/{total}"
    # gossip lanes are named so merged traces stay readable
    metas = [e for e in events if e["ph"] == "M"]
    assert any("gossip-" in str(e["args"].get("name", "")) for e in metas)


def test_gossip_aggregate_then_verify_real_crypto():
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()
    results = asyncio.run(
        run_gossip(4, threshold=3, scheme=scheme, verify_incoming=False,
                   timeout=60.0)
    )
    assert len(results) == 4


def test_gossipsub_completes():
    """Real gossipsub semantics (simul/p2p/libp2p/node.go:55-434): setup
    barrier, per-topic meshes, eager push — every node reaches threshold."""
    from handel_tpu.baselines.gossipsub import run_gossipsub

    finals = asyncio.run(run_gossipsub(12, threshold=7))
    assert len(finals) == 12
    for ms in finals.values():
        assert ms.bitset.cardinality() >= 7


def test_gossipsub_mesh_maintenance_and_lazy_repair():
    """GRAFT/PRUNE keep meshes inside [D_lo, D_hi] and IHAVE/IWANT repair
    holes: with a tiny eager degree the lazy channel must still complete
    the aggregation, and the control counters must show it happened."""
    from handel_tpu.baselines.gossip import run_gossip
    from handel_tpu.baselines.gossipsub import GossipSubAggregator

    nodes_seen = []
    post_prune_sizes = []

    class Spy(GossipSubAggregator):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            nodes_seen.append(self)

        def _heartbeat(self):
            super()._heartbeat()
            # snapshot right after the maintenance pass: this is the
            # moment the prune rule guarantees the cap (between beats,
            # v1.0 accepts every GRAFT, so mesh size is transiently
            # unbounded — asserting a cap at test end is a race)
            post_prune_sizes.extend(
                (len(m), self.D_hi) for m in self.mesh.values()
            )

    finals = asyncio.run(
        run_gossip(
            16,
            threshold=12,
            aggregator_cls=Spy,
            degree=2,
            degree_lo=2,
            degree_hi=4,
            degree_lazy=3,
        )
    )
    assert all(ms.bitset.cardinality() >= 12 for ms in finals.values())
    assert any(n.grafts_sent > 0 for n in nodes_seen)
    assert any(n.ihave_sent > 0 for n in nodes_seen)
    assert any(n.iwant_sent > 0 for n in nodes_seen)
    # the heartbeat's maintenance pass must cap every mesh at D_hi (prune
    # down to D when above): checked at the deterministic post-prune
    # instant, where the gossipsub §heartbeat contract actually holds
    assert post_prune_sizes, "no heartbeat ran during the aggregation"
    assert all(size <= d_hi for size, d_hi in post_prune_sizes)
    # the setup barrier completed everywhere before anyone published
    assert all(n.setup_complete for n in nodes_seen)


def test_gossipsub_malformed_control_frames_dropped():
    """A truncated IHAVE/IWANT payload must be dropped, not raise
    struct.error out of the transport's listener callback (ADVICE r3) —
    mirroring the unmarshal_signature guard on PUB frames."""
    import struct

    from handel_tpu.baselines.gossipsub import (
        _IHAVE,
        _IWANT,
        _PUB,
        GOSSIPSUB_LEVEL,
        GossipSubAggregator,
    )
    from handel_tpu.core.net import Packet

    class NullNet:
        def register_listener(self, l):
            pass

        def send(self, ids, pkt):
            pass

    from handel_tpu.core.identity import ArrayRegistry, Identity
    from handel_tpu.models.fake import FakeConstructor, FakePublic, FakeSecret

    reg = ArrayRegistry(
        [Identity(i, f"x-{i}", FakePublic(True)) for i in range(4)]
    )

    async def go():
        agg = GossipSubAggregator(
            NullNet(),
            reg,
            reg.identity(0),
            FakeConstructor(),
            b"m",
            FakeSecret(0).sign(b"m"),
            3,
        )
        # 5-byte header + topic list declaring ONE entry but carrying only
        # 3 of its 4 bytes — _parse_topics must hit struct.error inside
        # the guard, not propagate it
        truncated_list = struct.pack(">H", 1) + b"\x00\x00\x01"
        for kind in (_IHAVE, _IWANT):
            agg.new_packet(
                Packet(
                    origin=1,
                    level=GOSSIPSUB_LEVEL,
                    multisig=struct.pack(">BI", kind, 0) + truncated_list,
                )
            )
        # truncated PUB payload for a topic NOT already delivered (the
        # aggregator is node 0, whose own topic is pre-seeded) exercises
        # the existing unmarshal guard in _deliver
        agg.new_packet(
            Packet(
                origin=1, level=GOSSIPSUB_LEVEL, multisig=struct.pack(">BI", _PUB, 1)
            )
        )
        return True

    assert asyncio.run(go())

"""Gossip baseline tests (simul/p2p/test/test.go:23-50 shape)."""

import asyncio

import pytest

from handel_tpu.baselines.gossip import run_gossip
from handel_tpu.core.crypto import verify_multisignature


def test_gossip_full_mesh():
    results = asyncio.run(run_gossip(8, threshold=5, connector="full"))
    assert len(results) == 8
    for ms in results.values():
        assert ms.bitset.cardinality() >= 5


def test_gossip_random_fanout():
    results = asyncio.run(
        run_gossip(10, threshold=6, connector="random", fanout=4)
    )
    assert all(ms.bitset.cardinality() >= 6 for ms in results.values())


def test_gossip_aggregate_then_verify_real_crypto():
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()
    results = asyncio.run(
        run_gossip(4, threshold=3, scheme=scheme, verify_incoming=False,
                   timeout=60.0)
    )
    assert len(results) == 4


def test_mesh_gossip_completes():
    """gossipsub-analog mesh baseline (simul/p2p/libp2p/node.go:55-434):
    fixed-degree overlay still reaches threshold everywhere."""
    import asyncio

    from handel_tpu.baselines.gossipsub import run_mesh_gossip

    finals = asyncio.run(run_mesh_gossip(12, threshold=7, degree=3))
    assert len(finals) == 12
    for ms in finals.values():
        assert ms.bitset.cardinality() >= 7

"""Verified-aggregate dedup: duplicate aggregates must cost zero device lanes.

Handel's gossip pattern delivers the same winning aggregate from several
peers per level; before the dedup cache every copy burned a device lane.
Covered here: the cache itself (LRU bound, verdict memory, counters), the
per-node pipeline (`BatchProcessing`: in-batch duplicates share one lane,
re-received aggregates short-circuit entirely), and the process-wide service
plane (`BatchVerifierService`: cross-node dedup, in-flight coalescing, and
the stop()-mid-launch regression from ADVICE r5 #1).

Fast tier: fake crypto + device stubs, nothing compiles.
"""

import asyncio
import threading

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.identity import ArrayRegistry, Identity
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
from handel_tpu.core.processing import BatchProcessing
from handel_tpu.core.store import VerifiedAggCache
from handel_tpu.models.fake import FakeConstructor, FakePublic, FakeSignature


def run(coro):
    return asyncio.run(coro)


# -- the cache itself --------------------------------------------------------


def test_cache_remembers_both_verdicts_and_counts():
    cache = VerifiedAggCache(capacity=8)
    bs = BitSet(4)
    bs.set(1, True)
    good = VerifiedAggCache.key(2, MultiSignature(bs, FakeSignature(True)))
    bad = VerifiedAggCache.key(2, MultiSignature(bs, FakeSignature(False)))
    assert good != bad  # signature bytes are part of the identity
    assert cache.get(good) is None
    cache.put(good, True)
    cache.put(bad, False)
    assert cache.get(good) is True
    assert cache.get(bad) is False  # negative verdicts cached too
    assert (cache.hits, cache.misses) == (2, 1)
    vals = cache.values()
    assert vals["dedupHits"] == 2.0 and vals["dedupMisses"] == 1.0
    assert vals["dedupHitRate"] == pytest.approx(2 / 3)


def test_cache_lru_bound_evicts_oldest():
    cache = VerifiedAggCache(capacity=3)
    for i in range(5):
        cache.put((i,), True)
    assert len(cache) == 3
    assert cache.get((0,)) is None and cache.get((1,)) is None
    assert cache.get((4,)) is True
    # a get refreshes recency: (4,) survives the next eviction wave
    cache.put((5,), True)
    cache.put((6,), True)
    assert cache.get((4,)) is True


def test_cache_key_distinguishes_level_bits_and_sig():
    bs1 = BitSet(8)
    bs1.set(0, True)
    bs2 = BitSet(8)
    bs2.set(1, True)
    ms1 = MultiSignature(bs1, FakeSignature(True))
    ms2 = MultiSignature(bs2, FakeSignature(True))
    assert VerifiedAggCache.key(1, ms1) != VerifiedAggCache.key(2, ms1)
    assert VerifiedAggCache.key(1, ms1) != VerifiedAggCache.key(1, ms2)
    assert VerifiedAggCache.key(1, ms1) == VerifiedAggCache.key(
        1, MultiSignature(bs1.clone(), FakeSignature(True))
    )


# -- per-node pipeline -------------------------------------------------------


def _proc(verifier, batch_size=4, registry=8):
    reg = ArrayRegistry(
        [Identity(i, f"x-{i}", FakePublic(True)) for i in range(registry)]
    )
    part = BinomialPartitioner(0, reg)
    verified = []
    proc = BatchProcessing(
        part,
        FakeConstructor(),
        b"m",
        [None] * registry,
        type("E", (), {"evaluate": staticmethod(lambda sp: 1)})(),
        verified.append,
        batch_size=batch_size,
        verifier=verifier,
    )
    return proc, verified


def _dup_sig(level, origin, width=2, valid=True):
    """An aggregate for `level` whose CONTENT is identical across origins —
    the multi-peer duplicate-delivery shape."""
    bs = BitSet(width)
    for i in range(width):
        bs.set(i, True)
    return IncomingSig(
        origin=origin, level=level, ms=MultiSignature(bs, FakeSignature(valid))
    )


def test_in_batch_duplicates_share_one_lane():
    """Two copies of the same aggregate selected into ONE batch reach the
    verifier as a single request; both copies still publish."""
    lanes = []

    async def verifier(msg, pubkeys, requests):
        lanes.append(len(requests))
        return [True] * len(requests)

    async def go():
        proc, verified = _proc(verifier)
        proc.start()
        proc.add(_dup_sig(2, origin=2))
        proc.add(_dup_sig(2, origin=3))  # same content, different peer
        for _ in range(100):
            await asyncio.sleep(0.01)
            if len(verified) >= 2:
                break
        proc.stop()
        return proc, verified

    proc, verified = run(go())
    assert len(verified) == 2  # both copies published
    assert sum(lanes) == 1  # ... from ONE device lane
    assert proc.dedup.hits >= 1
    assert proc.values()["dedupHits"] >= 1.0


def test_rereceived_again_after_verify_costs_no_lane():
    """An aggregate re-delivered after this node already verified it takes
    the cached verdict: zero requests reach the device."""
    lanes = []

    async def verifier(msg, pubkeys, requests):
        lanes.append(len(requests))
        return [True] * len(requests)

    async def go():
        proc, verified = _proc(verifier)
        proc.start()
        proc.add(_dup_sig(2, origin=2))
        for _ in range(100):
            await asyncio.sleep(0.01)
            if len(verified) >= 1:
                break
        assert sum(lanes) == 1
        proc.add(_dup_sig(2, origin=3))  # the same winning aggregate again
        for _ in range(100):
            await asyncio.sleep(0.01)
            if len(verified) >= 2:
                break
        proc.stop()
        return proc, verified

    proc, verified = run(go())
    assert len(verified) == 2
    assert sum(lanes) == 1  # second delivery never reached the verifier


def test_cached_negative_verdict_blocks_republish():
    """A known-bad aggregate re-sent by a byzantine peer is rejected from
    cache: no lane, no publish."""
    lanes = []

    async def verifier(msg, pubkeys, requests):
        lanes.append(len(requests))
        return [False] * len(requests)

    async def go():
        proc, verified = _proc(verifier)
        proc.start()
        proc.add(_dup_sig(2, origin=2, valid=False))
        for _ in range(50):
            await asyncio.sleep(0.01)
            if sum(lanes) >= 1:
                break
        proc.add(_dup_sig(2, origin=3, valid=False))
        await asyncio.sleep(0.1)
        proc.stop()
        return proc, verified

    proc, verified = run(go())
    assert not verified
    assert sum(lanes) == 1
    assert proc.dedup.hits >= 1


def test_verifier_error_requeues_duplicates_too():
    """On a transient verifier error the in-batch duplicate is requeued with
    its primary, not silently dropped."""
    calls = {"n": 0}

    async def flaky(msg, pubkeys, requests):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient")
        return [True] * len(requests)

    async def go():
        proc, verified = _proc(flaky)
        proc.start()
        proc.add(_dup_sig(2, origin=2))
        proc.add(_dup_sig(2, origin=3))
        for _ in range(200):
            await asyncio.sleep(0.01)
            if len(verified) >= 2:
                break
        proc.stop()
        return verified

    verified = run(go())
    assert len(verified) == 2


# -- process-wide service plane ----------------------------------------------


class StubDevice:
    """BN254Device stand-in: instant verdicts, no kernels. `gate` (when set)
    blocks dispatch inside the executor thread — the stop()-mid-launch
    window."""

    batch_size = 4

    def __init__(self, gate: threading.Event | None = None):
        self.gate = gate
        self.dispatched = 0

    def dispatch(self, msg, reqs):
        if self.gate is not None:
            self.gate.wait(5.0)
        self.dispatched += len(reqs)
        return len(reqs)

    def fetch(self, handle):
        return [True] * handle


def _service(device):
    from handel_tpu.parallel.batch_verifier import BatchVerifierService

    return BatchVerifierService(device, max_delay_ms=0.5)


def _req(i=0, width=4):
    bs = BitSet(width)
    bs.set(i % width, True)
    return (bs, FakeSignature(True))


def test_service_dedups_across_nodes():
    """Node B verifying the aggregate node A already verified resolves from
    cache: the device sees it once."""

    async def go():
        svc = _service(StubDevice())
        a = await svc.verify(b"m", [], [_req(0)])
        b = await svc.verify(b"m", [], [_req(0)])  # same content, other node
        vals = svc.values()
        svc.stop()
        return a, b, svc, vals

    a, b, svc, vals = run(go())
    assert a == [True] and b == [True]
    assert svc.device.dispatched == 1
    assert vals["dedupHits"] == 1.0
    assert vals["dedupHitRate"] == 0.5


def test_service_coalesces_concurrent_duplicates():
    """Identical candidates in flight at the same time share ONE lane."""

    async def go():
        svc = _service(StubDevice())
        r = await asyncio.gather(
            svc.verify(b"m", [], [_req(1)]),
            svc.verify(b"m", [], [_req(1)]),
            svc.verify(b"m", [], [_req(1)]),
        )
        svc.stop()
        return r, svc

    results, svc = run(go())
    assert results == [[True], [True], [True]]
    assert svc.device.dispatched == 1
    assert svc.cache.hits == 2


def test_service_distinct_messages_not_deduped():
    async def go():
        svc = _service(StubDevice())
        await svc.verify(b"m1", [], [_req(0)])
        await svc.verify(b"m2", [], [_req(0)])
        svc.stop()
        return svc

    svc = run(go())
    assert svc.device.dispatched == 2


def test_stop_mid_dispatch_fails_waiters_not_hangs():
    """Regression (ADVICE r5 #1): stop() while the collector holds a batch
    in the dispatch executor — outside _pending and _fetch_q — must fail
    that batch's futures instead of stranding the callers forever."""

    async def go():
        gate = threading.Event()
        svc = _service(StubDevice(gate=gate))
        task = asyncio.ensure_future(svc.verify(b"m", [], [_req(0)]))
        # wait until the batch left _pending for the dispatch executor
        for _ in range(200):
            await asyncio.sleep(0.005)
            if svc._collecting is not None:
                break
        assert svc._collecting is not None, "collector never took the batch"
        svc.stop()
        gate.set()  # let the executor thread exit
        with pytest.raises(RuntimeError, match="stopped"):
            await asyncio.wait_for(task, timeout=2.0)

    run(go())


def test_stop_with_pending_queue_still_fails_everyone():
    """stop() failing _pending (the pre-existing path) keeps working with
    the dedup layer in front."""

    async def go():
        gate = threading.Event()
        svc = _service(StubDevice(gate=gate))
        t1 = asyncio.ensure_future(svc.verify(b"m", [], [_req(0)]))
        t2 = asyncio.ensure_future(svc.verify(b"m", [], [_req(0)]))  # coalesced
        t3 = asyncio.ensure_future(svc.verify(b"m", [], [_req(1)]))
        await asyncio.sleep(0.05)
        svc.stop()
        gate.set()
        for t in (t1, t2, t3):
            with pytest.raises(RuntimeError, match="stopped"):
                await asyncio.wait_for(t, timeout=2.0)

    run(go())

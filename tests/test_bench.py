"""bench.py plumbing tests: the accelerator measurement path (persist with
provenance, vs_baseline ratio, persisted-artifact re-emit) must work before
its first live-tunnel run (round-3 verdict "What's weak" #1: the TPU
measurement path was itself untested code). Runs bench.py as a subprocess —
the real driver surface — on the CPU backend with tiny forced sizes."""

import json
import os
import subprocess
import sys

import pytest

# slow tier: each test runs bench.py as a subprocess that compiles the
# verify kernel from scratch (XLA-compile-bound, ~10 min on one core) —
# runs in test-slow/test-all (nightly/CI)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(tmp_path, extra_env):
    env = dict(
        os.environ,
        HANDEL_TPU_PLATFORM="cpu",
        HANDEL_TPU_BENCH_ARTIFACT=str(tmp_path / "bench_tpu.json"),
        HANDEL_TPU_BENCH_FP_ARTIFACT=str(tmp_path / "fp.json"),
        HANDEL_TPU_BENCH_FP_BATCH=str(1 << 10),
        HANDEL_TPU_MEASURE_BUDGET_S="1500",
        # tiny host-pipeline shape: the packing/dedup metrics plumbing is
        # exercised without the full 1024-key keygen per bench subprocess
        HANDEL_TPU_BENCH_HOST_SHAPE="64,8,3",
        **extra_env,
    )
    r = subprocess.run(
        [sys.executable, BENCH],
        capture_output=True,
        text=True,
        timeout=1500,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"exactly one JSON line expected: {r.stdout!r}"
    return json.loads(lines[0]), r


def test_accel_measurement_path_persists_artifact(tmp_path):
    """Forced accel shape on CPU: the headline line carries a real
    vs_baseline ratio and the persisted artifact carries provenance +
    per-trial times; the fp microbench artifact is written too."""
    line, _ = _run_bench(
        tmp_path,
        {"HANDEL_TPU_BENCH_FORCE_ACCEL_SHAPE": "16,4,4,2"},
    )
    assert line["metric"] == "16sig_batch_verify_p50_ms"
    assert line["unit"] == "ms"
    # a forced tiny-CPU run must not present a baseline ratio or read as
    # a real accelerator measurement
    assert line["vs_baseline"] is None
    assert line["forced_shape"] is True
    assert line["backend"] == "cpu"
    # host half of the pipeline rides the same line: packing p50 for the
    # vectorized packer and the old loop, and the dedup-trace hit rate
    assert line["host_pack_ms"] > 0
    assert line["host_pack_loop_ms"] > 0
    assert 0.0 <= line["dedup_hit_rate"] <= 1.0

    art = json.load(open(tmp_path / "bench_tpu.json"))
    assert art["backend"] == "cpu"  # provenance is honest about the force
    assert art["registry"] == 16 and art["lanes"] == 4
    assert len(art["trials_ms"]) == 2
    assert "captured_at" in art

    fp = json.load(open(tmp_path / "fp.json"))
    assert fp["metric"] == "fp254_mont_mul_throughput_marginal"
    # at the forced tiny CPU batch the chain-delta slope can be lost to
    # timing noise; a 0.0 capture is then persisted with the honest
    # invalid_measurement flag — accept either outcome (advisor, r04)
    assert fp["value"] > 0 or fp.get("invalid_measurement") is True
    assert fp["dispatch_floor_ms"] >= 0


def test_persisted_artifact_reemitted_on_outage(tmp_path):
    """With the backend probe skipped (CPU forced) and a persisted
    non-CPU artifact present, bench re-emits it instead of measuring —
    the tunnel-outage evidence path."""
    artifact = {
        "metric": "4096sig_batch_verify_p50_ms",
        "value": 112.0,
        "unit": "ms",
        "vs_baseline": 8.036,
        "backend": "tpu",
        "device": "TPU_0",
        "captured_at": "2026-01-01T00:00:00Z",
    }
    (tmp_path / "bench_tpu.json").write_text(json.dumps(artifact))
    env = dict(
        os.environ,
        HANDEL_TPU_BENCH_ARTIFACT=str(tmp_path / "bench_tpu.json"),
        HANDEL_TPU_PROBE_BUDGET_S="1",
        # deterministic probe failure: a live tunnel must not flip this test
        # onto the measurement path (sitecustomize overrides JAX_PLATFORMS,
        # so masking the platform name alone cannot force the outage)
        HANDEL_TPU_BENCH_FORCE_PROBE_FAIL="1",
    )
    env.pop("HANDEL_TPU_PLATFORM", None)  # force the probe path
    r = subprocess.run(
        [sys.executable, BENCH],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["source"] == "persisted"
    assert line["value"] == 112.0
    assert line["backend"] == "tpu"

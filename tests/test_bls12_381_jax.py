"""BLS12-381 device kernels vs the scalar oracle.

The second device curve (ops/pairing.py `BLS12Pairing`,
models/bls12_381_jax.py) validated bit-exactly against
ops/bls12_381_ref.py — same strategy as tests/test_pairing_jax.py: shared
B=4 shapes so every graph compiles once into the persistent cache.

Where the reference offers two interchangeable BN256 backends
(bn256/go/bn256.go, bn256/cf/bn256.go), this framework offers two device
curves behind one Constructor registry (simul/lib/config.go:211-225).
"""

import random

import jax
import numpy as np
import jax.numpy as jnp
import pytest

# slow tier: XLA-compile-bound (381-bit kernel graphs) — runs in
# test-slow/test-all (nightly/CI); the fast tier keeps the oracle +
# protocol + sharding guards
pytestmark = pytest.mark.slow

from handel_tpu.ops import bls12_381_ref as bls
from handel_tpu.ops.curve import BLS12Curves
from handel_tpu.ops.pairing import BLS12Pairing

B = 4  # lane count shared by every test


@pytest.fixture(scope="module", params=["cios", "rns"])
def stack(request):
    """Both Field backends; the rns param runs the residue-resident
    pairing (BLS12-381 bound walk: M-type twist lines, the z-power
    conjugate chain) against the same oracle assertions."""
    curves = BLS12Curves(backend=request.param)
    return curves, BLS12Pairing(curves)


def _rand_points(seed):
    rng = random.Random(seed)
    ks = [rng.randrange(1, bls.R) for _ in range(B)]
    ls = [rng.randrange(1, bls.R) for _ in range(B)]
    g1s = [bls.g1_mul(bls.G1_GEN, k) for k in ks]
    g2s = [bls.g2_mul(bls.G2_GEN, l) for l in ls]
    return ks, ls, g1s, g2s


def _pack_pairs(curves, g1s, g2s):
    xp = curves.F.pack([p[0] for p in g1s])
    yp = curves.F.pack([p[1] for p in g1s])
    xq = curves.T.f2_pack([q[0] for q in g2s])
    yq = curves.T.f2_pack([q[1] for q in g2s])
    return (xp, yp), (xq, yq)


def test_curve_ops_match_oracle(stack):
    curves, _ = stack
    _, _, g1s, g2s = _rand_points(2)
    P = curves.pack_g1(g1s)
    assert curves.unpack_g1(curves.g1.double(P)) == [
        bls.g1_add(p, p) for p in g1s
    ]
    Q = curves.pack_g2(g2s)
    assert curves.unpack_g2(curves.g2.add(Q, Q)) == [
        bls.g2_add(q, q) for q in g2s
    ]
    assert np.asarray(curves.g1.on_curve(P)).all()
    assert np.asarray(curves.g2.on_curve(Q)).all()


def test_pairing_matches_oracle(stack):
    curves, pr = stack
    _, _, g1s, g2s = _rand_points(3)
    p, q = _pack_pairs(curves, g1s, g2s)
    f = jax.jit(lambda p, q: pr.miller_loop(p, q))(p, q)
    got = curves.T.f12_unpack(f)
    exp = [bls.miller_loop(q_, p_) for p_, q_ in zip(g1s, g2s)]
    assert got == exp
    e = jax.jit(pr.final_exp)(f)
    assert curves.T.f12_unpack(e) == [bls.final_exponentiation(x) for x in exp]


def test_pairing_check_bls_verify(stack):
    """e(H, X_j) * e(-S_j, B2) == 1 for valid BLS signatures; corrupt lane
    rejected (bls12_381_ref.pairing_check device form)."""
    curves, pr = stack
    rng = random.Random(11)
    F, T = curves.F, curves.T
    h = bls.g1_mul(bls.G1_GEN, rng.randrange(1, bls.R))  # H(m)
    sks = [rng.randrange(1, bls.R) for _ in range(B)]
    pks = [bls.g2_mul(bls.G2_GEN, sk) for sk in sks]
    sigs = [bls.g1_mul(h, sk) for sk in sks]
    sigs[B - 1] = bls.g1_mul(bls.G1_GEN, 777)  # corrupt last lane

    px = F.pack([h[0]] * B + [bls.g1_neg(s)[0] for s in sigs])
    py = F.pack([h[1]] * B + [bls.g1_neg(s)[1] for s in sigs])
    qx = T.f2_pack([pk[0] for pk in pks] + [bls.G2_GEN[0]] * B)
    qy = T.f2_pack([pk[1] for pk in pks] + [bls.G2_GEN[1]] * B)
    mask = jnp.ones((2 * B,), bool)
    verdicts = np.asarray(
        jax.jit(lambda p, q, m: pr.pairing_check(p, q, m, B))(
            (px, py), (qx, qy), mask
        )
    )
    assert verdicts.tolist() == [True] * (B - 1) + [False]


def test_device_scheme_batch_verify():
    """models/bls12_381_jax.py end-to-end: host keygen/sign, device verify
    through the Constructor interface (batch of 4: 3 valid + 1 forged)."""
    from handel_tpu.core.bitset import BitSet
    from handel_tpu.models.bls12_381 import BLS12381Signature, new_keypair
    from handel_tpu.models.bls12_381_jax import BLS12381JaxConstructor

    rng = random.Random(13)
    N = 8
    keys = [new_keypair(seed=i) for i in range(N)]
    pks = [pk for _, pk in keys]
    msg = b"bls12-381 device e2e"
    reqs, expect = [], []
    for j in range(B):
        signers = sorted(rng.sample(range(N), rng.randrange(2, N)))
        bs = BitSet(N)
        sig = None
        for i in signers:
            bs.set(i, True)
            s = keys[i][0].sign(msg)
            sig = s if sig is None else sig.combine(s)
        if j == B - 1:
            sig = BLS12381Signature(bls.g1_mul(bls.G1_GEN, 12345))
            expect.append(False)
        else:
            expect.append(True)
        reqs.append((bs, sig))
    cons = BLS12381JaxConstructor(batch_size=B)
    assert cons.batch_verify(msg, pks, reqs) == expect


def test_scheme_registry_dispatch():
    from handel_tpu.models.registry import new_scheme

    scheme = new_scheme("bls12-381-jax", batch_size=4)
    sk, pk = scheme.keygen(0)
    assert scheme.unmarshal_public(pk.marshal()).point == pk.point

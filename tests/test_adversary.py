"""Byzantine roles, peer penalties, and the bounded verification queue.

Units for the hardening layers ISSUE 3 added around the adversaries:
role assignment determinism (sim/adversary.py), decaying penalty scores with
demote/ban semantics (core/penalty.py), packet-validation hardening
(core/handel.py), and the drop-oldest pending-queue bound
(core/processing.py).
"""

import asyncio
import random

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.identity import ArrayRegistry, Identity
from handel_tpu.core.net import Packet
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
from handel_tpu.core.penalty import PeerScorer
from handel_tpu.core.processing import BatchProcessing
from handel_tpu.models.fake import (
    FakeConstructor,
    FakePublic,
    FakeSecret,
    FakeSignature,
)
from handel_tpu.sim.adversary import (
    adversary_roles,
    check_threshold_reachable,
    forged_signature,
)


def run(coro):
    return asyncio.run(coro)


# -- role assignment ---------------------------------------------------------


def test_adversary_roles_deterministic_and_skips_offline():
    counts = {"invalid_signer": 2, "flooder": 1}
    a = adversary_roles(counts, 16, offline={15, 13})
    b = adversary_roles(counts, 16, offline={15, 13})
    assert a == b  # every process derives the same mapping
    assert a == {14: "invalid_signer", 12: "invalid_signer", 11: "flooder"}


def test_adversary_roles_overflow_raises():
    with pytest.raises(ValueError):
        adversary_roles({"invalid_signer": 4}, 4, offline={0, 3})


def test_threshold_reachability_check():
    roles = adversary_roles({"invalid_signer": 3}, 8)
    with pytest.raises(ValueError):
        check_threshold_reachable(6, 8, 0, roles)  # only 5 honest sigs exist
    check_threshold_reachable(5, 8, 0, roles)
    # stale replayers still contribute valid signatures
    roles2 = adversary_roles({"stale_replayer": 3}, 8)
    check_threshold_reachable(8, 8, 0, roles2)


def test_forged_signature_fails_verification():
    # fake scheme: message-independent, so the forgery is the explicit
    # invalid construction
    fake = forged_signature(FakeSecret(1), b"msg")
    assert not FakePublic(True).verify(b"msg", fake)
    # bn254: a wrong-message signature over a real key
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()
    sk, pk = scheme.keygen(1)
    forged = forged_signature(sk, b"msg")
    assert not pk.verify(b"msg", forged)
    assert pk.verify(b"msg", sk.sign(b"msg"))


# -- penalty scoring ---------------------------------------------------------


def test_scorer_demotes_then_bans():
    t = [0.0]
    s = PeerScorer(
        demote_threshold=2.0, ban_threshold=4.0, half_life_s=10.0,
        clock=lambda: t[0],
    )
    assert not s.demoted(3) and not s.banned(3)
    s.report(3)
    s.report(3)
    assert s.demoted(3) and not s.banned(3)
    s.report(3)
    s.report(3)
    assert s.banned(3)
    assert not s.demoted(3)  # banned dominates demoted
    assert s.values()["peersBanned"] == 1.0


def test_scorer_decay_forgives():
    t = [0.0]
    s = PeerScorer(
        demote_threshold=2.0, ban_threshold=50.0, half_life_s=1.0,
        clock=lambda: t[0],
    )
    s.report(1)
    s.report(1)
    assert s.demoted(1)
    t[0] = 10.0  # ten half-lives: score ~2/1024
    assert not s.demoted(1)
    assert s.score(1) < 0.01


def test_scorer_ban_set_is_bounded():
    s = PeerScorer(ban_threshold=1.0, demote_threshold=0.5, ban_capacity=2)
    for peer in range(5):
        s.report(peer, weight=2.0)
    assert s.values()["peersBanned"] == 2.0
    assert s.values()["peerBanRefused"] > 0


def test_level_selection_skips_banned_and_halves_demoted():
    from handel_tpu.core.handel import Level

    idents = [Identity(i, f"x-{i}", None) for i in range(4)]
    scorer = PeerScorer(demote_threshold=2.0, ban_threshold=10.0)
    lvl = Level(1, idents, 4, scorer)
    scorer.report(2, weight=3.0)  # demoted
    picked = [p.id for p in lvl.select_next_peers(8)]
    assert 2 not in picked  # first encounter skipped (window refills past it)
    assert lvl.demote_skips == 1
    picked_next = [p.id for p in lvl.select_next_peers(8)]
    assert 2 in picked_next  # every OTHER encounter goes through

    banned = PeerScorer(demote_threshold=5.0, ban_threshold=5.0)
    lvl2 = Level(1, idents, 4, banned)
    banned.report(1, weight=6.0)
    picked2 = [p.id for p in lvl2.select_next_peers(8)]
    assert 1 not in picked2
    assert lvl2.banned_skips > 0
    # all-banned level degrades to empty selection, not a spin
    for i in range(4):
        banned.report(i, weight=6.0)
    assert lvl2.select_next_peers(4) == []


# -- packet validation hardening ---------------------------------------------


def _one_node_cluster(n=8):
    from handel_tpu.core.test_harness import LocalCluster

    return LocalCluster(n, seed=3)


def test_validate_rejects_own_origin_before_parsing():
    cluster = _one_node_cluster()
    h = cluster.handels[0]
    bs = BitSet(len(h.levels[1].nodes))
    bs.set(0)
    good = MultiSignature(bs, FakeSignature()).marshal()
    h.new_packet(Packet(origin=0, level=1, multisig=good))  # self-origin
    assert h.invalid_packet_ct == 1
    assert len(h.proc.pending()) == 0


def test_banned_origin_dropped_and_counted():
    cluster = _one_node_cluster()
    h = cluster.handels[0]
    for _ in range(20):  # drive origin 1 over the ban threshold
        h.scorer.report(1)
    assert h.scorer.banned(1)
    bs = BitSet(len(h.levels[1].nodes))
    bs.set(0)
    good = MultiSignature(bs, FakeSignature()).marshal()
    h.new_packet(Packet(origin=1, level=1, multisig=good))
    assert h.banned_packet_ct == 1
    assert len(h.proc.pending()) == 0


def test_parse_failures_attributed_to_origin():
    cluster = _one_node_cluster()
    h = cluster.handels[0]
    before = h.scorer.score(2)
    h.new_packet(Packet(origin=2, level=1, multisig=b"\xff"))  # unparseable
    assert h.invalid_packet_ct == 1
    assert h.scorer.score(2) > before


def test_invalid_signer_gets_banned_end_to_end():
    """A node fed a stream of garbage aggregates from one origin penalizes
    it into the ban set; subsequent packets die at validation."""

    async def go():
        cluster = _one_node_cluster()
        h = cluster.handels[0]
        h.proc.start()
        bs = BitSet(len(h.levels[1].nodes))
        bs.set(0)
        rng = random.Random(9)
        sent = 0
        for _ in range(100):
            if h.scorer.banned(1):
                break
            # content-distinct invalid multisigs (random sig bytes)
            wire = bs.marshal() + rng.randbytes(8)
            h.new_packet(Packet(origin=1, level=1, multisig=wire))
            sent += 1
            await asyncio.sleep(0.01)
        assert h.scorer.banned(1), "origin 1 never banned"
        before = h.banned_packet_ct
        h.new_packet(Packet(origin=1, level=1, multisig=bs.marshal() + b"\x00" * 8))
        assert h.banned_packet_ct == before + 1
        h.proc.stop()

    run(go())


# -- bounded pending queue ---------------------------------------------------


def _make_proc(**kwargs):
    reg = ArrayRegistry(
        [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
    )
    part = BinomialPartitioner(0, reg)
    verified = []

    async def never(msg, pubkeys, requests):  # pipeline never runs in these
        return [True] * len(requests)

    proc = BatchProcessing(
        part,
        FakeConstructor(),
        b"m",
        [None] * 8,
        type("E", (), {"evaluate": staticmethod(lambda sp: 1)})(),
        verified.append,
        verifier=never,
        **kwargs,
    )
    return proc, verified


def _sig(origin, marker=0):
    bs = BitSet(1)
    bs.set(0)
    return IncomingSig(
        origin=origin, level=1, ms=MultiSignature(bs, FakeSignature())
    )


def test_pending_queue_drop_oldest():
    proc, _ = _make_proc(max_pending=4)
    sigs = [_sig(origin=i % 7 + 1) for i in range(6)]
    for sp in sigs:
        proc.add(sp)
    assert proc.sig_dropped_overflow == 2
    assert proc.pending() == sigs[2:]  # oldest two evicted
    # the heap's dead entries are skipped, not selected
    batch = proc._select_batch()
    assert batch == sigs[2:]
    assert proc.pending() == []


def test_pending_queue_bound_in_fifo_pipeline():
    from handel_tpu.core.processing import FifoProcessing

    reg = ArrayRegistry(
        [Identity(i, f"x-{i}", FakePublic(True)) for i in range(8)]
    )
    part = BinomialPartitioner(0, reg)
    proc = FifoProcessing(
        part,
        FakeConstructor(),
        b"m",
        [None] * 8,
        type("E", (), {"evaluate": staticmethod(lambda sp: 1)})(),
        lambda sp: None,
        max_pending=3,
    )
    sigs = [_sig(origin=i + 1) for i in range(5)]
    for sp in sigs:
        proc.add(sp)
    assert proc.sig_dropped_overflow == 2
    assert proc.pending() == sigs[2:]


def test_overflow_counter_reported():
    proc, _ = _make_proc(max_pending=1)
    proc.add(_sig(1))
    proc.add(_sig(2))
    assert proc.values()["sigDroppedOverflow"] == 1.0


# -- RLC batch-check culprit attribution -------------------------------------


def test_rlc_bisection_isolates_culprits_and_matches_per_candidate_penalties():
    """A forged aggregate inside an RLC combined launch (models/rlc.py via
    service/driver.py HostDevice) is isolated by bisection to exactly the
    per-candidate culprit set, so PeerScorer penalties attributed off the
    verdicts are bit-for-bit identical to per_candidate mode."""
    from handel_tpu.models.bn254 import BN254Scheme
    from handel_tpu.service.driver import HostDevice

    scheme = BN254Scheme()
    keys = [scheme.keygen(i) for i in range(8)]
    pubs = [pk for _, pk in keys]

    def agg(msg, idxs, forge=False):
        bs = BitSet(8)
        sig = None
        for i in idxs:
            bs.set(i)
            s = forged_signature(keys[i][0], msg) if forge else keys[i][0].sign(msg)
            sig = s if sig is None else sig.combine(s)
        return (msg, pubs, bs, sig)

    # six candidates over two messages; 1 and 4 are forged aggregates
    items = [
        agg(b"m1", [0, 1]),
        agg(b"m1", [2, 3], forge=True),
        agg(b"m1", [4, 5, 6]),
        agg(b"m2", [1, 2]),
        agg(b"m2", [3, 7], forge=True),
        agg(b"m2", [5]),
    ]
    origins = [3, 4, 5, 6, 7, 2]  # packet origin of each candidate

    pc = HostDevice(scheme.constructor)
    v_pc = pc.fetch(pc.dispatch_multi(items))
    assert v_pc == [True, False, True, True, False, True]

    dev = HostDevice(
        scheme.constructor, batch_check="rlc", rlc_rng=random.Random(7)
    )
    v_rlc = dev.fetch(dev.dispatch_multi(items))
    assert v_rlc == v_pc  # bisection reached the exact culprit set
    st = dev.rlc_stats
    assert st.rlc_launches == 1
    assert st.bisection_ct > 0 and st.bisection_depth_max >= 1

    # attribute each failed verdict to its packet origin, as
    # Handel._on_verify_failed does — identical verdicts give identical
    # scorer state in both modes
    def attribute(verdicts):
        scorer = PeerScorer(clock=lambda: 0.0)
        for origin, ok in zip(origins, verdicts):
            if not ok:
                scorer.report(origin)
        return scorer

    a, b = attribute(v_rlc), attribute(v_pc)
    assert a.reports == b.reports == 2
    for origin in origins:
        assert a.score(origin) == b.score(origin), origin
    assert a.score(4) > 0 and a.score(7) > 0

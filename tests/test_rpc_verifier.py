"""Batch-plane RPC protocol tests (parallel/rpc_verifier.py) — framing,
multiplexing, error propagation, link-loss recovery — against a stub
verification service (no device, no jax)."""

import asyncio
import struct

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.models.fake import FakeConstructor, FakeSignature
from handel_tpu.parallel.rpc_verifier import RPCVerifier, VerifierServer


class StubService:
    """Echoes bit 0 of each candidate's bitset as its verdict."""

    def __init__(self):
        self.calls = 0

    async def verify(self, msg, pubkeys, requests):
        self.calls += 1
        return [bs.get(0) for bs, _ in requests]


def _requests(pattern):
    out = []
    for bit in pattern:
        bs = BitSet(8)
        bs.set(0, bit)
        bs.set(3, True)
        out.append((bs, FakeSignature(True)))
    return out


def test_rpc_roundtrip_and_multiplexing():
    async def go():
        svc = StubService()
        server = VerifierServer(svc, FakeConstructor(), host="127.0.0.1")
        await server.start()
        client = RPCVerifier(f"127.0.0.1:{server.port}")
        # several concurrent in-flight requests over the one connection
        outs = await asyncio.gather(
            client.verify(b"m", None, _requests([True, False, True])),
            client.verify(b"m", None, _requests([False, False])),
            client.verify(b"other", None, _requests([True])),
        )
        assert outs == [[True, False, True], [False, False], [True]]
        assert svc.calls == 3
        assert server.requests_served == 3
        assert server.candidates_served == 6
        assert client.values()["rpcSentCandidates"] == 6
        client.stop()
        server.stop()

    asyncio.run(go())


def test_rpc_server_error_propagates_not_crashes():
    class Exploding:
        async def verify(self, msg, pubkeys, requests):
            raise RuntimeError("device on fire")

    async def go():
        server = VerifierServer(Exploding(), FakeConstructor(), host="127.0.0.1")
        await server.start()
        client = RPCVerifier(f"127.0.0.1:{server.port}")
        with pytest.raises(RuntimeError, match="device on fire"):
            await client.verify(b"m", None, _requests([True]))
        # link survives an application error: next request still answered
        server.service = StubService()
        assert await client.verify(b"m", None, _requests([True])) == [True]
        assert server.errors == 1
        client.stop()
        server.stop()

    asyncio.run(go())


def test_rpc_malformed_frame_rejected():
    async def go():
        server = VerifierServer(StubService(), FakeConstructor(), host="127.0.0.1")
        await server.start()
        reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
        # header declares ONE item but carries no item bytes
        garbage = struct.pack(">QIH", 7, 0, 1)
        writer.write(struct.pack(">I", len(garbage)) + garbage)
        await writer.drain()
        body = await asyncio.wait_for(reader.readexactly(4), 5)
        (ln,) = struct.unpack(">I", body)
        resp = await asyncio.wait_for(reader.readexactly(ln), 5)
        rid, status = struct.unpack_from(">QB", resp, 0)
        assert status == 1  # error response, server still alive
        # the request's id must round-trip even though unpacking failed —
        # an id-0 error response would resolve no client future (hang)
        assert rid == 7
        writer.close()
        server.stop()

    asyncio.run(go())


def test_rpc_link_loss_fails_inflight_then_reconnects():
    async def go():
        class Stalling:
            """Holds requests until released."""

            def __init__(self):
                self.gate = asyncio.Event()

            async def verify(self, msg, pubkeys, requests):
                await self.gate.wait()
                return [True] * len(requests)

        svc = Stalling()
        server = VerifierServer(svc, FakeConstructor(), host="127.0.0.1")
        await server.start()
        client = RPCVerifier(f"127.0.0.1:{server.port}", retry_delay=0.05)
        task = asyncio.create_task(
            client.verify(b"m", None, _requests([True]))
        )
        await asyncio.sleep(0.1)  # request in flight, stalled server-side
        server.stop()
        # kill the server-side connection by cancelling through close
        client._writer.close()
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(task, 5)
        # a fresh server on the same port concept: reconnect path works
        server2 = VerifierServer(StubService(), FakeConstructor(), host="127.0.0.1")
        await server2.start()
        client2 = RPCVerifier(f"127.0.0.1:{server2.port}")
        assert await client2.verify(b"m", None, _requests([True])) == [True]
        client.stop()
        client2.stop()
        server2.stop()

    asyncio.run(go())

"""WAN scenario engine: weights plane, churn reachability, geo model.

Units for ISSUE 13's composed scenario axes: the weighted-bitset hot path
against a scalar oracle (count-weights must reproduce popcount exactly),
the churn-aware threshold reachability check at the margin, the seeded
GeoNetwork delay distribution, membership-schedule determinism, the
confgen `[scenario]` TOML round-trip, and one small end-to-end run per
axis through `run_scenario`.
"""

import asyncio
import math
import random

import pytest

from handel_tpu.core.bitset import AllOnesBitSet, BitSet
from handel_tpu.core.identity import Identity
from handel_tpu.models.fake import FakePublic
from handel_tpu.network.geo import GeoConfig, GeoNetwork
from handel_tpu.scenario import (
    MembershipSchedule,
    make_weights,
    planet_names,
    planet_preset,
    run_scenario,
)
from handel_tpu.sim.adversary import adversary_roles, check_threshold_reachable
from handel_tpu.sim.config import dump_config, load_config
from handel_tpu.sim.confgen import (
    scenario_churn,
    scenario_geo,
    scenario_geo_weighted,
    scenario_weighted,
)


def run(coro):
    return asyncio.run(coro)


# -- weighted bitset vs scalar oracle ---------------------------------------


def test_weight_sum_matches_scalar_oracle():
    rng = random.Random(13)
    for n in (1, 7, 64, 200):
        weights = [rng.uniform(0.1, 5.0) for _ in range(n)]
        for _ in range(20):
            bs = BitSet(n)
            for i in range(n):
                if rng.random() < 0.4:
                    bs.set(i, True)
            oracle = sum(weights[i] for i in bs.indices())
            assert math.isclose(bs.weight_sum(weights), oracle, rel_tol=1e-12)


def test_count_weights_reproduce_popcount_exactly():
    # the strict no-op contract: all-1.0 weights == cardinality, bit-exact
    rng = random.Random(7)
    for n in (1, 33, 512):
        ones = [1.0] * n
        bs = BitSet(n)
        for i in range(n):
            if rng.random() < 0.5:
                bs.set(i, True)
        assert bs.weight_sum(ones) == float(bs.cardinality())
        assert AllOnesBitSet(n).weight_sum(ones) == float(n)


def test_weight_sum_empty_and_full():
    weights = [2.0, 3.0, 5.0, 7.0]
    assert BitSet(4).weight_sum(weights) == 0.0
    full = BitSet(4)
    for i in range(4):
        full.set(i, True)
    assert full.weight_sum(weights) == pytest.approx(17.0)
    assert AllOnesBitSet(4).weight_sum(weights) == pytest.approx(17.0)


# -- weight profiles ---------------------------------------------------------


def test_weight_profiles_deterministic_and_normalized():
    n = 64
    assert make_weights("count", n) == [1.0] * n
    for profile in ("linear", "pareto", "split"):
        a = make_weights(profile, n, seed=3)
        b = make_weights(profile, n, seed=3)
        assert a == b
        assert sum(a) == pytest.approx(float(n))  # normalized to sum == n
    assert make_weights("pareto", n, seed=3) != make_weights("pareto", n, seed=4)
    with pytest.raises(ValueError):
        make_weights("nope", n)


# -- churn-aware threshold reachability --------------------------------------


def test_churn_reachability_count_margin():
    # 16 nodes, 2 churners, 1 failing -> 13 guaranteed honest contributions
    roles = adversary_roles({"churner": 2}, 16)
    check_threshold_reachable(12, 16, 1, roles)  # below margin
    check_threshold_reachable(13, 16, 1, roles)  # at margin
    with pytest.raises(ValueError):
        check_threshold_reachable(14, 16, 1, roles)  # above margin


def test_departed_identities_reduce_reachability():
    check_threshold_reachable(14, 16, 0, {}, departed={1, 2})
    with pytest.raises(ValueError):
        check_threshold_reachable(15, 16, 0, {}, departed={1, 2})
    # departed churners are not double-counted
    roles = adversary_roles({"churner": 2}, 16)
    departed = set(roles)
    check_threshold_reachable(14, 16, 0, roles, departed=departed)


def test_weighted_reachability_counts_heaviest_failing():
    # ids 0..3 weights 1,2,3,10; churner on id 3 removes the whale; the one
    # failing node then worst-cases onto the heaviest survivor (3.0)
    weights = [1.0, 2.0, 3.0, 10.0]
    roles = {3: "churner"}
    check_threshold_reachable(0, 4, 1, roles, weights=weights,
                              weight_threshold=3.0)
    with pytest.raises(ValueError):
        check_threshold_reachable(0, 4, 1, roles, weights=weights,
                                  weight_threshold=3.1)
    # derived threshold path: want = threshold * sum(w) / n, 6.0 reachable
    check_threshold_reachable(1, 4, 0, roles, weights=weights)  # want 4.0
    with pytest.raises(ValueError):
        check_threshold_reachable(2, 4, 0, roles, weights=weights)  # want 8.0


# -- geo model ----------------------------------------------------------------


class _CountingInner:
    def __init__(self):
        self.sent = []

    def send(self, idents, packet):
        self.sent.append((list(idents), packet))


def _geo(seed=7, jitter=0.0):
    return GeoConfig(
        regions=("a", "b"),
        rtt_ms=((0.0, 100.0), (100.0, 0.0)),
        jitter_ms=jitter,
        seed=seed,
        node_id=0,  # region "a"
    )


def test_geo_rtt_distribution_sanity():
    net = GeoNetwork(_CountingInner(), _geo(jitter=5.0))
    far = Identity(1, "fake-1", FakePublic(True))  # region "b"
    near = Identity(2, "fake-2", FakePublic(True))  # region "a"
    samples = [net.sample_delay_ms(far) for _ in range(600)]
    mean = sum(samples) / len(samples)
    assert abs(mean - 50.0) < 2.0  # one-way = RTT/2, jitter is zero-mean
    assert min(samples) >= 0.0
    sd = math.sqrt(sum((s - mean) ** 2 for s in samples) / len(samples))
    assert 3.5 < sd < 6.5
    # same-region link: pure jitter around 0, clamped non-negative
    assert all(0.0 <= net.sample_delay_ms(near) < 30.0 for _ in range(50))


def test_geo_sampling_is_seed_deterministic():
    far = Identity(1, "fake-1", FakePublic(True))
    a = [GeoNetwork(_CountingInner(), _geo(seed=7, jitter=3.0))
         .sample_delay_ms(far) for _ in range(1)]
    a_again = [GeoNetwork(_CountingInner(), _geo(seed=7, jitter=3.0))
               .sample_delay_ms(far) for _ in range(1)]
    b = [GeoNetwork(_CountingInner(), _geo(seed=8, jitter=3.0))
         .sample_delay_ms(far) for _ in range(1)]
    assert a == a_again
    assert a != b


def test_geo_records_delay_histogram_and_counter():
    inner = _CountingInner()
    net = GeoNetwork(inner, _geo())
    far = Identity(1, "fake-1", FakePublic(True))
    pkt = object()
    net._deliver(far, pkt)  # no running loop: sync fallback still records
    assert inner.sent, "sync fallback must deliver immediately"
    assert net.geo_delayed == 1
    hists = net.histograms()
    assert "delayMs" in hists
    assert hists["delayMs"].count == 1
    assert net.values()["geoDelayed"] == 1.0


def test_planet_presets_validate():
    for name in planet_names():
        regions, rtt = planet_preset(name)
        GeoConfig(regions=regions, rtt_ms=rtt).validate()
        # symmetric, with intra-region RTT strictly the row minimum
        n = len(regions)
        for i in range(n):
            assert rtt[i][i] == min(rtt[i])
            for j in range(n):
                assert rtt[i][j] == rtt[j][i]
                if i != j:
                    assert rtt[i][j] > rtt[i][i]
    with pytest.raises(ValueError):
        planet_preset("planet-unknown")


# -- membership schedule ------------------------------------------------------


def test_membership_schedule_deterministic_and_staggered():
    a = MembershipSchedule(32, churner_ids=[29, 30, 31], churn_after_s=0.4,
                           joins=2, join_at_s=1.0, seed=5)
    b = MembershipSchedule(32, churner_ids=[31, 30, 29], churn_after_s=0.4,
                           joins=2, join_at_s=1.0, seed=5)
    assert a.events == b.events  # id order at the call site is irrelevant
    leaves = a.leaves()
    assert {e.node_id for e in leaves} == {29, 30, 31}
    for e in leaves:
        assert 0.4 * 0.75 <= e.at_s <= 0.4 * 1.25
    assert len(set(e.at_s for e in leaves)) == 3  # actually staggered
    assert [e.node_id for e in a.joins()] == [32, 33]
    assert a.final_size() == 32 - 3 + 2
    assert a.leave_time_of(30) is not None
    assert a.leave_time_of(0) is None


# -- confgen round-trip -------------------------------------------------------


@pytest.mark.parametrize(
    "factory", [scenario_geo, scenario_churn, scenario_weighted,
                scenario_geo_weighted],
)
def test_scenario_toml_round_trip(factory, tmp_path):
    cfg = factory()
    text = dump_config(cfg)
    path = tmp_path / "scenario.toml"
    path.write_text(text)
    reloaded = load_config(str(path))
    assert dump_config(reloaded) == text  # stable fixed point
    s0, s1 = cfg.scenario, reloaded.scenario
    assert s1.enabled()
    assert (s1.name, s1.planet, s1.weight_profile, s1.joins) == (
        s0.name, s0.planet, s0.weight_profile, s0.joins
    )
    assert s1.weight_threshold_frac == s0.weight_threshold_frac
    a0, a1 = cfg.runs[0].adversaries, reloaded.runs[0].adversaries
    assert a1.churner == a0.churner
    if a0.churner:  # churn_after_ms only rides the wire with a churner
        assert a1.churn_after_ms == a0.churn_after_ms


# -- end-to-end scenario runs (small, fake scheme) ---------------------------


def _shrink(cfg, nodes):
    cfg.runs[0].nodes = nodes
    cfg.runs[0].threshold = 0  # re-derive the default for the new size
    return cfg


def test_run_scenario_geo_end_to_end(tmp_path):
    cfg = _shrink(scenario_geo(), 8)
    report = run(run_scenario(cfg, str(tmp_path)))
    assert report["ok"], report["checks"]
    assert report["scenario"]["regions"]
    assert report["checks"]["region_attributed"]
    assert (tmp_path / "scenario_report.json").exists()
    assert (tmp_path / "scenario_trace.json").exists()


@pytest.mark.slow
def test_run_scenario_churn_weighted_end_to_end(tmp_path):
    cfg = scenario_geo_weighted(32)
    report = run(run_scenario(cfg, str(tmp_path)))
    assert report["ok"], report["checks"]
    s = report["scenario"]
    assert s["churners"] >= 3 and s["departed_ids"]
    assert s["epochs_advanced"] >= 1
    assert s["achieved_weight"] >= s["weight_threshold"] - 1e-9

"""Ed25519 baseline scheme: RFC 8032 vectors, set-union aggregation, wire.

The non-aggregating control group for the BLS schemes (models/eddsa.py):
correctness against the RFC test vectors, the kid-tagged signature-set
combine semantics, the fixed-envelope wire round-trip through the
Constructor contract, and registry dispatch.
"""

import asyncio

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature, verify_multisignature
from handel_tpu.core.identity import ArrayRegistry, Identity
from handel_tpu.models.eddsa import (
    MAX_SIGNERS,
    EdDSAScheme,
    EdDSASecretKey,
    new_keypair,
)
from handel_tpu.models.registry import is_device_scheme, new_scheme

MSG = b"eddsa unit message"


def test_rfc8032_vectors():
    # RFC 8032 §7.1 TEST 1 (empty message) and TEST 3 (two bytes)
    sk1 = EdDSASecretKey(bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"))
    assert sk1.enc_pub == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a")
    assert next(iter(sk1.sign(b"").sigs.values())) == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249015"
        "55fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b")
    sk3 = EdDSASecretKey(bytes.fromhex(
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"))
    assert next(iter(sk3.sign(b"\xaf\x82").sigs.values())) == bytes.fromhex(
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3a"
        "c18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a")


def test_sign_verify_and_reject():
    sk, pk = new_keypair(seed=1)
    sig = sk.sign(MSG)
    assert pk.verify(MSG, sig)
    assert not pk.verify(b"other", sig)
    _, pk2 = new_keypair(seed=2)
    assert not pk2.verify(MSG, sig)  # wrong key: no matching kid entry


def test_combine_is_union_and_order_independent():
    pairs = [new_keypair(seed=i) for i in range(5)]
    sigs = [sk.sign(MSG) for sk, _ in pairs]
    fwd = sigs[0]
    for s in sigs[1:]:
        fwd = fwd.combine(s)
    rev = sigs[-1]
    for s in reversed(sigs[:-1]):
        rev = rev.combine(s)
    assert fwd.sigs == rev.sigs
    agg_pk = pairs[0][1]
    for _, pk in pairs[1:]:
        agg_pk = agg_pk.combine(pk)
    assert agg_pk.verify(MSG, fwd)
    # one missing entry fails the aggregate check
    partial = sigs[0]
    for s in sigs[1:-1]:
        partial = partial.combine(s)
    assert not agg_pk.verify(MSG, partial)


def test_wire_round_trip_fixed_envelope():
    scheme = EdDSAScheme()
    pairs = [scheme.keygen(i) for i in range(9)]
    agg = pairs[0][0].sign(MSG)
    for sk, _ in pairs[1:]:
        agg = agg.combine(sk.sign(MSG))
    wire = agg.marshal()
    assert len(wire) == scheme.constructor.signature_size()
    back = scheme.constructor.unmarshal_signature(wire)
    assert back.sigs == agg.sigs
    with pytest.raises(ValueError):
        scheme.constructor.unmarshal_signature(wire[:100])


def test_capacity_enforced():
    pairs = [new_keypair(seed=i) for i in range(MAX_SIGNERS + 1)]
    agg = pairs[0][0].sign(MSG)
    for sk, _ in pairs[1:]:
        agg = agg.combine(sk.sign(MSG))
    with pytest.raises(ValueError):
        agg.marshal()


def test_public_key_round_trip():
    scheme = EdDSAScheme()
    sk, pk = scheme.keygen(4)
    enc = pk.marshal()
    assert len(enc) == 32
    assert scheme.unmarshal_public(enc).verify(MSG, sk.sign(MSG))
    assert scheme.unmarshal_secret(sk.marshal()).enc_pub == sk.enc_pub


def test_registry_dispatch_and_multisignature():
    scheme = new_scheme("ed25519")
    assert not is_device_scheme("eddsa")
    n = 6
    pairs = [scheme.keygen(i) for i in range(n)]
    reg = ArrayRegistry(
        [Identity(i, f"eddsa-{i}", pk) for i, (_, pk) in enumerate(pairs)]
    )
    bs = BitSet(n)
    agg = None
    for i in (0, 2, 5):
        bs.set(i, True)
        s = pairs[i][0].sign(MSG)
        agg = s if agg is None else agg.combine(s)
    ms = MultiSignature(bs, agg)
    assert verify_multisignature(MSG, ms, reg, scheme.constructor)
    wire = ms.marshal()
    back = MultiSignature.unmarshal(wire, scheme.constructor)
    assert verify_multisignature(MSG, back, reg, scheme.constructor)
    # a bitset claiming a signer whose entry is absent must fail
    bs.set(1, True)
    assert not verify_multisignature(
        MSG, MultiSignature(bs, agg), reg, scheme.constructor
    )


@pytest.mark.slow
def test_protocol_round_over_eddsa():
    from handel_tpu.core.test_harness import LocalCluster

    async def go():
        cluster = LocalCluster(8, threshold=8, scheme=new_scheme("eddsa"))
        cluster.start()
        try:
            finals = await cluster.wait_complete_success(timeout=60)
        finally:
            cluster.stop()
        assert next(iter(finals.values())).bitset.cardinality() == 8

    asyncio.run(go())

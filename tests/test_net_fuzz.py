"""Fuzz/property tests for the wire codecs (ISSUE 3 satellite).

A byzantine peer controls every byte of a datagram, so `Packet.decode` (and
the `MultiSignature`/`BitSet` unmarshal stack behind it) must hold one
contract under arbitrary input: return a valid object or raise `ValueError`
— never a different exception, never a crash, never an over-read past the
buffer.
"""

import random
import struct

import pytest

from handel_tpu.core.bitset import MAX_WIRE_BITS, BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.net import Packet
from handel_tpu.models.fake import FakeConstructor, FakeSignature


def _random_packet(rng: random.Random) -> Packet:
    ms = rng.randbytes(rng.randrange(0, 64))
    ind = rng.randbytes(rng.randrange(1, 16)) if rng.random() < 0.5 else None
    return Packet(
        origin=rng.randrange(-(2**31), 2**31),
        level=rng.randrange(256),
        multisig=ms,
        individual_sig=ind,
    )


def test_packet_roundtrip_property():
    rng = random.Random(1)
    for _ in range(200):
        p = _random_packet(rng)
        q = Packet.decode(p.encode())
        assert (q.origin, q.level, q.multisig) == (p.origin, p.level, p.multisig)
        assert q.individual_sig == (p.individual_sig or None)


def test_packet_decode_truncations_raise_valueerror():
    rng = random.Random(2)
    for _ in range(50):
        wire = _random_packet(rng).encode()
        for cut in range(len(wire)):
            with pytest.raises(ValueError):
                Packet.decode(wire[:cut])


def test_packet_decode_oversized_length_fields():
    """Header length fields larger than the actual payload must raise, not
    over-read (a short buffer silently yielding truncated fields would let
    corrupt packets masquerade as valid)."""
    import struct

    for ms_len, ind_len, payload in [
        (0xFFFF, 0, b""),
        (8, 0xFFFF, b"x" * 8),
        (16, 16, b"y" * 20),  # sum exceeds what's there
    ]:
        wire = struct.pack(">iBHH", 1, 1, ms_len, ind_len) + payload
        with pytest.raises(ValueError):
            Packet.decode(wire)


def test_packet_decode_random_bytes_never_crash():
    rng = random.Random(3)
    outcomes = {"ok": 0, "rejected": 0}
    for _ in range(2000):
        data = rng.randbytes(rng.randrange(0, 96))
        try:
            p = Packet.decode(data)
        except ValueError:
            outcomes["rejected"] += 1
            continue
        outcomes["ok"] += 1
        # anything that decoded must re-encode without error and with
        # consistent field lengths (no over-read captured trailing junk)
        assert len(p.multisig) <= len(data)
        p.encode()
    assert outcomes["rejected"] > 0  # the guards actually fire


def test_packet_decode_corrupt_valid_packets():
    """Random byte flips over valid encodings: decode raises ValueError or
    yields a structurally consistent packet — corrupt length prefixes must
    not leak into negative-size or over-read states."""
    rng = random.Random(4)
    for _ in range(300):
        p = _random_packet(rng)
        wire = bytearray(p.encode())
        for _ in range(rng.randint(1, 4)):
            wire[rng.randrange(len(wire))] ^= 1 << rng.randrange(8)
        try:
            q = Packet.decode(bytes(wire))
        except ValueError:
            continue
        assert 0 <= q.level <= 255
        assert len(q.multisig) + len(q.individual_sig or b"") <= len(wire)


def test_packet_trace_context_roundtrip():
    """span_id/hop ride an optional wire trailer: present when set,
    absent (zero overhead) when not, and hop normalizes to 0/1."""
    rng = random.Random(6)
    for _ in range(200):
        p = _random_packet(rng)
        p.span_id = rng.randrange(2**64) if rng.random() < 0.7 else 0
        p.hop = rng.randrange(2) if p.span_id else 0
        wire = p.encode()
        q = Packet.decode(wire)
        assert (q.span_id, q.hop) == (p.span_id, p.hop)
        if not p.span_id and not p.hop:
            # untraced packets carry no trailer at all
            assert len(wire) == len(
                Packet(p.origin, p.level, p.multisig, p.individual_sig).encode()
            )


def test_packet_trace_trailer_truncation_degrades_to_unlinked():
    """A corrupt or truncated trace trailer must never raise: the packet
    decodes with span_id=0/hop=0 ("unlinked") as long as the legacy fields
    are intact — trace context is best-effort metadata, not payload."""
    rng = random.Random(7)
    for _ in range(100):
        p = _random_packet(rng)
        p.span_id = rng.randrange(1, 2**64)
        p.hop = 1
        wire = p.encode()
        base_len = len(wire) - Packet._TRAILER.size
        # every partial cut of the trailer -> unlinked, never an error
        for cut in range(base_len, len(wire)):
            q = Packet.decode(wire[:cut])
            assert (q.span_id, q.hop) == (0, 0)
            assert (q.origin, q.level, q.multisig) == (
                p.origin, p.level, p.multisig)


def test_packet_trace_trailer_hop_normalized():
    """Arbitrary trailing hop bytes (byzantine sender) normalize to 0/1."""
    rng = random.Random(8)
    for _ in range(100):
        p = _random_packet(rng)
        base = p.encode()
        trailer = Packet._TRAILER.pack(
            rng.randrange(2**64), rng.randrange(256))
        q = Packet.decode(base + trailer)
        assert q.hop in (0, 1)
        assert q.span_id >= 0
        q.encode()  # re-encode of whatever decoded must not raise


def test_multisig_unmarshal_fuzz():
    cons = FakeConstructor()
    rng = random.Random(5)
    for _ in range(1000):
        data = rng.randbytes(rng.randrange(0, 48))
        try:
            ms = MultiSignature.unmarshal(data, cons)
        except ValueError:
            continue
        # the wire cap is MAX_WIRE_BITS since the extended (escape) form —
        # swarm committees marshal bitsets well past the legacy 0xFFFF
        assert len(ms.bitset) <= MAX_WIRE_BITS


def test_bitset_sparse_roundtrip_property():
    """Sparse (varint-delta) wire form: random sizes past the legacy
    0xFFFF cap with sparse populations must round-trip exactly and beat
    the dense encoding (that is the only reason marshal picks it)."""
    rng = random.Random(9)
    for _ in range(50):
        n = rng.randrange(1, MAX_WIRE_BITS + 1)
        bs = BitSet(n)
        for _ in range(rng.randrange(0, 16)):
            bs.set(rng.randrange(n), True)
        wire = bs.marshal()
        assert len(wire) < (n + 7) // 8 + 7 or n < 512
        out, used = BitSet.unmarshal(wire)
        assert used == len(wire)
        assert out == bs and out.cardinality() == bs.cardinality()


def test_bitset_extended_dense_roundtrip():
    """Dense populations past 0xFFFF take the extended-dense escape."""
    rng = random.Random(10)
    for n in (0xFFFF, 0x10000, 0x10001, 1 << 17):
        bs = BitSet(n)
        bs.set_range(0, n // 2)
        for _ in range(64):
            bs.set(rng.randrange(n), True)
        out, used = BitSet.unmarshal(bs.marshal())
        assert used == len(bs.marshal())
        assert out == bs


def test_bitset_sparse_truncation_raises():
    """Every prefix cut of a sparse encoding raises ValueError — varint
    payloads must not silently yield a shorter population."""
    bs = BitSet(1 << 20)
    for i in range(0, 1 << 20, 1 << 16):
        bs.set(i, True)
    wire = bs.marshal()
    for cut in range(len(wire)):
        with pytest.raises(ValueError):
            BitSet.unmarshal(wire[:cut])


def test_bitset_extended_header_fuzz():
    """Arbitrary bytes after the escape marker: valid object or ValueError,
    and any declared length beyond MAX_WIRE_BITS is rejected up front (a
    forged header must not drive a huge allocation)."""
    rng = random.Random(11)
    escape = struct.pack(">H", 0xFFFF)
    for _ in range(500):
        data = escape + rng.randbytes(rng.randrange(0, 24))
        try:
            bs, used = BitSet.unmarshal(data)
        except ValueError:
            continue
        assert used <= len(data)
        assert len(bs) <= MAX_WIRE_BITS
    for n in (MAX_WIRE_BITS + 1, 1 << 30, 0xFFFFFFFF):
        for mode in (0, 1):
            with pytest.raises(ValueError):
                BitSet.unmarshal(struct.pack(">HBI", 0xFFFF, mode, n))


def test_multisig_sparse_roundtrip_through_packet():
    """A high-level aggregate (sparse, past the legacy cap) survives the
    full Packet encode/decode path."""
    bs = BitSet(1 << 18)
    for i in (0, 17, 4096, 65535, 65536, (1 << 18) - 1):
        bs.set(i, True)
    ms = MultiSignature(bs, FakeSignature())
    p = Packet(origin=7, level=18, multisig=ms.marshal())
    q = Packet.decode(p.encode())
    out = MultiSignature.unmarshal(q.multisig, FakeConstructor())
    assert out.bitset == bs


def test_multisig_unmarshal_truncated_signature():
    bs = BitSet(8)
    bs.set(3)
    wire = MultiSignature(bs, FakeSignature()).marshal()
    with pytest.raises(ValueError):
        MultiSignature.unmarshal(wire[:-1], FakeConstructor())


def test_bitset_unmarshal_oversized_length_prefix():
    import struct

    with pytest.raises(ValueError):
        BitSet.unmarshal(struct.pack(">H", 0xFFFF) + b"\x01")
    # stray bits beyond the declared length are cleared, not trusted
    bs, used = BitSet.unmarshal(struct.pack(">H", 3) + b"\xff")
    assert used == 3
    assert bs.cardinality() == 3  # only bits 0-2 survive

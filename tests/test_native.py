"""C++ host backend (handel_tpu/native) vs the pure-Python oracle.

The native library is the host-speed layer standing in for the reference's
assembly field ops (SURVEY.md §2.2, cloudflare/bn256 dep); every exported op
is cross-checked against ops/bn254_ref.py on random vectors.
"""

import random

import pytest

from handel_tpu import native
from handel_tpu.ops import bn254_ref as bn

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native backend did not build"
)

RNG = random.Random(20260729)


def rand_scalar():
    return RNG.randrange(1, bn.R)


def test_g1_mul_matches_oracle():
    for _ in range(10):
        k = rand_scalar()
        assert native.g1_mul(bn.G1_GEN, k) == bn.g1_mul(bn.G1_GEN, k)


def test_g1_small_and_edge_scalars():
    assert native.g1_mul(bn.G1_GEN, 0) is None
    assert native.g1_mul(None, 5) is None
    assert native.g1_mul(bn.G1_GEN, 1) == bn.G1_GEN
    assert native.g1_mul(bn.G1_GEN, 2) == bn.g1_add(bn.G1_GEN, bn.G1_GEN)
    # [r]G == O: the subgroup-check path needs unreduced scalars
    assert native.g1_mul(bn.G1_GEN, bn.R) is None


def test_g1_add_cases():
    p = native.g1_mul(bn.G1_GEN, 123)
    q = native.g1_mul(bn.G1_GEN, 456)
    assert native.g1_add(p, q) == bn.g1_add(p, q)
    assert native.g1_add(p, p) == bn.g1_add(p, p)  # doubling branch
    assert native.g1_add(p, None) == p
    assert native.g1_add(None, q) == q
    assert native.g1_add(p, bn.g1_neg(p)) is None  # inverse branch


def test_g2_mul_matches_oracle():
    for _ in range(4):
        k = rand_scalar()
        assert native.g2_mul(bn.G2_GEN, k) == bn.g2_mul(bn.G2_GEN, k)
    assert native.g2_mul(bn.G2_GEN, bn.R) is None  # subgroup check


def test_g2_add_cases():
    p = native.g2_mul(bn.G2_GEN, 33)
    q = native.g2_mul(bn.G2_GEN, 44)
    assert native.g2_add(p, q) == bn.g2_add(p, q)
    assert native.g2_add(p, p) == bn.g2_add(p, p)
    assert native.g2_add(p, None) == p


def test_batch_and_sum():
    ks = [rand_scalar() for _ in range(8)]
    assert native.g1_mul_batch([bn.G1_GEN] * 8, ks) == [
        bn.g1_mul(bn.G1_GEN, k) for k in ks
    ]
    assert native.g2_mul_batch([bn.G2_GEN] * 4, ks[:4]) == [
        bn.g2_mul(bn.G2_GEN, k) for k in ks[:4]
    ]
    pts = native.g1_mul_batch([bn.G1_GEN] * 5, ks[:5])
    acc = None
    for p in pts:
        acc = bn.g1_add(acc, p)
    assert native.g1_sum(pts + [None]) == acc
    qts = native.g2_mul_batch([bn.G2_GEN] * 3, ks[:3])
    acc2 = None
    for q in qts:
        acc2 = bn.g2_add(acc2, q)
    assert native.g2_sum(qts) == acc2


def test_sign_verify_through_scheme():
    """The host scheme rides the native path; signatures must still verify
    through the oracle pairing."""
    from handel_tpu.models.bn254 import new_keypair

    sk, pk = new_keypair(seed=7)
    msg = b"native-backed scheme"
    sig = sk.sign(msg)
    assert pk.verify(msg, sig)
    assert not pk.verify(b"other msg", sig)


def test_pairing_matches_oracle():
    """Native Miller loop + final exp vs the Python oracle, random points."""
    k, l = rand_scalar(), rand_scalar()
    p = native.g1_mul(bn.G1_GEN, k)
    q = native.g2_mul(bn.G2_GEN, l)
    assert native.pairing(q, p) == bn.pairing(q, p)


def test_pairing_check_bls_shape():
    sk = rand_scalar()
    h = native.g1_mul(bn.G1_GEN, 777)
    X = native.g2_mul(bn.G2_GEN, sk)
    S = native.g1_mul(h, sk)
    assert native.pairing_check([(h, X), (bn.g1_neg(S), bn.G2_GEN)])
    bad = native.g1_add(S, bn.G1_GEN)
    assert not native.pairing_check([(h, X), (bn.g1_neg(bad), bn.G2_GEN)])
    # infinity pairs contribute the identity
    assert native.pairing_check([(None, X), (h, None)])


def test_pairing_bilinearity():
    k, l = 1234567, 7654321
    lhs = native.pairing(
        native.g2_mul(bn.G2_GEN, l), native.g1_mul(bn.G1_GEN, k)
    )
    base = native.pairing(bn.G2_GEN, bn.G1_GEN)
    assert lhs == bn.f12_pow(base, k * l % bn.R)


def test_miller_matches_oracle():
    k, l = rand_scalar(), rand_scalar()
    p = native.g1_mul(bn.G1_GEN, k)
    q = native.g2_mul(bn.G2_GEN, l)
    assert native.miller(q, p) == bn.miller_loop_projective(q, p)
    assert native.miller(None, p) == bn.F12_ONE
    assert native.pairing(None, p) == bn.F12_ONE

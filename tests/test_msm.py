"""The windowed/bucketed MSM kernel (ops/curve.py `Curve.msm`) vs the host
scalar oracle, plus the vectorized scalar-bit packers.

Device property tests (random scalars, masked/hull candidates, G1 and G2,
both fp backends, edge scalars 0 / 1 / 2^64-1) are slow tier like the rest
of the curve-op graphs (see tests/test_curve_jax.py); the pure-host
scalar_bits checks stay tier-1.
"""

import random

import numpy as np
import pytest

from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BN254Curves

random.seed(0x35A1)


def _host_msm(pts, ks, add, mul):
    acc = None
    for p, k in zip(pts, ks):
        if k == 0 or p is None:
            continue
        t = mul(p, k) if k != 1 else p
        acc = t if acc is None else add(acc, t)
    return acc


# -- tier-1: host scalar-bit packing --------------------------------------


def test_scalar_bits_vectorized_matches_reference():
    ks = [0, 1, (1 << 64) - 1, 0xDEADBEEF, random.randrange(1 << 256)]
    for nbits in (64, 96, 256):
        got = np.asarray(BN254Curves.scalar_bits([k % (1 << nbits) for k in ks], nbits))
        want = np.zeros((nbits, len(ks)), np.uint32)
        for j, k in enumerate(ks):
            k %= 1 << nbits
            for i in range(nbits):
                want[nbits - 1 - i, j] = (k >> i) & 1
        assert (got == want).all(), nbits


def test_scalar_bits64_matches_scalar_bits():
    ks = [0, 1, (1 << 64) - 1] + [random.randrange(1 << 64) for _ in range(5)]
    got = np.asarray(BN254Curves.scalar_bits64(ks))
    want = np.asarray(BN254Curves.scalar_bits(ks, nbits=64))
    assert got.shape == (64, len(ks))
    assert (got == want).all()


# -- slow tier: the device MSM kernel vs the scalar oracle ----------------


@pytest.fixture(scope="module")
def curves():
    return BN254Curves()


@pytest.mark.slow
@pytest.mark.parametrize("window", [1, 2, 4])
def test_g1_msm_random_and_edge_scalars(curves, window):
    n, b = 4, 2
    pts = [bn.g1_mul(bn.G1_GEN, random.randrange(1, bn.R)) for _ in range(n * b)]
    ks = [random.randrange(0, 1 << 64) for _ in range(n * b)]
    # edge scalars: 0 (identity contribution), 1, all-ones
    ks[0], ks[1], ks[2] = 0, 1, (1 << 64) - 1
    out = curves.g1.msm(curves.pack_g1(pts), curves.scalar_bits64(ks), n, window=window)
    got = curves.unpack_g1(out)
    for j in range(b):
        want = _host_msm(
            [pts[i * b + j] for i in range(n)],
            [ks[i * b + j] for i in range(n)],
            bn.g1_add, bn.g1_mul,
        )
        assert got[j] == want, (window, j)


@pytest.mark.slow
def test_g1_msm_masked_hull_lanes(curves):
    """Zeroed scalar columns (the launch-hull mask) and infinity points
    both contribute the identity; an all-masked lane sums to infinity."""
    n, b = 4, 2
    pts = [bn.g1_mul(bn.G1_GEN, random.randrange(1, bn.R)) for _ in range(n * b)]
    pts[2 * b] = None  # infinity point block entry
    ks = [random.randrange(1, 1 << 64) for _ in range(n * b)]
    for i in range(n):  # lane 1 fully masked
        ks[i * b + 1] = 0
    out = curves.g1.msm(curves.pack_g1(pts), curves.scalar_bits64(ks), n, window=2)
    got = curves.unpack_g1(out)
    assert got[1] is None
    want = _host_msm(
        [pts[i * b] for i in range(n)], [ks[i * b] for i in range(n)],
        bn.g1_add, bn.g1_mul,
    )
    assert got[0] == want


@pytest.mark.slow
@pytest.mark.parametrize("window", [2, 4])
def test_g2_msm_random_scalars(curves, window):
    n, b = 3, 1
    pts = [bn.g2_mul(bn.G2_GEN, random.randrange(1, bn.R)) for _ in range(n * b)]
    ks = [0, (1 << 64) - 1, random.randrange(1 << 64)]
    out = curves.g2.msm(curves.pack_g2(pts), curves.scalar_bits64(ks), n, window=window)
    got = curves.unpack_g2(out)
    want = _host_msm(pts, ks, bn.g2_add, bn.g2_mul)
    assert got[0] == want


@pytest.mark.slow
def test_g1_msm_rns_backend_matches_cios(curves):
    """The MSM rides the Field backend seam: the rns kernel's result is
    bit-exact with cios (the backend contract, tests/test_rns.py)."""
    rns = BN254Curves(backend="rns")
    n, b = 3, 1
    pts = [bn.g1_mul(bn.G1_GEN, random.randrange(1, bn.R)) for _ in range(n * b)]
    ks = [1, random.randrange(1 << 64), random.randrange(1 << 64)]
    want = curves.unpack_g1(
        curves.g1.msm(curves.pack_g1(pts), curves.scalar_bits64(ks), n, window=2)
    )
    got = rns.unpack_g1(
        rns.g1.msm(rns.pack_g1(pts), rns.scalar_bits64(ks), n, window=2)
    )
    assert got == want
    assert got[0] == _host_msm(pts, ks, bn.g1_add, bn.g1_mul)


@pytest.mark.slow
def test_g2_msm_rns_backend_matches_oracle():
    rns = BN254Curves(backend="rns")
    n = 2
    pts = [bn.g2_mul(bn.G2_GEN, random.randrange(1, bn.R)) for _ in range(n)]
    ks = [random.randrange(1 << 64), random.randrange(1 << 64)]
    got = rns.unpack_g2(
        rns.g2.msm(rns.pack_g2(pts), rns.scalar_bits64(ks), n, window=2)
    )
    assert got[0] == _host_msm(pts, ks, bn.g2_add, bn.g2_mul)

"""Confgenerator, plots, and reporter-plane tests.

Reference models: simul/confgenerator/confgenerator.go:18-469 (scenario TOML
matrix), simul/plots/*.py (CSV -> figures), report.go:5-87 (Values()
aggregation).
"""

import os

from handel_tpu.core.report import KernelTimer, ReportAggregator, diff_values
from handel_tpu.sim.confgen import SCENARIOS, generate
from handel_tpu.sim.config import load_config
from handel_tpu.sim.monitor import Stats


def test_confgen_all_scenarios_roundtrip(tmp_path):
    paths = generate(str(tmp_path))
    assert len(paths) == len(SCENARIOS)
    for p in paths:
        cfg = load_config(p)  # every generated TOML must parse back
        assert cfg.runs, p
        for r in cfg.runs:
            assert r.nodes > 0 and 0 < r.resolved_threshold() <= r.nodes


def test_confgen_scenario_shapes(tmp_path):
    (p,) = generate(str(tmp_path), ["failing"])
    cfg = load_config(p)
    assert {r.failing for r in cfg.runs} == {0, 400, 1000, 1960}
    assert all(r.threshold == 2040 for r in cfg.runs)
    (p,) = generate(str(tmp_path), ["nsquare"])
    assert load_config(p).baseline == "nsquare"


def test_plots_render_png(tmp_path):
    # fabricate a monitor CSV and render every plot kind
    stats_rows = []
    for nodes, wall, sent in [(100, 0.2, 9000), (1000, 0.5, 30000), (4000, 0.9, 57000)]:
        st = Stats(extra={"nodes": nodes, "failing": 0})
        for i in range(4):
            st.update("sigen_wall", wall + 0.01 * i)
            st.update("net_sentBytes", sent + 100 * i)
            st.update("sigs_sigCheckedCt", 60 + i)
        stats_rows.append(st)
    csv_path = str(tmp_path / "handel.csv")
    for i, st in enumerate(stats_rows):
        st.write_csv(csv_path, append=i > 0)

    from handel_tpu.sim import plots

    for kind in ("time", "network", "sigchecked"):
        out = str(tmp_path / f"{kind}.png")
        plots.KINDS[kind]({"handel": csv_path}, out)
        assert os.path.getsize(out) > 1000

    # knob-sweep kinds read the per-run parameter columns the platforms
    # embed (confgenerator.go periodInc/timeoutInc/updateCount figures)
    sweep_rows = []
    for period, wall in [(10.0, 0.9), (50.0, 0.6), (100.0, 0.8)]:
        st = Stats(extra={"nodes": 2000, "period_ms": period})
        st.update("sigen_wall", wall)
        st.update("sigs_sigCheckedCt", 60)
        sweep_rows.append(st)
    sweep_csv = str(tmp_path / "period.csv")
    for i, st in enumerate(sweep_rows):
        st.write_csv(sweep_csv, append=i > 0)
    out = str(tmp_path / "period.png")
    plots.KINDS["period"]({"handel": sweep_csv}, out)
    assert os.path.getsize(out) > 1000


def test_report_aggregator_prefixes():
    class R:
        def __init__(self, **kv):
            self.kv = kv

        def values(self):
            return dict(self.kv)

    agg = ReportAggregator(handel=R(msgSentCt=3.0), net=R(sentPackets=5.0))
    agg.add("verifier", R(launches=2.0))
    vals = agg.values()
    assert vals == {
        "handel_msgSentCt": 3.0,
        "net_sentPackets": 5.0,
        "verifier_launches": 2.0,
    }


def test_kernel_timer_counts():
    timer = KernelTimer(lambda x: x * 2, name="verify")
    assert timer(21) == 42
    assert timer(1) == 2
    vals = timer.values()
    assert vals["verifyCalls"] == 2.0
    assert vals["verifyTimeMs"] >= 0.0
    assert vals["verifyMaxMs"] <= vals["verifyTimeMs"]


def test_diff_values():
    before = {"a": 1.0, "b": 2.0}
    after = {"a": 4.0, "b": 2.5, "c": 1.0}
    assert diff_values(before, after) == {"a": 3.0, "b": 0.5, "c": 1.0}

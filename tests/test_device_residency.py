"""Device-resident registry + zero-copy staging + batched-combine contracts.

The steady-state contract of the device-resident aggregation path
(models/bn254_jax.py): registry pubkeys and the prefix table are committed
to the device once, every per-launch input reaches the device through an
EXPLICIT `jax.device_put` of a rotated staging buffer, and therefore a
warm launch performs ZERO implicit host→device transfers — pinned here
under `jax.transfer_guard_host_to_device("disallow")` so device-residency
cannot silently regress (a stray `jnp.asarray(numpy)` in the hot path
fails these tests, not just a bench number).

Fast-tier by design: everything here drives the aggregation-stage kernels
(G1/G2 point adds, seconds-scale compiles) and the pack/stage layer. The
pairing-tail kernels — minutes of XLA on one core — stay slow-tier
(tests/test_bn254_device.py); they consume the same staged arrays, so the
transfer discipline proven here covers them.
"""

import random

import jax
import numpy as np
import pytest

from handel_tpu import native as nat
from handel_tpu.core.bitset import BitSet
from handel_tpu.core.processing import CombineShim
from handel_tpu.models.bn254 import BN254PublicKey, BN254Signature
from handel_tpu.models.bn254_jax import BN254Device, BN254JaxConstructor
from handel_tpu.ops import bn254_ref as bn

N = 12  # small: the prefix scan / masked-sum compile cost scales with N
C = 4


@pytest.fixture(scope="module")
def device():
    rng = random.Random(5)
    sks = [rng.randrange(1, 1 << 20) for _ in range(N)]
    pks = [BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * N, sks)]
    return BN254Device(pks, batch_size=C)


def _range_requests(rng, k=C):
    sig = BN254Signature(bn.G1_GEN)
    reqs = []
    for _ in range(k):
        size = rng.randrange(2, N)
        lo = rng.randrange(0, N - size + 1)
        holes = set(rng.sample(range(lo + 1, lo + size - 1), min(2, size - 2)))
        bs = BitSet(N)
        for i in range(lo, lo + size):
            if i not in holes:
                bs.set(i, True)
        reqs.append((bs, sig))
    return reqs


def _host_agg(pks, bs):
    acc = None
    for i in bs.indices():
        acc = pks[i].point if acc is None else bn.g2_add(acc, pks[i].point)
    return acc


def test_steady_state_zero_implicit_transfers(device):
    """After warmup, a pack → stage → aggregate launch performs no implicit
    host→device transfer of registry/prefix (or any other) data; the
    explicit staging-buffer device_puts are the allowlist."""
    rng = random.Random(11)
    reqs = _range_requests(rng)
    # warm: build the prefix table and compile the aggregation kernel
    plan = device._pack_requests(reqs)
    args = device._stage_plan(plan)
    jax.block_until_ready(device._range_agg_kernel(plan.miss_k)(*args[:4]))

    for _ in range(3):  # several launches: rotation boundaries included
        reqs = _range_requests(rng)
        with jax.transfer_guard_host_to_device("disallow"):
            plan = device._pack_requests(reqs)
            args = device._stage_plan(plan)
            agg = device._range_agg_kernel(plan.miss_k)(*args[:4])
            jax.block_until_ready(agg)

    # the guard itself must bite on this backend, or the test proves nothing
    with pytest.raises(Exception, match="[Dd]isallowed"):
        with jax.transfer_guard_host_to_device("disallow"):
            device._range_agg_kernel(plan.miss_k)(
                np.asarray(args[0]).copy(), *args[1:4]
            )


def test_range_aggregate_matches_host(device):
    """The staged on-device aggregate (prefix gather + hole patch) equals
    the host oracle's G2 sum over each candidate's signers."""
    rng = random.Random(13)
    reqs = _range_requests(rng)
    plan = device._pack_requests(reqs)
    args = device._stage_plan(plan)
    agg = device._range_agg_kernel(plan.miss_k)(*args[:4])
    x, y, inf = device.curves.g2.to_affine(agg)
    xs = device.curves.T.f2_unpack(x)
    ys = device.curves.T.f2_unpack(y)
    infs = np.asarray(inf)
    for j, (bs, _) in enumerate(reqs):
        expect = _host_agg(device_pks(device), bs)
        if expect is None:
            assert infs[j]
        else:
            assert not infs[j] and (xs[j], ys[j]) == expect, j


def device_pks(device):
    """Registry points back from the device-resident arrays (round-trip
    through the committed copy, so the test reads what launches read)."""
    xs = device.curves.T.f2_unpack(device._reg_x)
    ys = device.curves.T.f2_unpack(device._reg_y)

    class _PK:
        __slots__ = ("point",)

        def __init__(self, p):
            self.point = p

    return [_PK((xs[i], ys[i])) for i in range(device.n)]


def test_unpack_words_matches_host_mask(device):
    """The dense kernel's on-device word unpack reproduces the host mask
    the old packer materialized, for random bitsets."""
    rng = random.Random(17)
    unpack = jax.jit(device._unpack_words)
    for _ in range(5):
        words = np.zeros((C, (N + 63) // 64), np.uint64)
        valid = np.zeros((C,), bool)
        want = np.zeros((C, N), bool)
        for j in range(C):
            bs = BitSet(N)
            for i in rng.sample(range(N), rng.randrange(0, N)):
                bs.set(i, True)
            words[j] = bs.words()
            valid[j] = rng.random() < 0.8
            if valid[j]:
                for i in bs.indices():
                    want[j, i] = True
        got = np.asarray(
            unpack(
                jax.device_put(words.view(np.uint32)), jax.device_put(valid)
            )
        ).reshape(N, C)
        assert (got == want.T).all()


def test_epoch_flip_reaches_compiled_kernels():
    """Registry rotation vs the jitted-kernel cache: a kernel compiled
    under epoch 0 must answer for the NEW bank after `activate_staged`.
    The bank is a jit ARGUMENT (see _range_aggregate) — were it a closure
    read, the cached executable would bake the old prefix/registry in as
    compile-time constants and every post-flip launch would keep verifying
    against the retired validator set. Also pins the flip's residency: the
    staged bank was device_put at stage time, so the first post-flip
    launch performs no implicit host→device transfer."""
    rng = random.Random(31)

    def mk(seed):
        r = random.Random(seed)
        sks = [r.randrange(1, 1 << 20) for _ in range(N)]
        return [
            BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * N, sks)
        ]

    pks_a, pks_b = mk(37), mk(41)
    device = BN254Device(pks_a, batch_size=C)
    reqs = _range_requests(rng)

    def launch():
        plan = device._pack_requests(reqs)
        agg = device._range_agg_kernel(plan.miss_k)(
            *device._stage_plan(plan)[:4]
        )
        jax.block_until_ready(agg)
        return agg

    def aggs(agg=None):
        # the eager affine epilogue stays outside any transfer guard: it
        # uploads Python scalar constants, which is fine off the hot path
        agg = launch() if agg is None else agg
        x, y, inf = device.curves.g2.to_affine(agg)
        xs = device.curves.T.f2_unpack(x)
        ys = device.curves.T.f2_unpack(y)
        infs = np.asarray(inf)
        return [
            None if infs[j] else (xs[j], ys[j]) for j in range(len(reqs))
        ]

    assert all(
        g == _host_agg(pks_a, bs) for g, (bs, _) in zip(aggs(), reqs)
    )
    device.stage_registry(pks_b)
    # staged but not flipped: the compiled kernel still serves the old bank
    assert all(
        g == _host_agg(pks_a, bs) for g, (bs, _) in zip(aggs(), reqs)
    )
    assert device.activate_staged() == 1
    with jax.transfer_guard_host_to_device("disallow"):
        agg = launch()
    assert all(
        g == _host_agg(pks_b, bs) for g, (bs, _) in zip(aggs(agg), reqs)
    )


def test_combine_batch_matches_host(device):
    """combine_batch (one masked G1 tree-sum launch) equals the host
    pairing-library fold for random group shapes, including infinities,
    empty lanes, and widths across the power-of-two kernel classes."""
    rng = random.Random(19)
    pts = [bn.g1_mul(bn.G1_GEN, rng.randrange(1, bn.R)) for _ in range(12)]
    groups = [
        [rng.choice(pts + [None]) for _ in range(rng.randrange(1, 9))]
        for _ in range(2 * C + 1)  # > batch_size: exercises chunking
    ]
    got = device.combine_batch(groups)
    for g, out in zip(groups, got):
        acc = None
        for p in g:
            if p is not None:
                acc = p if acc is None else bn.g1_add(acc, p)
        assert out == acc, g


def test_staging_fence_blocks_before_reuse(device):
    """_pack_requests must wait on the fence of the staging set it reuses
    (the launch that last read those buffers), and clear it."""

    class Fence:
        waited = False

        def block_until_ready(self):
            self.waited = True

    fences = [Fence() for _ in device._stage]
    for st, f in zip(device._stage, fences):
        st.fence = f
    rng = random.Random(23)
    for i in range(len(fences)):
        nxt = (device._stage_idx + 1) % len(device._stage)
        device._pack_requests(_range_requests(rng))
        assert fences[nxt].waited
        assert device._stage[nxt].fence is None


def test_combine_shim_routing():
    """CombineShim: wide groups take one device launch, narrow ones fold on
    the host, a declining device degrades to host, and accumulate/flush
    resolves every queued group in a single combine_batch call."""
    calls = []

    def dev_combine(groups):
        calls.append([len(g) for g in groups])
        out = []
        for g in groups:
            acc = None
            for p in g:
                acc = p if acc is None else bn.g1_add(acc, p)
            out.append(acc)
        return out

    sigs = [
        BN254Signature(bn.g1_mul(bn.G1_GEN, k)) for k in (3, 5, 7, 11, 13)
    ]
    host = sigs[0]
    for s in sigs[1:]:
        host = host.combine(s)

    shim = CombineShim(dev_combine, min_device_points=4)
    assert shim.combine_many(sigs) == host  # wide: device
    assert calls == [[5]]
    assert shim.combine_many(sigs[:2]) == sigs[0].combine(sigs[1])  # narrow
    assert calls == [[5]]  # no new device call
    assert shim.combine_device_groups == 1 and shim.combine_host_groups == 1

    # accumulate-and-flush: both groups ride ONE device call
    shim.accumulate(sigs)
    shim.accumulate(sigs[1:])
    out = shim.flush()
    assert calls[-1] == [5, 4] and len(calls) == 2
    assert out[0] == host

    # device declines -> host fold, same result
    declining = CombineShim(lambda groups: None, min_device_points=2)
    assert declining.combine_many(sigs) == host
    assert declining.combine_host_groups == 1


def test_constructor_device_combine_lazy():
    """The constructor's device_combine hook declines (None) before the
    device exists — the shim must never force an eager registry upload —
    declines per-group while a width class is uncompiled (never a mid-round
    XLA compile), and serves real combines once the class is warm."""
    cons = BN254JaxConstructor(batch_size=2, warmup=False)
    assert cons.device_combine([[bn.G1_GEN, bn.G1_GEN]]) is None
    rng = random.Random(29)
    sks = [rng.randrange(1, 1 << 20) for _ in range(4)]
    pks = [BN254PublicKey(p) for p in nat.g2_mul_batch([bn.G2_GEN] * 4, sks)]
    cons.prepare(pks)
    # warmup=False: the k=2 class is not compiled -> per-group decline
    assert cons.device_combine([[bn.G1_GEN, bn.G1_GEN]]) == [None]
    cons._device.combine_batch([[bn.G1_GEN, bn.G1_GEN]])  # compiles k=2
    (got,) = cons.device_combine([[bn.G1_GEN, bn.G1_GEN]])
    assert got == bn.g1_add(bn.G1_GEN, bn.G1_GEN)

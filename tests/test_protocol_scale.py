"""Protocol tests at reference scale (slow tier).

Reference tables: handel_test.go:30-40 (TestHandelWithFailures: 333 nodes,
24 offline, threshold 51%), :53-84 (TestHandelTestNetworkFull to 128 nodes /
TestHandelTestNetworkLarge behind testing.Short()), and the loss-rate
scenario exercising the harness's lossy router (test_harness.py loss_rate —
packets vanish like WAN UDP; timeouts + individual-sig patching must win).
"""

import asyncio
import random

import pytest

from handel_tpu.core.config import Config
from handel_tpu.core.test_harness import LocalCluster, run_cluster


def run(coro):
    return asyncio.run(coro)


def test_levels_structure_65536():
    """N=65536 with the fake scheme: the structural assumptions that broke
    above 2^12 — every level a contiguous O(1) range view (no materialized
    candidate lists), the 16 level ranges tiling the full ID space, and the
    un-shuffled send rotation staggered per id so sibling subtrees don't aim
    their fast-path bursts at the same candidates (core/handel.py
    create_levels)."""
    from handel_tpu.core.config import Config
    from handel_tpu.core.handel import create_levels
    from handel_tpu.core.partitioner import BinomialPartitioner
    from handel_tpu.swarm.driver import fake_committee

    n = 65536
    registry, _ = fake_committee(n)
    for nid in (0, 1, 4097, 32767, 32768, n - 1):
        part = BinomialPartitioner(nid, registry)
        assert part.max_level() == 16
        assert part.levels() == list(range(1, 17))
        # the level ranges plus our own id tile [0, n) exactly once
        seen = {nid}
        for lvl in part.levels():
            lo, hi = part.range_level(lvl)
            assert hi - lo == 1 << (lvl - 1)
            assert not (set(range(lo, hi)) & seen) or hi - lo > 4096
            if hi - lo <= 4096:
                seen.update(range(lo, hi))
        assert part.size_of(16) == 32768
        cfg = Config(disable_shuffling=True)
        levels = create_levels(cfg, part)
        for lvl, level in levels.items():
            # O(1) range views, never list copies of up-to-32768 identities
            assert not isinstance(level.nodes, list)
            assert len(level.nodes) == part.size_of(lvl)
            assert level.send_pos == nid % len(level.nodes)
    # full-tile check on one node without the sample shortcut
    part = BinomialPartitioner(12345, registry)
    total = 1  # our own id
    for lvl in part.levels():
        lo, hi = part.range_level(lvl)
        total += hi - lo
    assert total == n


def test_levels_structure_non_power_of_two_above_2_12():
    """Non-power-of-two committees above 4096: top levels may be partial or
    empty; ranges must clamp to the registry size and never go negative."""
    from handel_tpu.core.partitioner import BinomialPartitioner, EmptyLevelError
    from handel_tpu.swarm.driver import fake_committee

    n = 40000  # between 2^15 and 2^16
    registry, _ = fake_committee(n)
    for nid in (0, n // 2, n - 1):
        part = BinomialPartitioner(nid, registry)
        covered = 1
        for lvl in range(1, part.max_level() + 1):
            try:
                lo, hi = part.range_level(lvl)
            except EmptyLevelError:
                continue
            assert 0 <= lo < hi <= n
            covered += hi - lo
        assert covered == n


@pytest.mark.slow
def test_full_aggregation_128():
    results = run(run_cluster(128, timeout=60.0))
    assert len(results) == 128
    for sig in results.values():
        assert sig.cardinality() >= 66


@pytest.mark.slow
def test_with_failures_333():
    n, offline_ct = 333, 24
    rng = random.Random(1234)
    offline = tuple(sorted(rng.sample(range(n), offline_ct)))
    threshold = (n * 51 + 99) // 100

    async def go():
        cluster = LocalCluster(n, offline=offline, threshold=threshold)
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=120.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == n - offline_ct
    for sig in results.values():
        assert sig.cardinality() >= threshold
        for off in offline:
            assert not sig.bitset.get(off)


@pytest.mark.slow
def test_lossy_network_converges():
    """20% packet loss: periodic resends + timeouts must still converge
    (the WAN robustness the reference gets from UDP fire-and-forget)."""

    def cfg_factory(i):
        c = Config()
        c.rand = random.Random(50 + i)
        return c

    async def go():
        cluster = LocalCluster(
            24, threshold=13, loss_rate=0.2, config_factory=cfg_factory
        )
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=60.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == 24
    for sig in results.values():
        assert sig.cardinality() >= 13


@pytest.mark.slow
def test_real_crypto_37_nodes():
    """37-node end-to-end with real BN254 (bn256/cf/bn256_test.go:13-37)."""
    from handel_tpu.core.crypto import verify_multisignature
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()

    async def go():
        cluster = LocalCluster(37, scheme=scheme, threshold=19)
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=600.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == 37
    h0 = next(iter(results.values()))
    assert h0.cardinality() >= 19

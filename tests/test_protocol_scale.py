"""Protocol tests at reference scale (slow tier).

Reference tables: handel_test.go:30-40 (TestHandelWithFailures: 333 nodes,
24 offline, threshold 51%), :53-84 (TestHandelTestNetworkFull to 128 nodes /
TestHandelTestNetworkLarge behind testing.Short()), and the loss-rate
scenario exercising the harness's lossy router (test_harness.py loss_rate —
packets vanish like WAN UDP; timeouts + individual-sig patching must win).
"""

import asyncio
import random

import pytest

from handel_tpu.core.config import Config
from handel_tpu.core.test_harness import LocalCluster, run_cluster


def run(coro):
    return asyncio.run(coro)


@pytest.mark.slow
def test_full_aggregation_128():
    results = run(run_cluster(128, timeout=60.0))
    assert len(results) == 128
    for sig in results.values():
        assert sig.cardinality() >= 66


@pytest.mark.slow
def test_with_failures_333():
    n, offline_ct = 333, 24
    rng = random.Random(1234)
    offline = tuple(sorted(rng.sample(range(n), offline_ct)))
    threshold = (n * 51 + 99) // 100

    async def go():
        cluster = LocalCluster(n, offline=offline, threshold=threshold)
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=120.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == n - offline_ct
    for sig in results.values():
        assert sig.cardinality() >= threshold
        for off in offline:
            assert not sig.bitset.get(off)


@pytest.mark.slow
def test_lossy_network_converges():
    """20% packet loss: periodic resends + timeouts must still converge
    (the WAN robustness the reference gets from UDP fire-and-forget)."""

    def cfg_factory(i):
        c = Config()
        c.rand = random.Random(50 + i)
        return c

    async def go():
        cluster = LocalCluster(
            24, threshold=13, loss_rate=0.2, config_factory=cfg_factory
        )
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=60.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == 24
    for sig in results.values():
        assert sig.cardinality() >= 13


@pytest.mark.slow
def test_real_crypto_37_nodes():
    """37-node end-to-end with real BN254 (bn256/cf/bn256_test.go:13-37)."""
    from handel_tpu.core.crypto import verify_multisignature
    from handel_tpu.models.bn254 import BN254Scheme

    scheme = BN254Scheme()

    async def go():
        cluster = LocalCluster(37, scheme=scheme, threshold=19)
        cluster.start()
        try:
            return await cluster.wait_complete_success(timeout=600.0)
        finally:
            cluster.stop()

    results = run(go())
    assert len(results) == 37
    h0 = next(iter(results.values()))
    assert h0.cardinality() >= 19

"""Binomial partitioner range/index/combine tables.

Reference test model: partitioner_test.go:9-396 (range tables, level indexing,
combine offset placement). Expected values below are hand-derived from the
common-prefix-length construction, not copied.
"""

import pytest

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.partitioner import (
    BinomialPartitioner,
    EmptyLevelError,
    IncomingSig,
    InvalidLevelError,
)
from handel_tpu.models.fake import FakeSignature, fake_registry


def part(n, id):
    return BinomialPartitioner(id, fake_registry(n))


@pytest.mark.parametrize(
    "n,id,level,expected",
    [
        # n=8, id=1 (0b001)
        (8, 1, 0, (1, 2)),
        (8, 1, 1, (0, 1)),
        (8, 1, 2, (2, 4)),
        (8, 1, 3, (4, 8)),
        # n=8, id=5 (0b101)
        (8, 5, 1, (4, 5)),
        (8, 5, 2, (6, 8)),
        (8, 5, 3, (0, 4)),
        # n=6 (non power of two), id=0: level 3 truncated to size
        (6, 0, 3, (4, 6)),
        # n=6, id=5: level 3 is the lower half
        (6, 5, 3, (0, 4)),
        (6, 5, 1, (4, 5)),
        # n=16, id=0
        (16, 0, 4, (8, 16)),
        (16, 0, 1, (1, 2)),
    ],
)
def test_range_level(n, id, level, expected):
    assert part(n, id).range_level(level) == expected


def test_empty_level_non_power_of_two():
    # n=6, id=5 (0b101): level 2 range is [6,8) which is beyond size -> empty
    p = part(6, 5)
    with pytest.raises(EmptyLevelError):
        p.range_level(2)
    assert p.size_of(2) == 0
    assert p.levels() == [1, 3]


def test_levels_full_power_of_two():
    assert part(8, 0).levels() == [1, 2, 3]
    assert part(16, 3).levels() == [1, 2, 3, 4]
    assert part(1, 0).levels() == []


def test_invalid_level():
    p = part(8, 0)
    with pytest.raises(InvalidLevelError):
        p.range_level(5)
    with pytest.raises(InvalidLevelError):
        p.range_level(-1)


def test_index_at_level():
    p = part(8, 1)
    # level 2 of id=1 covers [2,4)
    assert p.index_at_level(2, 2) == 0
    assert p.index_at_level(3, 2) == 1
    with pytest.raises(ValueError):
        p.index_at_level(4, 2)  # out of level range: bug or attack


def test_range_level_inverse():
    p = part(8, 1)
    # own subtree at level 3 = lower half [0,4); at level 1 = own id
    assert p.range_level_inverse(3) == (0, 4)
    assert p.range_level_inverse(1) == (1, 2)
    # level 4 = whole registry
    assert p.range_level_inverse(4) == (0, 8)


def _inc(level, bits, size):
    bs = BitSet(size)
    for b in bits:
        bs.set(b)
    return IncomingSig(origin=-1, level=level, ms=MultiSignature(bs, FakeSignature()))


def test_combine_offsets():
    # id=1, n=8: combining level-0 (own, [1,2)) and level-1 ([0,1)) and
    # level-2 ([2,4)) sigs for sending to level 3 -> bitset over [0,4)
    p = part(8, 1)
    sigs = [
        _inc(0, [0], 1),  # own sig: global id 1
        _inc(1, [0], 1),  # peer 0
        _inc(2, [0, 1], 2),  # peers 2,3
    ]
    ms = p.combine(sigs, 3)
    assert len(ms.bitset) == 4
    assert ms.bitset.indices() == [0, 1, 2, 3]


def test_combine_rejects_higher_level():
    p = part(8, 1)
    assert p.combine([_inc(3, [0], 4)], 2) is None


def test_combine_full_offsets():
    p = part(8, 5)
    sigs = [
        _inc(0, [0], 1),  # own sig -> global 5
        _inc(1, [0], 1),  # level 1 covers [4,5)
        _inc(3, [1, 3], 4),  # level 3 covers [0,4) -> globals 1,3
    ]
    ms = p.combine_full(sigs)
    assert len(ms.bitset) == 8
    assert ms.bitset.indices() == [1, 3, 4, 5]


def test_combine_empty():
    p = part(8, 1)
    assert p.combine([], 2) is None
    assert p.combine_full([]) is None


def test_depth_14_committee_structure():
    """16k committee = the depth-14 binomial tree (BASELINE.json configs[4]).
    Structural invariants of partitioner.go:133-178 at scale: each level l
    of a power-of-two committee spans 2^(l-1) ids, the levels partition
    everything except the node itself, and level ranges are symmetric
    (j in id's level-l range <=> id in j's level-l range) — the property
    the protocol relies on so level-l packets land on peers that place the
    sender at the same level."""
    n = 16384
    for nid in (0, 1, 5000, 12345, n - 1):
        p = part(n, nid)
        assert p.levels() == list(range(1, 15))
        seen = set()
        for level in range(1, 15):
            lo, hi = p.range_level(level)
            assert hi - lo == 1 << (level - 1)
            assert p.size_of(level) == hi - lo
            rng = set(range(lo, hi))
            assert nid not in rng
            assert not (seen & rng)
            seen |= rng
        assert len(seen) == n - 1

    # symmetry probe across a few (id, peer) pairs at the deep levels
    for nid, level in ((0, 14), (12345, 14), (5000, 13)):
        p = part(n, nid)
        lo, hi = p.range_level(level)
        for peer in (lo, (lo + hi) // 2, hi - 1):
            q = part(n, peer)
            qlo, qhi = q.range_level(level)
            assert qlo <= nid < qhi

    # non-power-of-two at the same depth: truncated-but-covering partition
    # (rangeLevel clamps max to size, empty levels are skipped)
    n2 = 16000
    p = part(n2, n2 - 1)
    seen = set()
    for level in p.levels():
        lo, hi = p.range_level(level)
        assert hi <= n2
        rng = set(range(lo, hi))
        assert not (seen & rng)
        seen |= rng
    assert len(seen) == n2 - 1

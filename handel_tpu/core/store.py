"""Best-per-level signature store with merge/patch logic and verification scoring.

Reference: store.go:14-282 — `SignatureStore` interface, the scoring function
`unsafeEvaluate` (store.go:111-183) that prioritizes which unverified signatures
are worth a pairing check, and `unsafeCheckMerge` (store.go:188-229) which
merges non-overlapping multisigs and patches holes with already-verified
individual signatures.

The exact scoring/merging semantics matter for protocol convergence
(SURVEY.md §7 hard part (d)); they are reproduced faithfully. Point additions go
through `Signature.combine`, which device schemes batch (store.go:201,225 →
batched G1 adds).

Concurrency note: the reference store carries its own mutex (store.go:41)
because goroutines race on it. Here every caller runs on one asyncio event
loop, so no lock is needed — single-threaded discipline is the framework-wide
design (SURVEY.md §5.2).
"""

from __future__ import annotations

from collections import OrderedDict
from hashlib import blake2b
from typing import Callable

from handel_tpu.core.bitset import AllOnesBitSet, BitSet
from handel_tpu.core.crypto import Constructor, MultiSignature
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig


class VerifiedAggCache:
    """Bounded LRU of aggregate-verification verdicts.

    Handel's gossip pattern re-delivers the same winning aggregate from many
    peers per level (the reference re-verifies every copy,
    processing.go:258-287); each re-verification burns a device lane.  This
    cache keys a candidate by its exact content — (level, digest of bitset
    words + signature bytes) — so a copy this node has already judged
    short-circuits to the remembered verdict with zero device work.  Negative verdicts are
    cached too: a known-bad aggregate re-sent by a byzantine peer costs
    nothing after the first pairing check.

    Used per-node by `BatchProcessing` (core/processing.py) and, keyed by
    message instead of level, process-wide by `BatchVerifierService`
    (parallel/batch_verifier.py) where co-located nodes dedup each other.
    Bounded so a flood of distinct aggregates cannot grow host memory
    unboundedly; LRU because Handel traffic is bursty per level — the
    current level's winners stay hot, finished levels age out.

    Single-threaded like the store itself (module docstring): every caller
    runs on one asyncio loop, so no lock.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        self.capacity = capacity
        self._map: OrderedDict[tuple, bool] = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def content_digest(bitset, signature) -> bytes:
        """16-byte blake2b over the exact bitset words + signature bytes.
        Keys store the digest, not the raw words: a top-level bitset in a
        65k committee is 4 KB of words, and with one cache per vnode the
        raw-words keys were a measured multi-GB term in the swarm memory
        curve (ISSUE 11). 128-bit content hash — collision odds at any
        reachable entry count are negligible next to the protocol's own
        failure modes."""
        h = blake2b(bitset.words().tobytes(), digest_size=16)
        h.update(signature.marshal())
        return h.digest()

    @staticmethod
    def key(scope, ms: MultiSignature) -> tuple:
        """Content identity of a candidate: scope (level, message, or a
        (session, level) pair — the multi-tenant service prepends the
        session id so identical bytes in two sessions never cross-dedup),
        plus the content digest of the exact bitset words and signature
        bytes."""
        return (scope, VerifiedAggCache.content_digest(ms.bitset, ms.signature))

    def drop_scope(self, scope) -> int:
        """Forget every verdict whose key LEADS with `scope` — either as
        the key's first element or as the first element of a tuple scope.
        The multi-tenant eviction hook (handel_tpu/service/): a retired
        session's verdicts must not keep occupying LRU capacity the live
        tenants could use. O(cache size) — evictions are rare next to
        lookups. Returns the number of entries dropped."""
        dead = [
            k
            for k in self._map
            if k[0] == scope
            or (isinstance(k[0], tuple) and k[0] and k[0][0] == scope)
        ]
        for k in dead:
            del self._map[k]
        return len(dead)

    def get(self, key: tuple) -> bool | None:
        """Remembered verdict for `key`, or None; counts the hit/miss."""
        verdict = self._map.get(key)
        if verdict is None:
            self.misses += 1
            return None
        self._map.move_to_end(key)
        self.hits += 1
        return verdict

    def put(self, key: tuple, verdict: bool) -> None:
        self._map[key] = verdict
        self._map.move_to_end(key)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def __len__(self) -> int:
        return len(self._map)

    def values(self) -> dict[str, float]:
        """Reporter surface for the monitor plane (sim/monitor.py CounterIO)."""
        total = self.hits + self.misses
        return {
            "dedupHits": float(self.hits),
            "dedupMisses": float(self.misses),
            "dedupHitRate": self.hits / total if total else 0.0,
            "dedupSize": float(len(self._map)),
        }

    def gauge_keys(self) -> set[str]:
        """Point-in-time keys, declared explicitly so the metrics/monitor
        planes never delta them (core/metrics.py is_gauge_key)."""
        return {"dedupHitRate", "dedupSize"}


class SignatureStore:
    """Store of the best verified multisignature per level.

    Also the default `SigEvaluator` — the store knows best which candidate
    signatures are worth verifying (store.go:14-18).
    """

    def __init__(
        self,
        partitioner: BinomialPartitioner,
        new_bitset: Callable[[int], BitSet] = BitSet,
        constructor: Constructor | None = None,
        combiner: Callable[[list], object] | None = None,
        weights=None,
    ):
        self.part = partitioner
        self.nbs = new_bitset
        self.cons = constructor
        # per-identity stake weights in GLOBAL registry coordinates (the
        # scenario plane's weighted committees); None = count-based scoring.
        # Level bitsets slice it through range_level, which is exact because
        # the partitioner embeds level l's bitset at range_level(l)[0].
        self.weights = weights
        # batched signature combiner: list of Signatures -> their combined
        # Signature in ONE call (core/processing.py CombineShim routes it to
        # the device scheme's combine_batch). None = host-serial
        # `Signature.combine` folds, the reference behavior.
        self.combiner = combiner
        # best multisignature per level (store.go:43)
        self.best_by_level: dict[int, MultiSignature] = {}
        self.highest = 0
        # which individual sigs we have verified, per level (store.go:55).
        # Allocated LAZILY on first touch: eagerly preallocating every
        # level's bitset is Σ size_of(level) ≈ N bits per identity, which is
        # O(N²) across a co-resident swarm committee before any packet flows
        self.indiv_verified: dict[int, BitSet] = {0: new_bitset(1)}
        # the verified individual sigs themselves (store.go:58)
        self.individual_sigs: dict[int, dict[int, MultiSignature]] = {0: {}}
        # reporter counters (report.go:80-87)
        self.replace_trial = 0
        self.success_replace = 0
        # combined()/full_signature() results are pure functions of
        # best_by_level; gossip re-sends the SAME bests every period, so the
        # recombination (bitset embeds + signature folds) is memoized on a
        # generation counter bumped whenever a level's best changes
        self._gen = 0
        self._combined_cache: dict[int, tuple[int, MultiSignature | None]] = {}
        self._full_cache: tuple[int, MultiSignature | None] | None = None

    def _iv(self, level: int) -> BitSet:
        """The level's verified-individuals bitset, created on first touch."""
        bs = self.indiv_verified.get(level)
        if bs is None:
            bs = self.indiv_verified[level] = self.nbs(self.part.size_of(level))
            self.individual_sigs.setdefault(level, {})
        return bs

    # -- evaluation (store.go:101-183) -------------------------------------

    def evaluate(self, sp: IncomingSig) -> int:
        """Score an unverified signature: 0 = discard, higher = verify sooner."""
        score = self._evaluate(sp)
        if score < 0:
            raise AssertionError("negative score")
        return score

    def _evaluate(self, sp: IncomingSig) -> int:
        to_receive = self.part.size_of(sp.level)
        cur_best = self.best_by_level.get(sp.level)

        if cur_best is not None and to_receive == cur_best.cardinality():
            return 0  # completed level: nothing more to gain
        if sp.individual and self._iv(sp.level).get(sp.mapped_index):
            return 0  # already verified this exact individual sig
        if (
            cur_best is not None
            and not sp.individual
            and cur_best.bitset.is_superset(sp.ms.bitset)
        ):
            return 0  # strictly dominated by what we already have

        # what we'd have after patching with known-verified individual sigs
        with_indiv = sp.ms.bitset.or_(self._iv(sp.level))
        final_set = with_indiv
        if cur_best is None:
            new_total = with_indiv.cardinality()
            added_sigs = new_total
            combine_ct = new_total - sp.ms.cardinality()
        elif sp.ms.bitset.intersection_cardinality(cur_best.bitset) != 0:
            # overlap: would replace, not merge
            new_total = with_indiv.cardinality()
            added_sigs = new_total - cur_best.cardinality()
            combine_ct = new_total - sp.ms.cardinality()
        else:
            # disjoint: merge with current best + verified individuals
            final_set = with_indiv.or_(cur_best.bitset)
            new_total = final_set.cardinality()
            added_sigs = new_total - cur_best.cardinality()
            combine_ct = final_set.xor(
                cur_best.bitset.or_(sp.ms.bitset)
            ).cardinality()

        if added_sigs <= 0:
            # no gain; keep individual sigs anyway for BFT patching
            return 1 if sp.individual else 0
        if new_total == to_receive:
            # completes a level — top priority, lower levels first
            return 1_000_000 - sp.level * 10 - combine_ct
        # useful but incomplete: favor lower levels and bigger gains. With
        # stake weights, the gain term scores the weight the candidate adds,
        # normalized back to count units so it stays inside this bracket —
        # all-1.0 weights reduce to exactly added_sigs (the count no-op).
        return (
            100_000
            - sp.level * 100
            + self._gain_units(sp.level, added_sigs, cur_best, final_set)
            - combine_ct
        )

    def _gain_units(self, level, added_sigs, cur_best, final_set) -> int:
        """The `added_sigs * 10` scoring term, stake-aware.

        Count path: added_sigs * 10, the reference score (store.go:180).
        Weighted path: the weight the candidate's new bits add, scaled by
        level_size/level_weight into equivalent-count units and clamped to
        the count bracket's natural range. All-1.0 weights make the scale
        factor exactly 1.0, so the two paths return identical ints.
        """
        if self.weights is None:
            return added_sigs * 10
        lo, hi = self.part.range_level(level)
        lvl_w = self.weights[lo:hi]
        gained = final_set.weight_sum(lvl_w)
        if cur_best is not None:
            gained -= cur_best.bitset.weight_sum(lvl_w)
        total_w = float(sum(lvl_w))
        if total_w <= 0.0:
            return added_sigs * 10
        units = gained * ((hi - lo) / total_w)
        return max(0, min(hi - lo, round(units))) * 10

    # -- storage (store.go:82-99, 188-229) ---------------------------------

    def store(self, sp: IncomingSig) -> MultiSignature | None:
        """Save or merge a *verified* signature; returns the resulting best."""
        if sp.individual:
            if sp.ms.cardinality() != 1:
                raise AssertionError("individual sig with cardinality != 1")
            self._iv(sp.level).set(sp.mapped_index, True)
            self.individual_sigs[sp.level][sp.mapped_index] = sp.ms

        new_ms, should_store = self._check_merge(sp)
        if should_store:
            self.best_by_level[sp.level] = new_ms
            self._gen += 1
            if sp.level > self.highest:
                self.highest = sp.level
        return new_ms

    def _check_merge(self, sp: IncomingSig) -> tuple[MultiSignature | None, bool]:
        cur_best = self.best_by_level.get(sp.level)
        if cur_best is None:
            return sp.ms, True
        self.replace_trial += 1

        # collect every signature the resulting best aggregates — the new
        # candidate, the current best when disjoint, and the individual-sig
        # patches — and combine them in ONE call at the end: a batched
        # device scheme (combine_batch via `combiner`) then pays a single
        # launch where the reference pays one pairing-library point add per
        # contribution (store.go:201,225)
        bits = sp.ms.bitset.clone()
        parts = [sp.ms.signature]
        merged = sp.ms.bitset.or_(cur_best.bitset)
        if merged.cardinality() == cur_best.cardinality() + sp.ms.cardinality():
            # disjoint: aggregate the two signatures
            bits = merged
            parts.append(cur_best.signature)

        # patch holes with verified individual sigs (store.go:204-226)
        vl = self._iv(sp.level)
        patchable = bits.and_(vl).xor(vl)
        if patchable.cardinality() + bits.cardinality() <= cur_best.cardinality():
            return None, False

        for pos in patchable.indices():
            parts.append(self.individual_sigs[sp.level][pos].signature)
            bits.set(pos, True)
        self.success_replace += 1
        return MultiSignature(bits, self._combine_sigs(parts)), True

    def _combine_sigs(self, parts: list):
        """Sum a list of signatures: one batched-combiner call when wired
        (point addition is commutative, so the batched sum is the same
        group element as the reference's sequential fold), else the
        reference's serial `Signature.combine` chain."""
        if len(parts) == 1:
            return parts[0]
        if self.combiner is not None:
            return self.combiner(parts)
        sig = parts[0]
        for s in parts[1:]:
            sig = s.combine(sig)
        return sig

    # -- queries (store.go:231-262) ----------------------------------------

    def best(self, level: int) -> MultiSignature | None:
        return self.best_by_level.get(level)

    def combined(self, level: int) -> MultiSignature | None:
        """Best combination of all levels <= `level`, sized for level+1's
        candidate set (store.go:248-262). Memoized per generation — callers
        (the gossip/fast-path send plane) treat the result as immutable."""
        hit = self._combined_cache.get(level)
        if hit is not None and hit[0] == self._gen:
            return hit[1]
        sigs = [
            IncomingSig(origin=-1, level=lvl, ms=ms)
            for lvl, ms in self.best_by_level.items()
            if lvl <= level
        ]
        send_level = level + 1 if level < self.part.max_level() else level
        ms = self.part.combine(sigs, send_level, self.nbs,
                               combiner=self.combiner)
        self._combined_cache[level] = (self._gen, ms)
        return ms

    def combined_cardinality(self, level: int) -> int:
        """Cardinality `combined(level)` would have, without combining.

        Level ranges are disjoint by construction, so the count is a plain
        sum of per-level best cardinalities — O(levels) dict lookups. The
        verified-signature actors use this to skip the (bitset-embed +
        point-add) combine when the result cannot beat what was already
        sent, which is the common case once a level has propagated.
        """
        return sum(
            ms.cardinality()
            for lvl, ms in self.best_by_level.items()
            if lvl <= level
        )

    def full_cardinality(self) -> int:
        """Cardinality `full_signature()` would have, without combining."""
        return sum(ms.cardinality() for ms in self.best_by_level.values())

    def full_weight(self, weights=None) -> float:
        """Stake weight `full_signature()` would carry, without combining —
        the weighted sibling of `full_cardinality()`. Level ranges are
        disjoint, so the total is a per-level `weight_sum` over the level's
        slice of the global weight vector (range_level gives exactly the
        offsets `combine_full` embeds at). With all-1.0 weights this equals
        `full_cardinality()` exactly."""
        w = self.weights if weights is None else weights
        if w is None:
            return float(self.full_cardinality())
        total = 0.0
        for lvl, ms in self.best_by_level.items():
            lo, hi = self.part.range_level(lvl)
            total += ms.bitset.weight_sum(w[lo:hi])
        return total

    def full_signature(self) -> MultiSignature | None:
        """Registry-sized combination of everything we have (store.go:238-246).
        Memoized per generation like `combined`."""
        if self._full_cache is not None and self._full_cache[0] == self._gen:
            return self._full_cache[1]
        sigs = [
            IncomingSig(origin=-1, level=lvl, ms=ms)
            for lvl, ms in self.best_by_level.items()
        ]
        ms = self.part.combine_full(sigs, self.nbs, combiner=self.combiner)
        self._full_cache = (self._gen, ms)
        return ms

    def values(self) -> dict[str, float]:
        """Reporter counters (report.go:80-87)."""
        return {
            "successReplace": float(self.success_replace),
            "replaceTrial": float(self.replace_trial),
        }


class WindowedSignatureStore(SignatureStore):
    """SignatureStore whose completed levels RETIRE (ISSUE 11).

    The reference store keeps every level's individual-sig structures for
    the whole run — per identity that is Σ size_of(level) ≈ N bits of
    verified-individual bitsets plus up to N stored individual sigs, i.e.
    O(N) per identity and O(N²) across a co-resident swarm committee. Once a
    level is receive-complete nothing at that level can improve (the best
    already covers the full candidate range), so `retire_level`:

    - drops the level's `indiv_verified` bitset and `individual_sigs` dict
      (the only O(level size) state), and
    - compacts the complete best's dense bitset to `AllOnesBitSet` — the
      best AGGREGATE itself is never dropped; `combined()` and
      `full_signature()` keep reading it through `best_by_level`.

    Contributions arriving for a retired level afterwards (gossip
    re-deliveries racing the completion, or stale peers) are
    counted-and-ignored: `staleRetiredCt` in the reporter surface, zero
    score, zero store mutation. Memory per identity is then O(active
    levels), not O(N) — the property the 65k-committee run depends on.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.retired: set[int] = set()
        self.stale_retired_ct = 0

    def retire_level(self, level: int) -> None:
        """Free the level's individual-sig window; keep (and compact) the
        best aggregate. Idempotent."""
        if level in self.retired:
            return
        self.retired.add(level)
        self.indiv_verified.pop(level, None)
        self.individual_sigs.pop(level, None)
        best = self.best_by_level.get(level)
        if (
            best is not None
            and not isinstance(best.bitset, AllOnesBitSet)
            and best.cardinality() == len(best.bitset)
        ):
            self.best_by_level[level] = MultiSignature(
                AllOnesBitSet(len(best.bitset)), best.signature
            )
            self._gen += 1  # cached combinations reference the old object

    def evaluate(self, sp: IncomingSig) -> int:
        if sp.level in self.retired:
            self.stale_retired_ct += 1
            return 0
        return super().evaluate(sp)

    def store(self, sp: IncomingSig) -> MultiSignature | None:
        if sp.level in self.retired:
            # a candidate verified before the level completed, landing
            # after: the level's window is gone and the best already covers
            # it — count, ignore, return the standing best
            self.stale_retired_ct += 1
            return self.best_by_level.get(sp.level)
        return super().store(sp)

    def values(self) -> dict[str, float]:
        return {
            **super().values(),
            "staleRetiredCt": float(self.stale_retired_ct),
            "retiredLevelCt": float(len(self.retired)),
        }

"""In-process multi-node test harness.

Reference: test.go:15-251 — the `Test` struct building N fully wired Handel
instances over an in-memory network (`TestNetwork`, test.go:226-251), with
offline-node injection (:79-90), threshold control, and a complete-success
barrier (`WaitCompleteSuccess`).

Here the "network" routes packets between nodes sharing one asyncio event loop
(encode/decode round-trips exercise the wire path), and the cluster is the main
CI vehicle for protocol tests (SURVEY.md §4 tier 2) — and, with the TPU scheme
plus a shared batch verifier, for pod-local simulation of thousands of logical
nodes (SURVEY.md §2.3).
"""

from __future__ import annotations

import asyncio
import random
from typing import Callable, Sequence

from handel_tpu.core.config import Config
from handel_tpu.core.crypto import Constructor, MultiSignature
from handel_tpu.core.handel import Handel
from handel_tpu.core.identity import ArrayRegistry, Identity
from handel_tpu.core.net import Listener, Packet
from handel_tpu.core.timeout import InfiniteTimeout
from handel_tpu.network.chaos import ChaosConfig, ChaosNetwork
from handel_tpu.network.geo import GeoConfig, GeoNetwork


class InProcessRouter:
    """Address -> listener routing table shared by all in-process networks."""

    def __init__(self, loss_rate: float = 0.0, rand: random.Random | None = None):
        self.listeners: dict[str, list[Listener]] = {}
        self.loss_rate = loss_rate
        self.rand = rand or random.Random(0)
        self.sent_packets = 0

    def route(self, identities: Sequence[Identity], packet: Packet) -> None:
        loop = asyncio.get_running_loop()
        wire = packet.encode()
        for ident in identities:
            if self.loss_rate and self.rand.random() < self.loss_rate:
                continue
            for lst in self.listeners.get(ident.address, []):
                self.sent_packets += 1
                # deliver asynchronously, like a real datagram (test.go:242-250)
                loop.call_soon(lst.new_packet, Packet.decode(wire))

    def values(self) -> dict[str, float]:
        """Reporter surface: the cluster-wide transport plane (the udp/tcp
        per-node counters' in-process analog, for the metrics registry)."""
        return {"sentPackets": float(self.sent_packets)}


class InProcessNetwork:
    """Per-node Network bound to a shared router (test.go:226-251)."""

    def __init__(self, router: InProcessRouter, address: str):
        self.router = router
        self.address = address

    def send(self, identities: Sequence[Identity], packet: Packet) -> None:
        self.router.route(identities, packet)

    def register_listener(self, listener: Listener) -> None:
        self.router.listeners.setdefault(self.address, []).append(listener)


class FakeScheme:
    """Keygen facade over the fake scheme for the harness."""

    def __init__(self):
        from handel_tpu.models.fake import FakeConstructor, FakePublic, FakeSecret

        self.constructor = FakeConstructor()
        self._pub = FakePublic
        self._sec = FakeSecret

    def keygen(self, i: int):
        return self._sec(i), self._pub(True)


class LocalCluster:
    """N wired Handel instances over the in-process network (test.go:15-222)."""

    def __init__(
        self,
        n: int,
        scheme=None,
        threshold: int | None = None,
        offline: Sequence[int] = (),
        msg: bytes = b"hello world",
        config_factory: Callable[[int], Config] | None = None,
        seed: int = 1,
        loss_rate: float = 0.0,
        chaos: ChaosConfig | None = None,
        geo: GeoConfig | None = None,
        adversaries: dict[int, str] | None = None,
        recorder=None,
        metrics_port: int | None = None,
        verifier_service=None,
        churn_after_s: float = 0.5,
    ):
        self.n = n
        self.scheme = scheme or FakeScheme()
        self.msg = msg
        self.offline = set(offline)
        # byzantine roles (sim/adversary.py): node id -> role name. These
        # nodes run — adversarially — so the honest cohort must converge
        # around them, not without them.
        self.roles = dict(adversaries or {})
        self.router = InProcessRouter(
            loss_rate=loss_rate, rand=random.Random(seed)
        )
        cons: Constructor = self.scheme.constructor

        secrets, idents = [], []
        for i in range(n):
            sk, pk = self.scheme.keygen(i)
            secrets.append(sk)
            idents.append(Identity(i, f"inproc-{i}", pk))
        self.registry = ArrayRegistry(idents)

        self.handels: dict[int, Handel] = {}
        self.adversaries: dict[int, Handel] = {}
        # geo delays are not failures, but they do defer deliveries past
        # the no-timeout harness's patience — keep real timeouts on
        has_byzantine = bool(self.offline or self.roles or chaos or geo)
        for i in range(n):
            if i in self.offline:
                continue  # offline nodes are simply never built (test.go:105-113)
            cfg = config_factory(i) if config_factory else Config()
            if recorder is not None:
                # shared flight recorder (core/trace.py): all in-process
                # nodes record into one ring, tid = node id
                cfg.recorder = recorder
            if threshold is not None:
                cfg.contributions = threshold
            if cfg.rand is None or config_factory is None:
                cfg.rand = random.Random(seed + i)
            if not has_byzantine and config_factory is None:
                # no failures -> no timeouts, so stalls are real bugs
                # (handel_test.go:99-101, 442-455)
                cfg.new_timeout = InfiniteTimeout
            net = InProcessNetwork(self.router, f"inproc-{i}")
            if geo is not None:
                # geo-latency planet model (network/geo.py): region-pair
                # WAN delay, chaos faults composed on top when given
                net = GeoNetwork(
                    net,
                    geo.for_node(i),
                    chaos=chaos.for_node(i)
                    if chaos is not None and chaos.any()
                    else None,
                )
                if not cfg.region:
                    cfg.region = geo.region_of(i)
            elif chaos is not None and chaos.any():
                net = ChaosNetwork(net, chaos.for_node(i))
            if i in self.roles:
                from handel_tpu.sim.adversary import build_adversary

                self.adversaries[i] = build_adversary(
                    self.roles[i],
                    net,
                    self.registry,
                    idents[i],
                    cons,
                    self.msg,
                    secrets[i],
                    cfg,
                    leave_after_s=churn_after_s,
                )
                continue
            own_sig = secrets[i].sign(self.msg)
            self.handels[i] = Handel(
                net, self.registry, idents[i], cons, self.msg, own_sig, cfg
            )
        self.threshold = next(iter(self.handels.values())).threshold

        # churn (sim/adversary.py Churner): a departing node broadcasts
        # Handel.mark_departed to every co-resident peer, so survivors
        # re-level and re-evaluate threshold reachability immediately
        churners = [
            a for a in self.adversaries.values()
            if getattr(a, "role", None) == "churner"
        ]
        if churners:
            peers = list(self.handels.values()) + list(
                self.adversaries.values()
            )

            def _on_depart(departed_id: int, _peers=peers) -> None:
                for p in _peers:
                    md = getattr(p, "mark_departed", None)
                    if md is not None:
                        md(departed_id)

            for c in churners:
                c.on_depart = _on_depart

        # live telemetry (core/metrics.py): one registry + HTTP endpoint for
        # the whole in-process cluster, every node's planes under a `node`
        # label — the single-process analog of the sim platform's
        # per-process /metrics servers. metrics_port=None = fully off.
        self.metrics = None
        self.metrics_server = None
        self.verifier_service = verifier_service
        if metrics_port is not None:
            from handel_tpu.core.metrics import MetricsRegistry, MetricsServer

            reg = MetricsRegistry()
            for i, h in self.handels.items():
                lbl = {"node": str(i)}
                reg.register_values("sigs", h, labels=lbl)
                reg.register_histograms("sigs", h, labels=lbl)
                if h.scorer is not None:
                    reg.register_values("penalty", h.scorer, labels=lbl)
            reg.register_values("net", self.router)
            if verifier_service is not None:
                reg.register_values("device_verifier", verifier_service)
            self._started = False
            reg.add_readiness("cluster_started", lambda: self._started)
            reg.add_readiness(
                "breaker_closed",
                lambda: (
                    self.verifier_service is None
                    or self.verifier_service.breaker.state != "open"
                ),
            )
            self.metrics = reg
            self.metrics_server = MetricsServer(reg, port=metrics_port).start()

    def start(self) -> None:
        for h in self.handels.values():
            h.start()
        for a in self.adversaries.values():
            a.start()
        if self.metrics is not None:
            self._started = True

    def stop(self) -> None:
        for h in self.handels.values():
            h.stop()
        for a in self.adversaries.values():
            a.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()

    async def wait_complete_success(self, timeout: float = 10.0) -> dict[int, MultiSignature]:
        """Wait until every online node emitted a final signature >= threshold
        (test.go WaitCompleteSuccess)."""

        async def one(h: Handel) -> MultiSignature:
            return await h.final_signatures.get()

        results = await asyncio.wait_for(
            asyncio.gather(*(one(h) for h in self.handels.values())),
            timeout=timeout,
        )
        return dict(zip(self.handels.keys(), results))


async def run_cluster(
    n: int, timeout: float = 10.0, **kwargs
) -> dict[int, MultiSignature]:
    """Build, run to complete success, and tear down a cluster."""
    cluster = LocalCluster(n, **kwargs)
    cluster.start()
    try:
        return await cluster.wait_complete_success(timeout)
    finally:
        cluster.stop()

"""Pull-based metrics plane: registry, Prometheus exposition, HTTP endpoints.

The monitor plane (sim/monitor.py) is push-based and post-hoc: nodes fire
UDP measures at the master which aggregates ONE CSV row after the run. This
module is the live half the trace plane (ISSUE 4) never had — a
process-local `MetricsRegistry` that wraps the existing reporter surfaces
(`values()` maps, core/report.py; `histograms()` maps, core/trace.py)
behind one scrapeable object, and a stdlib-only `MetricsServer`
(`http.server`, zero new deps) exposing

    GET  /metrics            Prometheus text exposition format 0.0.4
    GET  /healthz            liveness (200 while the process serves)
    GET  /readyz             readiness (200 only when every probe passes)
    GET  /alerts             alert/incident JSON snapshot (obs/plane.py)
    POST /debug/profile?seconds=N   on-demand profiler capture hook

Metric naming convention: `handel_<plane>_<snake_case_key>` — e.g.
`Handel.values()["msgSentCt"]` under plane "sigs" becomes
`handel_sigs_msg_sent_ct`. Planes mirror the monitor measure names:
sigs (protocol), net (transport), penalty (peer scoring), device_verifier
(shared batch service), device (XLA/runtime telemetry,
parallel/telemetry.py).

Counter/gauge classification reuses the reporter contract: a reporter may
declare its point-in-time keys explicitly via `gauge_keys()`; the name
suffix heuristic (`Rate`/`Occupancy`/`Size`/`State`, sim/monitor.py
CounterIO) stays as a fallback only.

Thread model: the HTTP server scrapes from its own daemon thread(s) while
the asyncio loop mutates the counters. Reads of int/float attributes are
atomic under the GIL; a dict mutated mid-iteration can raise, so each
collector is sampled under a retry-once guard and failures surface as the
registry's own `handel_metrics_scrape_errors` counter instead of a 500.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Mapping
from urllib.parse import parse_qs, urlsplit

from handel_tpu.core.trace import LogHistogram

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: point-in-time key suffixes (the sim/monitor.py CounterIO heuristic —
#: kept ONLY as a fallback behind explicit `gauge_keys()` declarations)
GAUGE_SUFFIXES = ("Rate", "Occupancy", "Size", "State")


def is_gauge_key(key: str, declared: Iterable[str] | None = None) -> bool:
    """Explicit declaration first, name-suffix heuristic as fallback."""
    if declared is not None and key in declared:
        return True
    return key.endswith(GAUGE_SUFFIXES)


def snake(key: str) -> str:
    """camelCase reporter key -> snake_case metric suffix
    (`msgSentCt` -> `msg_sent_ct`, `levelCompleteS` -> `level_complete_s`)."""
    out = []
    for i, ch in enumerate(key):
        if ch.isupper():
            if i and (not key[i - 1].isupper() or
                      (i + 1 < len(key) and key[i + 1].islower())):
                out.append("_")
            out.append(ch.lower())
        elif ch.isalnum():
            out.append(ch)
        else:
            out.append("_")
    return "".join(out)


def metric_name(plane: str, key: str) -> str:
    return f"handel_{snake(plane)}_{snake(key)}"


def _fmt_labels(labels: Mapping[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


class Sample:
    """One exposition line: (labels, value) under a family name."""

    __slots__ = ("labels", "value")

    def __init__(self, labels: Mapping[str, str] | None, value: float):
        self.labels = dict(labels or {})
        self.value = float(value)


class Family:
    """A named metric family (one `# TYPE` header, many labeled samples)."""

    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str, help: str = ""):
        self.name = name
        self.mtype = mtype
        self.help = help
        self.samples: list[Sample] = []


class Counter:
    """Directly-incremented counter instrument."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, by: float = 1.0) -> None:
        self.value += by

    def collect(self) -> Iterable[Family]:
        fam = Family(self.name, "counter", self.help)
        fam.samples.append(Sample(None, self.value))
        yield fam


class Gauge:
    """Directly-set gauge instrument; `fn` makes it callback-backed."""

    def __init__(self, name: str, help: str = "",
                 fn: Callable[[], float] | None = None):
        self.name = name
        self.help = help
        self.fn = fn
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def collect(self) -> Iterable[Family]:
        fam = Family(self.name, "gauge", self.help)
        fam.samples.append(Sample(None, self.fn() if self.fn else self.value))
        yield fam


class HistogramMetric:
    """LogHistogram-backed histogram instrument (fixed log buckets)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.hist = LogHistogram()

    def observe(self, v: float) -> None:
        self.hist.add(v)

    def collect(self) -> Iterable[Family]:
        yield _hist_family(self.name, self.help, [(None, self.hist)])


def _hist_family(name, help_, labeled_hists) -> Family:
    fam = Family(name, "histogram", help_)
    for labels, h in labeled_hists:
        labels = dict(labels or {})
        acc = 0
        for i, c in enumerate(h.counts):
            if not c:
                continue  # only emit buckets where the cumulative count moves
            acc += c
            _, hi = h.bucket_bounds(i)
            fam.samples.append(
                Sample({**labels, "le": _fmt_value(hi)}, acc)
            )
        # the mandatory +Inf bucket, _sum and _count
        fam.samples.append(Sample({**labels, "le": "+Inf"}, h.count))
        fam.samples.append(Sample({**labels, "__kind": "sum"}, h.sum))
        fam.samples.append(Sample({**labels, "__kind": "count"}, h.count))
        if h.count:
            # observed extrema: quantile() clamps to [lo, hi], so a scrape
            # that only carries bucket edges reconstructs edge quantiles
            # biased to the geometric midpoint. Carrying min/max makes the
            # exposition round trip exact (merged_histogram reads them back).
            fam.samples.append(Sample({**labels, "__kind": "min"}, h.lo))
            fam.samples.append(Sample({**labels, "__kind": "max"}, h.hi))
    return fam


class _ReporterCollector:
    """Bridges a `values()` reporter into labeled counter/gauge families."""

    def __init__(self, plane, reporter, labels, gauges):
        self.plane = plane
        self.reporter = reporter
        self.labels = dict(labels or {})
        self._explicit = set(gauges) if gauges is not None else None

    def _gauge_set(self):
        if self._explicit is not None:
            return self._explicit
        gk = getattr(self.reporter, "gauge_keys", None)
        return set(gk()) if callable(gk) else set()

    def collect(self) -> Iterable[Family]:
        vals = dict(self.reporter.values())
        declared = self._gauge_set()
        for k, v in vals.items():
            mtype = "gauge" if is_gauge_key(k, declared) else "counter"
            fam = Family(metric_name(self.plane, k), mtype)
            fam.samples.append(Sample(self.labels, v))
            yield fam


class _LabeledReporterCollector:
    """Bridges a `labeled_values()` reporter — {label value: {key: val}} —
    into families carrying a label DIMENSION (one family per key, one
    sample per label value). The multi-tenant service plane uses it with
    label="session" (`handel_service_pending{session="s3"} 17`), the
    device plane with label="device"
    (`handel_device_verifier_launches{device="3"} 12`)."""

    def __init__(self, plane, reporter, label, labels, gauges,
                 cap=0, on_drop=None):
        self.plane = plane
        self.reporter = reporter
        self.label = label
        self.labels = dict(labels or {})
        self._explicit = set(gauges) if gauges is not None else None
        #: cardinality governance: >0 keeps the top-`cap` label values by
        #: activity, folds the rest into one explicit `_overflow` row and
        #: reports them via `on_drop` — truncation is never silent
        self.cap = int(cap or 0)
        self._on_drop = on_drop
        self._dropped_logged: frozenset = frozenset()

    def _gauge_set(self):
        if self._explicit is not None:
            return self._explicit
        # a reporter may expose different gauge sets for its aggregate
        # values() and its per-label rows (parallel/plane.py DevicePlane
        # does): the labeled declaration wins here when present
        gk = getattr(self.reporter, "labeled_gauge_keys", None)
        if not callable(gk):
            gk = getattr(self.reporter, "gauge_keys", None)
        return set(gk()) if callable(gk) else set()

    def _apply_cap(self, rows: dict, declared) -> tuple[dict, list]:
        """Top-`cap`-by-activity selection. Activity is the summed counter
        mass of a row (gauges ignored so a hot session outranks a deep
        queue); ties and all-gauge reporters fall back to total mass,
        then label order for determinism. Dropped rows are summed into an
        explicit `_overflow` row — the scrape still conserves counter
        totals."""
        def activity(vals) -> tuple:
            counter_mass = sum(
                float(v) for k, v in vals.items()
                if not is_gauge_key(k, declared)
            )
            total = sum(float(v) for v in vals.values())
            return (counter_mass, total)

        ranked = sorted(rows, key=lambda lv: (activity(rows[lv]),
                                              str(lv)), reverse=True)
        keep = set(ranked[:self.cap])
        dropped = [lv for lv in ranked[self.cap:]]
        overflow: dict[str, float] = {}
        for lv in dropped:
            for k, v in rows[lv].items():
                overflow[k] = overflow.get(k, 0.0) + float(v)
        kept = {lv: rows[lv] for lv in rows if lv in keep}
        kept["_overflow"] = overflow
        return kept, dropped

    def collect(self) -> Iterable[Family]:
        declared = self._gauge_set()
        rows = {lv: dict(vals)
                for lv, vals in dict(self.reporter.labeled_values()).items()}
        if self.cap > 0 and len(rows) > self.cap:
            rows, dropped = self._apply_cap(rows, declared)
            key = frozenset(str(lv) for lv in dropped)
            if key != self._dropped_logged:
                self._dropped_logged = key
                logging.getLogger("handel_tpu.metrics").warning(
                    "labeled family %s/%s over series cap %d: folded %d "
                    "rows into _overflow: %s", self.plane, self.label,
                    self.cap, len(dropped),
                    ", ".join(sorted(key)[:16]),
                )
            if self._on_drop is not None:
                self._on_drop(len(dropped))
        fams: dict[str, Family] = {}
        for lv, vals in rows.items():
            for k, v in vals.items():
                name = metric_name(self.plane, k)
                fam = fams.get(name)
                if fam is None:
                    mtype = (
                        "gauge" if is_gauge_key(k, declared) else "counter"
                    )
                    fam = fams[name] = Family(name, mtype)
                fam.samples.append(
                    Sample({**self.labels, self.label: str(lv)}, v)
                )
        yield from fams.values()


class _HistogramReporterCollector:
    """Bridges a `histograms()` reporter (key -> LogHistogram)."""

    def __init__(self, plane, reporter, labels):
        self.plane = plane
        self.reporter = reporter
        self.labels = dict(labels or {})

    def collect(self) -> Iterable[Family]:
        for k, h in dict(self.reporter.histograms()).items():
            yield _hist_family(metric_name(self.plane, k), "",
                               [(self.labels, h)])


class MetricsRegistry:
    """Process-local pull registry over the existing reporter surfaces.

    Collection happens at scrape time: nothing is sampled or copied until
    `/metrics` is hit, so an idle registry costs nothing on the hot path.
    """

    def __init__(self, series_cap: int = 0):
        self._collectors: list = []
        self._readiness: dict[str, Callable[[], bool]] = {}
        self._lock = threading.Lock()
        self.scrapes = 0
        self.scrape_errors = 0
        #: default per-family label-cardinality cap for labeled reporters
        #: (0 = uncapped); [alerts] series_cap in the TOML
        self.series_cap = int(series_cap or 0)
        #: rows folded into `_overflow` across all capped collectors,
        #: exported as handel_metrics_rollup_dropped_series_ct
        self.dropped_series = 0
        #: `GET /alerts` JSON payload source (obs/plane.py AlertPlane
        #: .alerts_payload); None -> the endpoint answers 501
        self.alerts_source: Callable[[], dict] | None = None
        #: `GET /fleet` JSON payload source (obs/rollup.py FleetRollup
        #: .fleet_payload); None -> the endpoint answers 501
        self.fleet_source: Callable[[], dict] | None = None

    def set_alerts_source(self, fn: Callable[[], dict] | None) -> None:
        """Wire the /alerts endpoint to a payload callable (the alert
        plane's rule/incident snapshot). Replaceable: last writer wins."""
        self.alerts_source = fn

    def set_fleet_source(self, fn: Callable[[], dict] | None) -> None:
        """Wire the /fleet endpoint to a payload callable (the fleet
        roll-up's host/merge snapshot). Replaceable: last writer wins."""
        self.fleet_source = fn

    def _note_dropped(self, n: int) -> None:
        with self._lock:
            self.dropped_series += int(n)

    # -- registration -------------------------------------------------------

    def register(self, collector) -> None:
        """Anything with `collect() -> Iterable[Family]`."""
        with self._lock:
            self._collectors.append(collector)

    def register_values(self, plane: str, reporter,
                        labels: Mapping[str, str] | None = None,
                        gauges: Iterable[str] | None = None) -> None:
        """Expose a `values()` reporter under `handel_<plane>_*`. Gauge keys
        come from `gauges`, else the reporter's own `gauge_keys()`, else the
        suffix fallback."""
        self.register(_ReporterCollector(plane, reporter, labels, gauges))

    def register_labeled_values(self, plane: str, reporter,
                                label: str = "session",
                                labels: Mapping[str, str] | None = None,
                                gauges: Iterable[str] | None = None,
                                cap: int | None = None) -> None:
        """Expose a `labeled_values()` reporter ({label value: {key: v}})
        under `handel_<plane>_*` with `label` as a label dimension — the
        session axis of the multi-tenant service. Gauge classification as
        in register_values. `cap` bounds label cardinality (top-K by
        activity + `_overflow`); None inherits the registry's series_cap,
        0 disables."""
        self.register(
            _LabeledReporterCollector(
                plane, reporter, label, labels, gauges,
                cap=self.series_cap if cap is None else cap,
                on_drop=self._note_dropped,
            )
        )

    def register_histograms(self, plane: str, reporter,
                            labels: Mapping[str, str] | None = None) -> None:
        """Expose a `histograms()` reporter (key -> LogHistogram)."""
        self.register(_HistogramReporterCollector(plane, reporter, labels))

    def counter(self, name: str, help: str = "") -> Counter:
        c = Counter(name, help)
        self.register(c)
        return c

    def gauge(self, name: str, help: str = "",
              fn: Callable[[], float] | None = None) -> Gauge:
        g = Gauge(name, help, fn=fn)
        self.register(g)
        return g

    def histogram(self, name: str, help: str = "") -> HistogramMetric:
        h = HistogramMetric(name, help)
        self.register(h)
        return h

    # -- readiness ----------------------------------------------------------

    def add_readiness(self, name: str, probe: Callable[[], bool]) -> None:
        with self._lock:
            self._readiness[name] = probe

    def ready(self) -> tuple[bool, dict[str, bool]]:
        """(all probes pass, per-probe status). A probe that raises counts
        as not-ready — a dying dependency must not read as healthy."""
        status: dict[str, bool] = {}
        with self._lock:
            probes = list(self._readiness.items())
        for name, probe in probes:
            try:
                status[name] = bool(probe())
            except Exception:
                status[name] = False
        return all(status.values()), status

    # -- collection / exposition --------------------------------------------

    def collect(self) -> dict[str, Family]:
        """Merged families by name (one `# TYPE` per name even when many
        nodes register the same plane under different labels)."""
        self.scrapes += 1
        merged: dict[str, Family] = {}
        with self._lock:
            collectors = list(self._collectors)
        for col in collectors:
            for attempt in (0, 1):
                try:
                    fams = list(col.collect())
                    break
                except RuntimeError:
                    # reporter dict resized mid-iteration: retry once
                    if attempt:
                        fams = []
                        self.scrape_errors += 1
                except Exception:
                    fams = []
                    self.scrape_errors += 1
                    break
            for fam in fams:
                dst = merged.get(fam.name)
                if dst is None:
                    merged[fam.name] = dst = Family(fam.name, fam.mtype,
                                                    fam.help)
                dst.samples.extend(fam.samples)
        self_fams = [
            ("handel_metrics_scrapes", "counter", float(self.scrapes)),
            ("handel_metrics_scrape_errors", "counter",
             float(self.scrape_errors)),
            ("handel_metrics_rollup_dropped_series_ct", "counter",
             float(self.dropped_series)),
            ("handel_metrics_families", "gauge", float(len(merged) + 4)),
        ]
        for name, mtype, v in self_fams:
            fam = Family(name, mtype)
            fam.samples.append(Sample(None, v))
            merged[name] = fam
        return merged

    def exposition(self) -> str:
        fams = self.collect()
        lines: list[str] = []
        for name in sorted(fams):
            fam = fams[name]
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.mtype}")
            for s in fam.samples:
                kind = s.labels.pop("__kind", "")
                suffix = f"_{kind}" if kind else (
                    "_bucket" if fam.mtype == "histogram" else ""
                )
                lines.append(
                    f"{name}{suffix}{_fmt_labels(s.labels)} "
                    f"{_fmt_value(s.value)}"
                )
        return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> dict[str, dict]:
    """Minimal exposition-format parser for the watch dashboard and tests:
    {family: {"type": t, "samples": [(labels dict, value)]}}. Bucket/sum/
    count lines of a histogram family land under the family name with their
    `_bucket`/`_sum`/`_count` suffix recorded in the labels as `__suffix`."""
    fams: dict[str, dict] = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
                fams.setdefault(parts[2], {"type": parts[3], "samples": []})
            continue
        if "{" in line:
            mname, rest = line.split("{", 1)
            labelstr, _, valstr = rest.rpartition("}")
            labels = {}
            for item in labelstr.split(","):
                if not item:
                    continue
                k, _, v = item.partition("=")
                labels[k.strip()] = v.strip().strip('"')
            value = valstr.strip()
        else:
            mname, _, value = line.rpartition(" ")
            labels = {}
        mname = mname.strip()
        base, suffix = mname, ""
        for suf in ("_bucket", "_sum", "_count", "_min", "_max"):
            cand = mname[: -len(suf)]
            if mname.endswith(suf) and cand in types \
                    and types[cand] == "histogram":
                base, suffix = cand, suf
                break
        if suffix:
            labels["__suffix"] = suffix
        fam = fams.setdefault(base, {"type": types.get(base, "untyped"),
                                     "samples": []})
        try:
            fam["samples"].append((labels, float(value)))
        except ValueError:
            continue
    return fams


def merged_histogram(fams: dict, name: str) -> LogHistogram | None:
    """Rebuild one LogHistogram from parsed `_bucket` samples (summed across
    all label sets — i.e. across nodes). Quantiles are then exact to the
    shared fixed bucket grid, which is all the dashboard needs."""
    fam = fams.get(name)
    if fam is None or fam["type"] != "histogram":
        return None
    h = LogHistogram()
    per_labels: dict[tuple, list[tuple[float, float]]] = {}
    total_sum = 0.0
    obs_lo = obs_hi = None
    for labels, v in fam["samples"]:
        suffix = labels.get("__suffix", "")
        key = tuple(sorted(
            (k, lv) for k, lv in labels.items()
            if k not in ("le", "__suffix")
        ))
        if suffix == "_bucket" and labels.get("le") not in (None, "+Inf"):
            per_labels.setdefault(key, []).append((float(labels["le"]), v))
        elif suffix == "_sum":
            total_sum += v
        elif suffix == "_min":
            obs_lo = v if obs_lo is None else min(obs_lo, v)
        elif suffix == "_max":
            obs_hi = v if obs_hi is None else max(obs_hi, v)
    for buckets in per_labels.values():
        acc = 0.0
        for le, cum in sorted(buckets):
            c = int(cum - acc)
            acc = cum
            if c <= 0:
                continue
            # invert the bucket upper bound back to its index
            i = LogHistogram._index(le * 0.99)
            h.counts[i] += c
            h.count += c
            lo, _ = LogHistogram.bucket_bounds(i)
            h.lo = min(h.lo, lo)
            h.hi = max(h.hi, le)
    # observed extrema from the _min/_max samples override the bucket-edge
    # approximation: quantile() clamps to [lo, hi], so with these restored
    # the round trip through the exposition format is exact
    if obs_lo is not None:
        h.lo = obs_lo
    if obs_hi is not None:
        h.hi = obs_hi
    h.sum = total_sum
    return h if h.count else None


# -- the HTTP server ---------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the registry/server ride on the server object (set by MetricsServer)
    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes,
               ctype: str = "text/plain; charset=utf-8") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        path = urlsplit(self.path).path
        reg: MetricsRegistry = self.server.registry  # type: ignore[attr-defined]
        if path == "/metrics":
            self._reply(200, reg.exposition().encode(), CONTENT_TYPE)
        elif path == "/healthz":
            self._reply(200, b"ok\n")
        elif path == "/readyz":
            ok, status = reg.ready()
            body = json.dumps({"ready": ok, "checks": status}).encode() + b"\n"
            self._reply(200 if ok else 503, body, "application/json")
        elif path == "/alerts":
            src = reg.alerts_source
            if src is None:
                self._reply(501, b"no alert plane wired on this node\n")
                return
            try:
                payload = src()
            except Exception as e:  # a broken plane must not kill the server
                self._reply(500, f"alerts snapshot failed: {e}\n".encode())
                return
            body = json.dumps(payload).encode() + b"\n"
            self._reply(200, body, "application/json")
        elif path == "/fleet":
            src = reg.fleet_source
            if src is None:
                self._reply(501, b"no fleet rollup wired on this node\n")
                return
            try:
                payload = src()
            except Exception as e:  # a broken rollup must not kill the server
                self._reply(500, f"fleet snapshot failed: {e}\n".encode())
                return
            body = json.dumps(payload).encode() + b"\n"
            self._reply(200, body, "application/json")
        else:
            self._reply(404, b"not found\n")

    def do_POST(self) -> None:  # noqa: N802
        parts = urlsplit(self.path)
        if parts.path != "/debug/profile":
            self._reply(404, b"not found\n")
            return
        profiler = self.server.profiler  # type: ignore[attr-defined]
        if profiler is None:
            self._reply(501, b"no profiler wired on this node\n")
            return
        try:
            seconds = float(parse_qs(parts.query).get("seconds", ["1"])[0])
            seconds = min(max(seconds, 0.05), 120.0)
        except ValueError:
            self._reply(400, b"bad seconds value\n")
            return
        try:
            out = profiler(seconds)
        except Exception as e:  # capture failure must not kill the server
            self._reply(500, f"profile capture failed: {e}\n".encode())
            return
        body = json.dumps({"seconds": seconds, "trace": out}).encode() + b"\n"
        self._reply(200, body, "application/json")

    def log_message(self, fmt, *args) -> None:  # scrapes are not log events
        pass


class MetricsServer:
    """stdlib HTTP endpoint thread for one process's registry.

    port=0 binds an ephemeral port; the bound port is available as `.port`
    after start() (the sim platform writes it into the run dir so `sim
    watch` can find every node). Daemon threads: the server never blocks
    process exit, and stop() is idempotent.
    """

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1",
                 profiler: Callable[[float], str] | None = None):
        self.registry = registry
        self.host = host
        self.port = port
        self.profiler = profiler
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.registry = self.registry  # type: ignore[attr-defined]
        httpd.profiler = self.profiler  # type: ignore[attr-defined]
        self.port = httpd.server_address[1]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="metrics-server", daemon=True
        )
        self._thread.start()
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def set_profiler(self, profiler: Callable[[float], str] | None) -> None:
        """Wire (or replace) the /debug/profile handler after start —
        telemetry is typically built later than the server, which must be
        up before a slow scheme warmup begins."""
        self.profiler = profiler
        if self._httpd is not None:
            self._httpd.profiler = profiler  # type: ignore[attr-defined]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._thread = None

"""Node identities and the registry.

Reference: identity.go:11-134 — `Identity` (address + public key + int32 id),
`Registry` (size / identity(i) / identities(from,to)), the array-backed
implementation, and the deterministic seeded shuffle (identity.go:116-125) used
to randomize per-level candidate ordering.

TPU-first note: a device-backed scheme additionally uploads the registry's
public keys once as a dense array in device memory (SURVEY.md §2.1), so
per-candidate aggregation is a masked segment-sum instead of host point adds;
see models/bn254_jax.py.
"""

from __future__ import annotations

import random
from typing import Sequence

import numpy as np

from handel_tpu.core.crypto import PublicKey


class Identity:
    """A participant: network address + public key + dense integer id.

    `weight` is the identity's stake for weighted-threshold committees
    (PAPERS.md arxiv 2302.00418); the default 1.0 makes every weighted
    surface reduce to plain counting, so count-weight committees behave
    bit-for-bit like the unweighted protocol.
    """

    __slots__ = ("id", "address", "public_key", "weight")

    def __init__(
        self,
        id: int,
        address: str,
        public_key: PublicKey | None,
        weight: float = 1.0,
    ):
        self.id = id
        self.address = address
        self.public_key = public_key
        self.weight = weight

    def __repr__(self) -> str:
        return f"Identity(id={self.id}, addr={self.address!r})"


class Registry:
    """Registry interface (identity.go:24-31)."""

    def size(self) -> int:
        raise NotImplementedError

    def identity(self, idx: int) -> Identity:
        raise NotImplementedError

    def identities(self, from_idx: int, to_idx: int) -> Sequence[Identity]:
        """Identities in [from_idx, to_idx) — empty on out-of-range."""
        raise NotImplementedError

    def identity_range(self, from_idx: int, to_idx: int) -> "RegistrySlice":
        """O(1) read-only view of [from_idx, to_idx) — no per-call copy.

        The swarm runtime keeps one Handel instance per identity in one
        process; per-level candidate LISTS are the sum-over-levels ≈ N
        references per node, i.e. O(N²) pointers across a committee. A
        shared view makes level candidate sets O(1) per node instead.
        """
        lo = max(0, from_idx)
        hi = min(self.size(), to_idx)
        return RegistrySlice(self, lo, max(lo, hi))


class RegistrySlice(Sequence):
    """Lazy contiguous registry window: Sequence protocol over identity(i)."""

    __slots__ = ("_reg", "_lo", "_hi")

    def __init__(self, registry: Registry, lo: int, hi: int):
        self._reg = registry
        self._lo = lo
        self._hi = hi

    def __len__(self) -> int:
        return self._hi - self._lo

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            lo, hi, step = idx.indices(len(self))
            if step == 1:
                return RegistrySlice(self._reg, self._lo + lo, self._lo + hi)
            return [self._reg.identity(self._lo + i) for i in range(lo, hi, step)]
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        return self._reg.identity(self._lo + idx)

    def __iter__(self):
        for i in range(self._lo, self._hi):
            yield self._reg.identity(i)

    def __repr__(self) -> str:
        return f"RegistrySlice([{self._lo},{self._hi}))"


class ArrayRegistry(Registry):
    """Dense array-backed registry (identity.go:60-98)."""

    def __init__(self, identities: Sequence[Identity]):
        self._ids = list(identities)
        self._pks: list[PublicKey] | None = None
        self._weights = None
        for i, ident in enumerate(self._ids):
            if ident.id != i:
                raise ValueError(f"registry identity {i} has id {ident.id}")

    def size(self) -> int:
        return len(self._ids)

    def identity(self, idx: int) -> Identity:
        return self._ids[idx]

    def identities(self, from_idx: int, to_idx: int) -> Sequence[Identity]:
        if from_idx < 0 or to_idx > len(self._ids) or from_idx > to_idx:
            return []
        return self._ids[from_idx:to_idx]

    def public_keys(self) -> list[PublicKey]:
        # cached: every co-resident Handel instance asks for this list, and
        # a fresh N-element copy per instance is another O(N²) at swarm scale
        if self._pks is None:
            self._pks = [i.public_key for i in self._ids]
        return self._pks

    def weights(self):
        """Dense float64 stake vector indexed by identity id — the array
        `BitSet.weight_sum` dots against. Cached like public_keys(); call
        sites treat it read-only."""
        if self._weights is None:
            self._weights = np.array(
                [i.weight for i in self._ids], dtype=np.float64
            )
        return self._weights


def shuffle(items: list, seed_rng: random.Random) -> None:
    """Deterministic in-place Fisher-Yates shuffle (identity.go:116-125).

    Callers pass a `random.Random` seeded from Config.rand so that level
    candidate orderings are reproducible across runs and in tests.
    """
    for i in range(len(items) - 1, 0, -1):
        j = seed_rng.randrange(i + 1)
        items[i], items[j] = items[j], items[i]

"""Per-peer penalty scoring: demote and ban misbehaving origins.

The reference has no peer accounting at all — a byzantine peer can feed
invalid signatures forever and every one costs the receiver a pairing check
(processing.go:282-284 just logs and moves on). Here every failed
verification (and, at lower weight, every unparseable packet) is attributed
back to the packet origin; the origin accumulates a decaying penalty score
that first demotes it in `Level.select_next_peers` (half the outbound
updates) and then bans it outright (inbound packets dropped at
`Handel._validate_packet`, before any signature parsing).

Decay is exponential with a configurable half-life, so a peer that hiccuped
once (e.g. a corrupting link, network/chaos.py) recovers, while a persistent
invalid-signer (sim/adversary.py) crosses the ban threshold and stays there.
The ban set is bounded: scores are keyed by registry id (already
range-checked by packet validation, so spoofed origins cannot grow it), and
the ban set refuses growth past `ban_capacity` — an adversary cannot turn
the penalty layer itself into a memory attack.

Single-threaded like the rest of the protocol plane (core/store.py module
docstring): every caller runs on one asyncio loop, so no lock.
"""

from __future__ import annotations

import time
from typing import Callable

DEFAULT_DEMOTE_THRESHOLD = 3.0
DEFAULT_BAN_THRESHOLD = 8.0
DEFAULT_HALF_LIFE_S = 10.0
DEFAULT_BAN_CAPACITY = 256

# attribution weights: a failed pairing check is strong evidence (honest
# nodes only forward verified content), an unparseable packet is weaker
# (cheap to produce, and a corrupting link blames an honest sender)
WEIGHT_VERIFY_FAIL = 1.0
WEIGHT_PARSE_FAIL = 0.25


class PeerScorer:
    """Decaying per-peer penalty scores with a bounded ban set."""

    def __init__(
        self,
        demote_threshold: float = DEFAULT_DEMOTE_THRESHOLD,
        ban_threshold: float = DEFAULT_BAN_THRESHOLD,
        half_life_s: float = DEFAULT_HALF_LIFE_S,
        ban_capacity: int = DEFAULT_BAN_CAPACITY,
        clock: Callable[[], float] = time.monotonic,
    ):
        if demote_threshold <= 0 or ban_threshold <= 0:
            raise ValueError("penalty thresholds must be > 0")
        if ban_threshold < demote_threshold:
            raise ValueError("ban threshold must be >= demote threshold")
        self.demote_threshold = demote_threshold
        self.ban_threshold = ban_threshold
        self.half_life_s = half_life_s
        self.ban_capacity = ban_capacity
        self.clock = clock
        self._scores: dict[int, tuple[float, float]] = {}  # id -> (score, ts)
        self._banned: set[int] = set()
        # reporter counters
        self.reports = 0
        self.ban_refused = 0

    def _decayed(self, peer: int, now: float) -> float:
        entry = self._scores.get(peer)
        if entry is None:
            return 0.0
        score, ts = entry
        if self.half_life_s > 0 and now > ts:
            score *= 0.5 ** ((now - ts) / self.half_life_s)
        return score

    def report(self, peer: int, weight: float = WEIGHT_VERIFY_FAIL) -> None:
        """Attribute one offense of the given weight to `peer`."""
        now = self.clock()
        score = self._decayed(peer, now) + weight
        self._scores[peer] = (score, now)
        self.reports += 1
        if score >= self.ban_threshold and peer not in self._banned:
            if len(self._banned) < self.ban_capacity:
                self._banned.add(peer)
            else:
                self.ban_refused += 1

    def score(self, peer: int) -> float:
        return self._decayed(peer, self.clock())

    def demoted(self, peer: int) -> bool:
        """Penalized enough to receive only every other outbound update."""
        return (
            peer not in self._banned
            and self.score(peer) >= self.demote_threshold
        )

    def banned(self, peer: int) -> bool:
        return peer in self._banned

    def values(self) -> dict[str, float]:
        """Reporter surface for the monitor plane."""
        return {
            "peerPenaltyReports": float(self.reports),
            "peersBanned": float(len(self._banned)),
            "peerBanRefused": float(self.ban_refused),
        }

    def gauge_keys(self) -> set[str]:
        """The ban-set size is a level, not an event count."""
        return {"peersBanned"}


class SessionScorers:
    """Per-tenant penalty state for the multi-tenant service.

    One aggregation session is one trust domain: a peer that misbehaves in
    session A earned its penalty against A's committee, not against every
    committee this process will ever host — and a retired session's scores
    must not linger as host memory or stale bans. This registry keys one
    `PeerScorer` per session id; `drop` (the SessionManager evict hook)
    removes a tenant's whole penalty footprint in one call, and the
    registry itself is bounded: past `capacity` live scorers the
    least-recently-touched one is evicted, so session-id churn cannot turn
    the penalty layer into a memory attack (the same argument as
    PeerScorer's own ban_capacity).

    Single-threaded like PeerScorer (module docstring): no lock.
    """

    def __init__(
        self,
        factory: Callable[[], PeerScorer] = PeerScorer,
        capacity: int = 256,
    ):
        if capacity < 1:
            raise ValueError("scorer capacity must be >= 1")
        self.factory = factory
        self.capacity = capacity
        self._scorers: dict[str, PeerScorer] = {}  # insertion = recency
        self.evicted = 0

    def for_session(self, session: str) -> PeerScorer:
        """The session's scorer, created on first use (LRU-touched)."""
        sc = self._scorers.pop(session, None)
        if sc is None:
            sc = self.factory()
            while len(self._scorers) >= self.capacity:
                self._scorers.pop(next(iter(self._scorers)))
                self.evicted += 1
        self._scorers[session] = sc  # re-insert = most recent
        return sc

    def drop(self, session: str) -> bool:
        """Forget one tenant's penalties entirely (session evict)."""
        return self._scorers.pop(session, None) is not None

    def __len__(self) -> int:
        return len(self._scorers)

    def values(self) -> dict[str, float]:
        """Aggregate reporter surface (per-session detail rides the
        `session`-labeled plane via labeled_values)."""
        return {
            "penaltySessions": float(len(self._scorers)),
            "penaltySessionsEvicted": float(self.evicted),
            "peerPenaltyReports": float(
                sum(s.reports for s in self._scorers.values())
            ),
            "peersBanned": float(
                sum(len(s._banned) for s in self._scorers.values())
            ),
        }

    def labeled_values(self) -> dict[str, dict[str, float]]:
        """{session id: scorer values} for the session-labeled metrics
        plane (core/metrics.py register_labeled_values)."""
        return {sid: s.values() for sid, s in self._scorers.items()}

    def gauge_keys(self) -> set[str]:
        return {"penaltySessions", "peersBanned"}

"""Per-peer penalty scoring: demote and ban misbehaving origins.

The reference has no peer accounting at all — a byzantine peer can feed
invalid signatures forever and every one costs the receiver a pairing check
(processing.go:282-284 just logs and moves on). Here every failed
verification (and, at lower weight, every unparseable packet) is attributed
back to the packet origin; the origin accumulates a decaying penalty score
that first demotes it in `Level.select_next_peers` (half the outbound
updates) and then bans it outright (inbound packets dropped at
`Handel._validate_packet`, before any signature parsing).

Decay is exponential with a configurable half-life, so a peer that hiccuped
once (e.g. a corrupting link, network/chaos.py) recovers, while a persistent
invalid-signer (sim/adversary.py) crosses the ban threshold and stays there.
The ban set is bounded: scores are keyed by registry id (already
range-checked by packet validation, so spoofed origins cannot grow it), and
the ban set refuses growth past `ban_capacity` — an adversary cannot turn
the penalty layer itself into a memory attack.

Single-threaded like the rest of the protocol plane (core/store.py module
docstring): every caller runs on one asyncio loop, so no lock.
"""

from __future__ import annotations

import time
from typing import Callable

DEFAULT_DEMOTE_THRESHOLD = 3.0
DEFAULT_BAN_THRESHOLD = 8.0
DEFAULT_HALF_LIFE_S = 10.0
DEFAULT_BAN_CAPACITY = 256

# attribution weights: a failed pairing check is strong evidence (honest
# nodes only forward verified content), an unparseable packet is weaker
# (cheap to produce, and a corrupting link blames an honest sender)
WEIGHT_VERIFY_FAIL = 1.0
WEIGHT_PARSE_FAIL = 0.25


class PeerScorer:
    """Decaying per-peer penalty scores with a bounded ban set."""

    def __init__(
        self,
        demote_threshold: float = DEFAULT_DEMOTE_THRESHOLD,
        ban_threshold: float = DEFAULT_BAN_THRESHOLD,
        half_life_s: float = DEFAULT_HALF_LIFE_S,
        ban_capacity: int = DEFAULT_BAN_CAPACITY,
        clock: Callable[[], float] = time.monotonic,
    ):
        if demote_threshold <= 0 or ban_threshold <= 0:
            raise ValueError("penalty thresholds must be > 0")
        if ban_threshold < demote_threshold:
            raise ValueError("ban threshold must be >= demote threshold")
        self.demote_threshold = demote_threshold
        self.ban_threshold = ban_threshold
        self.half_life_s = half_life_s
        self.ban_capacity = ban_capacity
        self.clock = clock
        self._scores: dict[int, tuple[float, float]] = {}  # id -> (score, ts)
        self._banned: set[int] = set()
        # reporter counters
        self.reports = 0
        self.ban_refused = 0

    def _decayed(self, peer: int, now: float) -> float:
        entry = self._scores.get(peer)
        if entry is None:
            return 0.0
        score, ts = entry
        if self.half_life_s > 0 and now > ts:
            score *= 0.5 ** ((now - ts) / self.half_life_s)
        return score

    def report(self, peer: int, weight: float = WEIGHT_VERIFY_FAIL) -> None:
        """Attribute one offense of the given weight to `peer`."""
        now = self.clock()
        score = self._decayed(peer, now) + weight
        self._scores[peer] = (score, now)
        self.reports += 1
        if score >= self.ban_threshold and peer not in self._banned:
            if len(self._banned) < self.ban_capacity:
                self._banned.add(peer)
            else:
                self.ban_refused += 1

    def score(self, peer: int) -> float:
        return self._decayed(peer, self.clock())

    def demoted(self, peer: int) -> bool:
        """Penalized enough to receive only every other outbound update."""
        return (
            peer not in self._banned
            and self.score(peer) >= self.demote_threshold
        )

    def banned(self, peer: int) -> bool:
        return peer in self._banned

    def values(self) -> dict[str, float]:
        """Reporter surface for the monitor plane."""
        return {
            "peerPenaltyReports": float(self.reports),
            "peersBanned": float(len(self._banned)),
            "peerBanRefused": float(self.ban_refused),
        }

    def gauge_keys(self) -> set[str]:
        """The ban-set size is a level, not an event count."""
        return {"peersBanned"}

"""Signature-scheme interfaces and the MultiSignature wire object.

Reference: crypto.go:14-137 — `PublicKey`/`SecretKey`/`Signature`/`Constructor`
interfaces, `MultiSignature` (bitset + aggregate signature) with its
length-prefixed wire format (crypto.go:65-110), and `VerifyMultiSignature`
(crypto.go:120-137).

TPU-first notes:
  * Schemes may implement `batch_verify` / `aggregate_public_keys` so the
    processing pipeline can hand a whole batch of candidate multisignatures to
    the device in one launch (SURVEY.md §2.1 "TPU plan" for processing.go).
  * `verify_multisignature`'s pubkey-sum loop (crypto.go:126-134) goes through
    `Constructor.aggregate_public_keys`, which a TPU scheme implements as a
    masked G2 segment-sum kernel instead of a Python loop.
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable

from handel_tpu.core.bitset import BitSet


@runtime_checkable
class Signature(Protocol):
    """An individual or aggregate signature (crypto.go:46-56)."""

    def marshal(self) -> bytes: ...

    def combine(self, other: "Signature") -> "Signature":
        """Aggregate (not verify) this signature with another one."""
        ...


@runtime_checkable
class PublicKey(Protocol):
    """A public key (crypto.go:14-27)."""

    def marshal(self) -> bytes: ...

    def verify(self, msg: bytes, sig: Signature) -> bool: ...

    def combine(self, other: "PublicKey") -> "PublicKey": ...


@runtime_checkable
class SecretKey(Protocol):
    """A secret key (crypto.go:36-41)."""

    def sign(self, msg: bytes) -> Signature: ...


class Constructor:
    """Factory for a signature scheme's objects (crypto.go:29-44).

    Subclasses implement `unmarshal_signature`/`signature_size` and may
    override the batch helpers with device kernels. Concrete schemes:
    models/fake.py, models/bn254.py (pure python), models/bn254_native.py
    (C++), models/bn254_jax.py (TPU), models/bls12_381.py.
    """

    def unmarshal_signature(self, data: bytes) -> Signature:
        raise NotImplementedError

    def signature_size(self) -> int:
        """Fixed wire size of one (possibly aggregate) signature in bytes."""
        raise NotImplementedError

    # -- batch extensions (TPU path; optional for host-only schemes) -------

    def aggregate_public_keys(
        self, keys: Sequence[PublicKey], bitset: BitSet
    ) -> PublicKey:
        """Sum of `keys[i]` for every set bit i (crypto.go:126-134 loop)."""
        agg = None
        for i in bitset.indices():
            agg = keys[i] if agg is None else agg.combine(keys[i])
        if agg is None:
            raise ValueError("empty bitset: no public keys to aggregate")
        return agg

    def batch_verify(
        self,
        msg: bytes,
        pubkeys: Sequence[PublicKey],
        requests: Sequence[tuple[BitSet, Signature]],
    ) -> list[bool]:
        """Verify many (bitset, aggregate signature) candidates against one msg.

        Default: serial aggregate-then-verify (what the reference does once per
        signature in processing.go:342-368). Device schemes override this with a
        single batched multi-pairing launch.
        """
        out = []
        for bs, sig in requests:
            if bs.cardinality() == 0:
                out.append(False)
                continue
            agg = self.aggregate_public_keys(pubkeys, bs)
            out.append(agg.verify(msg, sig))
        return out


class MultiSignature:
    """A (bitset, aggregate signature) pair — the protocol's unit of gossip.

    Wire format (crypto.go:65-110): marshaled bitset (uint16 bit-length prefix,
    bitset.go:150-177) followed by the fixed-size signature bytes.
    """

    __slots__ = ("bitset", "signature")

    def __init__(self, bitset: BitSet, signature: Signature):
        self.bitset = bitset
        self.signature = signature

    def cardinality(self) -> int:
        return self.bitset.cardinality()

    def marshal(self) -> bytes:
        return self.bitset.marshal() + self.signature.marshal()

    @classmethod
    def unmarshal(cls, data: bytes, constructor: Constructor) -> "MultiSignature":
        bs, used = BitSet.unmarshal(data)
        sig_bytes = data[used:]
        if len(sig_bytes) < constructor.signature_size():
            raise ValueError("multisignature wire data truncated")
        sig = constructor.unmarshal_signature(
            sig_bytes[: constructor.signature_size()]
        )
        return cls(bs, sig)

    def __repr__(self) -> str:
        return f"MultiSignature(bits={self.bitset!r})"


def verify_multisignature(
    msg: bytes,
    ms: MultiSignature,
    registry: "Registry",  # noqa: F821 - circular, typed loosely
    constructor: Constructor,
) -> bool:
    """Registry-wide final verification (crypto.go:120-137).

    Aggregates the public keys of every signer in `ms.bitset` (over the full
    registry) and checks the aggregate signature against `msg`.
    """
    n = registry.size()
    if len(ms.bitset) != n:
        return False
    if ms.bitset.cardinality() == 0:
        return False
    keys = [registry.identity(i).public_key for i in range(n)]
    agg = constructor.aggregate_public_keys(keys, ms.bitset)
    return agg.verify(msg, ms.signature)

"""Network abstraction: packets, transports, listeners.

Reference: net.go:6-44 — `Network` (Send/RegisterListener), `Listener`
(NewPacket), and `Packet{Origin int32, Level byte, MultiSig, IndividualSig}`.

The wire codec here is a fixed binary layout (length-prefixed fields) rather
than the reference's gob encoding (network/gobEncoding.go:10-32) — simpler,
language-neutral, and cheap to parse. Transports live in handel_tpu/network/.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable

from handel_tpu.core.identity import Identity


@dataclass
class Packet:
    """One protocol datagram (net.go:24-44).

    `sent_ts` is the sender's epoch-seconds send timestamp (core/trace.py
    trace clock). Processes on one host share the clock, so a receiving
    node's flight recorder can emit the network-transit span of every
    contribution; 0.0 means "not stamped".

    `span_id`/`hop` are the compact trace context beside the stamp: the
    sender's flow-event id linking its `send` span to the receiver's
    pipeline chain (core/trace.py flow events), and a flag marking the
    multisig as an aggregate that itself rode earlier hops. They travel as
    an OPTIONAL 9-byte trailer after the payloads — a packet without one
    (or with a truncated/corrupt one) decodes as "unlinked" (`span_id=0,
    hop=0`), never as an error: trace context must not create a new way
    for a byzantine peer to make packets unparseable.
    """

    origin: int  # global id of the sender
    level: int  # level this packet's multisig belongs to
    multisig: bytes  # marshaled MultiSignature
    individual_sig: bytes | None = None  # optional marshaled individual sig
    sent_ts: float = 0.0  # sender trace-clock timestamp (0 = unstamped)
    span_id: int = 0  # sender flow-link id (0 = unlinked)
    hop: int = 0  # 1 = aggregate carries earlier hops' contributions

    # origin, level, len(multisig), len(indiv), sent_ts
    _HDR = struct.Struct(">iBHHd")
    # optional trace-context trailer: span id, hop flag
    _TRAILER = struct.Struct(">QB")

    def encode(self) -> bytes:
        ind = self.individual_sig or b""
        wire = (
            self._HDR.pack(
                self.origin, self.level, len(self.multisig), len(ind), self.sent_ts
            )
            + self.multisig
            + ind
        )
        if self.span_id or self.hop:
            wire += self._TRAILER.pack(
                self.span_id & 0xFFFFFFFFFFFFFFFF, 1 if self.hop else 0
            )
        return wire

    @classmethod
    def decode(cls, data: bytes) -> "Packet":
        if len(data) < cls._HDR.size:
            raise ValueError("packet too short")
        origin, level, ms_len, ind_len, sent_ts = cls._HDR.unpack_from(data)
        off = cls._HDR.size
        if len(data) < off + ms_len + ind_len:
            raise ValueError("packet truncated")
        ms = data[off : off + ms_len]
        ind = data[off + ms_len : off + ms_len + ind_len] if ind_len else None
        if not math.isfinite(sent_ts) or sent_ts < 0.0:
            sent_ts = 0.0  # corrupt stamps degrade to "unstamped", never NaN
        # optional trace-context trailer: anything shorter than the full 9
        # bytes (stripped, truncated mid-flight, pre-trailer sender) is
        # simply an unlinked packet — degrade, never raise
        span_id = hop = 0
        rest = len(data) - off - ms_len - ind_len
        if rest >= cls._TRAILER.size:
            span_id, hop_byte = cls._TRAILER.unpack_from(
                data, off + ms_len + ind_len
            )
            hop = 1 if hop_byte else 0
        return cls(
            origin=origin,
            level=level,
            multisig=ms,
            individual_sig=ind,
            sent_ts=sent_ts,
            span_id=span_id,
            hop=hop,
        )


@runtime_checkable
class Listener(Protocol):
    """Consumer of inbound packets (net.go:16-19)."""

    def new_packet(self, packet: Packet) -> None: ...


class Network(Protocol):
    """Point-to-point datagram plane (net.go:6-13)."""

    def send(self, identities: Sequence[Identity], packet: Packet) -> None: ...

    def register_listener(self, listener: Listener) -> None: ...

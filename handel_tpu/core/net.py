"""Network abstraction: packets, transports, listeners.

Reference: net.go:6-44 — `Network` (Send/RegisterListener), `Listener`
(NewPacket), and `Packet{Origin int32, Level byte, MultiSig, IndividualSig}`.

The wire codec here is a fixed binary layout (length-prefixed fields) rather
than the reference's gob encoding (network/gobEncoding.go:10-32) — simpler,
language-neutral, and cheap to parse. Transports live in handel_tpu/network/.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable

from handel_tpu.core.identity import Identity


@dataclass
class Packet:
    """One protocol datagram (net.go:24-44).

    `sent_ts` is the sender's epoch-seconds send timestamp (core/trace.py
    trace clock). Processes on one host share the clock, so a receiving
    node's flight recorder can emit the network-transit span of every
    contribution; 0.0 means "not stamped".
    """

    origin: int  # global id of the sender
    level: int  # level this packet's multisig belongs to
    multisig: bytes  # marshaled MultiSignature
    individual_sig: bytes | None = None  # optional marshaled individual sig
    sent_ts: float = 0.0  # sender trace-clock timestamp (0 = unstamped)

    # origin, level, len(multisig), len(indiv), sent_ts
    _HDR = struct.Struct(">iBHHd")

    def encode(self) -> bytes:
        ind = self.individual_sig or b""
        return (
            self._HDR.pack(
                self.origin, self.level, len(self.multisig), len(ind), self.sent_ts
            )
            + self.multisig
            + ind
        )

    @classmethod
    def decode(cls, data: bytes) -> "Packet":
        if len(data) < cls._HDR.size:
            raise ValueError("packet too short")
        origin, level, ms_len, ind_len, sent_ts = cls._HDR.unpack_from(data)
        off = cls._HDR.size
        if len(data) < off + ms_len + ind_len:
            raise ValueError("packet truncated")
        ms = data[off : off + ms_len]
        ind = data[off + ms_len : off + ms_len + ind_len] if ind_len else None
        if not math.isfinite(sent_ts) or sent_ts < 0.0:
            sent_ts = 0.0  # corrupt stamps degrade to "unstamped", never NaN
        return cls(
            origin=origin,
            level=level,
            multisig=ms,
            individual_sig=ind,
            sent_ts=sent_ts,
        )


@runtime_checkable
class Listener(Protocol):
    """Consumer of inbound packets (net.go:16-19)."""

    def new_packet(self, packet: Packet) -> None: ...


class Network(Protocol):
    """Point-to-point datagram plane (net.go:6-13)."""

    def send(self, identities: Sequence[Identity], packet: Packet) -> None: ...

    def register_listener(self, listener: Listener) -> None: ...

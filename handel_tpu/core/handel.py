"""The Handel protocol state machine.

Reference: handel.go:15-598 — the `Handel` struct, packet validation/parsing
(:127-152, :373-436), the four concurrent loops started by `Start()` (:156-164),
periodic updates (:167-225), the actor pattern (:257-328: checkCompletedLevel +
checkFinalSignature), per-level send state (:443-580), and level creation with
seeded shuffling (:498-519).

Concurrency redesign: the reference runs four goroutines per node under one
global mutex; here each node is a set of asyncio tasks on a single event loop —
no locks, and thousands of logical nodes can share one loop (and one device
batch queue) in-process. Verified signatures flow back via a direct callback
(`_on_verified`) instead of a channel.
"""

from __future__ import annotations

import asyncio
import time
from typing import Sequence

from handel_tpu.core.config import Config, merge_with_default
from handel_tpu.core.crypto import Constructor, MultiSignature, Signature
from handel_tpu.core.identity import Identity, Registry, shuffle
from handel_tpu.core.net import Network, Packet
from handel_tpu.core.partitioner import IncomingSig
from handel_tpu.core.penalty import (
    WEIGHT_PARSE_FAIL,
    PeerScorer,
)
from handel_tpu.core.processing import BatchProcessing, CombineShim
from handel_tpu.core.report import WarnOnce
from handel_tpu.core.store import SignatureStore
from handel_tpu.core.timeout import LinearTimeout
from handel_tpu.core.trace import LogHistogram, trace_now


class Level:
    """Per-level send/receive state (handel.go:443-580)."""

    def __init__(
        self,
        id: int,
        nodes: Sequence[Identity],
        send_expected_full_size: int,
        scorer: PeerScorer | None = None,
    ):
        if id <= 0:
            raise ValueError("level id must be >= 1")
        self.id = id
        # any indexable sequence works (list, RegistrySlice): keeping a lazy
        # range view instead of copying makes a level O(1) memory — summed
        # over levels and co-resident swarm nodes, copies would be O(N²)
        self.nodes = nodes if hasattr(nodes, "__getitem__") else list(nodes)
        # dynamic membership (handel_tpu/scenario/): global ids of members
        # of THIS level that left mid-aggregation. They are skipped in peer
        # selection and excluded from the receive-complete count — the
        # level's effective size shrinks without rebuilding the partitioner.
        self.departed: set[int] = set()
        self.send_started = False
        self.rcv_completed = False
        self.send_pos = 0
        self.send_peers_ct = 0
        self.send_expected_full_size = send_expected_full_size
        self.send_sig_size = 0
        # peer penalty plane (core/penalty.py): banned peers are skipped,
        # demoted peers get every other update
        self.scorer = scorer
        self._demote_tick: dict[int, int] = {}
        self.banned_skips = 0
        self.demote_skips = 0

    def active(self) -> bool:
        """Started and not yet done contacting every peer with the current
        signature (handel.go:526-528)."""
        return self.send_started and self.send_peers_ct < len(self.nodes)

    def set_started(self) -> None:
        self.send_started = True

    def select_next_peers(self, count: int) -> list[Identity]:
        """Rolling window over the (shuffled) peer list (handel.go:544-558).

        With a scorer attached, banned peers never get a slot (sending to a
        peer we refuse to hear from is pure waste) and demoted peers are
        handed only every other update — offenders fall behind honest peers
        without being cut off on a single bad packet. The scan is bounded so
        an all-banned level degrades to an empty selection, not a spin.
        """
        size = min(count, len(self.nodes))
        if self.scorer is None and not self.departed:
            res = []
            for _ in range(size):
                res.append(self.nodes[self.send_pos])
                self.send_pos = (self.send_pos + 1) % len(self.nodes)
            self.send_peers_ct += size
            return res

        res: list[Identity] = []
        # at most one full pass: each peer considered once per selection, so
        # skips shrink the selection instead of double-sending to survivors
        for _ in range(len(self.nodes)):
            if len(res) >= size:
                break
            peer = self.nodes[self.send_pos]
            self.send_pos = (self.send_pos + 1) % len(self.nodes)
            if peer.id in self.departed:
                continue  # a gone member: a packet there is pure loss
            if self.scorer is not None and self.scorer.banned(peer.id):
                self.banned_skips += 1
                continue
            if self.scorer is not None and self.scorer.demoted(peer.id):
                tick = self._demote_tick.get(peer.id, 0) + 1
                self._demote_tick[peer.id] = tick
                if tick % 2 == 1:
                    self.demote_skips += 1
                    continue
            res.append(peer)
        self.send_peers_ct += size
        return res

    def expected_members(self) -> int:
        """Members that can still contribute: level size minus departures."""
        return len(self.nodes) - len(self.departed)

    def update_sig_to_send(self, sig: MultiSignature) -> bool:
        """Track the best signature we can send at this level; reset the peer
        counter on improvement so the better sig propagates. Returns True when
        the sendable signature is complete (fast-path start, handel.go:565-580)."""
        card = sig.cardinality()
        if self.send_sig_size >= card:
            return False
        self.send_sig_size = card
        self.send_peers_ct = 0
        if self.send_sig_size == self.send_expected_full_size:
            self.set_started()
            return True
        return False


def create_levels(
    config: Config, partitioner, scorer: PeerScorer | None = None
) -> dict[int, Level]:
    """Build all levels, shuffling candidate order per level (handel.go:498-519).

    send_expected_full_size accumulates 1 (own sig) + the sizes of all lower
    levels — the complete signature one can send at each level.
    """
    levels: dict[int, Level] = {}
    first_active = False
    send_expected_full_size = 1
    for lvl in partitioner.levels():
        nodes = partitioner.identities_at(lvl)
        if not config.disable_shuffling:
            # shuffling forces a real copy; with it disabled (the swarm
            # default) the partitioner's O(1) range view is kept as-is
            nodes = list(nodes)
            shuffle(nodes, config.rand)
        levels[lvl] = Level(lvl, nodes, send_expected_full_size, scorer)
        if config.disable_shuffling:
            # un-shuffled candidate order is IDENTICAL for every node in a
            # sibling subtree, so a send_pos of 0 would aim the whole
            # subtree's fast-path burst at the level's first `count`
            # candidates and starve the rest until gossip rotates there.
            # Deriving the rotation start from our own id spreads the burst
            # uniformly with none of shuffling's per-node list copies.
            levels[lvl].send_pos = partitioner.id % len(nodes)
        send_expected_full_size += len(nodes)
        if not first_active:
            levels[lvl].set_started()
            first_active = True
    return levels


class Handel:
    """One logical aggregation node (handel.go:15-62).

    Consume final multisignatures from `final_signatures` (an asyncio.Queue,
    the reference's FinalSignatures() channel, handel.go:230-232).
    """

    def __init__(
        self,
        network: Network,
        registry: Registry,
        identity: Identity,
        constructor: Constructor,
        msg: bytes,
        own_sig: Signature,
        config: Config | None = None,
    ):
        self.c = merge_with_default(config, registry.size())
        self.net = network
        self.reg = registry
        self.id = identity
        self.cons = constructor
        self.msg = msg
        self.sig = own_sig
        self.log = self.c.logger.with_fields(id=identity.id)

        # byzantine peer accounting (core/penalty.py): failed verifications
        # and unparseable packets are attributed back to the packet origin
        if self.c.penalize_peers:
            self.scorer = (
                self.c.new_scorer(self) if self.c.new_scorer else PeerScorer()
            )
        else:
            self.scorer = None

        self.partitioner = self.c.new_partitioner(identity.id, registry, self.log)
        self.levels = create_levels(self.c, self.partitioner, self.scorer)
        self.ids = self.partitioner.levels()
        self.threshold = self.c.contributions
        self.done = False
        self.best: MultiSignature | None = None
        self.final_signatures: asyncio.Queue[MultiSignature] = asyncio.Queue()
        self.start_time = 0.0

        # span flight recorder (core/trace.py): shared across co-located
        # nodes, this node's events keyed by its id as the Chrome-trace tid.
        # None = tracing off; the hot-path hooks cost one None check.
        self.rec = self.c.recorder
        self._tid = identity.id
        if self.rec is not None:
            self.rec.name_thread(self._tid, f"node-{identity.id}")
        # outbound flow-link ids: (node id << 40) | seq is unique fleet-wide
        # without coordination; generated only while tracing, so untraced
        # packets stay span_id=0 (no trailer on the wire)
        self._span_seq = 0
        # session/epoch tags folded into span args end to end (multi-tenant
        # runs; the epoch marks which validator set served this node)
        self._sargs = {"session": self.c.session} if self.c.session else {}
        if self.c.epoch:
            self._sargs = {**self._sargs, "epoch": self.c.epoch}
        if self.c.region:
            # WAN region tag (scenario/geo plane): rides every span this
            # node emits so the critical-path analyzer can attribute hops
            # to region pairs (sender's send span vs receiver's recv span)
            self._sargs = {**self._sargs, "region": self.c.region}
        # distributional measures (always on — a handful of clock reads per
        # level/batch): level-completion latency since start, for the
        # monitor plane's _p50/_p90/_p99 columns (sim/monitor.py)
        self.hist_level_complete = LogHistogram()

        # batched aggregate combine: device constructors expose
        # `device_combine`, and the shim routes the store's merge/patch
        # point-addition chains through one combine_batch launch per group
        # instead of one host pairing-library add per contribution; host
        # constructors get no shim and the store keeps its serial path
        self.combine_shim = CombineShim.for_constructor(constructor)
        store_cls = self.c.new_store or SignatureStore
        self.store = store_cls(
            self.partitioner,
            self.c.new_bitset,
            constructor,
            combiner=(
                self.combine_shim.combine_many if self.combine_shim else None
            ),
            weights=self.c.weights,
        )
        # stake-weighted threshold (handel_tpu/scenario/): with a weight
        # vector set, the final-signature gate compares accumulated stake
        # against `weight_threshold` — by default the same fraction of
        # total stake that `contributions` is of the node count, computed
        # as (threshold * total) / n so all-1.0 weights yield EXACTLY the
        # integer count threshold (no float drift on the no-op path).
        self.weights = self.c.weights
        self.weight_threshold = 0.0
        self.total_weight = 0.0
        if self.weights is not None:
            self.total_weight = float(sum(self.weights))
            self.weight_threshold = self.c.weight_threshold or (
                self.threshold * self.total_weight / registry.size()
            )
        # dynamic membership: global ids known to have left mid-run
        # (scenario engine / churner adversaries broadcast departures)
        self.departed: set[int] = set()
        self.threshold_unreachable_ct = 0
        # our own signature seeds the store at level 0 (handel.go:108-116)
        first_bs = self.c.new_bitset(1)
        first_bs.set(0, True)
        self.store.store(
            IncomingSig(
                origin=identity.id,
                level=0,
                ms=MultiSignature(first_bs, own_sig),
                is_ind=True,
                mapped_index=0,
            )
        )

        evaluator = (
            self.c.new_evaluator(self.store, self)
            if self.c.new_evaluator
            else self.store
        )
        processing_cls = self.c.new_processing or BatchProcessing
        self.proc = processing_cls(
            self.partitioner,
            constructor,
            msg,
            registry.public_keys()
            if hasattr(registry, "public_keys")
            else [registry.identity(i).public_key for i in range(registry.size())],
            evaluator,
            self._on_verified,
            batch_size=self.c.batch_size,
            verifier=self.c.verifier,
            unsafe_sleep_ms=self.c.unsafe_sleep_on_verify_ms,
            max_pending=self.c.max_pending,
            on_verify_failed=self._on_verify_failed,
            logger=self.log,
            recorder=self.rec,
            trace_tid=self._tid,
            session=self.c.session,
            epoch=self.c.epoch,
        )
        self.net.register_listener(self)
        self.timeout = (
            self.c.new_timeout(self, self.ids)
            if self.c.new_timeout
            else LinearTimeout(self, self.ids, self.c.level_timeout)
        )

        # minimal stats (handel.go:594-598) + reporter hook
        self.msg_sent_ct = 0
        self.msg_rcv_ct = 0
        self.invalid_packet_ct = 0
        self.banned_packet_ct = 0
        # warn-once log keys: a flooder spamming malformed packets must not
        # turn the log itself into the DoS — first offense per reason is
        # WARN, the rest are debug + the logWarnCt counter (core/report.py)
        self._warn = WarnOnce(self.log)
        self._periodic_task: asyncio.Task | None = None

    # -- lifecycle (handel.go:156-182) -------------------------------------

    def start(self, periodic: bool = True) -> None:
        """Start processing, timeouts and the periodic updater. Must be called
        from a running asyncio event loop.

        `periodic=False` skips the per-node updater task: an external ticker
        (core/timeout.py TimerWheel, driving thousands of co-resident swarm
        nodes off ONE task) calls `periodic_update()` instead — an asyncio
        task per node is exactly what the virtual-node runtime exists to
        avoid."""
        self.start_time = time.monotonic()
        self.proc.start()
        self.timeout.start()
        if periodic:
            self._periodic_task = asyncio.get_running_loop().create_task(
                self._periodic_loop()
            )

    def stop(self) -> None:
        self.timeout.stop()
        self.proc.stop()
        if self._periodic_task is not None:
            self._periodic_task.cancel()
            self._periodic_task = None
        self.done = True

    async def _periodic_loop(self) -> None:
        while True:
            await asyncio.sleep(self.c.update_period)
            self._periodic_update()

    def periodic_update(self) -> None:
        """External-ticker entry (TimerWheel): one gossip round, now."""
        if not self.done:
            self._periodic_update()

    def _periodic_update(self) -> None:
        """Gossip our best combined sig on every active level (handel.go:186-194)."""
        for lvl in self.levels.values():
            if lvl.active():
                self._send_update(lvl, self.c.update_count)

    # -- inbound path (handel.go:127-152) ----------------------------------

    def new_packet(self, p: Packet) -> None:
        if self.done:
            return
        rec = self.rec
        tracing = rec is not None and rec.enabled
        t0 = trace_now() if tracing else 0.0
        try:
            self._validate_packet(p)
        except ValueError as e:
            self.invalid_packet_ct += 1
            self._warn_once("invalid_packet", e)
            return
        try:
            ms, ind = self._parse_signatures(p)
        except ValueError as e:
            self.invalid_packet_ct += 1
            self._warn_once("invalid_packet_multisig", e)
            # an unparseable payload from an in-range origin is attributed
            # (at low weight — a corrupting link blames an honest sender)
            if self.scorer is not None:
                self.scorer.report(p.origin, WEIGHT_PARSE_FAIL)
            return
        if tracing:
            # the sender's stamp lines the network-transit span up with our
            # local spans (both sides use the shared epoch trace clock)
            if p.sent_ts and p.sent_ts <= t0:
                rec.span(
                    "net_transit",
                    p.sent_ts,
                    t0,
                    tid=self._tid,
                    cat="net",
                    args={
                        "origin": p.origin,
                        "level": p.level,
                        "span": p.span_id,
                        **self._sargs,
                    },
                )
            ms.recv_ts = t0
            ms.span_id = p.span_id
            if ind is not None:
                ind.recv_ts = t0
                ind.span_id = p.span_id
        if not self.levels[p.level].rcv_completed:
            self.proc.add(ms)
            if ind is not None:
                self.proc.add(ind)
            if tracing:
                # `rts` (arrival stamp, µs) discriminates re-deliveries of
                # the same (origin, level) so the trace CLI reconstructs
                # each physical contribution's chain separately
                t1 = trace_now()
                rec.span(
                    "recv",
                    t0,
                    t1,
                    tid=self._tid,
                    cat="pipeline",
                    args={
                        "origin": p.origin,
                        "level": p.level,
                        "rts": int(t0 * 1e6),
                        "span": p.span_id,
                        "hop": p.hop,
                        **self._sargs,
                    },
                )
                if p.span_id:
                    # flow step: binds the sender's `send` arrow into this
                    # recv span ("t" + bp:e attaches to the enclosing slice)
                    rec.flow("contrib", p.span_id, "t", t1, tid=self._tid)

    def _warn_once(self, key: str, detail) -> None:
        """WARN on the first occurrence per reason, debug + counter after —
        a flooder cannot turn per-packet logging into the attack, and the
        suppressed volume stays visible as `logWarnCt` in the CSVs."""
        self._warn.warn(key, detail)

    def _validate_packet(self, p: Packet) -> None:
        """Origin/level range + byzantine checks (handel.go:373-386), all
        BEFORE any signature bytes are parsed: a reflected or spoofed-origin
        packet costs an integer compare, never an unmarshal."""
        self.msg_rcv_ct += 1
        if p.origin < 0 or p.origin >= self.reg.size():
            raise ValueError("packet's origin out of range")
        if p.origin == self.id.id:
            raise ValueError("packet claims to originate from this node")
        if self.scorer is not None and self.scorer.banned(p.origin):
            self.banned_packet_ct += 1
            raise ValueError(f"origin {p.origin} is banned")
        if p.level not in self.levels:
            raise ValueError(f"invalid packet level {p.level}")

    def _parse_signatures(
        self, p: Packet
    ) -> tuple[IncomingSig, IncomingSig | None]:
        """Unmarshal + sanity-check the multisig and optional individual sig
        (handel.go:390-436)."""
        ms = MultiSignature.unmarshal(p.multisig, self.cons)
        lvl = self.levels[p.level]
        if len(ms.bitset) != len(lvl.nodes):
            raise ValueError("invalid bitset size for given level")
        if ms.bitset.cardinality() == 0:
            raise ValueError("no signature in the bitset")
        inc = IncomingSig(origin=p.origin, level=p.level, ms=ms)

        if p.individual_sig is None:
            return inc, None
        if len(p.individual_sig) != self.cons.signature_size():
            raise ValueError("individual signature has wrong wire size")
        individual = self.cons.unmarshal_signature(p.individual_sig)
        level_index = self.partitioner.index_at_level(p.origin, p.level)
        bs = self.c.new_bitset(len(lvl.nodes))
        bs.set(level_index, True)
        ind = IncomingSig(
            origin=p.origin,
            level=p.level,
            ms=MultiSignature(bs, individual),
            is_ind=True,
            mapped_index=level_index,
        )
        return inc, ind

    # -- verified-signature actors (handel.go:239-328) ---------------------

    def _on_verified(self, sp: IncomingSig) -> None:
        """Store the verified signature, then run the actors
        (rangeOnVerified, handel.go:239-248)."""
        rec = self.rec
        if rec is not None and rec.enabled:
            t0 = trace_now()
            self.store.store(sp)
            self._check_completed_level(sp)
            self._check_final_signature(sp)
            t1 = trace_now()
            rec.span(
                "merge",
                t0,
                t1,
                tid=self._tid,
                cat="pipeline",
                args={
                    "origin": sp.origin,
                    "level": sp.level,
                    "rts": int(sp.recv_ts * 1e6),
                    "ind": sp.is_ind,
                    "span": sp.span_id,
                    **self._sargs,
                },
            )
            if sp.span_id:
                # flow finish: the inbound contribution's causal chain ends
                # where it lands in the store (fast-path sends that happened
                # inside this merge already opened their own outbound flows)
                rec.flow("contrib", sp.span_id, "f", t1, tid=self._tid)
            return
        self.store.store(sp)
        self._check_completed_level(sp)
        self._check_final_signature(sp)

    def _on_verify_failed(self, sp: IncomingSig) -> None:
        """A candidate failed its pairing check: penalize the packet origin
        (honest nodes only forward verified content, so a bad signature is
        strong evidence against the sender — core/penalty.py)."""
        if self.scorer is not None and sp.origin >= 0:
            self.scorer.report(sp.origin)

    def _check_final_signature(self, sp: IncomingSig) -> None:
        """Emit a new best full signature above the threshold (handel.go:271-296).

        With stake weights the gate is the accumulated weight against
        `weight_threshold`; the count path is untouched when `weights` is
        None, and all-1.0 weights make both gates open at the same instant.
        """
        card = self.store.full_cardinality()
        if self.weights is not None:
            if self.store.full_weight(self.weights) < self.weight_threshold:
                return
        elif card < self.threshold:
            return
        if self.best is not None and card <= self.best.cardinality():
            return
        if self.done:
            return
        sig = self.store.full_signature()
        if sig is None:
            return
        first = self.best is None
        self.best = sig
        self.log.info(
            "new_sig",
            f"{sig.cardinality()}/{self.threshold}/{self.reg.size()}",
        )
        if first and self.rec is not None:
            # the critical-path walk (sim/trace_cli.py) anchors on the
            # earliest of these across the fleet's node files
            self.rec.instant(
                "threshold_reached",
                tid=self._tid,
                cat="protocol",
                args={
                    "card": sig.cardinality(),
                    "threshold": self.threshold,
                    **self._sargs,
                },
            )
        self.final_signatures.put_nowait(sig)

    def _check_completed_level(self, sp: IncomingSig) -> None:
        """Mark levels receive-complete and fast-path-forward improved combined
        signatures upward (handel.go:301-328)."""
        lvl = self.levels[sp.level] if sp.level in self.levels else None
        if lvl is not None:
            if lvl.rcv_completed:
                return
            self._maybe_complete_level(sp.level, lvl)

        for lid, up in self.levels.items():
            if lid < sp.level + 1:
                continue
            self._fastpath_level(lid, up)

    def _maybe_complete_level(self, lid: int, lvl: Level) -> None:
        """Mark a level receive-complete when the best covers every member
        that can still contribute — with departures the effective size
        shrinks, so a level missing only gone members completes instead of
        waiting forever on signatures that will never come."""
        best = self.store.best(lid)
        if best is None or best.cardinality() < lvl.expected_members():
            return
        self.log.debug("level_complete", lid)
        lvl.rcv_completed = True
        # tail-visible completion latency: since node start, on the
        # mergeable histogram plane (p50/p90/p99 CSV columns)
        self.hist_level_complete.add(time.monotonic() - self.start_time)
        if self.rec is not None:
            self.rec.instant(
                "level_complete",
                tid=self._tid,
                cat="protocol",
                args={"level": lid},
            )
        # windowed stores (core/store.py) free the level's individual
        # sig structures once nothing at this level can improve —
        # memory O(active levels) per identity at swarm scale
        retire = getattr(self.store, "retire_level", None)
        if retire is not None:
            retire(lid)

    def _fastpath_level(self, lid: int, up: Level) -> None:
        # update_sig_to_send rejects anything not strictly better than
        # what this level already propagated; the disjoint-range
        # cardinality sum answers that without paying for the combine
        if self.store.combined_cardinality(lid - 1) <= up.send_sig_size:
            return
        ms = self.store.combined(lid - 1)
        if ms is not None and up.update_sig_to_send(ms):
            self._send_update(up, self.c.fast_path)

    # -- dynamic membership (handel_tpu/scenario/) --------------------------

    def mark_departed(self, node_id: int) -> None:
        """Record that `node_id` left the committee mid-aggregation.

        Re-levels without rebuilding the partitioner: the member's level
        shrinks (peer selection skips it, receive-completion stops waiting
        for it), its future individual sigs are suppressed in the pipeline,
        and the threshold is re-evaluated against what the remaining
        membership can still deliver. Idempotent; contributions the member
        delivered BEFORE leaving keep counting — a signature is a fact.
        """
        if node_id == self.id.id or node_id in self.departed:
            return
        self.departed.add(node_id)
        mark = getattr(self.proc, "mark_departed", None)
        if mark is not None:
            mark(node_id)
        for lid, lvl in self.levels.items():
            lo, hi = self.partitioner.range_level(lid)
            if lo <= node_id < hi:
                lvl.departed.add(node_id)
                if not lvl.rcv_completed:
                    self._maybe_complete_level(lid, lvl)
                    if lvl.rcv_completed:
                        # completing a level can unlock upward fast paths
                        for uid, up in self.levels.items():
                            if uid > lid:
                                self._fastpath_level(uid, up)
                break
        self._recheck_threshold_reachable()

    def _recheck_threshold_reachable(self) -> None:
        """Departure-time threshold re-evaluation: can the REMAINING
        membership still reach the (weighted) threshold? Banked
        contributions from departed members still count; only their
        missing, never-coming contributions are written off."""
        full = self.store.full_signature()
        have = full.bitset if full is not None else None

        def missing(d: int) -> bool:
            return have is None or not have.get(d)

        if self.weights is not None:
            gone = sum(float(self.weights[d]) for d in self.departed if missing(d))
            unreachable = self.total_weight - gone < self.weight_threshold
        else:
            gone_ct = sum(1 for d in self.departed if missing(d))
            unreachable = self.reg.size() - gone_ct < self.threshold
        if unreachable:
            self.threshold_unreachable_ct += 1
            self._warn_once(
                "threshold_unreachable",
                f"{len(self.departed)} departures leave the threshold "
                f"unreachable for the remaining membership",
            )

    # -- outbound path (handel.go:198-225, 343-368) ------------------------

    def start_level(self, level: int) -> None:
        """Timeout-strategy entry: begin sending for a level (handel.go:198-212)."""
        lvl = self.levels.get(level)
        if lvl is None or lvl.send_started:
            return
        lvl.set_started()
        self._send_update(lvl, self.c.update_count)

    def _send_update(self, lvl: Level, count: int) -> None:
        """Send our best combined signature to the next `count` peers of the
        level (handel.go:216-225)."""
        ms = self.store.combined(lvl.id - 1)
        if ms is None:
            return
        peers = lvl.select_next_peers(count)
        # attach our individual sig until the level completes (handel.go:219-223)
        ind = self.sig if not lvl.rcv_completed else None
        self._send_to(lvl.id, peers, ms, ind)

    def _send_to(
        self,
        level: int,
        ids: Sequence[Identity],
        ms: MultiSignature,
        ind: Signature | None,
    ) -> None:
        if not ids:
            return
        self.msg_sent_ct += len(ids)
        rec = self.rec
        tracing = rec is not None and rec.enabled
        if tracing:
            self._span_seq += 1
            sid = (self.id.id << 40) | self._span_seq
            t0 = trace_now()
        else:
            sid = 0
        p = Packet(
            origin=self.id.id,
            level=level,
            multisig=ms.marshal(),
            individual_sig=ind.marshal() if ind is not None else None,
            # always stamped (one clock read per send): a traced RECEIVER
            # can line up cross-node transit spans even when we don't trace
            sent_ts=trace_now(),
            span_id=sid,
            # an aggregate of >1 contributions carries earlier hops
            hop=1 if sid and ms.cardinality() > 1 else 0,
        )
        self.net.send(ids, p)
        if tracing:
            t1 = trace_now()
            rec.span(
                "send",
                t0,
                t1,
                tid=self._tid,
                cat="pipeline",
                args={
                    "level": level,
                    "card": ms.cardinality(),
                    "peers": len(ids),
                    "span": sid,
                    **self._sargs,
                },
            )
            # flow start: receivers' recv/merge steps bind to this span
            rec.flow("contrib", sid, "s", t0, tid=self._tid)

    # -- reporting ---------------------------------------------------------

    def values(self) -> dict[str, float]:
        out = {
            "msgSentCt": float(self.msg_sent_ct),
            "msgRcvCt": float(self.msg_rcv_ct),
            "invalidPacketCt": float(self.invalid_packet_ct),
            "bannedPacketCt": float(self.banned_packet_ct),
            # live aggregation-wave progress (the `sim watch` dashboard
            # renders the fleet's distribution of this): levels fully
            # received out of the level count, plus the best cardinality
            "levelsCompletedCt": float(
                sum(1 for l in self.levels.values() if l.rcv_completed)
            ),
            "bestCardinality": float(
                self.best.cardinality() if self.best is not None else 0
            ),
            # dynamic-membership plane (handel_tpu/scenario/)
            "departedCt": float(len(self.departed)),
            "thresholdUnreachableCt": float(self.threshold_unreachable_ct),
            **self._warn.values(),
            **self.proc.values(),
            **self.store.values(),
            **(self.combine_shim.values() if self.combine_shim else {}),
        }
        if self.scorer is not None:
            out.update(self.scorer.values())
            out["peerBannedSkips"] = float(
                sum(lvl.banned_skips for lvl in self.levels.values())
            )
            out["peerDemoteSkips"] = float(
                sum(lvl.demote_skips for lvl in self.levels.values())
            )
        return out

    def gauge_keys(self) -> set[str]:
        """Explicit gauge declarations for the metrics/monitor planes
        (core/metrics.py is_gauge_key; the suffix heuristic is fallback)."""
        keys = {"bestCardinality"} | self.proc.gauge_keys()
        if self.scorer is not None:
            keys |= self.scorer.gauge_keys()
        return keys

    def histograms(self) -> dict[str, LogHistogram]:
        """Distribution measures for the monitor's histogram plane
        (sim/monitor.py HistogramIO -> `_p50/_p90/_p99` CSV columns)."""
        return {
            "levelCompleteS": self.hist_level_complete,
            **self.proc.histograms(),
        }

"""Handel runtime configuration.

Reference: config.go:12-165 — the `Config` struct with factory-closure
injection points for every pluggable strategy, the defaults
(DefaultContributionsPerc=51, DefaultCandidateCount=10, DefaultUpdatePeriod=10ms,
DefaultUpdateCount=1, config.go:87-97), merge-with-default (:128-165), and
`PercentageToContributions` (:124-126).

Additions for the TPU build: `batch_size` (max signatures per device verify
launch) and `verifier` (an async batch-verify service shared across co-located
logical nodes, see parallel/batch_verifier.py).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.partitioner import BinomialPartitioner

DEFAULT_CONTRIBUTIONS_PERC = 51  # config.go:87
DEFAULT_CANDIDATE_COUNT = 10  # FastPath fanout, config.go:90
DEFAULT_UPDATE_PERIOD = 0.010  # seconds, config.go:93
DEFAULT_UPDATE_COUNT = 1  # config.go:97
DEFAULT_LEVEL_TIMEOUT = 0.050  # seconds, timeout.go:31
DEFAULT_BATCH_SIZE = 16  # TPU verify batch per launch
DEFAULT_MAX_PENDING = 4096  # inbound verification queue bound (flood defense)


def percentage_to_contributions(perc: int, n: int) -> int:
    """Exact contribution count for a percentage threshold (config.go:124-126)."""
    return math.ceil(n * perc / 100.0)


@dataclass
class Config:
    """Runtime knobs + factories for pluggable strategies."""

    # minimum contributions in an output multisignature (config.go:19)
    contributions: int = 0
    # seconds between periodic update gossip rounds (config.go:23)
    update_period: float = DEFAULT_UPDATE_PERIOD
    # peers contacted per periodic update per level (config.go:27)
    update_count: int = DEFAULT_UPDATE_COUNT
    # peers contacted when a level completes — the fast path (config.go:31)
    fast_path: int = DEFAULT_CANDIDATE_COUNT
    # seconds between successive level starts (timeout.go:31)
    level_timeout: float = DEFAULT_LEVEL_TIMEOUT

    new_bitset: Callable[[int], BitSet] = BitSet
    new_partitioner: Callable = BinomialPartitioner
    # (store, handel) -> SigEvaluator; default = the store itself
    new_evaluator: Optional[Callable] = None
    # processing pipeline class (BatchProcessing ctor signature); None =
    # BatchProcessing. FifoProcessing gives the reference's deprecated
    # arrival-order strategy for A/B runs (processing.go:380-493)
    new_processing: Optional[Callable] = None
    # (handel, levels) -> TimeoutStrategy; default = LinearTimeout
    new_timeout: Optional[Callable] = None
    # signature-store class (SignatureStore ctor signature); None =
    # SignatureStore. The swarm runtime passes WindowedSignatureStore so
    # completed levels retire their individual-sig structures and memory
    # stays O(active levels) per identity (core/store.py)
    new_store: Optional[Callable] = None

    logger: Logger = DEFAULT_LOGGER
    # entropy for per-level candidate shuffling (config.go:55)
    rand: random.Random = field(default_factory=random.Random)
    # debugging: keep candidate lists in registry order (config.go:59)
    disable_shuffling: bool = False
    # test knob: replace verification by a sleep of this many ms (config.go:61-65)
    unsafe_sleep_on_verify_ms: int = 0

    # -- byzantine hardening (core/penalty.py) -----------------------------
    # attribute failed verifications / unparseable packets to their origin,
    # demote then ban persistent offenders. None disables peer accounting.
    # (handel, ) -> PeerScorer; the default builds one with the thresholds
    # from core/penalty.py
    new_scorer: Optional[Callable] = None
    penalize_peers: bool = True
    # cap on queued unverified candidates per node; beyond it the OLDEST
    # pending candidate is dropped, so a flooder bounds host memory instead
    # of growing it (core/processing.py)
    max_pending: int = DEFAULT_MAX_PENDING

    # -- observability (core/trace.py) -------------------------------------
    # span flight recorder following every contribution recv -> queue ->
    # verify -> merge; None disables tracing (the hooks cost one None check
    # per contribution). Shared across co-located nodes — each node records
    # under its own id as the Chrome-trace tid.
    recorder: Optional[object] = None

    # -- multi-tenant service (handel_tpu/service/) ------------------------
    # aggregation-session id this node belongs to ("" = the single-tenant
    # default). Scopes the per-instance state — dedup verdict keys, the
    # shared verifier's fairness/admission queues, penalty attribution —
    # so N concurrent sessions sharing one process/device plane never
    # bleed state into each other.
    session: str = ""
    # validator-set epoch this node was spawned under (lifecycle/epoch.py
    # EpochManager). A registry rotation bumps the service-side epoch; the
    # epoch joins every dedup key and trace span so a verdict computed
    # against epoch E's registry is never replayed for epoch E+1's, and a
    # traced run can attribute work to the validator set that served it.
    # 0 = the single-epoch default (pre-lifecycle key shapes unchanged).
    epoch: int = 0

    # -- WAN scenario plane (handel_tpu/scenario/) -------------------------
    # region label this node aggregates from (GeoNetwork planet model). Tags
    # every send/recv/verify/merge trace span beside session/epoch so the
    # critical-path analyzer can attribute WAN hops by region pair.
    # "" = untagged (span args unchanged).
    region: str = ""
    # per-identity stake weights, indexed by identity id (any array-like the
    # bitset's weight_sum can dot against — ArrayRegistry.weights()). None
    # keeps the count-based threshold; all-1.0 weights are bit-for-bit
    # equivalent to counting.
    weights: Optional[object] = None
    # minimum weight sum in an output multisignature; only read when
    # `weights` is set. 0.0 = derive from `contributions` as the same
    # fraction of total weight that `contributions` is of the node count
    # (so a 51% count threshold becomes a 51% stake threshold).
    weight_threshold: float = 0.0

    # -- TPU batch plane ---------------------------------------------------
    # max candidates per device verification launch
    batch_size: int = DEFAULT_BATCH_SIZE
    # shared async batch-verify service (parallel/batch_verifier.py); None
    # means verify through the scheme's own batch_verify
    verifier: Optional[Callable] = None
    # NOTE: the device-mesh width for the verification plane is NOT a
    # runtime Config field — it is fixed at scheme construction
    # (BN254Device(mesh_devices=...), models/bn254_jax.py; the sim TOML's
    # `mesh_devices` knob plumbs it through sim/node.py). Handel itself is
    # mesh-agnostic: it only sees the scheme's batch_verify.


def default_config(num_nodes: int) -> Config:
    """DefaultConfig (config.go:69-83)."""
    c = Config()
    c.contributions = percentage_to_contributions(
        DEFAULT_CONTRIBUTIONS_PERC, num_nodes
    )
    return c


def merge_with_default(c: Config | None, num_nodes: int) -> Config:
    """Fill unset fields from defaults (config.go:128-165)."""
    if c is None:
        return default_config(num_nodes)
    if c.contributions == 0:
        c.contributions = percentage_to_contributions(
            DEFAULT_CONTRIBUTIONS_PERC, num_nodes
        )
    return c

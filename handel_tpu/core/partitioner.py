"""Binomial-tree (San Fermin) candidate-set partitioner.

Reference: partitioner.go:13-296 — `Partitioner` interface, the
common-prefix-length binary search (`rangeLevel`, partitioner.go:133-178 and
`rangeLevelInverse`, :185-211), level-local indexing (:107-119), and signature
combination across levels (`Combine` :224-261, `CombineFull` :263-278).

The algorithm is pure index arithmetic and stays host-side; `Combine*` hand the
actual point additions to `Signature.combine`, which a device scheme implements
as batched G1 adds (SURVEY.md §2.1 partitioner row).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import MultiSignature
from handel_tpu.core.identity import Identity, Registry
from handel_tpu.utils.math import is_set, log2_ceil, pow2


class EmptyLevelError(Exception):
    """A level whose candidate range is empty (non-power-of-two N)."""


class InvalidLevelError(Exception):
    """Requested level outside [0, bitsize+1]."""


@dataclass
class IncomingSig:
    """A parsed signature attributed to a protocol level (processing.go:16-25).

    `mapped_index` is the origin's index inside the level's bitset; only
    meaningful when `is_ind` (an individual signature).
    """

    origin: int
    level: int
    ms: MultiSignature | None
    is_ind: bool = False
    mapped_index: int = 0
    verify_tries: int = 0  # verifier-error retry count (processing requeue)
    # trace stamps (core/trace.py clock): packet arrival and (re)enqueue
    # into the pending queue — the span boundaries of recv/queue/verify
    recv_ts: float = 0.0
    enqueue_ts: float = 0.0
    # sender flow-link id (Packet.span_id): rides through queue/verify/merge
    # span args so the causal chain survives the pending-queue reorder
    span_id: int = 0

    @property
    def individual(self) -> bool:
        return self.is_ind


class BinomialPartitioner:
    """Partition the ID space by common-prefix length with our own ID.

    Mirrors binomialPartitioner (partitioner.go:52-222): level ranges are found
    by a binary search over the bits of `id` from the most significant bit down,
    flipping the half-choice at bit (level-1) to select the *other* subtree.
    """

    def __init__(self, id: int, registry: Registry, logger=None):
        self.id = int(id)
        self.reg = registry
        self.size = registry.size()
        self.bitsize = log2_ceil(self.size)
        self.logger = logger
        # ranges are pure functions of (id, size, level): memoized, because
        # every combine() walks them per level per verified contribution —
        # the binary search was ~15% of a swarm block's CPU before caching
        self._range_cache: dict[int, tuple[int, int]] = {}
        self._range_inv_cache: dict[int, tuple[int, int]] = {}

    def max_level(self) -> int:
        return self.bitsize

    def range_level(self, level: int) -> tuple[int, int]:
        """[min, max) of the candidate set at `level` as seen from self.id.

        partitioner.go:133-178. Raises EmptyLevelError when the subtree falls
        entirely beyond `size` (non-power-of-two registries).
        """
        cached = self._range_cache.get(level)
        if cached is not None:
            return cached
        if level < 0 or level > self.bitsize + 1:
            raise InvalidLevelError(f"level {level} out of range")
        lo, hi = 0, pow2(self.bitsize)
        inverse_idx = level - 1
        idx = self.bitsize - 1
        while idx >= inverse_idx and idx >= 0 and lo < hi:
            middle = (lo + hi) // 2
            if is_set(self.id, idx):
                # invert the half at the common-prefix bit to get the
                # *candidate* set rather than our own subtree
                if idx == inverse_idx:
                    hi = middle
                else:
                    lo = middle
            else:
                if idx == inverse_idx:
                    lo = middle
                else:
                    hi = middle
            idx -= 1
        if lo >= self.size:
            raise EmptyLevelError(f"level {level} empty for id {self.id}")
        out = (lo, min(hi, self.size))
        self._range_cache[level] = out
        return out

    def range_level_inverse(self, level: int) -> tuple[int, int]:
        """[min, max) of *our own* subtree at `level` (partitioner.go:185-211).

        This is the ID range whose contributions a signature *sent to* `level`
        must cover — peers at that level expect everything below `level` from
        our side of the tree.
        """
        cached = self._range_inv_cache.get(level)
        if cached is not None:
            return cached
        if level < 0 or level > self.bitsize + 1:
            raise InvalidLevelError(f"level {level} out of range")
        lo, hi = 0, pow2(self.bitsize)
        max_idx = level - 1
        idx = self.bitsize - 1
        while idx >= max_idx and idx >= 0 and lo < hi:
            middle = (lo + hi) // 2
            if is_set(self.id, idx):
                lo = middle
            else:
                hi = middle
            idx -= 1
        out = (lo, min(hi, self.size))
        self._range_inv_cache[level] = out
        return out

    def size_of(self, level: int) -> int:
        """Number of peers at `level`; 0 for empty levels (partitioner.go:213-222)."""
        try:
            lo, hi = self.range_level(level)
        except EmptyLevelError:
            return 0
        return hi - lo

    def levels(self) -> list[int]:
        """Non-empty level ids, ascending, excluding level 0 (partitioner.go:95-105)."""
        out = []
        for lvl in range(1, self.max_level() + 1):
            try:
                self.range_level(lvl)
            except EmptyLevelError:
                continue
            out.append(lvl)
        return out

    def identities_at(self, level: int) -> Sequence[Identity]:
        """Candidate identities at `level` as an O(1) range view.

        Level ranges are contiguous by construction, so no copy is needed:
        at swarm scale (one Handel per identity, co-resident) materialized
        candidate lists are Σ-over-levels ≈ N references per node — O(N²)
        across the committee — while views keep it O(levels) per node.
        """
        lo, hi = self.range_level(level)
        ids = self.reg.identity_range(lo, hi)
        if not ids and hi > lo:
            raise ValueError("registry can't find ids in range")
        return ids

    def index_at_level(self, global_id: int, level: int) -> int:
        """Map a global node id to its index inside `level`'s bitset
        (partitioner.go:107-119). Raises ValueError for out-of-range ids —
        'either a bug either an attack' (partitioner.go:115)."""
        lo, hi = self.range_level(level)
        if global_id < lo or global_id >= hi:
            raise ValueError(
                f"id {global_id} outside level {level} range [{lo},{hi})"
            )
        return global_id - lo

    # -- combination (partitioner.go:224-296) ------------------------------

    def combine(
        self,
        sigs: Sequence[IncomingSig],
        level: int,
        new_bitset: Callable[[int], BitSet] = BitSet,
        combiner: Callable[[list], object] | None = None,
    ) -> MultiSignature | None:
        """Merge per-level best sigs into one sig sized for sending to `level`.

        The bitset covers range_level_inverse(level) — the ID span peers at
        `level` expect from us; each per-level sig lands at its range offset.
        """
        if not sigs:
            return None
        for s in sigs:
            if s.level > level:
                return None
        try:
            gmin, gmax = self.range_level_inverse(level)
        except InvalidLevelError:
            return None

        def offset_of(s: IncomingSig) -> int:
            lo, _ = self.range_level(s.level)
            return lo - gmin

        return self._combine_into(
            sigs, new_bitset(gmax - gmin), offset_of, combiner
        )

    def combine_full(
        self,
        sigs: Sequence[IncomingSig],
        new_bitset: Callable[[int], BitSet] = BitSet,
        combiner: Callable[[list], object] | None = None,
    ) -> MultiSignature | None:
        """Merge per-level best sigs into a registry-sized multisignature."""
        if not sigs:
            return None

        def offset_of(s: IncomingSig) -> int:
            lo, _ = self.range_level(s.level)
            return lo

        return self._combine_into(sigs, new_bitset(self.size), offset_of, combiner)

    def _combine_into(
        self, sigs, bitset: BitSet, offset_of, combiner=None
    ) -> MultiSignature:
        parts = []
        for s in sigs:
            off = offset_of(s)
            bs = s.ms.bitset
            if hasattr(bitset, "or_embed"):
                # word-level shift-or (+ O(1) run fill for retired AllOnes
                # levels): combined()/full_signature() run per verified
                # contribution, and a per-index embed of a complete level is
                # O(N) Python per event — untenable at swarm scale
                bitset.or_embed(bs, off)
            else:
                for i in bs.indices():
                    bitset.set(off + i, True)
            parts.append(s.ms.signature)
        if not parts:
            final_sig = None
        elif len(parts) == 1 or combiner is None:
            final_sig = parts[0]
            for sig in parts[1:]:
                final_sig = final_sig.combine(sig)
        else:
            # batched: one combiner call (device combine_batch launch)
            # instead of one host point add per level (point addition is
            # commutative; same group element as the serial fold)
            final_sig = combiner(parts)
        return MultiSignature(bitset, final_sig)

"""Fixed-length bitset with the reference wire format and a device-friendly view.

Reference: bitset.go:12-207 — the `BitSet` interface (Set/Get/Cardinality/Len/
Or/And/Xor/IsSuperSet/NextSet/IntersectionCardinality/All/None/Any/Clone) and the
WilffBitSet implementation with its uint16-length-prefixed wire format
(bitset.go:150-177).

TPU-first design: the backing store is a little-endian array of uint64 words
(NumPy), so `mask_bool()` can hand the same bits to device kernels as dense
masks for batched pairing / segment-sum work without a per-bit Python loop
(SURVEY.md §2.1 "packed uint32[] device representation used as pairing-batch
masks").

Wire format (ISSUE 11): the reference's uint16-length dense form caps the
bit-length at 0xFFFE and costs ceil(n/8) bytes regardless of population — a
level-15 update in a 65k committee would ship 4 KiB of mostly-zero bytes and
a registry-sized bitset would not fit the header at all. The length value
0xFFFF is reclaimed as an ESCAPE marker introducing an extended header
(mode byte + uint32 bit-length) with two payload modes: dense bytes, or a
varint-delta index list (run-length/index form) chosen whenever it is the
smaller encoding. Legacy decoders never saw 0xFFFF on the wire (the old
marshal refused n > 0xFFFF), so the escape is backward-compatible; decode
caps the declared bit-length so a hostile header cannot allocate gigabytes.
"""

from __future__ import annotations

import struct

import numpy as np

# extended-header caps: enough for >1M-identity registries while bounding a
# hostile header's allocation to 512 KiB of words (memory-bomb defense)
MAX_WIRE_BITS = 1 << 22
_ESCAPE = 0xFFFF
_MODE_DENSE = 0
_MODE_SPARSE = 1
_WORD_ALL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _varint(value: int) -> bytes:
    """Unsigned LEB128."""
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    """(value, next position); ValueError on truncation/oversize."""
    value = shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("bitset varint truncated")
        b = data[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
        if shift > 28:  # 5 bytes bound any index < MAX_WIRE_BITS
            raise ValueError("bitset varint overlong")


class BitSet:
    """Fixed-length mutable bitset.

    Unlike the reference's interface/impl split (bitset.go:12-54 vs 56-148) there
    is a single concrete class; it is cheap, NumPy-backed, and already in the
    layout device code wants.
    """

    __slots__ = ("_n", "_words", "_card")

    def __init__(self, length: int, _words: np.ndarray | None = None):
        if length < 0:
            raise ValueError("bitset length must be >= 0")
        self._n = length
        nwords = (length + 63) // 64
        if _words is not None:
            assert _words.shape == (nwords,) and _words.dtype == np.uint64
            self._words = _words
        else:
            self._words = np.zeros(nwords, dtype=np.uint64)
        # popcount cache: the store's evaluate/merge plane reads cardinality
        # many times between mutations, and the numpy reduction dominated
        # swarm profiles before caching. Mutators invalidate.
        self._card: int | None = None

    # -- basic ops ---------------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def set(self, idx: int, value: bool = True) -> None:
        if not 0 <= idx < self._n:
            raise IndexError(f"bit {idx} out of range [0,{self._n})")
        w, b = divmod(idx, 64)
        if value:
            self._words[w] |= np.uint64(1 << b)
        else:
            self._words[w] &= np.uint64(~(1 << b) & 0xFFFFFFFFFFFFFFFF)
        self._card = None

    def get(self, idx: int) -> bool:
        if not 0 <= idx < self._n:
            raise IndexError(f"bit {idx} out of range [0,{self._n})")
        w, b = divmod(idx, 64)
        return bool((int(self._words[w]) >> b) & 1)

    def cardinality(self) -> int:
        if self._card is None:
            self._card = int(np.bitwise_count(self._words).sum())
        return self._card

    def clone(self) -> "BitSet":
        return BitSet(self._n, self._words.copy())

    # -- set algebra (reference bitset.go:93-148) --------------------------

    def _check_same(self, other: "BitSet") -> None:
        if self._n != len(other):
            raise ValueError(f"bitset length mismatch: {self._n} vs {len(other)}")

    def or_(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_or(self._words, other._words))

    def and_(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_and(self._words, other._words))

    def xor(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_xor(self._words, other._words))

    def is_superset(self, other: "BitSet") -> bool:
        self._check_same(other)
        return bool(
            np.all(np.bitwise_and(self._words, other._words) == other._words)
        )

    def intersection_cardinality(self, other: "BitSet") -> int:
        self._check_same(other)
        return int(
            np.bitwise_count(np.bitwise_and(self._words, other._words)).sum()
        )

    def all(self) -> bool:
        return self.cardinality() == self._n

    def none(self) -> bool:
        return not self._words.any()

    def any(self) -> bool:
        return bool(self._words.any())

    def next_set(self, start: int = 0) -> int | None:
        """Index of the first set bit >= start, or None (bitset.go:131-139)."""
        for i in range(start, self._n):
            if self.get(i):
                return i
        return None

    def indices(self) -> list[int]:
        """All set-bit indices, ascending."""
        if self._n == 0:
            return []
        bits = np.unpackbits(
            self._words.view(np.uint8), bitorder="little"
        )[: self._n]
        return np.nonzero(bits)[0].tolist()

    def weight_sum(self, weights) -> float:
        """Sum of `weights[i]` over set bits — the stake-weighted sibling of
        `cardinality()`. One unpackbits + dot, no per-bit Python: the
        weighted threshold check runs on every verified contribution, the
        same hot path popcount sits on. `weights` is any array-like of
        length >= n; with all-1.0 weights this equals `cardinality()`
        exactly (float sums of 1.0 are exact well past any registry size).
        """
        if self._n == 0:
            return 0.0
        bits = np.unpackbits(
            self._words.view(np.uint8), bitorder="little"
        )[: self._n]
        w = np.asarray(weights, dtype=np.float64)
        return float(bits.astype(np.float64) @ w[: self._n])

    # -- device views ------------------------------------------------------

    def words(self) -> np.ndarray:
        """The packed little-endian uint64 word array backing this bitset.

        A VIEW, not a copy — callers must treat it as read-only. This is the
        zero-copy handoff the vectorized launch packer consumes: a batch of
        bitsets stacks to a (C, W) uint64 matrix and one `np.unpackbits`
        yields every candidate's dense mask without per-bit Python
        (models/bn254_jax.py `_pack_requests`). Also the cheap identity for
        dedup keys: `words().tobytes()` hashes the exact bit content."""
        return self._words

    def mask_bool(self, length: int | None = None) -> np.ndarray:
        """Dense bool mask (optionally zero-padded to `length`) for device kernels."""
        n = self._n if length is None else length
        bits = np.unpackbits(self._words.view(np.uint8), bitorder="little")
        out = np.zeros(n, dtype=bool)
        m = min(self._n, n)
        out[:m] = bits[:m]
        return out

    # -- bulk word-level mutation (swarm combine hot path) -----------------

    def set_range(self, lo: int, hi: int) -> None:
        """Set bits [lo, hi) true with word fills, not a per-bit loop."""
        if lo < 0 or hi > self._n or lo > hi:
            raise IndexError(f"range [{lo},{hi}) out of [0,{self._n})")
        if lo == hi:
            return
        self._card = None
        w0, b0 = divmod(lo, 64)
        w1, b1 = divmod(hi - 1, 64)
        if w0 == w1:
            self._words[w0] |= np.uint64(
                ((1 << (hi - lo)) - 1) << b0 & 0xFFFFFFFFFFFFFFFF
            )
            return
        self._words[w0] |= np.uint64((~((1 << b0) - 1)) & 0xFFFFFFFFFFFFFFFF)
        self._words[w0 + 1 : w1] = _WORD_ALL
        self._words[w1] |= np.uint64(((1 << (b1 + 1)) - 1) & 0xFFFFFFFFFFFFFFFF)

    def or_embed(self, other, offset: int) -> None:
        """self |= other << offset — the store's cross-level merge primitive.

        One arbitrary-precision-int shift-or instead of a Python loop over
        set indices: at swarm scale `combined()`/`full_signature()` run on
        every verified contribution, and per-index embedding of a 32k-bit
        complete level is exactly the O(N)-per-event cost the virtual-node
        runtime cannot afford.
        """
        olen = len(other)
        if offset < 0 or offset + olen > self._n:
            raise IndexError(
                f"embed [{offset},{offset + olen}) out of [0,{self._n})"
            )
        if isinstance(other, AllOnesBitSet):
            self.set_range(offset, offset + olen)
            return
        if olen == 0:
            return
        ov = int.from_bytes(other._words.tobytes(), "little")
        if not ov:
            return
        sv = int.from_bytes(self._words.tobytes(), "little") | (ov << offset)
        self._words = np.frombuffer(
            sv.to_bytes(self._words.size * 8, "little"), dtype=np.uint64
        ).copy()
        self._card = None

    # -- wire format (reference bitset.go:150-177 + 0xFFFF escape) ---------

    def marshal(self) -> bytes:
        """Smallest of: legacy dense (uint16 length || LE-bit bytes, n <
        0xFFFF), extended dense, extended sparse (varint-delta indices)."""
        if self._n > MAX_WIRE_BITS:
            raise ValueError("bitset too large for wire format")
        nbytes = (self._n + 7) // 8
        dense_total = (2 if self._n < _ESCAPE else 7) + nbytes
        card = self.cardinality()
        sparse = None
        # only pay the O(population) index walk when sparse can win: every
        # index costs >= 1 payload byte after the 7+ byte extended header
        if card + 8 < dense_total:
            payload = bytearray(_varint(card))
            prev = -1
            for i in self.indices():
                payload += _varint(i - prev - 1)  # gap to the previous bit
                prev = i
            if 7 + len(payload) < dense_total:
                sparse = bytes(payload)
        if sparse is not None:
            return (
                struct.pack(">HBI", _ESCAPE, _MODE_SPARSE, self._n) + sparse
            )
        payload = self._words.view(np.uint8).tobytes()[:nbytes]
        if self._n < _ESCAPE:
            return struct.pack(">H", self._n) + payload
        return struct.pack(">HBI", _ESCAPE, _MODE_DENSE, self._n) + payload

    @classmethod
    def unmarshal(cls, data: bytes) -> tuple["BitSet", int]:
        """Parse a marshaled bitset; returns (bitset, bytes consumed)."""
        if len(data) < 2:
            raise ValueError("bitset wire data too short")
        (n,) = struct.unpack(">H", data[:2])
        if n == _ESCAPE:
            return cls._unmarshal_extended(data)
        nbytes = (n + 7) // 8
        if len(data) < 2 + nbytes:
            raise ValueError("bitset wire data truncated")
        bs = cls(n)
        bs._fill_dense(data[2 : 2 + nbytes])
        return bs, 2 + nbytes

    @classmethod
    def _unmarshal_extended(cls, data: bytes) -> tuple["BitSet", int]:
        if len(data) < 7:
            raise ValueError("extended bitset header truncated")
        _, mode, n = struct.unpack(">HBI", data[:7])
        if n > MAX_WIRE_BITS:
            raise ValueError(f"bitset length {n} beyond wire cap")
        if mode == _MODE_DENSE:
            nbytes = (n + 7) // 8
            if len(data) < 7 + nbytes:
                raise ValueError("bitset wire data truncated")
            bs = cls(n)
            bs._fill_dense(data[7 : 7 + nbytes])
            return bs, 7 + nbytes
        if mode == _MODE_SPARSE:
            card, pos = _read_varint(data, 7)
            if card > n:
                raise ValueError("sparse bitset population beyond length")
            bs = cls(n)
            idx = -1
            for _ in range(card):
                gap, pos = _read_varint(data, pos)
                idx += gap + 1
                if idx >= n:
                    raise ValueError("sparse bitset index beyond length")
                bs._words[idx >> 6] |= np.uint64(1 << (idx & 63))
            return bs, pos
        raise ValueError(f"unknown bitset wire mode {mode}")

    def _fill_dense(self, raw_bytes: bytes) -> None:
        raw = np.frombuffer(raw_bytes, dtype=np.uint8)
        padded = np.zeros(self._words.size * 8, dtype=np.uint8)
        padded[: len(raw)] = raw
        self._words = padded.view(np.uint64).copy()
        self._card = None
        # zero any bits beyond n that a malicious peer may have set
        extra = self._words.size * 64 - self._n
        if extra and self._words.size:
            keep = (
                np.uint64((1 << (64 - extra)) - 1) if extra < 64 else np.uint64(0)
            )
            self._words[-1] &= keep

    def __repr__(self) -> str:
        return f"BitSet({self._n}, set={self.cardinality()})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitSet)
            and self._n == other._n
            and bool(np.all(self._words == other._words))
        )


class AllOnesBitSet:
    """Immutable all-set bitset in O(1) memory (a single roaring-style run).

    The windowed store (core/store.py) swaps a completed level's dense best
    bitset for this when the level retires: a complete level's bitset is by
    definition the full [0, n) run, and keeping N/8 dense bytes per level
    per identity is the O(N)-per-identity memory the swarm runtime removes.
    Supports exactly the read surface the store/partitioner/evaluator use on
    a retired best: length, cardinality, membership, indices, superset
    algebra, and (rarely) a dense materialization for the wire.
    """

    __slots__ = ("_n",)

    def __init__(self, length: int):
        if length < 0:
            raise ValueError("bitset length must be >= 0")
        self._n = length

    def __len__(self) -> int:
        return self._n

    def cardinality(self) -> int:
        return self._n

    def get(self, idx: int) -> bool:
        if not 0 <= idx < self._n:
            raise IndexError(f"bit {idx} out of range [0,{self._n})")
        return True

    def all(self) -> bool:
        return True

    def none(self) -> bool:
        return self._n == 0

    def any(self) -> bool:
        return self._n > 0

    def next_set(self, start: int = 0) -> int | None:
        return start if start < self._n else None

    def indices(self) -> range:
        return range(self._n)

    def weight_sum(self, weights) -> float:
        """Every bit is set, so the weighted cardinality is the plain sum —
        O(n) numpy reduction, no unpack."""
        if self._n == 0:
            return 0.0
        return float(
            np.asarray(weights, dtype=np.float64)[: self._n].sum()
        )

    def clone(self) -> "AllOnesBitSet":
        return self  # immutable

    def is_superset(self, other) -> bool:
        if self._n != len(other):
            raise ValueError(
                f"bitset length mismatch: {self._n} vs {len(other)}"
            )
        return True

    def intersection_cardinality(self, other) -> int:
        if self._n != len(other):
            raise ValueError(
                f"bitset length mismatch: {self._n} vs {len(other)}"
            )
        return other.cardinality()

    def to_dense(self) -> BitSet:
        bs = BitSet(self._n)
        bs.set_range(0, self._n)
        return bs

    def marshal(self) -> bytes:
        return self.to_dense().marshal()

    def __repr__(self) -> str:
        return f"AllOnesBitSet({self._n})"

"""Fixed-length bitset with the reference wire format and a device-friendly view.

Reference: bitset.go:12-207 — the `BitSet` interface (Set/Get/Cardinality/Len/
Or/And/Xor/IsSuperSet/NextSet/IntersectionCardinality/All/None/Any/Clone) and the
WilffBitSet implementation with its uint16-length-prefixed wire format
(bitset.go:150-177).

TPU-first design: the backing store is a little-endian array of uint64 words
(NumPy), so `mask_bool()` can hand the same bits to device kernels as dense
masks for batched pairing / segment-sum work without a per-bit Python loop
(SURVEY.md §2.1 "packed uint32[] device representation used as pairing-batch
masks").
"""

from __future__ import annotations

import struct

import numpy as np


class BitSet:
    """Fixed-length mutable bitset.

    Unlike the reference's interface/impl split (bitset.go:12-54 vs 56-148) there
    is a single concrete class; it is cheap, NumPy-backed, and already in the
    layout device code wants.
    """

    __slots__ = ("_n", "_words")

    def __init__(self, length: int, _words: np.ndarray | None = None):
        if length < 0:
            raise ValueError("bitset length must be >= 0")
        self._n = length
        nwords = (length + 63) // 64
        if _words is not None:
            assert _words.shape == (nwords,) and _words.dtype == np.uint64
            self._words = _words
        else:
            self._words = np.zeros(nwords, dtype=np.uint64)

    # -- basic ops ---------------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def set(self, idx: int, value: bool = True) -> None:
        if not 0 <= idx < self._n:
            raise IndexError(f"bit {idx} out of range [0,{self._n})")
        w, b = divmod(idx, 64)
        if value:
            self._words[w] |= np.uint64(1 << b)
        else:
            self._words[w] &= np.uint64(~(1 << b) & 0xFFFFFFFFFFFFFFFF)

    def get(self, idx: int) -> bool:
        if not 0 <= idx < self._n:
            raise IndexError(f"bit {idx} out of range [0,{self._n})")
        w, b = divmod(idx, 64)
        return bool((int(self._words[w]) >> b) & 1)

    def cardinality(self) -> int:
        return int(np.bitwise_count(self._words).sum())

    def clone(self) -> "BitSet":
        return BitSet(self._n, self._words.copy())

    # -- set algebra (reference bitset.go:93-148) --------------------------

    def _check_same(self, other: "BitSet") -> None:
        if self._n != len(other):
            raise ValueError(f"bitset length mismatch: {self._n} vs {len(other)}")

    def or_(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_or(self._words, other._words))

    def and_(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_and(self._words, other._words))

    def xor(self, other: "BitSet") -> "BitSet":
        self._check_same(other)
        return BitSet(self._n, np.bitwise_xor(self._words, other._words))

    def is_superset(self, other: "BitSet") -> bool:
        self._check_same(other)
        return bool(
            np.all(np.bitwise_and(self._words, other._words) == other._words)
        )

    def intersection_cardinality(self, other: "BitSet") -> int:
        self._check_same(other)
        return int(
            np.bitwise_count(np.bitwise_and(self._words, other._words)).sum()
        )

    def all(self) -> bool:
        return self.cardinality() == self._n

    def none(self) -> bool:
        return not self._words.any()

    def any(self) -> bool:
        return bool(self._words.any())

    def next_set(self, start: int = 0) -> int | None:
        """Index of the first set bit >= start, or None (bitset.go:131-139)."""
        for i in range(start, self._n):
            if self.get(i):
                return i
        return None

    def indices(self) -> list[int]:
        """All set-bit indices, ascending."""
        if self._n == 0:
            return []
        bits = np.unpackbits(
            self._words.view(np.uint8), bitorder="little"
        )[: self._n]
        return np.nonzero(bits)[0].tolist()

    # -- device views ------------------------------------------------------

    def words(self) -> np.ndarray:
        """The packed little-endian uint64 word array backing this bitset.

        A VIEW, not a copy — callers must treat it as read-only. This is the
        zero-copy handoff the vectorized launch packer consumes: a batch of
        bitsets stacks to a (C, W) uint64 matrix and one `np.unpackbits`
        yields every candidate's dense mask without per-bit Python
        (models/bn254_jax.py `_pack_requests`). Also the cheap identity for
        dedup keys: `words().tobytes()` hashes the exact bit content."""
        return self._words

    def mask_bool(self, length: int | None = None) -> np.ndarray:
        """Dense bool mask (optionally zero-padded to `length`) for device kernels."""
        n = self._n if length is None else length
        bits = np.unpackbits(self._words.view(np.uint8), bitorder="little")
        out = np.zeros(n, dtype=bool)
        m = min(self._n, n)
        out[:m] = bits[:m]
        return out

    # -- wire format (reference bitset.go:150-177) -------------------------

    def marshal(self) -> bytes:
        """uint16 big-endian bit-length || minimal little-endian-bit bytes."""
        if self._n > 0xFFFF:
            raise ValueError("bitset too large for wire format")
        nbytes = (self._n + 7) // 8
        payload = self._words.view(np.uint8).tobytes()[:nbytes]
        return struct.pack(">H", self._n) + payload

    @classmethod
    def unmarshal(cls, data: bytes) -> tuple["BitSet", int]:
        """Parse a marshaled bitset; returns (bitset, bytes consumed)."""
        if len(data) < 2:
            raise ValueError("bitset wire data too short")
        (n,) = struct.unpack(">H", data[:2])
        nbytes = (n + 7) // 8
        if len(data) < 2 + nbytes:
            raise ValueError("bitset wire data truncated")
        bs = cls(n)
        raw = np.frombuffer(data[2 : 2 + nbytes], dtype=np.uint8)
        padded = np.zeros(((n + 63) // 64) * 8, dtype=np.uint8)
        padded[: len(raw)] = raw
        bs._words = padded.view(np.uint64).copy()
        # zero any bits beyond n that a malicious peer may have set
        extra = bs._words.size * 64 - n
        if extra and bs._words.size:
            keep = np.uint64((1 << (64 - extra)) - 1) if extra < 64 else np.uint64(0)
            bs._words[-1] &= keep
        return bs, 2 + nbytes

    def __repr__(self) -> str:
        return f"BitSet({self._n}, set={self.cardinality()})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitSet)
            and self._n == other._n
            and bool(np.all(self._words == other._words))
        )

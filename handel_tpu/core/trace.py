"""Span flight recorder + log-bucket latency histograms (ISSUE 4 tentpole).

The paper's claims are distributional (logarithmic completion time across
large committees), yet min/max/avg/sum/dev aggregation hides exactly the
tail the claims are about. Two primitives fix that:

- `FlightRecorder`: a bounded in-memory ring of span events following every
  contribution through `recv -> queue -> verify -> merge` (plus the shared
  verifier's dispatch/device stages), exported as Chrome `trace_event` JSON
  loadable in `chrome://tracing` / Perfetto. Disabled, a span call is one
  attribute check — well under the 1 us/contribution budget — so the hooks
  stay compiled into the hot path permanently.

- `LogHistogram`: fixed log-spaced buckets (identical boundaries everywhere,
  so per-node histograms merge master-side by summing counts) feeding the
  `_p50/_p90/_p99` CSV columns next to the existing stats (sim/monitor.py).

The trace clock is `time.time()` (epoch seconds): processes on one host
share it, so cross-node spans line up in one timeline — `Packet.sent_ts`
(core/net.py) carries it across the wire for network-transit spans.

Causal links (ISSUE 10): packets also carry an 8-byte span id, and the
recorder emits Chrome flow events (`ph: "s"/"t"/"f"`, shared `id`) binding a
sender's `send` span to the receiver's `recv -> queue -> verify -> merge`
chain — cross-process causality is recorded, not guessed. Multi-host runs
additionally carry a per-process `clock_offset` (estimated over the sync
barrier handshake, sim/sync.py) in the export; `merge_traces` applies it so
node timelines align within the handshake's RTT bound.
"""

from __future__ import annotations

import json
import math
import time
from typing import Iterable, Mapping

#: epoch-seconds trace clock shared by every process on a host
trace_now = time.time

#: Chrome-trace thread id for process-scoped (non-node) actors like the
#: shared batch-verifier service
SERVICE_TID = -1


class FlightRecorder:
    """Bounded ring of trace events; ~zero cost when disabled.

    Events are stored as tuples and only materialized into Chrome
    `trace_event` dicts at export, so recording is an index store. When the
    ring wraps, the oldest events are overwritten (`dropped` counts them) —
    a run that outlives the ring keeps its most recent window, which is the
    one a stall diagnosis needs.
    """

    __slots__ = (
        "enabled",
        "capacity",
        "pid",
        "dropped",
        "clock_offset",
        "_buf",
        "_pos",
        "_count",
        "_pushed",
        "_t0",
        "_names",
    )

    def __init__(self, capacity: int = 1 << 16, enabled: bool = True, pid: int = 0):
        self.enabled = enabled
        self.capacity = max(1, capacity)
        self.pid = pid
        self.dropped = 0
        # seconds to ADD to this process's timestamps to land on the sync
        # master's clock (sim/sync.py offset estimation); applied at merge
        self.clock_offset = 0.0
        self._buf: list = [None] * self.capacity
        self._pos = 0
        self._count = 0
        self._pushed = 0  # lifetime events (span-emit rate denominator)
        self._t0 = trace_now()
        self._names: dict[int, str] = {}  # tid -> thread name metadata

    # -- recording (the hot path) -------------------------------------------

    def span(
        self,
        name: str,
        start: float,
        end: float,
        tid: int = 0,
        cat: str = "",
        args: dict | None = None,
    ) -> None:
        """Complete event ("X"): [start, end] in trace-clock seconds."""
        if not self.enabled:
            return
        self._push((name, "X", start, end - start, tid, cat, args, 0))

    def instant(
        self,
        name: str,
        ts: float | None = None,
        tid: int = 0,
        cat: str = "",
        args: dict | None = None,
    ) -> None:
        if not self.enabled:
            return
        self._push((
            name, "i", ts if ts is not None else trace_now(), 0.0, tid, cat,
            args, 0,
        ))

    def flow(
        self,
        name: str,
        fid: int,
        ph: str,
        ts: float,
        tid: int = 0,
        cat: str = "flow",
    ) -> None:
        """Flow event (`ph` in "s"/"t"/"f") carrying the causal link id
        `fid` — the packet span id (core/net.py). A flow start on the
        sender's `send` span and a step/finish on the receiver's pipeline
        spans draw one contribution's cross-process arrow in Perfetto, and
        the critical-path analyzer (sim/trace_cli.py) walks the same ids."""
        if not self.enabled:
            return
        self._push((name, ph, ts, 0.0, tid, cat, None, fid))

    def _push(self, ev: tuple) -> None:
        self._pushed += 1
        if self._count >= self.capacity:
            self.dropped += 1
        else:
            self._count += 1
        self._buf[self._pos] = ev
        self._pos = (self._pos + 1) % self.capacity

    # -- metadata / export --------------------------------------------------

    def name_thread(self, tid: int, name: str) -> None:
        self._names[tid] = name

    def events(self) -> list[tuple]:
        """Recorded events, oldest first."""
        if self._count < self.capacity:
            return [e for e in self._buf[: self._count]]
        return self._buf[self._pos :] + self._buf[: self._pos]

    def export(self) -> dict:
        """Chrome `trace_event` JSON-object format (ts/dur in microseconds)."""
        out = []
        for tid, name in sorted(self._names.items()):
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.pid,
                    "tid": tid,
                    "args": {"name": name},
                }
            )
        for name, ph, ts, dur, tid, cat, args, fid in self.events():
            ev = {
                "name": name,
                "ph": ph,
                "ts": ts * 1e6,
                "pid": self.pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = max(0.0, dur) * 1e6
            elif ph in ("s", "t", "f"):
                ev["id"] = fid
                if ph != "s":
                    # bind to the enclosing slice, not the next one
                    ev["bp"] = "e"
            if cat:
                ev["cat"] = cat
            if args:
                ev["args"] = args
            out.append(ev)
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            # per-process clock alignment, applied by merge_traces
            "clockOffset": self.clock_offset,
        }

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.export(), f)
        return path

    def values(self) -> dict[str, float]:
        """Reporter-plane counters (core/report.py shape): ring occupancy,
        silent-truncation count, and the live span-emit rate — the
        `/metrics` + `sim watch` surface that makes a wrapped ring visible
        while the run is still going."""
        dt = trace_now() - self._t0
        return {
            "traceEvents": float(self._count),
            "traceDropped": float(self.dropped),
            "traceSpanRate": self._pushed / dt if dt > 0 else 0.0,
        }

    def gauge_keys(self) -> set[str]:
        """Explicit gauge declaration (core/metrics.py is_gauge_key)."""
        return {"traceSpanRate"}


class LogHistogram:
    """Fixed log-bucket histogram with mergeable, node-independent buckets.

    Bucket i covers [BASE * GROWTH^i, BASE * GROWTH^(i+1)); GROWTH = 2^0.25
    gives <= 19% relative quantile error, and 120 buckets span 1 us to
    ~18 min — the whole latency range a run can produce. Because boundaries
    are fixed (not data-dependent), per-node histograms serialize as sparse
    {bucket: count} maps through the UDP sink and merge master-side by
    summing counts (sim/monitor.py), which exact-sample designs cannot do
    in bounded space.
    """

    BASE = 1e-6
    GROWTH = 2.0 ** 0.25
    NBUCKETS = 120
    _LOG2_GROWTH = 0.25  # log2(GROWTH)

    __slots__ = ("counts", "count", "sum", "lo", "hi")

    def __init__(self):
        self.counts = [0] * self.NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.lo = math.inf
        self.hi = -math.inf

    def add(self, v: float) -> None:
        self.counts[self._index(v)] += 1
        self.count += 1
        self.sum += v
        if v < self.lo:
            self.lo = v
        if v > self.hi:
            self.hi = v

    @classmethod
    def _index(cls, v: float) -> int:
        if v <= cls.BASE:
            return 0
        i = int(math.log2(v / cls.BASE) / cls._LOG2_GROWTH)
        return min(i, cls.NBUCKETS - 1)

    @classmethod
    def bucket_bounds(cls, i: int) -> tuple[float, float]:
        lo = cls.BASE * cls.GROWTH**i
        return lo, lo * cls.GROWTH

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile at the geometric midpoint of its bucket,
        clamped to the observed [lo, hi] for sub-bucket fidelity."""
        if self.count == 0:
            return float("nan")
        target = max(1, math.ceil(q * self.count))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                blo, bhi = self.bucket_bounds(i)
                mid = math.sqrt(blo * bhi)
                return min(max(mid, self.lo), self.hi)
        return self.hi  # unreachable while count is consistent

    # -- wire form (sim/monitor.py sink payloads) ---------------------------

    def to_sparse(self) -> dict:
        return {
            "b": {str(i): c for i, c in enumerate(self.counts) if c},
            "sum": self.sum,
            "lo": self.lo if self.count else 0.0,
            "hi": self.hi if self.count else 0.0,
        }

    def merge_sparse(self, payload: Mapping) -> None:
        """Merge one sink datagram's partial histogram. Bucket counts add;
        lo/hi merge idempotently (every chunk of a split histogram repeats
        them); `sum` adds (a chunked send carries it on one chunk only)."""
        added = 0
        for k, c in dict(payload.get("b", {})).items():
            i = int(k)
            if 0 <= i < self.NBUCKETS:
                c = int(c)
                self.counts[i] += c
                added += c
        self.count += added
        self.sum += float(payload.get("sum", 0.0))
        if added:
            self.lo = min(self.lo, float(payload.get("lo", math.inf)))
            self.hi = max(self.hi, float(payload.get("hi", -math.inf)))

    def merge(self, other: "LogHistogram") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.lo = min(self.lo, other.lo)
        self.hi = max(self.hi, other.hi)

    @classmethod
    def from_sparse(cls, payload: Mapping) -> "LogHistogram":
        """Rebuild from one COMPLETE sparse wire form (all buckets + the
        sum present). The roll-up plane ships absolute sparse maps, so
        `from_sparse(h.to_sparse())` round-trips exactly."""
        h = cls()
        h.merge_sparse(payload)
        return h

    def copy(self) -> "LogHistogram":
        h = type(self)()
        h.merge(self)
        return h


def merge_traces(exports: Iterable[Mapping]) -> dict:
    """Combine per-process Chrome trace exports into one timeline.

    Each export's estimated `clockOffset` (seconds, sim/sync.py handshake)
    is applied here — shifting every event onto the sync master's clock —
    so multi-host timelines align within the handshake's RTT bound instead
    of drifting by whatever NTP left behind."""
    events: list = []
    for ex in exports:
        off_us = float(ex.get("clockOffset", 0.0) or 0.0) * 1e6
        for e in ex.get("traceEvents", []):
            if off_us and e.get("ph") != "M":
                e = {**e, "ts": e.get("ts", 0.0) + off_us}
            events.append(e)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}

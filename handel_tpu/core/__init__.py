"""Core aggregation runtime: interfaces + Handel state machine (reference L1+L3)."""

"""Structured key-value logger (reference: log.go:13-78).

A thin adapter over stdlib logging that mirrors the reference's leveled KV
interface (`Debug/Info/Warn/Error` with alternating key/value args and a
`with_fields` context, log.go:13-21).
"""

from __future__ import annotations

import logging


def _fmt(args) -> str:
    if not args:
        return ""
    if len(args) == 1:
        return str(args[0])
    pairs = []
    it = iter(args)
    for k in it:
        v = next(it, "")
        pairs.append(f"{k}={v}")
    return " ".join(pairs)


class Logger:
    """Leveled KV logger with bound context fields."""

    def __init__(self, name: str = "handel", fields: dict | None = None):
        self._log = logging.getLogger(name)
        self._fields = fields or {}

    def with_fields(self, **fields) -> "Logger":
        merged = {**self._fields, **fields}
        return Logger(self._log.name, merged)

    def _prefix(self) -> str:
        if not self._fields:
            return ""
        return " ".join(f"{k}={v}" for k, v in self._fields.items()) + " "

    def debug(self, *args):
        self._log.debug("%s%s", self._prefix(), _fmt(args))

    def info(self, *args):
        self._log.info("%s%s", self._prefix(), _fmt(args))

    def warn(self, *args):
        self._log.warning("%s%s", self._prefix(), _fmt(args))

    def error(self, *args):
        self._log.error("%s%s", self._prefix(), _fmt(args))


DEFAULT_LOGGER = Logger()

"""Level-start timeout strategies.

Reference: timeout.go:11-88 — `TimeoutStrategy` (Start/Stop) and the linear
strategy that starts level i at time i*period (default 50 ms).
"""

from __future__ import annotations

import asyncio
from typing import Sequence


class LinearTimeout:
    """Starts level i at time i*period (timeout.go:18-88), as an asyncio task."""

    def __init__(self, handel, levels: Sequence[int], period: float):
        self.handel = handel
        self.levels = list(levels)
        self.period = period
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        for lvl in self.levels:
            self.handel.start_level(lvl)
            await asyncio.sleep(self.period)


class InfiniteTimeout:
    """Never starts a level by timeout — only fast-path completion advances.

    Test strategy trick from the reference (handel_test.go:442-455): with no
    failing nodes, any stall becomes a real bug instead of being masked by
    timeouts.
    """

    def __init__(self, handel=None, levels: Sequence[int] = ()):
        pass

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

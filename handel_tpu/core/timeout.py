"""Level-start timeout strategies + the shared cooperative timer wheel.

Reference: timeout.go:11-88 — `TimeoutStrategy` (Start/Stop) and the linear
strategy that starts level i at time i*period (default 50 ms).

Swarm addition (ISSUE 11): `LinearTimeout` plus the per-node periodic
updater is 2+ asyncio tasks per Handel instance — 130k+ tasks for a 65,536
virtual-node committee, each with its own heap entry churn in the loop. The
`TimerWheel` replaces them with ONE task ticking a hashed wheel of
callbacks; every virtual node holds at most one outstanding one-shot handle
(its next level start) plus one periodic handle (its gossip round), so the
scheduler state is O(nodes), not O(tasks), and the loop stays responsive.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Sequence


class LinearTimeout:
    """Starts level i at time i*period (timeout.go:18-88), as an asyncio task."""

    def __init__(self, handel, levels: Sequence[int], period: float):
        self.handel = handel
        self.levels = list(levels)
        self.period = period
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        for lvl in self.levels:
            self.handel.start_level(lvl)
            await asyncio.sleep(self.period)


class InfiniteTimeout:
    """Never starts a level by timeout — only fast-path completion advances.

    Test strategy trick from the reference (handel_test.go:442-455): with no
    failing nodes, any stall becomes a real bug instead of being masked by
    timeouts.
    """

    def __init__(self, handel=None, levels: Sequence[int] = ()):
        pass

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class WheelHandle:
    """One scheduled callback; `cancel()` is O(1) (the wheel skips it)."""

    __slots__ = ("cb", "period_ticks", "cancelled")

    def __init__(self, cb: Callable[[], None], period_ticks: int = 0):
        self.cb = cb
        self.period_ticks = period_ticks  # 0 = one-shot
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class TimerWheel:
    """One asyncio task driving many cooperative timers (module docstring).

    Resolution is `tick_s`; callbacks land on their due tick's bucket and
    run inline on the wheel task. Long buckets yield to the loop every
    `YIELD_EVERY` callbacks so a 65k-node periodic burst never starves
    packet delivery for a whole bucket. Callbacks must not raise — an
    exception is counted (`wheelCbErrors`) and swallowed so one broken
    vnode cannot stop the committee's clock.
    """

    YIELD_EVERY = 512

    def __init__(self, tick_s: float = 0.010):
        if tick_s <= 0:
            raise ValueError("tick_s must be > 0")
        self.tick_s = tick_s
        self._buckets: dict[int, list[WheelHandle]] = {}
        self._task: asyncio.Task | None = None
        self._tick = 0  # last processed tick
        # reporter counters
        self.scheduled_ct = 0
        self.fired_ct = 0
        self.cancelled_ct = 0
        self.cb_error_ct = 0

    # -- scheduling --------------------------------------------------------

    def _ticks(self, delay_s: float) -> int:
        return max(1, round(delay_s / self.tick_s))

    def schedule(self, delay_s: float, cb: Callable[[], None]) -> WheelHandle:
        """One-shot callback after ~delay_s (rounded to the tick)."""
        h = WheelHandle(cb)
        self._buckets.setdefault(self._tick + self._ticks(delay_s), []).append(h)
        self.scheduled_ct += 1
        return h

    def schedule_periodic(
        self, period_s: float, cb: Callable[[], None], phase_s: float = 0.0
    ) -> WheelHandle:
        """Recurring callback every ~period_s; `phase_s` staggers the first
        fire so thousands of same-period nodes don't land on one tick."""
        h = WheelHandle(cb, period_ticks=self._ticks(period_s))
        first = self._ticks(phase_s) if phase_s > 0 else h.period_ticks
        self._buckets.setdefault(self._tick + first, []).append(h)
        self.scheduled_ct += 1
        return h

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tick = int(loop.time() / self.tick_s)
        self._task = loop.create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._buckets.clear()

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            target = (self._tick + 1) * self.tick_s
            delay = target - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            # process every tick between the last one and now (a saturated
            # loop skips wall ticks; their buckets still run, in order)
            now_tick = max(self._tick + 1, int(loop.time() / self.tick_s))
            ran = 0
            for t in range(self._tick + 1, now_tick + 1):
                bucket = self._buckets.pop(t, None)
                if not bucket:
                    continue
                for h in bucket:
                    if h.cancelled:
                        self.cancelled_ct += 1
                        continue
                    try:
                        h.cb()
                    except Exception:
                        self.cb_error_ct += 1
                    self.fired_ct += 1
                    if h.period_ticks:
                        self._buckets.setdefault(
                            t + h.period_ticks, []
                        ).append(h)
                    ran += 1
                    if ran % self.YIELD_EVERY == 0:
                        await asyncio.sleep(0)
            self._tick = now_tick

    def values(self) -> dict[str, float]:
        return {
            "wheelScheduledCt": float(self.scheduled_ct),
            "wheelFiredCt": float(self.fired_ct),
            "wheelCancelledCt": float(self.cancelled_ct),
            "wheelCbErrors": float(self.cb_error_ct),
            "wheelPendingSize": float(
                sum(len(b) for b in self._buckets.values())
            ),
        }

    def gauge_keys(self) -> set[str]:
        return {"wheelPendingSize"}


class WheelTimeout:
    """LinearTimeout semantics on the shared wheel: level i starts at
    i*period, but with ONE outstanding handle per node at any time (each
    fire schedules the next) instead of a dedicated sleeper task."""

    def __init__(self, wheel: TimerWheel, handel, levels: Sequence[int],
                 period: float):
        self.wheel = wheel
        self.handel = handel
        self.levels = list(levels)
        self.period = period
        self._idx = 0
        self._handle: WheelHandle | None = None
        self._stopped = False

    @classmethod
    def factory(cls, wheel: TimerWheel, period: float):
        """Config.new_timeout-compatible closure."""
        return lambda handel, levels: cls(wheel, handel, levels, period)

    def start(self) -> None:
        self._fire()  # level[0] starts immediately, like LinearTimeout

    def stop(self) -> None:
        self._stopped = True
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _fire(self) -> None:
        if self._stopped or self._idx >= len(self.levels):
            self._handle = None
            return
        self.handel.start_level(self.levels[self._idx])
        self._idx += 1
        if self._idx < len(self.levels):
            self._handle = self.wheel.schedule(self.period, self._fire)

"""Reporter plane: uniform counter surfaces + the aggregating decorator.

Reference: report.go:5-87 — the `Reporter` interface (`Values() map[string]
float64`), `ReportHandel` wrapping a Handel to also expose its store's and
network's counters, and `ReportStore` counting merge attempts. Here the
components already expose `values()` (core/handel.py:355, store, processing,
networks, parallel/batch_verifier.py); this module adds the missing
aggregation layer — one object the monitor's CounterIO can snapshot — plus
the TPU-specific kernel-time counters (SURVEY.md §5.1 "same counter plane +
kernel time").
"""

from __future__ import annotations

import time
from typing import Mapping, Protocol


class Reporter(Protocol):
    """Anything exposing a flat float counter map (report.go:10-13)."""

    def values(self) -> dict[str, float]: ...


class ReportAggregator:
    """Namespaced union of many reporters (report.go ReportHandel, widened:
    any set of components, each under a prefix).

    >>> agg = ReportAggregator(handel=h, net=net, verifier=svc)
    >>> agg.values()  # {"handel_msgSentCt": ..., "net_sentPackets": ...}
    """

    def __init__(self, **reporters: Reporter):
        self._reporters = dict(reporters)

    def add(self, prefix: str, reporter: Reporter) -> None:
        self._reporters[prefix] = reporter

    def values(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for prefix, rep in self._reporters.items():
            for k, v in rep.values().items():
                out[f"{prefix}_{k}"] = float(v)
        return out

    def gauge_keys(self) -> set[str]:
        """Union of the children's explicit gauge declarations, carrying
        the same prefix their values get (core/metrics.py is_gauge_key)."""
        out: set[str] = set()
        for prefix, rep in self._reporters.items():
            gk = getattr(rep, "gauge_keys", None)
            if callable(gk):
                out |= {f"{prefix}_{k}" for k in gk()}
        return out


class WarnOnce:
    """Warn-once log gate with a reporter-plane counter.

    A flooder spamming malformed packets must not turn per-packet logging
    into the attack, so repeat offenses per reason drop to debug — but a
    suppressed warning is invisible in a CSV capture. Every occurrence
    (warned or suppressed) increments a per-key counter that rides the
    monitor plane as `logWarnCt` (core/handel.py, network/udp.py)."""

    def __init__(self, logger):
        self.log = logger
        self.counts: dict[str, int] = {}

    def warn(self, key: str, detail) -> None:
        n = self.counts.get(key, 0) + 1
        self.counts[key] = n
        (self.log.warn if n == 1 else self.log.debug)(key, detail)

    def total(self) -> int:
        return sum(self.counts.values())

    def values(self) -> dict[str, float]:
        return {"logWarnCt": float(self.total())}


class KernelTimer:
    """Device launch-time counters for the monitor plane.

    Wraps a callable (e.g. BN254Device.batch_verify); accumulates wall time
    spent inside launches and the launch count. This is the kernel-time trace
    hook the reference's `sigCheckingTime` counter (processing.go:280)
    becomes when verification moves on device."""

    def __init__(self, fn, name: str = "kernel"):
        self._fn = fn
        self.name = name
        self.calls = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return self._fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            self.calls += 1
            self.total_s += dt
            self.max_s = max(self.max_s, dt)

    def values(self) -> dict[str, float]:
        return {
            f"{self.name}Calls": float(self.calls),
            f"{self.name}TimeMs": self.total_s * 1000.0,
            f"{self.name}MaxMs": self.max_s * 1000.0,
        }


class CurveCheckCounters:
    """Process-wide counters for host-side point-validation cost.

    The G2 subgroup check (a scalar-mult by r on every pubkey-bearing
    unmarshal, models/bn254.py) is the biggest host-CPU item on the packet/
    registry-load path; without a counter a large-N run can't attribute its
    host time. models/{bn254,bls12_381}.py feed the shared instance below;
    sim/node.py reports it through the monitor plane."""

    def __init__(self):
        self.g2_checks = 0
        self.g2_time_ms = 0.0

    def add_g2(self, dt_ms: float) -> None:
        self.g2_checks += 1
        self.g2_time_ms += dt_ms

    def values(self) -> dict[str, float]:
        return {
            "g2SubgroupChecks": float(self.g2_checks),
            "g2SubgroupCheckTimeMs": self.g2_time_ms,
        }


#: the per-process instance every curve backend feeds
SUBGROUP_CHECKS = CurveCheckCounters()


def diff_values(
    before: Mapping[str, float], after: Mapping[str, float]
) -> dict[str, float]:
    """Per-key delta of two counter snapshots (measure.go CounterMeasure)."""
    return {k: after[k] - before.get(k, 0.0) for k in after}

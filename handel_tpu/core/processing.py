"""Asynchronous, batch-oriented signature verification pipeline.

Reference: processing.go:37-368 — `SigEvaluator` (:37-42), the evaluator
processing loop (:144-287) that repeatedly picks the highest-scored pending
signature, verifies it (aggregate-pubkey loop + pairing, :342-368), and
publishes it; and the pre-queue `Filter` (:293-323) deduplicating individual
signatures.

TPU-first redesign (the one architectural change vs the reference, SURVEY.md
§7): instead of verifying one best signature at a time, each step drains the
todo queue, scores everything, and hands the top `batch_size` candidates to the
scheme's `batch_verify` — one vmap'd multi-pairing launch on device. Surviving
candidates are re-scored on the next step, preserving the reference's
prune-after-each-result semantics (SURVEY.md §7 hard part (e)): we may verify
slightly more than the serial reference, never less.

Verification requests are expressed as *global* registry bitsets (the level
bitset shifted to its range offset), so a device scheme can aggregate public
keys as a masked segment-sum over the dense on-device registry array.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Awaitable, Callable, Protocol, Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import Constructor, PublicKey, Signature
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.partitioner import BinomialPartitioner, IncomingSig
from handel_tpu.core.store import VerifiedAggCache
from handel_tpu.core.trace import LogHistogram, trace_now


class SigEvaluator(Protocol):
    """Scores unverified signatures: 0 = discard, higher = verify sooner
    (processing.go:37-42)."""

    def evaluate(self, sp: IncomingSig) -> int: ...


class Evaluator1:
    """Scores everything 1 — verify every signature (processing.go:46-51)."""

    def evaluate(self, sp: IncomingSig) -> int:
        return 1


class Filter(Protocol):
    """Pre-queue filter (processing.go:293-297)."""

    def accept(self, sp: IncomingSig) -> bool: ...


class IndividualSigFilter:
    """Accept each origin's individual signature only once
    (processing.go:299-323)."""

    def __init__(self):
        self._seen: set[int] = set()

    def accept(self, sp: IncomingSig) -> bool:
        if not sp.individual:
            return True
        if sp.origin in self._seen:
            return False
        self._seen.add(sp.origin)
        return True


class CombineShim:
    """Accumulate-and-flush batcher for aggregate-signature point additions.

    `SignatureStore` merge/patch chains and the partitioner's level
    combination hand whole signature groups to `combine_many` (wired as the
    store's `combiner` hook by core/handel.py); callers that can defer —
    anything resolving several independent merges in one step — `accumulate`
    groups and `flush`, and every group queued at flush time resolves in ONE
    device `combine_batch` launch (models/bn254_jax.py) instead of one host
    pairing-library point add per contribution.

    Groups below `min_device_points` fold host-side: a device launch beats
    the native host add only once enough point adds amortize its round
    trip. The device hook is `constructor.device_combine(groups)`, which
    returns None when the device is not ready, its breaker is open, or the
    launch failed — every degradation lands on the host fold, never on an
    error, so the shim is safe to wire unconditionally.
    """

    def __init__(self, device_combine, min_device_points: int = 4):
        self.device_combine = device_combine
        self.min_device_points = max(2, min_device_points)
        self._queue: list[list] = []
        self._flushed: list = []
        # reporter counters (Handel.values merges them onto the sigs plane)
        self.combine_groups = 0
        self.combine_points = 0
        self.combine_device_groups = 0
        self.combine_host_groups = 0

    @classmethod
    def for_constructor(cls, constructor, **kw) -> "CombineShim | None":
        """A shim when the constructor exposes a device combine hook
        (BN254JaxConstructor.device_combine and subclasses), else None —
        host schemes keep the store's plain serial path."""
        fn = getattr(constructor, "device_combine", None)
        return cls(fn, **kw) if callable(fn) else None

    @staticmethod
    def _host_fold(sigs):
        sig = sigs[0]
        for s in sigs[1:]:
            sig = s.combine(sig)
        return sig

    def _resolve(self, groups: list[list]) -> list:
        """Resolve many groups: one device launch for those wide enough to
        pay for it, host folds for the rest (and for every group when the
        device declines)."""
        out: list = [None] * len(groups)
        dev_idx = [
            i
            for i, g in enumerate(groups)
            if len(g) >= self.min_device_points
            and all(getattr(s, "point", None) is not None for s in g)
        ]
        if dev_idx and self.device_combine is not None:
            pts = self.device_combine(
                [[s.point for s in groups[i]] for i in dev_idx]
            )
            if pts is not None:
                for i, p in zip(dev_idx, pts):
                    if p is None:
                        # declined (class not warmed) or a legitimate
                        # infinity sum: both redo on the host, which is
                        # correct either way and never compiles mid-round
                        continue
                    out[i] = type(groups[i][0])(p)
                    self.combine_device_groups += 1
        for i, g in enumerate(groups):
            if out[i] is None:
                out[i] = self._host_fold(g)
                self.combine_host_groups += 1
        return out

    def combine_many(self, sigs):
        """Synchronous combiner (the `SignatureStore.combiner` hook): one
        group, resolved now — with any accumulated groups riding the same
        launch."""
        group = list(sigs)
        self.combine_groups += 1
        self.combine_points += len(group)
        if self._queue:
            queued, self._queue = self._queue, []
            results = self._resolve(queued + [group])
            self._flushed.extend(results[:-1])
            return results[-1]
        return self._resolve([group])[0]

    def accumulate(self, sigs) -> int:
        """Queue a group for the next flush; returns its result index."""
        group = list(sigs)
        self.combine_groups += 1
        self.combine_points += len(group)
        self._queue.append(group)
        return len(self._queue) - 1

    def flush(self) -> list:
        """Resolve every accumulated group in one launch; returns their
        combined signatures in accumulate order (plus any the last
        `combine_many` already swept up, first)."""
        swept, self._flushed = list(self._flushed), []
        if not self._queue:
            return swept
        queued, self._queue = self._queue, []
        return swept + self._resolve(queued)

    def values(self) -> dict[str, float]:
        return {
            "combineGroups": float(self.combine_groups),
            "combinePoints": float(self.combine_points),
            "combineDeviceGroups": float(self.combine_device_groups),
            "combineHostGroups": float(self.combine_host_groups),
        }


# An async verifier: (msg, registry pubkeys, [(global bitset, signature)]) ->
# list of verdicts. The default wraps Constructor.batch_verify; the shared
# device service in parallel/batch_verifier.py fuses many nodes' requests into
# one launch.
AsyncVerifier = Callable[
    [bytes, Sequence[PublicKey], Sequence[tuple[BitSet, Signature]]],
    Awaitable[list[bool]],
]


class BatchProcessing:
    """Evaluator-driven batched verification pipeline.

    Matches evaluatorProcessing's external contract (processing.go:93-287):
    `add` enqueues parsed signatures, a background task scores + verifies them,
    and every verified signature is delivered to `on_verified` (the reference's
    Verified() channel consumed by Handel.rangeOnVerified, handel.go:239-248).
    """

    def __init__(
        self,
        part: BinomialPartitioner,
        constructor: Constructor,
        msg: bytes,
        registry_pubkeys: Sequence[PublicKey],
        evaluator: SigEvaluator,
        on_verified: Callable[[IncomingSig], None],
        *,
        batch_size: int = 16,
        verifier: AsyncVerifier | None = None,
        unsafe_sleep_ms: int = 0,
        dedup_cache: VerifiedAggCache | None = None,
        max_pending: int = 4096,
        on_verify_failed: Callable[[IncomingSig], None] | None = None,
        logger: Logger = DEFAULT_LOGGER,
        recorder=None,
        trace_tid: int = 0,
        session: str = "",
        epoch: int = 0,
    ):
        self.part = part
        self.cons = constructor
        self.msg = msg
        self.pubkeys = registry_pubkeys
        self.evaluator = evaluator
        self.on_verified = on_verified
        self.batch_size = batch_size
        self.verifier = verifier or self._default_verifier
        self.unsafe_sleep_ms = unsafe_sleep_ms
        self.log = logger
        self.filter: Filter = IndividualSigFilter()
        self.max_retries = 3  # per-candidate verifier-error retry budget
        self.max_pending = max(1, max_pending)
        # byzantine attribution hook: called with the candidate whose
        # verification FAILED, so the node can penalize the packet origin
        # (core/penalty.py via Handel._on_verify_failed)
        self.on_verify_failed = on_verify_failed
        # multi-tenant scope (handel_tpu/service/): a non-empty session id
        # prefixes every dedup key below, so a cache shared across
        # sessions — or a future shared per-committee cache — can never
        # hand one tenant another tenant's verdict. "" keeps the
        # single-tenant key shape byte-for-byte.
        self.session = session
        # validator-set epoch (lifecycle/epoch.py): a nonzero epoch joins
        # the dedup scope so verdicts never survive a registry rotation —
        # the same bytes against a rotated validator set is a new fact.
        self.epoch = epoch
        # tenant/epoch tags folded into every queue/verify span (built once;
        # the tracing hot path only splats the dict)
        self._span_tags: dict = {}
        if session:
            self._span_tags["session"] = session
        if epoch:
            self._span_tags["epoch"] = epoch
        # verified-aggregate dedup: Handel re-receives the same winning
        # aggregate from several peers per level; each copy this node has
        # already judged short-circuits here instead of burning a device lane
        self.dedup = dedup_cache or VerifiedAggCache()
        # dynamic membership (handel_tpu/scenario/): origins known to have
        # left the committee. Their INDIVIDUAL sigs are suppressed at intake
        # (gossip keeps re-delivering them long after the member is gone,
        # and each copy would burn a verify lane); aggregates relayed by a
        # departed node still flow — they carry live members' signatures.
        self._departed: set[int] = set()
        self.sig_departed_dropped = 0

        # priority queue of (-score, seq, sig): scored once at enqueue, lazily
        # re-scored at dequeue (see _select_batch). `_live` maps seq -> sig
        # for every entry still pending; its dict insertion order IS arrival
        # order, which makes the flood bound's drop-oldest O(1): evict the
        # first key, and let the heap skip the dead seq lazily at pop.
        # `_todos` stays a plain list for the FIFO subclass, unused here.
        self._heap: list[tuple[int, int, IncomingSig]] = []
        self._live: dict[int, IncomingSig] = {}
        self._dirty = False  # store changed since last rebuild → scores stale
        self._seq = 0
        self._todos: list[IncomingSig] = []
        self._wakeup = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopped = False

        # observability plane (core/trace.py): per-contribution queue/verify
        # spans when a flight recorder is attached, plus always-on latency
        # histograms (one clock read per enqueue/batch — negligible)
        self.rec = recorder
        self.tid = trace_tid
        self.hist_queue_wait = LogHistogram()  # enqueue -> selected, per sig
        self.hist_verify = LogHistogram()  # verifier wall, per batch

        # reporter counters (processing.go:242-256)
        self.sig_checked_ct = 0
        self.sig_queue_size = 0
        self.sig_suppressed = 0
        self.sig_dropped_overflow = 0
        self.sig_verify_failed = 0
        self.sig_checking_time_ms = 0.0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        self._stopped = True
        self._wakeup.set()

    # -- intake ------------------------------------------------------------

    def add(self, sp: IncomingSig) -> None:
        if self._stopped:
            return
        if sp.individual and sp.origin in self._departed:
            self.sig_departed_dropped += 1
            return
        if self.filter.accept(sp):
            self._enqueue(sp)
            if self._queue_len():
                self._wakeup.set()

    def _enqueue(self, sp: IncomingSig) -> None:
        """Score once and push; worthless candidates die at the door
        (the reference prunes score-0 todos on every pass,
        processing.go:171-220 — here they are pruned at enqueue and again
        at dequeue, never verified). The pending set is BOUNDED: past
        `max_pending` the oldest queued candidate is evicted (drop-oldest —
        under a flood the oldest entries are the stalest, and the
        protocol's periodic resend recovers anything that mattered), so a
        flooder cannot grow host memory."""
        if sp.ms is None:
            self.sig_suppressed += 1
            return
        mark = self.evaluator.evaluate(sp)
        if mark <= 0:
            self.sig_suppressed += 1
            return
        sp.enqueue_ts = trace_now()  # queue-wait span start (re-stamped on requeue)
        self._seq += 1
        heapq.heappush(self._heap, (-mark, self._seq, sp))
        self._live[self._seq] = sp
        if len(self._live) > self.max_pending:
            oldest = next(iter(self._live))  # dict order = arrival order
            del self._live[oldest]  # its heap entry dies lazily at pop
            self.sig_dropped_overflow += 1
        if len(self._heap) > 2 * self.max_pending:
            # a sustained flood evicts faster than pops drain: compact the
            # dead heap entries so the heap itself stays bounded. Triggered
            # at most once per max_pending enqueues — O(1) amortized.
            self._heap = [e for e in self._heap if e[1] in self._live]
            heapq.heapify(self._heap)

    def _queue_len(self) -> int:
        return len(self._live)

    def mark_departed(self, origin: int) -> None:
        """Suppress future individual sigs from a departed member (the
        already-queued ones fail no invariants — they just verify and merge,
        which is correct: the member signed before leaving)."""
        self._departed.add(origin)

    def pending(self) -> list[IncomingSig]:
        """Snapshot of queued candidates (test/introspection hook)."""
        return list(self._live.values())

    # -- processing loop ---------------------------------------------------

    async def _loop(self) -> None:
        while not self._stopped:
            if not self._queue_len():
                self._wakeup.clear()
                await self._wakeup.wait()
                continue
            batch = self._select_batch()
            if not batch:
                continue
            await self._verify_and_publish(batch)

    def _select_batch(self) -> list[IncomingSig]:
        """Pop the best-scored candidates, re-scoring lazily but EXACTLY.

        The reference's readTodos (processing.go:171-220) re-scores the WHOLE
        queue per pick — O(queue) Python per step melts at a 4000-node flood.
        Here enqueue-time scores order the heap; a popped entry is re-scored
        against the current store and, if its score went stale, re-inserted
        at the fresh score instead of taking a batch slot. The store is fixed
        within one call, so a refreshed entry popped again matches its key
        and is taken — every entry costs at most two pops per call.

        Pop-refresh-reinsert alone is only exact while scores never RISE
        after enqueue (a risen entry keeps its stale-low key and stays
        buried, never reaching the top to be refreshed) — and store scores
        DO rise: a queued sig can jump into the ~1,000,000 level-completion
        bracket as indiv_verified grows (store.py _evaluate). Scores only
        move when the store changes, and the store only changes through the
        on_verified publishes this pipeline itself issues, so
        _verify_and_publish marks the heap dirty after publishing and the
        next call here rebuilds it with fresh scores — one O(queue) rescan
        per *successful batch* (≤ 1/batch_size of the reference's per-pick
        rescan) instead of per pick. The selected batch is therefore exactly
        the current top of the queue. Order fidelity is load-bearing: a
        stale-ordered variant of this loop verified ~4x more signatures per
        node at N=2000 because each check contributed less.
        """
        if self._dirty:
            self._dirty = False
            stale = self._heap
            self._heap = []
            for _, seq, sp in stale:
                if seq not in self._live:
                    continue  # overflow-evicted: already counted at drop
                fresh = self.evaluator.evaluate(sp) if sp.ms is not None else 0
                if fresh <= 0:
                    self.sig_suppressed += 1
                    del self._live[seq]
                else:
                    self._heap.append((-fresh, seq, sp))
            heapq.heapify(self._heap)

        batch: list[IncomingSig] = []
        while self._heap and len(batch) < self.batch_size:
            neg, seq, sp = heapq.heappop(self._heap)
            if seq not in self._live:
                continue  # overflow-evicted: already counted at drop
            fresh = self.evaluator.evaluate(sp) if sp.ms is not None else 0
            if fresh <= 0:
                self.sig_suppressed += 1
                del self._live[seq]
                continue
            if fresh != -neg:
                heapq.heappush(self._heap, (-fresh, seq, sp))
                continue
            del self._live[seq]
            batch.append(sp)

        self.sig_checked_ct += len(batch)
        self.sig_queue_size += self._queue_len()
        return batch

    async def _verify_and_publish(self, batch: list[IncomingSig]) -> None:
        start = time.perf_counter()
        rec = self.rec
        tracing = rec is not None and rec.enabled
        t_deq = trace_now()
        for sp in batch:
            if sp.enqueue_ts:
                self.hist_queue_wait.add(max(0.0, t_deq - sp.enqueue_ts))
                if tracing:
                    rec.span(
                        "queue",
                        sp.enqueue_ts,
                        t_deq,
                        tid=self.tid,
                        cat="pipeline",
                        args={
                            "origin": sp.origin,
                            "level": sp.level,
                            "rts": int(sp.recv_ts * 1e6),
                            "ind": sp.is_ind,
                            "tries": sp.verify_tries,
                            "span": sp.span_id,
                            **self._span_tags,
                        },
                    )
        # Dedup pass: a candidate whose exact content — (level, bitset words,
        # signature bytes) — this node has already judged takes its remembered
        # verdict; duplicates WITHIN the batch ride the first copy's lane.
        # Only the remainder goes to the device.
        oks: list[bool | None] = [None] * len(batch)
        keys: list[tuple] = []
        first_at: dict[tuple, int] = {}
        to_verify: list[int] = []
        for i, sp in enumerate(batch):
            # scope: level alone (single-tenant default, key shape
            # unchanged), else (session, level) or — post-rotation —
            # (session, epoch, level), so an epoch bump invalidates every
            # verdict computed against the previous validator set
            if self.epoch:
                scope = (self.session, self.epoch, sp.level)
            elif self.session:
                scope = (self.session, sp.level)
            else:
                scope = sp.level
            k = VerifiedAggCache.key(scope, sp.ms)
            keys.append(k)
            if k in first_at:
                self.dedup.hits += 1  # in-batch duplicate: zero extra lanes
                continue
            cached = self.dedup.get(k)
            if cached is not None:
                oks[i] = cached
            else:
                first_at[k] = i
                to_verify.append(i)

        if self.unsafe_sleep_ms > 0 and to_verify:
            # test/simulation knob replacing verification with a sleep
            # (config.go:61-65, UnsafeSleepTimeOnSigVerify); dedup hits cost
            # no simulated device time, same as on the real device
            await asyncio.sleep(self.unsafe_sleep_ms * len(to_verify) / 1000.0)
            for i in to_verify:
                oks[i] = True
        elif to_verify:
            try:
                requests = [
                    (self._global_bitset(batch[i]), batch[i].ms.signature)
                    for i in to_verify
                ]
                verdicts = await self.verifier(self.msg, self.pubkeys, requests)
                if len(verdicts) != len(to_verify):
                    self.log.error(
                        "verifier_contract",
                        f"{len(verdicts)} verdicts for {len(to_verify)} requests",
                    )
                    verdicts = None
            except Exception as e:
                # A transient verifier error (device hiccup, RPC failure) must
                # not silently discard candidates: requeue the batch with a
                # per-candidate retry cap so the evaluator re-scores it on the
                # next step. (The reference logs per-signature errors and moves
                # on, processing.go:282-284; the protocol's periodic resend is
                # not guaranteed for individual sigs, hence the requeue.)
                self.log.warn("verifier_error", e)
                verdicts = None
            if verdicts is None:
                # requeue every unresolved candidate (the device subset AND
                # its in-batch duplicates); cached verdicts still publish
                self._requeue([sp for sp, ok in zip(batch, oks) if ok is None])
            else:
                for i, ok in zip(to_verify, verdicts):
                    oks[i] = bool(ok)
                    self.dedup.put(keys[i], bool(ok))
        # resolve in-batch duplicates from their first copy's verdict (which
        # stays None — and so requeued, above — if the verifier errored)
        for i, k in enumerate(keys):
            if oks[i] is None and first_at.get(k, i) != i:
                oks[i] = oks[first_at[k]]
        self.sig_checking_time_ms += (time.perf_counter() - start) * 1000.0
        t_verified = trace_now()
        if to_verify:
            # device-verify latency per launch — the histogram behind the
            # CSV's verifyLatencyS_p50/_p90/_p99 columns
            self.hist_verify.add(max(0.0, t_verified - t_deq))
        if tracing:
            for sp, ok in zip(batch, oks):
                # dedup-cached candidates resolve at the scan: near-zero span
                rec.span(
                    "verify",
                    t_deq,
                    t_verified,
                    tid=self.tid,
                    cat="pipeline",
                    args={
                        "origin": sp.origin,
                        "level": sp.level,
                        "rts": int(sp.recv_ts * 1e6),
                        "ind": sp.is_ind,
                        "ok": bool(ok) if ok is not None else None,
                        "batch": len(batch),
                        "span": sp.span_id,
                        **self._span_tags,
                    },
                )
                if sp.span_id:
                    # flow step through the verify stage keeps the arrow
                    # alive across the queue reorder (merge emits the "f")
                    rec.flow("contrib", sp.span_id, "t", t_verified, tid=self.tid)

        for sp, ok in zip(batch, oks):
            if ok is None:
                continue  # verifier error: already requeued above
            if ok:
                self.on_verified(sp)
                # the publish mutates the store, which can RAISE queued
                # scores — rebuild before the next selection (_select_batch)
                self._dirty = True
            else:
                self.sig_verify_failed += 1
                # warn-once: a byzantine peer can force unlimited failures;
                # the counter + penalty attribution carry the signal
                log = (
                    self.log.warn
                    if self.sig_verify_failed == 1
                    else self.log.debug
                )
                log("verify_failed", f"origin={sp.origin} level={sp.level}")
                if self.on_verify_failed is not None:
                    # attribute the bad signature to the packet origin so
                    # the node can demote/ban a byzantine peer
                    self.on_verify_failed(sp)

    def _requeue(self, batch: list[IncomingSig]) -> None:
        """Put errored candidates back on the todo queue, up to max_retries
        attempts each; drop (with a log line) beyond that."""
        for sp in batch:
            sp.verify_tries += 1
            tries = sp.verify_tries
            if tries <= self.max_retries:
                self._enqueue(sp)
            else:
                self.log.error(
                    "verify_retries_exhausted",
                    f"origin={sp.origin} level={sp.level} tries={tries}",
                )
        if self._queue_len():
            self._wakeup.set()

    def _global_bitset(self, sp: IncomingSig) -> BitSet:
        """Shift a level-local bitset to registry coordinates
        (the aggregation span of processing.go:342-361)."""
        lo, hi = self.part.range_level(sp.level)
        if len(sp.ms.bitset) != hi - lo:
            raise ValueError("inconsistent bitset with given level")
        out = BitSet(len(self.pubkeys))
        # word-level shift-or: this runs once per device-bound candidate,
        # and a per-index Python loop over a 32k-wide top level is the kind
        # of O(N) per event the swarm runtime cannot afford
        out.or_embed(sp.ms.bitset, lo)
        return out

    async def _default_verifier(self, msg, pubkeys, requests):
        return self.cons.batch_verify(msg, pubkeys, requests)

    # -- reporting (processing.go:242-256) ---------------------------------

    def values(self) -> dict[str, float]:
        checked = self.sig_checked_ct
        return {
            "sigCheckedCt": float(checked),
            "sigQueueSize": self.sig_queue_size / checked if checked else 0.0,
            "sigSuppressed": float(self.sig_suppressed),
            "sigDroppedOverflow": float(self.sig_dropped_overflow),
            "sigDepartedDropped": float(self.sig_departed_dropped),
            "sigVerifyFailed": float(self.sig_verify_failed),
            "sigCheckingTime": (
                self.sig_checking_time_ms / checked if checked else 0.0
            ),
            # dedup plane: sigCheckedCt counts SELECTED candidates; subtract
            # dedupHits for actual device verifications
            **self.dedup.values(),
        }

    def gauge_keys(self) -> set[str]:
        """Explicit gauge declarations: the per-candidate averages and the
        dedup cache's point-in-time keys must never be delta'd or averaged
        as counters (sim/monitor.py CounterIO, core/metrics.py)."""
        return {"sigQueueSize", "sigCheckingTime"} | self.dedup.gauge_keys()

    def histograms(self) -> dict[str, LogHistogram]:
        """Latency distributions for the monitor's histogram plane."""
        return {
            "queueWaitS": self.hist_queue_wait,
            "verifyLatencyS": self.hist_verify,
        }


class FifoProcessing(BatchProcessing):
    """Arrival-order pipeline without evaluator scoring
    (the reference's deprecated fifoProcessing, processing.go:380-493).

    Kept for A/B comparison against the evaluator strategy (the
    confgenerator's `evaluator` scenario sweeps exactly this axis,
    simul/confgenerator/confgenerator.go). Batching still applies — the
    first `batch_size` arrivals go to the device together — but nothing is
    suppressed and nothing is reordered, so a flood of stale candidates is
    verified in full.
    """

    def _enqueue(self, sp: IncomingSig) -> None:
        sp.enqueue_ts = trace_now()
        self._todos.append(sp)
        if len(self._todos) > self.max_pending:  # same drop-oldest bound
            self._todos.pop(0)
            self.sig_dropped_overflow += 1

    def _queue_len(self) -> int:
        return len(self._todos)

    def pending(self) -> list[IncomingSig]:
        return list(self._todos)

    def _select_batch(self) -> list[IncomingSig]:
        # drop ms-less entries up front so they neither consume batch slots
        # nor escape the suppressed counter
        usable = [sp for sp in self._todos if sp.ms is not None]
        self.sig_suppressed += len(self._todos) - len(usable)
        batch = usable[: self.batch_size]
        self._todos = usable[self.batch_size :]
        self.sig_checked_ct += len(batch)
        self.sig_queue_size += len(self._todos)
        return batch

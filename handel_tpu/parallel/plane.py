"""Fleet-of-chips device plane: K devices behind one verifier service.

ROADMAP item 2 ("standing ceiling"): every service launch used to land on
one chip, so the 8-device mesh kernels compiled by the MULTICHIP gate were
never fed by a real dispatch path. A `DevicePlane` owns K device engines —
real mesh chips, or host devices forced via
`XLA_FLAGS=--xla_force_host_platform_device_count=8` so the whole plane is
testable on a CPU-only CI box — and gives each one a `DeviceLane`: its own
dispatch hand-off cell, in-flight fetch window, circuit breaker, and
occupancy counters. `BatchVerifierService` schedules launch groups onto
lanes least-loaded-first, so fetch latency on one chip never idles the
others; a lane whose breaker opens simply stops receiving work until its
cooldown probe succeeds (degrade to K-1 chips, not to zero).

The plane is also the fleet's reporter surface: `values()` sums the
per-engine host pack/dispatch costs (the service used to read them off
device 0 only), and `labeled_values()` exposes one row per device for the
`device`-labeled metrics dimension beside `session`
(`handel_device_verifier_launches{device="3"}`).

This module must import neither jax nor the service driver at module level
— fake-crypto simulations construct planes of host stubs in processes that
never touch jax. The jax-backed builder (`bn254_plane`) imports lazily.
"""

from __future__ import annotations

import asyncio

from handel_tpu.utils.breaker import CircuitBreaker

__all__ = ["DeviceLane", "DevicePlane", "bn254_plane", "host_plane"]

#: breaker state -> exposition value (shared with BatchVerifierService)
BREAKER_CODE = {"closed": 0.0, "half-open": 0.5, "open": 1.0}


class DeviceLane:
    """One chip of the plane: an engine plus everything the scheduler needs
    to route around it — hand-off cell, in-flight window, breaker, and
    per-device counters. The asyncio queues are created by the service at
    start() (they must bind to its event loop) and torn down at stop().

    `dispatching` holds the launch group from the moment the scheduler
    hands it to this lane until its handle reaches `fetch_q` (or it fails
    over): while set, the lane's dispatch slot is occupied AND stop() can
    fail the group's futures. `fetching` mirrors it for the fetch stage.
    """

    __slots__ = (
        "index", "engine", "breaker", "q", "fetch_q", "dispatching",
        "fetching", "launches", "candidates", "fill_sum", "last_fill",
        "retries", "fetched", "queued_ts", "draining", "tasks", "mesh",
    )

    def __init__(self, index: int, engine, breaker: CircuitBreaker | None = None,
                 mesh: bool = False):
        self.index = index
        self.engine = engine
        self.breaker = breaker or CircuitBreaker()
        # latency plane (parallel/mesh_plane.py): a mesh lane's engine
        # spans the WHOLE device mesh for one launch. pick() skips it —
        # only latency-mode groups routed via pick_mesh() land here.
        self.mesh = mesh
        self.q: asyncio.Queue | None = None
        self.fetch_q: asyncio.Queue | None = None
        self.dispatching: list | None = None
        self.fetching: list | None = None
        self.launches = 0
        self.candidates = 0
        self.fill_sum = 0.0
        self.last_fill = 0.0
        self.retries = 0
        self.fetched = 0
        # trace stamp: when the launch group currently in `q` was handed to
        # this lane (the launch_queued span's start, batch_verifier.py)
        self.queued_ts = 0.0
        # elasticity (lifecycle/autoscaler.py): a draining lane finishes
        # its in-flight launches but the scheduler stops routing to it —
        # the graceful half of drain_lane/remove_lane
        self.draining = False
        # the lane's dispatcher/fetcher task pair while the service runs
        # (BatchVerifierService start()/attach_lane(); drain cancels them)
        self.tasks: tuple = ()

    @property
    def trace_tid(self) -> int:
        """Chrome-trace thread id for this lane's launch-lifecycle spans:
        negative ids keep lanes clear of node tids, below SERVICE_TID."""
        return -(2 + self.index)

    def free(self) -> bool:
        """Can accept a launch group right now (dispatch slot empty)."""
        return self.dispatching is None

    def inflight(self) -> int:
        """Launches dispatched to the device whose verdicts haven't landed."""
        n = 1 if self.fetching is not None else 0
        if self.fetch_q is not None:
            n += self.fetch_q.qsize()
        return n

    def load(self) -> int:
        """Launches this lane is responsible for right now — the scheduling
        key: queued/dispatching + awaiting fetch."""
        return (1 if self.dispatching is not None else 0) + self.inflight()

    def values(self) -> dict[str, float]:
        """One `device`-labeled metrics row."""
        st = getattr(self.engine, "rlc_stats", None)
        return {
            # scheduling mode of this row: 1 = whole-mesh latency lane,
            # 0 = per-chip throughput lane (`sim watch` mode column)
            "mode": 1.0 if self.mesh else 0.0,
            # batch-check mode of the engine (models/rlc.py): 1 = rlc
            # combined check, 0 = per-candidate (`sim watch` check column)
            "checkMode": (
                1.0 if getattr(self.engine, "batch_check", "per_candidate")
                == "rlc" else 0.0
            ),
            # RLC plane: top-level combined checks, post-failure bisection
            # rechecks, deepest recheck level this engine ever reached
            "rlcLaunches": float(st.rlc_launches) if st else 0.0,
            "bisectionCt": float(st.bisection_ct) if st else 0.0,
            "bisectionDepthMax": float(st.bisection_depth_max) if st else 0.0,
            "launches": float(self.launches),
            "candidates": float(self.candidates),
            "fillRatio": (
                self.fill_sum / self.launches if self.launches else 0.0
            ),
            "lastFill": self.last_fill,
            "inflight": float(self.inflight()),
            "load": float(self.load()),
            "retries": float(self.retries),
            "breakerState": BREAKER_CODE[self.breaker.state],
            "breakerOpenCt": float(self.breaker.open_count),
        }


class DevicePlane:
    """K `DeviceLane`s and the least-loaded-first pick over them.

    `pick()` returns the least-loaded FREE lane among those whose breaker
    admits work, or None when every admissible lane is occupied (the
    caller waits) — so an idle chip is always preferred over queueing
    behind a busy one. `sched_picks`/`idle_violations` audit exactly the
    acceptance property "no device idles while another has ≥ 2 queued
    launches": a violation is counted iff an idle admissible lane existed,
    some lane carried ≥ 2 launches, and the pick was NOT idle — impossible
    under min-load, so the bench asserts the counter stays 0.
    """

    def __init__(self, engines, breakers=None):
        engines = list(engines)
        if not engines:
            raise ValueError("DevicePlane needs at least one device engine")
        if breakers is not None and len(breakers) != len(engines):
            raise ValueError("breakers must match engines 1:1")
        self.lanes = [
            DeviceLane(i, eng, breakers[i] if breakers else None)
            for i, eng in enumerate(engines)
        ]
        self.sched_picks = 0
        self.idle_violations = 0
        # elasticity counters (lifecycle/autoscaler.py) + a monotonically
        # increasing index source so a replacement lane never reuses a
        # retired lane's metrics row / trace thread
        self._next_index = len(self.lanes)
        self.lanes_added = 0
        self.lanes_removed = 0
        # dual-mode scheduling audit (parallel/mesh_plane.py): latency-mode
        # picks taken off the mesh lane(s)
        self.mesh_picks = 0

    def __len__(self) -> int:
        return len(self.lanes)

    @property
    def batch_size(self) -> int:
        # the THROUGHPUT batch width: a mesh lane's engine is typically a
        # small-batch shape and must not set the collector's drain size
        for l in self.lanes:
            if not l.mesh:
                return l.engine.batch_size
        return self.lanes[0].engine.batch_size

    def add_lane(self, engine, breaker: CircuitBreaker | None = None,
                 mesh: bool = False) -> DeviceLane:
        """Grow the plane by one lane (verify-plane elasticity, or a
        latency-plane mesh lane when `mesh=True`). The caller
        (BatchVerifierService.attach_lane) wires the asyncio plumbing; a
        bare plane user just gets a new schedulable lane."""
        lane = DeviceLane(self._next_index, engine, breaker, mesh=mesh)
        self._next_index += 1
        self.lanes.append(lane)
        self.lanes_added += 1
        return lane

    def remove_lane(self, lane: DeviceLane) -> None:
        """Retire one lane. The last lane is irremovable — a plane with no
        engine cannot serve, and `batch_size`/`device` aliases would
        dangle. Likewise the last THROUGHPUT lane while mesh lanes remain:
        bulk groups don't fit a small-batch mesh engine, so a mesh-only
        plane (unless built that way outright) cannot serve them."""
        others = [l for l in self.lanes if l is not lane]
        if not others:
            raise ValueError("cannot remove the last lane of a DevicePlane")
        if not lane.mesh and all(l.mesh for l in others):
            raise ValueError(
                "cannot remove the last throughput lane of a DevicePlane"
            )
        self.lanes.remove(lane)
        self.lanes_removed += 1

    def allowed(self) -> list[DeviceLane]:
        """Lanes whose breaker currently admits launches (a draining lane
        admits nothing — it only finishes what it already carries)."""
        return [l for l in self.lanes if not l.draining and l.breaker.allow()]

    def throughput_pool(self) -> list[DeviceLane]:
        """Admissible lanes a THROUGHPUT pick may return: the non-mesh
        lanes. A plane built purely of mesh lanes (degenerate, but must not
        deadlock the collector) falls back to the whole admissible set —
        there a "bulk" group is whatever fits the mesh engine."""
        allowed = self.allowed()
        if any(not l.mesh for l in self.lanes):
            return [l for l in allowed if not l.mesh]
        return allowed

    def mesh_lanes(self) -> list[DeviceLane]:
        return [l for l in self.lanes if l.mesh]

    def pick(self) -> DeviceLane | None:
        """Least-loaded free admissible THROUGHPUT lane; None when none is
        free. Mesh lanes are never returned here — only latency-mode
        groups, routed via `pick_mesh`, may occupy the whole mesh."""
        pool = self.throughput_pool()
        free = [l for l in pool if l.free()]
        if not free:
            return None
        lane = min(free, key=lambda l: (l.load(), l.index))
        self.sched_picks += 1
        if (
            lane.load() > 0
            and any(l.load() == 0 for l in pool)
            and any(l.load() >= 2 for l in self.lanes if not l.mesh)
        ):
            self.idle_violations += 1
        return lane

    def pick_mesh(self) -> DeviceLane | None:
        """Free admissible mesh lane for a latency-mode group (least-loaded
        when several), or None — the caller falls back to the throughput
        path and counts a mesh fallback. A mesh lane whose breaker is open
        simply makes latency mode unavailable; it never fails the group."""
        free = [
            l for l in self.mesh_lanes()
            if not l.draining and l.breaker.allow() and l.free()
        ]
        if not free:
            return None
        lane = min(free, key=lambda l: (l.load(), l.index))
        self.mesh_picks += 1
        return lane

    def inflight_launches(self) -> int:
        return sum(l.inflight() for l in self.lanes)

    def host_cost(self) -> dict[str, float]:
        """Per-launch host accounting SUMMED over the fleet's engines (the
        service used to read the counters off device 0 only)."""
        out = {"pack_ms": 0.0, "pack_launches": 0.0,
               "dispatch_ms": 0.0, "dispatch_launches": 0.0}
        for lane in self.lanes:
            eng = lane.engine
            out["pack_ms"] += float(getattr(eng, "host_pack_ms", 0.0))
            out["pack_launches"] += float(
                getattr(eng, "host_pack_launches", 0)
            )
            out["dispatch_ms"] += float(
                getattr(eng, "host_dispatch_ms", 0.0)
            )
            out["dispatch_launches"] += float(
                getattr(eng, "host_dispatch_launches", 0)
            )
        return out

    def values(self) -> dict[str, float]:
        """Fleet aggregates (folded into the service's values())."""
        mesh = self.mesh_lanes()
        stats = [
            st for l in self.lanes
            if (st := getattr(l.engine, "rlc_stats", None)) is not None
        ]
        return {
            # RLC batch-check plane (models/rlc.py): counters SUM over the
            # fleet, the depth high-water mark is a MAX (a per-engine
            # maximum summed across lanes would mean nothing)
            "rlcLaunches": float(sum(s.rlc_launches for s in stats)),
            "bisectionCt": float(sum(s.bisection_ct for s in stats)),
            "bisectionDepthMax": float(max(
                (s.bisection_depth_max for s in stats), default=0
            )),
            "checkMode": (
                1.0 if any(
                    getattr(l.engine, "batch_check", "per_candidate") == "rlc"
                    for l in self.lanes
                ) else 0.0
            ),
            "devicesTotal": float(len(self.lanes)),
            "devicesAvailable": float(len(self.allowed())),
            "schedPicks": float(self.sched_picks),
            "schedIdleViolations": float(self.idle_violations),
            "lanesAdded": float(self.lanes_added),
            "lanesRemoved": float(self.lanes_removed),
            # latency plane (parallel/mesh_plane.py): mesh lane census +
            # the launches that actually rode the whole mesh
            "meshLanes": float(len(mesh)),
            "meshLanesAvailable": float(sum(
                1 for l in mesh if not l.draining and l.breaker.allow()
            )),
            "meshPicks": float(self.mesh_picks),
            "meshLaunches": float(sum(l.launches for l in mesh)),
        }

    def labeled_values(self) -> dict[str, dict[str, float]]:
        """Per-device rows for the `device` label dimension
        (core/metrics.py register_labeled_values(label="device"))."""
        return {str(l.index): l.values() for l in self.lanes}

    def labeled_gauge_keys(self) -> set[str]:
        return {
            "fillRatio", "lastFill", "inflight", "load", "breakerState",
            "mode",
        }


def host_plane(constructor, devices: int, batch_size: int = 64,
               launch_ms: float = 0.0,
               batch_check: str = "per_candidate") -> DevicePlane:
    """A plane of K host-math engines (service/driver.py HostDevice) — the
    CI/bench shape: real scheduling + breakers, no kernels compiled."""
    from handel_tpu.service.driver import HostDevice

    return DevicePlane([
        HostDevice(constructor, batch_size=batch_size, launch_ms=launch_ms,
                   batch_check=batch_check)
        for _ in range(max(1, devices))
    ])


def bn254_plane(registry_pubkeys, devices: int, batch_size: int = 16,
                curves=None, warmup: bool = False) -> DevicePlane:
    """A plane of K BN254 engines, one pinned to each visible jax device.
    Each engine commits the registry to ITS chip once at startup (the
    single-chip resident-registry pattern, per device). Warmup is off by
    default: pairing-tail compiles are minutes each — smokes drive the
    aggregation stage only, exactly like scripts/launch_smoke.py."""
    import jax

    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops.curve import BN254Curves

    devs = jax.devices()
    if devices > len(devs):
        raise ValueError(
            f"requested {devices} devices but only {len(devs)} visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    shared = curves or BN254Curves()
    engines = []
    for i in range(max(1, devices)):
        eng = BN254Device(
            registry_pubkeys, batch_size=batch_size, curves=shared,
            jax_device=devs[i],
        )
        if warmup:
            eng.warmup()
        engines.append(eng)
    return DevicePlane(engines)

"""Multi-chip scaling: device meshes, sharded aggregation, batch fusion.

The reference has no collective-communication layer (SURVEY.md §2.4, §5.8 —
point-to-point sockets only); this package is the TPU-native addition: scale
the verification batch axis over a `jax.sharding.Mesh` with XLA collectives
riding ICI, and fuse many co-located logical nodes' verify requests into one
device launch.
"""

from handel_tpu.parallel.sharding import (
    make_mesh,
    sharded_pairing_check,
    sharded_masked_sum_g2,
)
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.parallel.plane import DeviceLane, DevicePlane

__all__ = [
    "make_mesh",
    "sharded_pairing_check",
    "sharded_masked_sum_g2",
    "BatchVerifierService",
    "DeviceLane",
    "DevicePlane",
]
